import time

from stateright_tpu.models.two_phase_commit import TwoPhaseSys

if __name__ == "__main__":
    t0 = time.perf_counter()
    c = TwoPhaseSys(3).checker().threads(2).spawn_bfs().join()
    print("2pc-3 pbfs:", c.unique_state_count(), f"{time.perf_counter()-t0:.1f}s")
    p = c.discovery("abort agreement")
    print("abort path:", len(p.into_states()) if p else None)
