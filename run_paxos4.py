import time

from stateright_tpu import TensorModelAdapter
from stateright_tpu.models.paxos import PaxosTensorExhaustive

t0 = time.perf_counter()
c = (
    TensorModelAdapter(PaxosTensorExhaustive(4))
    .checker()
    .threads(8)
    .timeout(1800)
    .spawn_bfs()
    .join()
)
dt = time.perf_counter() - t0
print(
    f"paxos-4 vbfs: secs={dt:.1f} unique={c.unique_state_count()} "
    f"gen={c.state_count()} rate={c.state_count()/dt:,.0f} done={c.is_done()}",
    flush=True,
)
