import time

from stateright_tpu import TensorModelAdapter
from stateright_tpu.models.paxos import PaxosTensorExhaustive

if __name__ == "__main__":
    tm = PaxosTensorExhaustive(6)
    opts = dict(
        chunk_size=8192,
        queue_capacity=1 << 21,
        table_capacity=1 << 26,
        sync_steps=128,
    )
    t0 = time.perf_counter()
    c = TensorModelAdapter(tm).checker().spawn_tpu_bfs(**opts).join()
    dt = time.perf_counter() - t0
    print(
        f"paxos-6 device: secs={dt:.1f} unique={c.unique_state_count()} "
        f"gen={c.state_count()} rate={c.state_count()/dt:,.0f} tel={c.telemetry()}",
        flush=True,
    )
    assert c.unique_state_count() == 9_357_525, c.unique_state_count()
    for name in ("network within capacity", "ballot rounds within range", "linearizable"):
        assert c.discovery(name) is None, name
    print("GOLDEN MATCH + guards quiet", flush=True)
