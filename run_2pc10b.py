import time

from stateright_tpu import TensorModelAdapter
from stateright_tpu.models import TwoPhaseTensor

tm = TwoPhaseTensor(10)
opts = dict(
    chunk_size=8192,
    queue_capacity=1 << 24,
    table_capacity=1 << 29,
    sync_steps=128,
)
t0 = time.perf_counter()
c = TensorModelAdapter(tm).checker().spawn_tpu_bfs(**opts).join()
dt = time.perf_counter() - t0
print(
    f"2pc-10 device: secs={dt:.1f} unique={c.unique_state_count()} "
    f"gen={c.state_count()} rate={c.state_count()/dt:,.0f} tel={c.telemetry()}",
    flush=True,
)
assert c.unique_state_count() == 61_515_776, c.unique_state_count()
print("GOLDEN MATCH", flush=True)
