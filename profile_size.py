"""Scratch: random-gather cost vs array size (cache cliff) at W=75776."""
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

u = jnp.uint32
K = 30
W = 75776
iota = jnp.arange(W, dtype=u)


def mix(x, salt):
    x = (x ^ u(salt)) * u(0x9E3779B9)
    return x ^ (x >> u(16))


for logcap in (18, 19, 20, 21, 22, 23):
    CAP = 1 << logcap
    arr = jnp.arange(CAP, dtype=u) * u(0x9E3779B9)

    def f(arr=arr, CAP=CAP):
        def body(i, acc):
            idx = mix(iota + i * u(W), 3) & u(CAP - 1)
            return acc ^ arr[idx].sum(dtype=u)
        return lax.fori_loop(u(0), u(K), body, u(0))

    g = jax.jit(f)
    np.asarray(g())
    t0 = time.perf_counter()
    s = np.asarray(g())
    dt = time.perf_counter() - t0
    print(f"gather W=75776 from {CAP*4/1e6:6.1f}MB u32: {dt/K*1000:7.2f} ms/iter", flush=True)

    pair = jnp.stack([arr, arr ^ u(1)], axis=1)

    def fp(pair=pair, CAP=CAP):
        def body(i, acc):
            idx = mix(iota + i * u(W), 3) & u(CAP - 1)
            rows = pair[idx]
            return acc ^ rows[:, 0].sum(dtype=u) ^ rows[:, 1].sum(dtype=u)
        return lax.fori_loop(u(0), u(K), body, u(0))

    g = jax.jit(fp)
    np.asarray(g())
    t0 = time.perf_counter()
    s = np.asarray(g())
    dt = time.perf_counter() - t0
    print(f"pair-g W=75776 from {CAP*8/1e6:6.1f}MB [c,2]: {dt/K*1000:7.2f} ms/iter", flush=True)

# scatter cost vs size
for logcap in (19, 20, 22):
    CAP = 1 << logcap

    def fs(CAP=CAP):
        buf0 = jnp.zeros(CAP, dtype=u)
        def body(i, st):
            buf, acc = st
            idx = mix(iota + i * u(W), 7) & u(CAP - 1)
            buf = buf.at[idx].set(iota, mode="drop")
            return buf, acc ^ buf[0]
        out = lax.fori_loop(u(0), u(K), body, (buf0, u(0)))
        return out[1]

    g = jax.jit(fs)
    np.asarray(g())
    t0 = time.perf_counter()
    s = np.asarray(g())
    dt = time.perf_counter() - t0
    print(f"scatter W=75776 into {CAP*4/1e6:6.1f}MB u32: {dt/K*1000:7.2f} ms/iter", flush=True)
