"""Scratch: 2pc-10 on the device engine (round 5, VERDICT #2)."""
import sys
import time

from stateright_tpu import TensorModelAdapter
from stateright_tpu.models import TwoPhaseTensor

chunk = int(sys.argv[1]) if len(sys.argv) > 1 else 12288
qcap = int(sys.argv[2]) if len(sys.argv) > 2 else 1 << 23
tcap = int(sys.argv[3]) if len(sys.argv) > 3 else 1 << 28

tm = TwoPhaseTensor(10)
opts = dict(chunk_size=chunk, queue_capacity=qcap, table_capacity=tcap)
t0 = time.perf_counter()
c = TensorModelAdapter(tm).checker().spawn_tpu_bfs(**opts).join()
dt = time.perf_counter() - t0
print(
    f"2pc-10 device: secs={dt:.1f} unique={c.unique_state_count()} "
    f"gen={c.state_count()} rate={c.state_count()/dt:,.0f} tel={c.telemetry()}",
    flush=True,
)
assert c.unique_state_count() == 61_515_776, c.unique_state_count()
print("GOLDEN MATCH", flush=True)
