#!/usr/bin/env bash
# The CI gate, reproducible locally with one command:
#
#   scripts/ci.sh
#
# Sixteen stages, fail-fast:
#   1. ruff over the repo (mechanical lint scope; see ruff.toml) — a hard
#      failure when $CI is set, a loud skip on dev machines without it,
#   2. the speclint dogfood — every bundled model must analyze with zero
#      error-severity findings (`python -m stateright_tpu.analysis`),
#   3. the proglint dogfood — every bundled TensorModel's device programs
#      must pass the deep STR6xx tier (`--program`: transfer/donation/
#      dtype detectors, the committed op-count budgets, the STR606 cost
#      model), and a deliberately perturbed budget file must TRIP the
#      STR604 gate — proving the ratchet actually fails CI,
#   4. a stage-profiler smoke: one tiny device-engine run with
#      `.stage_profile()` must populate the per-stage era breakdown and
#      reconcile with the era wall time within 10%,
#   5. a conformance smoke: the replicated counter runs ~1s on loopback
#      UDP under seeded drop/duplicate/delay faults, records a trace, and
#      the trace must conform against the actor model with ZERO
#      divergences and yield a nonzero linearizable client history,
#   6. a netobs smoke: a ~1s faulted counter run on every available
#      engine with a live NetObs attached — the live fault-kind counters
#      must match the trace's recorded fault lines exactly, the Chrome
#      flow events must balance 1:1 (every `s` start has its `f` finish,
#      one pair per matched delivery), and `GET /deployment` must serve
#      the topology + per-link edges from the recorded trace,
#   7. a serve smoke: the run server admits a 2pc-3 check plus a batch of
#      8 small increment checks over REST, multiplexes the batch into one
#      fused executable, matches the golden state counts, and reports an
#      executable-cache hit on resubmission,
#   8. a durability smoke: a checkpointed 2pc-5 device run is stopped
#      mid-flight, resumed from its crash-safe checkpoint to the exact
#      golden, and a journaled run service is killed with queued jobs and
#      restarted — every job must recover and finish,
#   9. an observability smoke: one submitted job must yield span events
#      over the /events SSE stream, histogram _bucket series in
#      /metrics.prom, and a Chrome-trace export that JSON-parses with
#      matching B/E pairs,
#  10. a perf-gate smoke: `bench.py --smoke` (tiny 2pc-5 device run)
#      seeds a throwaway history, a parity rerun must pass the gate,
#      and a BENCH_PERTURB_SLEEP-degraded rerun must trip it — proving
#      `bench.py --gate` actually fails CI on a real regression,
#  11. a pipelining smoke: a tiny run with speculative era dispatch
#      forced ON (many short eras) must golden-match the serial driver
#      bit-for-bit and report a flight summary with `host_gap_pct`,
#  12. a mega-dispatch smoke: the same workload with the speculative
#      chain at depth 4 AND 4 eras fused per compiled dispatch must
#      golden-match the serial driver bit-for-bit, report strictly
#      fewer dispatches than eras, and the stage profiler must still
#      reconcile its per-stage breakdown with the (fused) era wall
#      time within 10%,
#  13. a memory smoke: the capacity planner predicts a small run's
#      footprint before dispatch, the run's memory ledger must match
#      the live buffers' nbytes EXACTLY and the planner's prediction,
#      and the `memory_bytes{component=...}` series must render in the
#      Prometheus exposition,
#  14. a space smoke: the deterministic bottom-k state sample from a
#      pipelined device run must equal the host oracle's sample
#      EXACTLY, the profile must carry field sketches, and the
#      `space_*` gauges must render in the Prometheus exposition,
#  15. an out-of-core smoke: 2pc-5 under a device byte cap AND a spill
#      host-RAM budget small enough to force the frontier onto the disk
#      tier, with delta checkpoints at a tight cadence — must match the
#      8,832 golden bit-for-bit while having tiered spill to disk (and
#      refilled every row back), fired >= 1 forecast-triggered proactive
#      reshard, written >= 2 delta checkpoint generations, and kept the
#      mean delta save strictly smaller than the mean full save,
#  16. the tier-1 pytest line from ROADMAP.md (host/CPU; the device
#      goldens run under JAX_PLATFORMS=cpu like the test suite does).
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== ruff =="
if command -v ruff >/dev/null 2>&1; then
  ruff check .
elif python -m ruff --version >/dev/null 2>&1; then
  python -m ruff check .
elif [ -n "${CI:-}" ]; then
  # A CI lane without the linter is a misconfigured lane, not a lane
  # that gets to skip linting.
  echo "ERROR: \$CI is set but ruff is not installed" >&2
  exit 1
else
  # Dev machines stay runnable without the linter baked in; skipping is
  # LOUD so the gap is still visible.
  echo "WARNING: ruff not installed; skipping the lint stage" >&2
fi

echo "== speclint dogfood =="
for model in 2pc:4 2pc-host:3 abd:2 abd-ordered:2 binary-clock \
             increment:2 increment-host:2 increment-lock:2 \
             increment-lock-host:2 linear-equation:1,2,20 \
             linearizable-register:2,2 lww-register:2 paxos:2 \
             single-copy:2,2 write-once-register:2; do
  echo "-- $model"
  JAX_PLATFORMS=cpu python -m stateright_tpu.analysis "$model"
done

echo "== proglint dogfood =="
# The deep STR6xx tier over every bundled TensorModel: trace + scan all
# five device programs, gate op counts against the committed budgets
# (analysis/op_budgets.json), and run the STR606 compile + cost model.
for model in 2pc:4 2pc:7 abd:2 abd-ordered:2 increment:2 \
             increment-lock:2 paxos:2 single-copy:2,2; do
  echo "-- $model"
  JAX_PLATFORMS=cpu python -m stateright_tpu.analysis "$model" --program
done

# The ratchet must-fail smoke: shrink one committed budget by one op so
# the measured count EXCEEDS it — the STR604 gate must fail the lint.
proglint_tmp="$(mktemp -d /tmp/_proglint_smoke.XXXXXX)"
JAX_PLATFORMS=cpu python - "$proglint_tmp/budgets.json" <<'PY'
import json
import sys

from stateright_tpu.analysis.program import BUDGETS_PATH
from stateright_tpu.engines.compiled import model_signature
from stateright_tpu.models import TwoPhaseTensor

doc = json.load(open(BUDGETS_PATH))
key = f"tpu_bfs|{model_signature(TwoPhaseTensor(4))}"
doc["entries"][key]["ops"] -= 1
print(f"perturbed {key}: budget now {doc['entries'][key]['ops']} ops")
json.dump(doc, open(sys.argv[1], "w"))
PY
if JAX_PLATFORMS=cpu python -m stateright_tpu.analysis 2pc:4 --program \
   --budgets "$proglint_tmp/budgets.json"; then
  echo "proglint smoke FAILED: op-count growth passed the STR604 gate" >&2
  exit 1
fi
rm -rf "$proglint_tmp"
echo "proglint smoke OK: budgets green, perturbed budget tripped STR604"

echo "== stage-profiler smoke =="
JAX_PLATFORMS=cpu python - <<'PY'
from stateright_tpu.models import TwoPhaseTensor
from stateright_tpu.tensor import TensorModelAdapter

c = (
    TensorModelAdapter(TwoPhaseTensor(3))
    .checker()
    .stage_profile(iters=2)
    .spawn_tpu_bfs(chunk_size=64, queue_capacity=1 << 10, table_capacity=1 << 10)
    .join()
)
tel = c.telemetry()
assert "stage_profile_error" not in tel, tel.get("stage_profile_error")
stages = {k: v for k, v in tel["phase_ms"].items() if k.startswith("stage_")}
assert stages, "stage_profile() produced no stage_* phases"
era = tel["phase_ms"]["device_era"]
assert era > 0 and abs(sum(stages.values()) - era) <= 0.1 * era, (stages, era)
print(f"stage smoke OK: {len(stages)} stages attribute {era:.0f} ms of era time")
PY

echo "== conformance smoke =="
JAX_PLATFORMS=cpu python - <<'PY'
from examples.increment import conform_counter_trace, record_counter_demo

path = "/tmp/_conform_smoke.jsonl"
record_counter_demo(path, duration=1.0, seed=7, base_port=46100, client_count=2)
report, tester = conform_counter_trace(path, client_count=2)
print(report.format())
assert not report.divergences, report.format()
assert tester.serialized_history() is not None and len(tester) > 0, (
    "expected a nonzero linearizable client history"
)
print(f"conformance smoke OK: {report.steps} steps, {len(tester)} history ops")
PY

echo "== netobs smoke =="
JAX_PLATFORMS=cpu python - <<'PY'
import collections
import json
import os
import tempfile
import urllib.request

from examples.increment import counter_model, record_counter_demo
from stateright_tpu.conformance import load_trace
from stateright_tpu.explorer.server import serve
from stateright_tpu.native import runtime as native_runtime
from stateright_tpu.obs.netobs import NetObs, assign_lamport, export_chrome_trace

tmp = tempfile.mkdtemp(prefix="_netobs_smoke.")
engines = ["python"] + (["native"] if native_runtime.is_available() else [])
for i, engine in enumerate(engines):
    path = os.path.join(tmp, f"{engine}.jsonl")
    nob = NetObs()
    # ~1s faulted counter run, live-instrumented on both engines.
    record_counter_demo(
        path, duration=1.0, seed=7, base_port=46600 + 10 * i,
        client_count=2, engine=engine, netobs=nob,
    )
    meta, events = load_trace(path)
    assert meta["v"] == 2 and meta["faults"]["seed"] == 7, meta.get("faults")

    # Live fault counters must match the trace's recorded fault lines.
    recorded = collections.Counter(
        ev["fault"] for ev in events if ev["kind"] == "fault"
    )
    live = nob.snapshot().get("fault_injected", {})
    assert dict(recorded) == live, (engine, dict(recorded), live)
    assert recorded, "seeded plan injected no faults"

    # Chrome flow events must balance: every s has its f, 1:1 by id.
    out = os.path.join(tmp, f"{engine}.chrome.json")
    pairs = export_chrome_trace((meta, events), out)
    records = json.load(open(out))
    starts = {r["id"] for r in records if r.get("ph") == "s"}
    finishes = {r["id"] for r in records if r.get("ph") == "f"}
    assert starts == finishes and len(starts) == pairs, (engine, pairs)
    matched = sum(
        1 for ev in assign_lamport(events)
        if ev["kind"] == "deliver" and "sent_by" in ev
    )
    assert pairs == matched, (engine, pairs, matched)
    print(f"  {engine}: {sum(recorded.values())} faults, {pairs} flow pairs")

# GET /deployment must serve topology + edges from the recorded trace.
server = serve(
    counter_model(2).checker(), "127.0.0.1:0", block=False,
    trace=os.path.join(tmp, "python.jsonl"),
)
try:
    body = json.loads(
        urllib.request.urlopen(server.url.rstrip("/") + "/deployment").read()
    )
    assert body["actors"] and body["edges"] and body["tail"], body.keys()
finally:
    server.shutdown()
print(f"netobs smoke OK: {len(engines)} engines, /deployment serves")
PY

echo "== serve smoke =="
JAX_PLATFORMS=cpu python - <<'PY'
import json
import time
import urllib.request

from stateright_tpu import TensorModelAdapter
from stateright_tpu.models import TwoPhaseTensor
from stateright_tpu.serve import RunService, ServeServer

# Host oracle for the 2pc-3 golden (288 uniques) before anything serves.
oracle = TensorModelAdapter(TwoPhaseTensor(3)).checker().spawn_bfs().join()
assert oracle.unique_state_count() == 288, oracle.unique_state_count()

service = RunService(workers=1, lanes=8, lint_samples=32)
server = ServeServer(service, "127.0.0.1:0").serve_in_background()
base = server.url.rstrip("/")


def req(method, path, body=None):
    data = json.dumps(body).encode() if body is not None else None
    r = urllib.request.Request(base + path, data=data, method=method)
    with urllib.request.urlopen(r) as resp:
        return json.loads(resp.read())


req("POST", "/scheduler/pause")
inc_ids = [
    req("POST", "/submit", {"spec": "increment:2"})["job_id"] for _ in range(8)
]
tpc_id = req("POST", "/submit", {"spec": "2pc:3"})["job_id"]
req("POST", "/scheduler/resume")

deadline = time.time() + 600
while time.time() < deadline:
    views = req("GET", "/jobs")["jobs"]
    if all(v["status"] not in ("queued", "running") for v in views):
        break
    time.sleep(0.2)
for v in req("GET", "/jobs")["jobs"]:
    assert v["status"] == "done", v

for job_id in inc_ids:
    result = req("GET", f"/jobs/{job_id}/result")["result"]
    assert result["unique_state_count"] == 13, result
    assert result["engine"] == "multiplex", result
tpc = req("GET", f"/jobs/{tpc_id}/result")["result"]
assert tpc["unique_state_count"] == oracle.unique_state_count(), tpc

# Same-shape resubmission must hit the executable cache.
before = req("GET", "/stats")["cache"]
job_id = req("POST", "/submit", {"spec": "increment:2"})["job_id"]
while req("GET", f"/jobs/{job_id}")["status"] in ("queued", "running"):
    time.sleep(0.2)
after = req("GET", "/stats")["cache"]
assert after["hits"] == before["hits"] + 1, (before, after)
assert after["misses"] == before["misses"], (before, after)
server.shutdown()
print(
    f"serve smoke OK: 8 multiplexed + 2pc-3 golden-matched, "
    f"cache {after['hits']} hits / {after['misses']} misses"
)
PY

echo "== durability smoke =="
JAX_PLATFORMS=cpu python - <<'PY'
import os
import tempfile
import time

from stateright_tpu import TensorModelAdapter
from stateright_tpu.models import TwoPhaseTensor
from stateright_tpu.serve import RunService

tmp = tempfile.mkdtemp(prefix="_dura_smoke.")
opts = dict(chunk_size=64, queue_capacity=1 << 12, table_capacity=1 << 11)

# Crash-safe checkpoints: stop a 2pc-5 run mid-flight, then resume the
# checkpoint to the exact golden (8,832 uniques).
ckpt = os.path.join(tmp, "2pc5.ckpt.npz")
part = (
    TensorModelAdapter(TwoPhaseTensor(5))
    .checker()
    .target_state_count(3_000)
    .spawn_tpu_bfs(checkpoint_path=ckpt, **opts)
    .join()
)
assert 0 < part.unique_state_count() < 8832, part.unique_state_count()
assert os.path.exists(ckpt)
resumed = (
    TensorModelAdapter(TwoPhaseTensor(5))
    .checker()
    .spawn_tpu_bfs(resume_from=ckpt, **opts)
    .join()
)
assert resumed.unique_state_count() == 8832, resumed.unique_state_count()

# Serve journal recovery: kill a service with queued jobs, restart on the
# same journal, and every job must finish with its result served.
dura = dict(
    journal_path=os.path.join(tmp, "jobs.jsonl"),
    results_dir=os.path.join(tmp, "results"),
)
svc = RunService(workers=1, lint_samples=32, **dura)
svc.pause()
ids = [svc.submit({"spec": "increment:2"})[1]["job_id"] for _ in range(3)]
svc.shutdown()  # "crash" with everything still queued

svc = RunService(workers=1, lint_samples=32, **dura)
assert svc.telemetry().get("journal_recovered_queued") == 3
deadline = time.time() + 600
while time.time() < deadline:
    if all(svc.job(i).status not in ("queued", "running") for i in ids):
        break
    time.sleep(0.2)
for i in ids:
    job = svc.job(i)
    assert job.status == "done", (i, job.status, job.error)
    assert job.result["unique_state_count"] == 13, job.result
svc.shutdown()
print("durability smoke OK: checkpoint resumed to 8832; 3 jobs recovered")
PY

echo "== observability smoke =="
JAX_PLATFORMS=cpu python - <<'PY'
import json
import os
import tempfile
import time
import urllib.request

from stateright_tpu.serve import RunService, ServeServer

service = RunService(workers=1, lanes=8, lint_samples=32)
server = ServeServer(service, "127.0.0.1:0").serve_in_background()
base = server.url.rstrip("/")


def req(method, path, body=None):
    data = json.dumps(body).encode() if body is not None else None
    r = urllib.request.Request(base + path, data=data, method=method)
    with urllib.request.urlopen(r) as resp:
        return json.loads(resp.read())


body = req("POST", "/submit", {"spec": "increment:2"})
job_id, trace_id = body["job_id"], body["trace_id"]
while req("GET", f"/jobs/{job_id}")["status"] in ("queued", "running"):
    time.sleep(0.2)
assert req("GET", f"/jobs/{job_id}")["status"] == "done"

# The /events SSE stream must yield span events (replay seeds the
# already-finished job's ledger; limit+duration bound the read).
raw = urllib.request.urlopen(
    f"{base}/events?replay=50&limit=5&duration=5"
).read().decode()
span_events = [
    json.loads(blk.split("data: ", 1)[1])
    for blk in raw.split("\n\n")
    if blk.startswith("event: span")
]
assert span_events, raw[:400]
names = {s["name"] for s in span_events}
assert names & {"job", "admission", "queue_wait", "execute"}, names

# The job's full ledger hangs off /jobs/{id}/trace in submit order.
ledger = req("GET", f"/jobs/{job_id}/trace")
assert ledger["trace_id"] == trace_id
lnames = [s["name"] for s in ledger["spans"]]
for expected in ("admission", "queue_wait", "execute", "job"):
    assert expected in lnames, lnames

# Prometheus exposition must carry the latency histogram series.
prom = urllib.request.urlopen(f"{base}/metrics.prom").read().decode()
assert "_bucket{le=" in prom, prom[:400]
assert "submit_to_result_secs_count" in prom, prom[:400]

# The exported Chrome trace must JSON-parse with matching B/E pairs.
from stateright_tpu.obs.spans import spans_to_chrome

out = os.path.join(tempfile.mkdtemp(prefix="_obs_smoke."), "trace.json")
service.spans.export_chrome(out)
with open(out) as fh:
    events = json.load(fh)
begins = sum(1 for e in events if e.get("ph") == "B")
ends = sum(1 for e in events if e.get("ph") == "E")
assert begins and begins == ends, (begins, ends)
assert begins == len(spans_to_chrome(service.spans.spans())) // 2

server.shutdown()
print(
    f"observability smoke OK: {len(span_events)} SSE spans, "
    f"{len(ledger['spans'])}-span job ledger, {begins} B/E pairs"
)
PY

echo "== perf-gate smoke =="
gate_tmp="$(mktemp -d /tmp/_gate_smoke.XXXXXX)"
hist="$gate_tmp/history.jsonl"
# Seed run: empty history passes the gate and writes the baseline row.
JAX_PLATFORMS=cpu python bench.py --smoke --gate "$hist" --history "$hist"
# Parity rerun of the same workload must stay within budget.
JAX_PLATFORMS=cpu python bench.py --smoke --gate "$hist" --history "$hist"
# A sleep injected INSIDE the timing window must trip the gate.
if JAX_PLATFORMS=cpu BENCH_PERTURB_SLEEP=2.5 \
   python bench.py --smoke --gate "$hist"; then
  echo "perf-gate smoke FAILED: degraded run passed the gate" >&2
  exit 1
fi
rm -rf "$gate_tmp"
echo "perf-gate smoke OK: parity passed, degraded run tripped the gate"

echo "== pipelining smoke =="
JAX_PLATFORMS=cpu python - <<'PY'
from stateright_tpu import TensorModelAdapter
from stateright_tpu.models import TwoPhaseTensor

# Many short eras (sync_steps=4) so speculative chains actually engage.
opts = dict(
    chunk_size=64, queue_capacity=1 << 12, table_capacity=1 << 11,
    sync_steps=4,
)


def run(pipelined):
    c = (
        TensorModelAdapter(TwoPhaseTensor(5))
        .checker()
        .coverage()
        .pipeline(pipelined)
        .spawn_tpu_bfs(**opts)
        .join()
    )
    cov = c.coverage()
    return c, (
        c.unique_state_count(), c.state_count(), c.max_depth(),
        dict(c._discovery_fps), cov["actions"], cov["depths"],
    )


piped, fp_on = run(True)
_serial, fp_off = run(False)
assert fp_on[0] == 8832, fp_on[0]
assert fp_on == fp_off, "pipelined run diverged from the serial driver"
tel = piped.telemetry()
assert tel.get("spec_dispatch", 0) >= 1, "pipelining never speculated"
fsum = tel["flight"]
assert "host_gap_pct" in fsum, fsum
print(
    f"pipelining smoke OK: 8832 uniques golden-match serial, "
    f"{tel['spec_dispatch']} speculative dispatches "
    f"({tel.get('spec_wasted', 0)} wasted), "
    f"host_gap_pct={fsum['host_gap_pct']}"
)
PY

echo "== mega-dispatch smoke =="
JAX_PLATFORMS=cpu python - <<'PY'
from stateright_tpu import TensorModelAdapter
from stateright_tpu.models import TwoPhaseTensor

# Many short eras (sync_steps=4) so the K-deep chain fills and the
# fused inner loop actually runs several eras per compiled dispatch.
opts = dict(
    chunk_size=64, queue_capacity=1 << 12, table_capacity=1 << 11,
    sync_steps=4,
)


def fingerprint(c):
    cov = c.coverage()
    return (
        c.unique_state_count(), c.state_count(), c.max_depth(),
        dict(c._discovery_fps), cov["actions"], cov["depths"],
        tuple(c._sampler.fingerprints()),
    )


def run(builder_fn):
    b = TensorModelAdapter(TwoPhaseTensor(5)).checker().coverage().sample(k=32)
    return builder_fn(b).spawn_tpu_bfs(**opts).join()


serial = run(lambda b: b.pipeline(False))
mega = run(lambda b: b.pipeline(depth=4, fuse=4))
assert fingerprint(serial) == fingerprint(mega), (
    "mega-dispatch run diverged from the serial driver"
)
assert mega.unique_state_count() == 8832, mega.unique_state_count()
tel = mega.telemetry()
eras, dispatches = tel["eras"], tel["dispatches"]
assert dispatches < eras, (dispatches, eras)
assert tel.get("fused_eras_per_dispatch", 0.0) > 1.0, tel
assert tel.get("spec_chain_depth", 0) >= 1, tel

# The stage profiler must still reconcile against the FUSED era body:
# stage micro-benches attribute >=90% of the measured era wall time.
prof = (
    TensorModelAdapter(TwoPhaseTensor(5))
    .checker()
    .stage_profile(iters=2)
    .pipeline(depth=4, fuse=4)
    .spawn_tpu_bfs(**opts)
    .join()
)
ptel = prof.telemetry()
assert "stage_profile_error" not in ptel, ptel.get("stage_profile_error")
stages = {k: v for k, v in ptel["phase_ms"].items() if k.startswith("stage_")}
era = ptel["phase_ms"]["device_era"]
assert era > 0 and abs(sum(stages.values()) - era) <= 0.1 * era, (stages, era)
print(
    f"mega-dispatch smoke OK: 8832 uniques golden-match serial in "
    f"{dispatches} dispatches over {eras} eras "
    f"(chain depth {tel['spec_chain_depth']}, "
    f"{tel['fused_eras_per_dispatch']} eras/dispatch); "
    f"{len(stages)} stages reconcile {era:.0f} ms of fused era time"
)
PY

echo "== memory smoke =="
JAX_PLATFORMS=cpu python - <<'PY'
from stateright_tpu import TensorModelAdapter
from stateright_tpu.models import TwoPhaseTensor
from stateright_tpu.obs.memory import plan
from stateright_tpu.obs.metrics import MEMORY_SERIES_LABELS, render_prometheus

# Plan BEFORE dispatch at a fixed no-growth geometry...
geometry = dict(chunk=256, queue_capacity=1 << 12, table_capacity=1 << 15)
model = TensorModelAdapter(TwoPhaseTensor(3))
p = plan(model, engine="tpu_bfs", **geometry)
assert p["total_bytes"] > 0, p

# ...then run at the same geometry: the ledger must equal BOTH the live
# buffers' nbytes and the planner's prediction, exactly.
c = (
    model.checker()
    .spawn_tpu_bfs(
        chunk_size=geometry["chunk"],
        queue_capacity=geometry["queue_capacity"],
        table_capacity=geometry["table_capacity"],
    )
    .join()
)
assert c.unique_state_count() == 288, c.unique_state_count()
snap = c.telemetry()["memory"]
assert snap["total_bytes"] == c._memory.ledger.live_nbytes(), snap
assert snap["total_bytes"] == p["total_bytes"], (snap["total_bytes"], p)

# The per-component residency must land in the Prometheus exposition.
prom = render_prometheus(c.telemetry(), labels=MEMORY_SERIES_LABELS)
assert 'memory_bytes{component="visited_table"}' in prom, prom[:400]
print(
    f"memory smoke OK: plan == ledger == nbytes == {p['total_bytes']} B "
    f"across {len(snap['components'])} components"
)
PY

echo "== space smoke =="
JAX_PLATFORMS=cpu python - <<'PY'
from stateright_tpu import TensorModelAdapter
from stateright_tpu.models import IncrementTensor, TwoPhaseTensor
from stateright_tpu.obs.metrics import render_prometheus

# The sample is a pure function of the explored set: the pipelined
# device run must produce the host oracle's sample bit-for-bit.
host = (
    TensorModelAdapter(TwoPhaseTensor(4)).checker().sample(k=64)
    .spawn_bfs().join()
)
dev = (
    TensorModelAdapter(TwoPhaseTensor(4)).checker().sample(k=64)
    .spawn_tpu_bfs(chunk_size=64, queue_capacity=1 << 12,
                   table_capacity=1 << 11)
    .join()
)
assert dev.unique_state_count() == 1568, dev.unique_state_count()
hfps, dfps = host._sampler.fingerprints(), dev._sampler.fingerprints()
assert dfps == hfps, "device sample diverged from the host oracle"
assert not dev._sampler.degraded

profile = dev.space_profile()
assert profile["fields"], profile.keys()
assert profile["unresolved"] == 0, profile["unresolved"]
assert profile["depths"] and profile["actions"]

# Below k the sample IS the space: KMV estimate exact on increment.
tiny = (
    TensorModelAdapter(IncrementTensor(2)).checker().sample(k=64)
    .spawn_bfs().join()
)
assert tiny.telemetry()["space"]["est_states"] == 13

# The flat gauges must land in the Prometheus exposition.
prom = render_prometheus(dev.telemetry())
assert "space_samples 64" in prom, prom[:400]
assert "space_est_states" in prom, prom[:400]
print(
    f"space smoke OK: 64-sample parity on 2pc-4, "
    f"est_states={profile['est_states']}, "
    f"{len(profile['fields'])} field sketches"
)
PY

echo "== out-of-core smoke =="
_OC_TMP=$(mktemp -d)
JAX_PLATFORMS=cpu STPU_OC_TMP="$_OC_TMP" python - <<'PY'
import os

# Uncapped oracle FIRST — the caps are read from the environment at
# engine construction, so the reference spawns before they exist.
from stateright_tpu import TensorModelAdapter
from stateright_tpu.models import TwoPhaseTensor


def fingerprint(c):
    return (c.unique_state_count(), c.state_count(), c.max_depth(),
            dict(c._discovery_fps))


# chunk 32 / queue 1<<10: small enough that the 2pc-5 frontier overflows
# the device queue AND the 8 KiB host budget, pushing spill blocks onto
# the npz disk tier; sync_steps 4 gives the forecaster many short eras.
opts = dict(chunk_size=32, queue_capacity=1 << 10, table_capacity=1 << 8,
            sync_steps=4)
ref = TensorModelAdapter(TwoPhaseTensor(5)).checker().spawn_tpu_bfs(**opts).join()
assert ref.unique_state_count() == 8832, ref.unique_state_count()

os.environ["STPU_DEVICE_MEMORY_BYTES"] = "300000"   # forces exhaustion forecast
os.environ["STPU_SPILL_HOST_BUDGET_BYTES"] = "8192"  # forces the disk tier
ckpt = os.path.join(os.environ["STPU_OC_TMP"], "oc.ckpt.npz")
capped = (
    TensorModelAdapter(TwoPhaseTensor(5)).checker()
    .spawn_tpu_bfs(checkpoint_path=ckpt, checkpoint_every=1e-4, **opts)
    .join()
)
tel = capped.telemetry()
assert fingerprint(capped) == fingerprint(ref), "capped run diverged"
assert tel.get("spill_tier_rows", 0) > 0, "no frontier rows hit the disk tier"
assert tel.get("spill_tier_refill_rows") == tel.get("spill_tier_rows"), (
    "disk tier not fully refilled", tel.get("spill_tier_rows"),
    tel.get("spill_tier_refill_rows"))
assert tel.get("reshard_proactive", 0) >= 1, "no proactive reshard fired"
assert tel.get("checkpoint_delta_saves", 0) >= 2, tel.get("checkpoint_delta_saves")
delta_per = tel["checkpoint_delta_bytes"] / tel["checkpoint_delta_saves"]
full_per = tel["checkpoint_bytes"] / tel["checkpoint_saves"]
assert delta_per < full_per, (delta_per, full_per)
print(
    f"out-of-core smoke OK: 8832 golden under 300 KB cap, "
    f"{tel['spill_tier_rows']} rows tiered to disk and refilled, "
    f"{tel['reshard_proactive']} proactive reshards, "
    f"{tel['checkpoint_delta_saves']} delta saves "
    f"({delta_per / 1024:.1f} KiB/delta vs {full_per / 1024:.1f} KiB/full)"
)
PY
rm -rf "$_OC_TMP"

echo "== tier-1 tests =="
set -o pipefail
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
  --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly \
  2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)
exit $rc
