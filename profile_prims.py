"""Scratch: primitive-cost calibration on this TPU (round 5)."""
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

u = jnp.uint32
K = 30


def mix(x, salt):
    x = (x ^ u(salt)) * u(0x9E3779B9)
    x = (x ^ (x >> u(16))) * u(0x85EBCA6B)
    return x ^ (x >> u(13))


def timeit(name, fn, *args):
    f = jax.jit(fn)
    out = f(*args)
    np.asarray(out)
    t0 = time.perf_counter()
    out = f(*args)
    s = np.asarray(out)
    dt = time.perf_counter() - t0
    print(f"{name:44s} {dt/K*1e6:9.1f} us/iter  sum={s}", flush=True)


def loop(body):
    def run():
        def step(i, acc):
            return acc ^ body(i)
        return lax.fori_loop(u(0), u(K), step, u(0))
    return run


TCAP = 1 << 22
tab = mix(jnp.arange(TCAP, dtype=u), 99)

for W in (28672, 65536, 227328):
    iota = jnp.arange(W, dtype=u)

    def f_rand_gather(i, iota=iota, W=W):
        idx = mix(iota + i * u(W), 3) & u(TCAP - 1)
        return tab[idx].sum(dtype=u)
    timeit(f"random gather W={W}", loop(f_rand_gather))

    def f_2rand_gather(i, iota=iota, W=W):
        idx = mix(iota + i * u(W), 3) & u(TCAP - 1)
        return tab[idx].sum(dtype=u) + tab[idx + u(1)].sum(dtype=u)
    timeit(f"2x random gather same idx W={W}", loop(f_2rand_gather))

    def f_sorted_gather(i, iota=iota, W=W):
        # strictly increasing indices (compaction-style coalesced access)
        base = jnp.cumsum(mix(iota, 5) & u(15)) + i
        idx = base & u(TCAP - 1)
        return tab[idx].sum(dtype=u)
    timeit(f"sorted gather W={W}", loop(f_sorted_gather))

    def f_scatter(i, iota=iota, W=W):
        idx = mix(iota + i * u(W), 7) & u(TCAP - 1)
        out = jnp.zeros(TCAP, dtype=u).at[idx].set(iota, mode="drop")
        return out[0] + out[TCAP - 1]
    timeit(f"random scatter(+memset) W={W}", loop(f_scatter))

    def f_elem(i, iota=iota, W=W):
        return mix(iota + i, 11).sum(dtype=u)
    timeit(f"elementwise mix+sum W={W}", loop(f_elem))

    def f_cumsum(i, iota=iota, W=W):
        return jnp.cumsum(mix(iota + i, 13) & u(1))[W - 1]
    timeit(f"cumsum W={W}", loop(f_cumsum))

# op-count overhead: 64 small [6144] ops that can't fuse into one (chained
# shifts with gathers of scalar? use separate adds on distinct arrays)
C = 6144
lanes = [mix(jnp.arange(C, dtype=u), 20 + s) for s in range(64)]
def f_many_ops(i):
    acc = u(0)
    for s in range(64):
        acc = acc + (lanes[s] + i).sum(dtype=u)
    return acc
timeit("64 separate [6144] add+sum ops", loop(f_many_ops))

def f_one_op(i):
    big = jnp.concatenate(lanes)
    return (big + i).sum(dtype=u)
timeit("1 fused [393216] add+sum op", loop(f_one_op))

# dynamic_slice pop vs gather pop at ring widths
QCAP = 1 << 20
ring = mix(jnp.arange(QCAP + C, dtype=u), 31)
def f_dslice(i):
    head = (i * u(977)) & u(QCAP - 1)
    return lax.dynamic_slice(ring, (head,), (C,)).sum(dtype=u)
timeit("dynamic_slice pop [6144]", loop(f_dslice))

ring2 = ring[:QCAP]
def f_gpop(i):
    head = (i * u(977)) & u(QCAP - 1)
    idx = (head + jnp.arange(C, dtype=u)) & u(QCAP - 1)
    return ring2[idx].sum(dtype=u)
timeit("gather pop [6144]", loop(f_gpop))

# lax.cond with expensive branch, predicate usually false
big_idx = mix(jnp.arange(65536, dtype=u), 41) & u(TCAP - 1)
def f_cond(i):
    pred = (i & u(0xFFFF)) == u(0xFFFF)  # never true for i<K
    def expensive(_):
        return tab[big_idx + i].sum(dtype=u)
    def cheap(_):
        return u(0)
    return lax.cond(pred, expensive, cheap, None)
timeit("cond(mostly-false) w/ 65k-gather branch", loop(f_cond))

def f_cond_true(i):
    pred = (i & u(0)) == u(0)  # always true
    def expensive(_):
        return tab[big_idx + i].sum(dtype=u)
    def cheap(_):
        return u(0)
    return lax.cond(pred, expensive, cheap, None)
timeit("cond(always-true) w/ 65k-gather branch", loop(f_cond_true))
