"""Scratch: isolate the big-carry while_loop penalty (round 5)."""
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

u = jnp.uint32


def run_case(name, mk_fn, mk_args, K):
    f = jax.jit(mk_fn(K), donate_argnums=tuple(range(len(mk_args()))))
    out = f(*mk_args())
    np.asarray(jax.tree.leaves(out)[-1])
    args = mk_args()
    t0 = time.perf_counter()
    out = f(*args)
    s = np.asarray(jax.tree.leaves(out)[-1])
    dt = time.perf_counter() - t0
    print(f"{name:58s} K={K:4d}  total={dt*1000:9.1f} ms  ({dt/K*1000:7.2f} ms/iter)", flush=True)
    return dt


# case A: carry from donated jit arguments
def mk_A(K):
    def run(l0, l1, l2, l3, i0):
        def cond(c):
            return c[-1] < u(K)
        def body(c):
            ls, i = c[:-1], c[-1]
            ls = tuple(l.at[0].add(u(1)) for l in ls)
            return ls + (i + u(1),)
        return lax.while_loop(cond, body, (l0, l1, l2, l3, i0))
    return run

mkargs4 = lambda: tuple(np.zeros(1 << 22, dtype=np.uint32) for _ in range(4)) + (np.uint32(0),)
for K in (1, 10, 30, 100):
    run_case("A: while 4x[4M] from donated args, touch0", mk_A, mkargs4, K)

# case B: carry created INSIDE jit (like the seeder does)
def mk_B(K):
    def run(i0):
        ls = tuple(jnp.zeros(1 << 22, dtype=u) + i0 * u(0) for _ in range(4))
        def cond(c):
            return c[-1] < u(K)
        def body(c):
            ls, i = c[:-1], c[-1]
            ls = tuple(l.at[0].add(u(1)) for l in ls)
            return ls + (i + u(1),)
        out = lax.while_loop(cond, body, ls + (i0,))
        return out[-1] + out[0][0]
    return run

for K in (1, 30, 100):
    run_case("B: while 4x[4M] created in-jit, touch0", mk_B, lambda: (np.uint32(0),), K)

# case C: nested — outer fori(K) whose body runs inner fori(2) over the
# same big carry (insert-like shape)
def mk_C(K):
    def run(l0, l1, l2, l3, i0):
        def obody(i, ls):
            def ibody(j, ls2):
                return tuple(l.at[j].add(u(1)) for l in ls2)
            return lax.fori_loop(0, 2, ibody, ls)
        out = lax.fori_loop(0, K, obody, (l0, l1, l2, l3))
        return out
    return run

for K in (30,):
    run_case("C: fori K x inner-fori2, 4x[4M] args, touch", mk_C, mkargs4, K)

# case D: 2-D carry layout
def mk_D(K):
    def run(l0, l1, l2, l3, i0):
        def cond(c):
            return c[-1] < u(K)
        def body(c):
            ls, i = c[:-1], c[-1]
            ls = tuple(l.at[0, 0].add(u(1)) for l in ls)
            return ls + (i + u(1),)
        return lax.while_loop(cond, body, (l0, l1, l2, l3, i0))
    return run

mkargs2d = lambda: tuple(np.zeros((1 << 11, 1 << 11), dtype=np.uint32) for _ in range(4)) + (np.uint32(0),)
run_case("D: while 4x[2048,2048] 2-D args, touch0", mk_D, mkargs2d, 30)

# case E: same as A but fori instead of while
def mk_E(K):
    def run(l0, l1, l2, l3, i0):
        def body(i, ls):
            return tuple(l.at[0].add(u(1)) for l in ls)
        return lax.fori_loop(0, K, body, (l0, l1, l2, l3))
    return run

run_case("E: fori 4x[4M] from donated args, touch0", mk_E, mkargs4, 30)

# case F: while with REAL scatter work per iter (not just elem 0)
def mk_F(K):
    iota = jnp.arange(1 << 15, dtype=u)
    def run(l0, l1, l2, l3, i0):
        def cond(c):
            return c[-1] < u(K)
        def body(c):
            ls, i = c[:-1], c[-1]
            idx = ((iota + i) * u(0x9E3779B9)) & u((1 << 22) - 1)
            ls = tuple(l.at[idx].set(iota, mode="drop") for l in ls)
            return ls + (i + u(1),)
        return lax.while_loop(cond, body, (l0, l1, l2, l3, i0))
    return run

run_case("F: while 4x[4M] args, 32k-scatter each lane", mk_F, mkargs4, 30)
