"""Scratch: duplication-delta profiling of the new step pipeline (round 5).

Doubling an op inside the real era loop keeps semantics identical (both
calls are applied, the second is a no-op state-wise) while the wall-clock
delta vs baseline reveals the op's true in-situ cost. CSE can't merge the
pairs because the second call's inputs include the first call's output.
"""
import sys
import time

import numpy as np

import stateright_tpu.engines.tpu_bfs as tb
import stateright_tpu.ops.frontier as fr
import stateright_tpu.ops.visited_set as vs
from stateright_tpu import TensorModelAdapter
from stateright_tpu.models import TwoPhaseTensor

MODE = sys.argv[1]

orig_insert = vs.insert
orig_scatter = fr.ring_scatter
orig_compact = vs._compact_ids

if MODE == "full":
    pass
elif MODE == "double_insert":
    def ins2(table, h1, h2, p1, p2, active, rcap=None, primary_rounds=vs.PRIMARY_ROUNDS):
        table, is_new, unres, ovf = orig_insert(table, h1, h2, p1, p2, active,
                                                rcap=rcap, primary_rounds=primary_rounds)
        table, _n2, _u2, _o2 = orig_insert(table, h1, h2, p1, p2, active,
                                           rcap=rcap, primary_rounds=primary_rounds)
        return table, is_new, unres, ovf
    vs.insert = ins2
elif MODE == "double_scatter":
    def sc2(lanes, tail, cand_lanes, valid):
        lanes = orig_scatter(lanes, tail, cand_lanes, valid)
        lanes = orig_scatter(lanes, tail, cand_lanes, valid)
        return lanes
    fr.ring_scatter = sc2
elif MODE == "double_compact":
    def cp2(mask, cap):
        ids, valid, n = orig_compact(mask, cap)
        # Perturb the second call's input through the first's output so CSE
        # cannot merge them; mask2 == mask always (ids<=n... use a bit that
        # is always false: valid has True bits; & with mask keeps mask).
        import jax.numpy as jnp
        m2 = mask ^ (jnp.zeros_like(mask) & (ids[0] > 0))
        ids2, valid2, n2 = orig_compact(m2, cap)
        return ids2, valid2, n2
    vs._compact_ids = cp2
elif MODE == "primary1":
    vs.PRIMARY_ROUNDS = 1
elif MODE == "primary3":
    vs.PRIMARY_ROUNDS = 3
elif MODE == "tail1x6":
    vs.TAIL_STAGES = 1
    vs.TAIL_ROUNDS = 6
else:
    raise SystemExit(f"unknown mode {MODE}")

tm = TwoPhaseTensor(7)
opts = dict(chunk_size=6144, queue_capacity=1 << 20, table_capacity=1 << 22)

def run():
    return TensorModelAdapter(tm).checker().spawn_tpu_bfs(**opts).join()

c = run()
for _ in range(3):
    t0 = time.perf_counter()
    c = run()
    dt = time.perf_counter() - t0
    tel = c.telemetry()
    print(
        f"[{MODE}] secs={dt:.3f} steps={tel['steps']} ms/step={dt/max(1,tel['steps'])*1000:.1f} "
        f"unique={c.unique_state_count()}",
        flush=True,
    )
