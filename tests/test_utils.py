"""Utility tests. Reference: src/util/vector_clock.rs:109-275,
src/util/densenatmap.rs tests, src/checker/rewrite_plan.rs:126-206."""

import pytest

from stateright_tpu.fingerprint import fingerprint
from stateright_tpu.symmetry import RewritePlan
from stateright_tpu.utils import DenseNatMap, VectorClock


# -- VectorClock -------------------------------------------------------------

def test_vector_clock_display():
    assert str(VectorClock([1, 2, 3, 4])) == "<1, 2, 3, 4, ...>"
    assert str(VectorClock()) == "<...>"


def test_vector_clock_eq_ignores_trailing_zeros():
    assert VectorClock([1, 2]) == VectorClock([1, 2, 0, 0])
    assert VectorClock() == VectorClock([0, 0])
    assert VectorClock([1, 2]) != VectorClock([1, 2, 3])
    assert hash(VectorClock([1, 2])) == hash(VectorClock([1, 2, 0]))
    assert fingerprint(VectorClock([1, 2])) == fingerprint(VectorClock([1, 2, 0]))


def test_vector_clock_merge_max():
    a, b = VectorClock([1, 5, 0]), VectorClock([3, 2])
    assert VectorClock.merge_max(a, b) == VectorClock([3, 5, 0])


def test_vector_clock_incremented_grows():
    c = VectorClock().incremented(2)
    assert c == VectorClock([0, 0, 1])
    assert c.incremented(0) == VectorClock([1, 0, 1])


def test_vector_clock_partial_cmp():
    assert VectorClock([1, 2]).partial_cmp(VectorClock([1, 2, 0])) == 0
    assert VectorClock([1, 2]).partial_cmp(VectorClock([1, 3])) == -1
    assert VectorClock([1, 3]).partial_cmp(VectorClock([1, 2])) == 1
    # Concurrent clocks are incomparable.
    assert VectorClock([1, 2, 4]).partial_cmp(VectorClock([1, 3, 0])) is None
    assert VectorClock([0, 1]) < VectorClock([1, 1])
    assert not VectorClock([0, 1]) < VectorClock([1, 0])


# -- DenseNatMap -------------------------------------------------------------

def test_densenatmap_insert_in_order():
    m = DenseNatMap()
    m.insert(0, "first")
    m.insert(1, "second")
    assert m[0] == "first" and m[1] == "second"
    assert len(m) == 2
    with pytest.raises(ValueError):
        m.insert(5, "gap")


def test_densenatmap_from_pairs_any_order():
    m = DenseNatMap.from_pairs([(1, "second"), (0, "first")])
    assert m.values() == ["first", "second"]
    with pytest.raises(ValueError):
        DenseNatMap.from_pairs([(0, "a"), (2, "b")])
    with pytest.raises(ValueError):
        DenseNatMap.from_pairs([(0, "a"), (0, "b")])


def test_densenatmap_eq_and_fingerprint():
    a = DenseNatMap.from_pairs([(0, 10), (1, 20)])
    b = DenseNatMap([10, 20])
    assert a == b
    assert fingerprint(a) == fingerprint(b)


# -- RewritePlan -------------------------------------------------------------

class Pid(int):
    """A dedicated id type, standing in for actor Id."""


def test_rewrite_plan_from_values_to_sort():
    # The rewrite_plan.rs:87-99 worked example: values [B, C, A] sort to
    # [A, B, C], so old ids 0,1,2 get new ids 1,2,0.
    plan = RewritePlan.from_values_to_sort(Pid, ["B", "C", "A"])
    assert plan.mapping == [1, 2, 0]
    assert plan.rewrite(Pid(0)) == Pid(1)
    assert plan.rewrite(Pid(2)) == Pid(0)


def test_rewrite_plan_recurses_containers():
    plan = RewritePlan.from_values_to_sort(Pid, ["B", "C", "A"])
    assert plan.rewrite([Pid(0), (Pid(1), "x"), {Pid(2)}]) == [
        Pid(1),
        (Pid(2), "x"),
        {Pid(0)},
    ]
    assert plan.rewrite({Pid(0): Pid(2)}) == {Pid(1): Pid(0)}
    # Non-domain scalars pass through untouched — including plain ints.
    assert plan.rewrite([7, "s"]) == [7, "s"]


def test_rewrite_plan_reindex_sorts():
    plan = RewritePlan.from_values_to_sort(Pid, ["B", "C", "A"])
    assert plan.reindex(["B", "C", "A"]) == ["A", "B", "C"]
    # Elements are also rewritten while being permuted.
    assert plan.reindex([[Pid(0)], [Pid(1)], [Pid(2)]]) == [
        [Pid(0)],
        [Pid(1)],
        [Pid(2)],
    ]


def test_rewrite_plan_rejects_int_domain():
    with pytest.raises(TypeError):
        RewritePlan(int, [0, 1])


def test_rewrite_plan_stable_sort_for_duplicates():
    # Equal values keep their relative order (stable), so the plan is
    # deterministic even with duplicate sort keys.
    plan = RewritePlan.from_values_to_sort(Pid, ["A", "A", "A"])
    assert plan.mapping == [0, 1, 2]
