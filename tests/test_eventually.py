"""Eventually-property semantics, including the documented false negatives.

Mirrors src/checker.rs:589-681 (test_eventually_property_checker): the
checker finds counterexamples only at terminal states, and revisiting a
state (cycle or DAG join) suppresses terminality — a known false negative
that we reproduce for output parity rather than "fix".
"""

from stateright_tpu import Property
from stateright_tpu.models import DGraph


def eventually_odd() -> Property:
    return Property.eventually("odd", lambda _m, s: s % 2 == 1)


def test_can_validate():
    (
        DGraph.with_property(eventually_odd())
        .with_path([1])
        .with_path([2, 3])
        .with_path([2, 6, 7])
        .with_path([4, 9, 10])
        .check()
        .assert_properties()
    )
    DGraph.with_property(eventually_odd()).with_path([1]).check().assert_properties()
    DGraph.with_property(eventually_odd()).with_path([2, 3]).check().assert_properties()
    (
        DGraph.with_property(eventually_odd())
        .with_path([2, 6, 7])
        .check()
        .assert_properties()
    )
    (
        DGraph.with_property(eventually_odd())
        .with_path([4, 9, 10])
        .check()
        .assert_properties()
    )


def test_can_discover_counterexample():
    assert (
        DGraph.with_property(eventually_odd())
        .with_path([0, 1])
        .with_path([0, 2])
        .check()
        .discovery("odd")
        .into_states()
    ) == [0, 2]
    assert (
        DGraph.with_property(eventually_odd())
        .with_path([0, 1])
        .with_path([2, 4])
        .check()
        .discovery("odd")
        .into_states()
    ) == [2, 4]
    assert (
        DGraph.with_property(eventually_odd())
        .with_path([0, 1, 4, 6])
        .with_path([2, 4, 8])
        .check()
        .discovery("odd")
        .into_states()
    ) == [2, 4, 6]


def test_fixme_can_miss_counterexample_when_revisiting_a_state():
    # Cycle: the path 0 -> 2 -> 4 -> 2 never satisfies "odd" but is not seen
    # as terminal. Preserved false negative (checker.rs:663-680).
    assert (
        DGraph.with_property(eventually_odd())
        .with_path([0, 2, 4, 2])
        .check()
        .discovery("odd")
    ) is None
    # DAG join: revisiting 4 suppresses terminality on the second path.
    assert (
        DGraph.with_property(eventually_odd())
        .with_path([0, 2, 4])
        .with_path([1, 4, 6])
        .check()
        .discovery("odd")
    ) is None
