"""Integration tests: the example protocols with the reference's golden
unique-state counts and discovery traces.

Mirrors the #[test] fns embedded in the reference examples:
paxos.rs:300-352, single-copy-register.rs:90-135,
linearizable-register.rs:257-330, increment_lock.rs, timers.rs,
interaction.rs.
"""

import pytest

from stateright_tpu.actor import Deliver, Id, Network
from stateright_tpu.actor.register import Get, GetOk, Internal, Put, PutOk
from stateright_tpu.models import IncrementLock, IncrementLockTensor

from examples.linearizable_register import AckQuery, AckRecord, Query, Record, abd_model
from examples.lww_register import lww_model
from examples.paxos import Accept, Accepted, Decided, Prepare, Prepared, paxos_model
from examples.single_copy_register import single_copy_model
from examples.interaction import interaction_model
from examples.timers import timers_model


@pytest.mark.slow
@pytest.mark.parametrize("engine", ["bfs", "dfs"])
def test_can_model_paxos(engine):
    checker = paxos_model(2, 3).checker()
    checker = (checker.spawn_bfs() if engine == "bfs" else checker.spawn_dfs()).join()
    checker.assert_properties()
    checker.assert_discovery("value chosen", [
        Deliver(src=Id(4), dst=Id(1), msg=Put(4, "B")),
        Deliver(src=Id(1), dst=Id(0), msg=Internal(Prepare((1, Id(1))))),
        Deliver(src=Id(0), dst=Id(1), msg=Internal(Prepared((1, Id(1)), None))),
        Deliver(src=Id(1), dst=Id(2),
                msg=Internal(Accept((1, Id(1)), (4, Id(4), "B")))),
        Deliver(src=Id(2), dst=Id(1), msg=Internal(Accepted((1, Id(1))))),
        Deliver(src=Id(1), dst=Id(4), msg=PutOk(4)),
        Deliver(src=Id(1), dst=Id(2),
                msg=Internal(Decided((1, Id(1)), (4, Id(4), "B")))),
        Deliver(src=Id(4), dst=Id(2), msg=Get(8)),
    ])
    assert checker.unique_state_count() == 16_668


def test_can_model_single_copy_register():
    # Linearizable if only one server. DFS for this one.
    checker = single_copy_model(2, 1).checker().spawn_dfs().join()
    checker.assert_properties()
    checker.assert_discovery("value chosen", [
        Deliver(src=Id(2), dst=Id(0), msg=Put(2, "B")),
        Deliver(src=Id(0), dst=Id(2), msg=PutOk(2)),
        Deliver(src=Id(2), dst=Id(0), msg=Get(4)),
    ])
    assert checker.unique_state_count() == 93

    # More than one server is not linearizable. BFS this time.
    checker = single_copy_model(2, 2).checker().spawn_bfs().join()
    checker.assert_discovery("linearizable", [
        Deliver(src=Id(3), dst=Id(1), msg=Put(3, "B")),
        Deliver(src=Id(1), dst=Id(3), msg=PutOk(3)),
        Deliver(src=Id(3), dst=Id(0), msg=Get(6)),
        Deliver(src=Id(0), dst=Id(3), msg=GetOk(6, None)),
    ])
    checker.assert_discovery("value chosen", [
        Deliver(src=Id(3), dst=Id(1), msg=Put(3, "B")),
        Deliver(src=Id(1), dst=Id(3), msg=PutOk(3)),
        Deliver(src=Id(2), dst=Id(0), msg=Put(2, "A")),
        Deliver(src=Id(3), dst=Id(0), msg=Get(6)),
    ])
    # The reference reports 20 here (single-copy-register.rs:135). This run
    # stops early once every property has a discovery, so the count is an
    # enumeration-order artifact; our deterministic sorted action order
    # visits 22 before cutoff. (The exhaustive 93-state golden above is
    # order-independent and matches exactly.)
    assert checker.unique_state_count() == 22


@pytest.mark.parametrize("engine", ["bfs", "dfs"])
def test_can_model_linearizable_register(engine):
    checker = abd_model(2, 2).checker()
    checker = (checker.spawn_bfs() if engine == "bfs" else checker.spawn_dfs()).join()
    checker.assert_properties()
    checker.assert_discovery("value chosen", [
        Deliver(src=Id(3), dst=Id(1), msg=Put(3, "B")),
        Deliver(src=Id(1), dst=Id(0), msg=Internal(Query(3))),
        Deliver(src=Id(0), dst=Id(1), msg=Internal(AckQuery(3, (0, Id(0)), None))),
        Deliver(src=Id(1), dst=Id(0), msg=Internal(Record(3, (1, Id(1)), "B"))),
        Deliver(src=Id(0), dst=Id(1), msg=Internal(AckRecord(3))),
        Deliver(src=Id(1), dst=Id(3), msg=PutOk(3)),
        Deliver(src=Id(3), dst=Id(0), msg=Get(6)),
        Deliver(src=Id(0), dst=Id(1), msg=Internal(Query(6))),
        Deliver(src=Id(1), dst=Id(0), msg=Internal(AckQuery(6, (1, Id(1)), "B"))),
        Deliver(src=Id(0), dst=Id(1), msg=Internal(Record(6, (1, Id(1)), "B"))),
        Deliver(src=Id(1), dst=Id(0), msg=Internal(AckRecord(6))),
    ])
    assert checker.unique_state_count() == 544


def test_increment_lock_holds_invariants():
    checker = IncrementLock(2).checker().spawn_dfs().join()
    checker.assert_properties()
    sym = IncrementLock(3).checker().symmetry().spawn_dfs().join()
    sym.assert_properties()
    full = IncrementLock(3).checker().spawn_dfs().join()
    assert sym.unique_state_count() < full.unique_state_count()


def test_increment_lock_tensor_matches_host():
    host = IncrementLock(2).checker().spawn_bfs().join()
    tensor = IncrementLockTensor(2).checker().spawn_tpu_bfs().join()
    assert tensor.unique_state_count() == host.unique_state_count()
    tensor.assert_properties()


def test_lww_register_is_eventually_consistent():
    checker = lww_model(2).checker().target_max_depth(6).spawn_dfs().join()
    checker.assert_no_discovery("eventually consistent")
    assert checker.unique_state_count() > 100


def test_timers_pingers():
    checker = timers_model(2).checker().spawn_bfs().join()
    checker.assert_properties()
    assert checker.unique_state_count() > 10


def test_interaction_reaches_success():
    from stateright_tpu import StateRecorder
    from examples.interaction import InputState

    # The reference CLI uses depth 30 (interaction.rs:43); depth 8 already
    # covers the success path and keeps the duplicating-network blowup
    # test-sized.
    recorder, accessor = StateRecorder.new_with_accessor()
    checker = (
        interaction_model()
        .checker()
        .target_max_depth(8)
        .visitor(recorder)
        .spawn_bfs()
        .join()
    )
    checker.assert_properties()
    assert any(
        any(isinstance(s, InputState) and s.success for s in state.actor_states)
        for state in accessor()
    )
