"""Simulation-engine tests. Reference: src/checker/simulation.rs:443-462 plus
behavioral coverage for cycle detection and eventually-property semantics."""

from stateright_tpu.core import Property
from stateright_tpu.engines.simulation import UniformChooser
from stateright_tpu.models.fixtures import BinaryClock, DGraph, LinearEquation


def test_can_complete_by_eliminating_properties():
    # Mirrors simulation.rs:448-461: a solvable equation's `sometimes`
    # property is found by random walking, which completes the run.
    checker = LinearEquation(2, 10, 14).checker().spawn_simulation(0).join()
    checker.assert_properties()
    path = checker.assert_any_discovery("solvable")
    x, y = path.last_state()
    assert (2 * x + 10 * y) % 256 == 14


def test_seed_reproducibility():
    c1 = LinearEquation(2, 10, 14).checker().spawn_simulation(12345).join()
    c2 = LinearEquation(2, 10, 14).checker().spawn_simulation(12345).join()
    assert c1.discovery("solvable") == c2.discovery("solvable")


def test_cycle_detection_terminates_runs():
    # BinaryClock cycles forever; per-run loop detection must cut each walk
    # at <= 2 states so the target_state_count is what stops the checker.
    checker = (
        BinaryClock()
        .checker()
        .target_state_count(100)
        .spawn_simulation(0)
        .join()
    )
    assert checker.state_count() >= 100
    assert checker.max_depth() <= 2
    assert checker.discovery("in [0, 1]") is None


def test_eventually_counterexample_on_terminal_path():
    # 1 -> 2 -> 3 terminates without ever satisfying "eventually state==9".
    model = DGraph.with_property(
        Property.eventually("reaches 9", lambda _m, s: s == 9)
    ).with_path([1, 2, 3])
    checker = model.checker().spawn_simulation(0).join()
    path = checker.assert_any_discovery("reaches 9")
    assert path.last_state() == 3


def test_eventually_satisfied_no_discovery():
    # A satisfied liveness property never yields a discovery, so simulation
    # keeps searching until an external stop condition (here: state budget).
    model = DGraph.with_property(
        Property.eventually("reaches 3", lambda _m, s: s == 3)
    ).with_path([1, 2, 3])
    checker = model.checker().target_state_count(50).spawn_simulation(0).join()
    checker.assert_no_discovery("reaches 3")


def test_always_violation_found():
    model = DGraph.with_property(
        Property.always("stays small", lambda _m, s: s < 3)
    ).with_path([1, 2, 3])
    checker = model.checker().spawn_simulation(7).join()
    path = checker.assert_any_discovery("stays small")
    assert path.last_state() == 3
    # The discovery is the exact violating walk: 1 -> 2 -> 3.
    assert path.into_states() == [1, 2, 3]


def test_timeout_stops_unbounded_simulation():
    checker = BinaryClock().checker().timeout(0.2).spawn_simulation(0).join()
    assert checker.is_done()
