"""Coverage observability (obs/coverage.py): per-action fire counts with
exact host/device parity, depth histograms that reconcile with unique
counts, dead-action detection (runtime + speclint STR306 + reporter
warning block), counterexample forensics (Path.explain), and the
Explorer/trace/bench/Prometheus wiring."""

from __future__ import annotations

import io
import json
import urllib.error
import urllib.request
from typing import List

import numpy as np
import pytest

from stateright_tpu import TensorModelAdapter, WriteReporter
from stateright_tpu.analysis import analyze
from stateright_tpu.has_discoveries import HasDiscoveries
from stateright_tpu.models import Increment, IncrementTensor, TwoPhaseTensor
from stateright_tpu.models.fixtures import BinaryClock
from stateright_tpu.tensor import TensorModel, TensorProperty

# ---------------------------------------------------------------------------
# Fixture models.
# ---------------------------------------------------------------------------


class IncrementTensorCov(IncrementTensor):
    """IncrementTensor plus an always-holding property, so exhaustive runs
    stay exhaustive after the 'fin' counterexample is found (with only
    violated properties, the host engines stop expanding once every
    property has a discovery — reference parity — which would make
    host/device visit sets diverge)."""

    def tensor_properties(self) -> List[TensorProperty]:
        return super().tensor_properties() + [
            TensorProperty.always("live", lambda xp, lanes: lanes[0] == lanes[0])
        ]


class DeadGuardTensor(TensorModel):
    """One live counter action and one action whose guard is never true on
    any reachable state — the canonical dead transition."""

    state_width = 1
    max_actions = 2

    def init_states_array(self) -> np.ndarray:
        return np.zeros((1, 1), dtype=np.uint32)

    def step_lanes(self, xp, lanes):
        u = xp.uint32
        x = lanes[0]
        succs = [((x + u(1)) & u(3),), ((x + u(7)) & u(15),)]
        masks = [x < u(3), x == u(999)]  # slot 1: unreachable guard
        return succs, masks

    def tensor_properties(self):
        return [
            TensorProperty.always("bounded", lambda xp, l: l[0] <= xp.uint32(4))
        ]

    def format_action(self, a: int) -> str:
        return "Step" if a == 0 else "Never"


EXHAUST = HasDiscoveries.any_of([])  # never matches: run to exhaustion


def _tiny_tpu_opts():
    return dict(chunk_size=64, queue_capacity=1 << 10, table_capacity=1 << 12)


# ---------------------------------------------------------------------------
# Host/device per-action parity (the acceptance criterion).
# ---------------------------------------------------------------------------


def test_action_counts_match_host_device_increment():
    tm = IncrementTensorCov(2)
    host = TensorModelAdapter(tm).checker().spawn_bfs().join()
    dev = TensorModelAdapter(tm).checker().spawn_tpu_bfs(**_tiny_tpu_opts()).join()
    hc, dc = host.coverage(), dev.coverage()
    assert hc["actions"] == dc["actions"]
    assert sum(hc["actions"].values()) > 0
    assert hc["depths"] == dc["depths"]
    assert host.unique_state_count() == dev.unique_state_count()


def test_action_counts_match_host_device_2pc4():
    tm = TwoPhaseTensor(4)
    host = TensorModelAdapter(tm).checker().spawn_bfs().join()
    dev = (
        TensorModelAdapter(tm)
        .checker()
        .spawn_tpu_bfs(chunk_size=256, queue_capacity=1 << 12, table_capacity=1 << 13)
        .join()
    )
    hc, dc = host.coverage(), dev.coverage()
    assert hc["actions"] == dc["actions"]
    assert dc["depths"] == hc["depths"]
    # Action counts decompose states_generated exactly.
    assert sum(dc["actions"].values()) == dev.telemetry()["states_generated"]


def test_action_counts_match_vbfs():
    tm = TwoPhaseTensor(4)
    host = TensorModelAdapter(tm).checker().spawn_bfs().join()
    v = TensorModelAdapter(tm).checker().threads(2).spawn_vbfs().join()
    assert v.coverage()["actions"] == host.coverage()["actions"]
    assert v.coverage()["depths"] == host.coverage()["depths"]


def test_action_counts_match_sharded():
    try:
        from stateright_tpu.compat import get_shard_map

        get_shard_map()
    except Exception:
        pytest.skip("shard_map unavailable on this jax version")
    tm = TwoPhaseTensor(3)
    host = TensorModelAdapter(tm).checker().spawn_bfs().join()
    s = (
        TensorModelAdapter(tm)
        .checker()
        .spawn_sharded_bfs(
            chunk_size=128,
            queue_capacity_per_shard=1 << 12,
            table_capacity_per_shard=1 << 12,
        )
        .join()
    )
    assert s.coverage()["actions"] == host.coverage()["actions"]
    assert s.coverage()["depths"] == host.coverage()["depths"]


# ---------------------------------------------------------------------------
# Depth histograms reconcile with unique counts.
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("spawn", ["bfs", "dfs", "vbfs", "tpu"])
def test_depth_histogram_sums_to_unique(spawn):
    builder = TensorModelAdapter(TwoPhaseTensor(3)).checker()
    if spawn == "bfs":
        c = builder.spawn_bfs().join()
    elif spawn == "dfs":
        c = builder.spawn_dfs().join()
    elif spawn == "vbfs":
        c = builder.threads(2).spawn_vbfs().join()
    else:
        c = builder.spawn_tpu_bfs(**_tiny_tpu_opts()).join()
    cov = c.coverage()
    assert sum(cov["depths"].values()) == c.unique_state_count()
    assert cov["max_depth"] == max(cov["depths"])


def test_simulation_coverage_counts_walk_states():
    c = (
        TensorModelAdapter(IncrementTensor(2))
        .checker()
        .target_state_count(150)
        .spawn_simulation(7)
        .join()
    )
    cov = c.coverage()
    # No dedup in simulation: depths count visited states, actions count
    # transitions taken (one fewer than states per walk).
    assert sum(cov["depths"].values()) == c.state_count()
    assert sum(cov["actions"].values()) > 0


def test_tpu_simulation_coverage():
    c = (
        TensorModelAdapter(IncrementTensor(2))
        .checker()
        .target_state_count(150)
        .spawn_tpu_simulation(7, walks=32, walk_cap=16)
        .join()
    )
    cov = c.coverage()
    assert sum(cov["depths"].values()) == c.state_count()
    assert sum(cov["actions"].values()) > 0
    assert cov["properties"]["fin"]["evaluations"] == c.state_count()


def test_pbfs_coverage():
    from stateright_tpu.models.two_phase_commit import TwoPhaseSys

    c = TwoPhaseSys(3).checker().threads(2).spawn_parallel_bfs().join()
    cov = c.coverage()
    assert sum(cov["depths"].values()) == c.unique_state_count()
    assert sum(cov["actions"].values()) > 0


def test_on_demand_coverage():
    c = BinaryClock().checker().spawn_on_demand()
    c.run_to_completion()
    c.join()
    cov = c.coverage()
    assert sum(cov["depths"].values()) == c.unique_state_count()
    assert sum(cov["actions"].values()) > 0


# ---------------------------------------------------------------------------
# Dead-action detection: runtime, reporter block, speclint STR306.
# ---------------------------------------------------------------------------


def test_dead_action_detected_host_and_device():
    tm = DeadGuardTensor()
    for checker in (
        TensorModelAdapter(tm).checker().spawn_bfs().join(),
        TensorModelAdapter(tm).checker().spawn_tpu_bfs(**_tiny_tpu_opts()).join(),
    ):
        cov = checker.coverage()
        assert cov["dead_actions"] == ["Never"]
        assert cov["actions"]["Step"] > 0
        assert checker.telemetry()["coverage_dead_actions"] == 1


def test_reporter_prints_dead_action_warning():
    out = io.StringIO()
    c = TensorModelAdapter(DeadGuardTensor()).checker().spawn_bfs()
    c.report(WriteReporter(out))
    text = out.getvalue()
    assert "Coverage. actions_fired=1/2" in text
    assert "never fired" in text and "STR306" in text
    assert "- Never" in text


def test_speclint_str306_flags_dead_guard():
    report = analyze(DeadGuardTensor())
    findings = report.by_code("STR306")
    assert findings and findings[0].severity.value == "warning"
    assert "Never" in findings[0].message
    assert report.ok  # warning, not error


def test_speclint_str306_clean_on_full_sample():
    report = analyze(TwoPhaseTensor(3), samples=512)
    assert not report.by_code("STR306")


def test_coverage_disabled():
    c = (
        TensorModelAdapter(IncrementTensorCov(2))
        .checker()
        .coverage(False)
        .spawn_tpu_bfs(**_tiny_tpu_opts())
        .join()
    )
    cov = c.coverage()
    assert cov["enabled"] is False
    assert not any(cov["actions"].values())
    assert not cov["depths"]
    # ...and disabling must not change the verdicts.
    assert c.discovery("fin") is not None


# ---------------------------------------------------------------------------
# Counterexample forensics: Path.explain / explain_steps.
# ---------------------------------------------------------------------------


def test_path_explain_narrative():
    c = Increment(2).checker().spawn_bfs().join()
    path = c.discovery("fin")
    text = path.explain(c.model())
    assert text.startswith(f"Path[{len(path)}] explained:")
    assert "'fin'" in text and "FALSE" in text  # the property flip
    assert "->" in text  # field-level diffs present


def test_path_explain_steps_structure():
    c = TensorModelAdapter(IncrementTensorCov(2)).checker().spawn_bfs().join()
    path = c.discovery("fin")
    steps = path.explain_steps(c.model())
    assert steps[0]["step"] == 0 and steps[0]["action"] is None
    assert len(steps) == len(path) + 1
    for rec in steps[1:]:
        assert isinstance(rec["action"], str)
        assert isinstance(rec["changes"], dict)
    # The final step flips 'fin' from True to False.
    assert steps[-1]["property_flips"].get("fin") == [True, False]
    # Records are JSON-serializable (the Explorer endpoint contract).
    json.dumps(steps)


def test_reporter_discovery_includes_explanation():
    out = io.StringIO()
    Increment(2).checker().spawn_bfs().report(WriteReporter(out))
    text = out.getvalue()
    assert 'Discovered "fin"' in text
    assert "explained:" in text


# ---------------------------------------------------------------------------
# Wiring round-trips: trace field, Chrome trace, Explorer, Prometheus, bench.
# ---------------------------------------------------------------------------


def test_trace_events_carry_coverage(tmp_path):
    path = str(tmp_path / "run.jsonl")
    c = (
        TensorModelAdapter(TwoPhaseTensor(3))
        .checker()
        .trace(path)
        .spawn_tpu_bfs(chunk_size=128)
        .join()
    )
    lines = [json.loads(line) for line in open(path)]
    eras = [rec for rec in lines if rec["event"] == "era"]
    assert eras
    assert all("coverage" in rec for rec in eras)
    final_actions = eras[-1]["coverage"]["actions"]
    assert final_actions == c.coverage()["actions"]


def test_chrome_trace_loads_in_perfetto_format(tmp_path):
    path = str(tmp_path / "run.chrome.json")
    TensorModelAdapter(TwoPhaseTensor(3)).checker().trace(
        path, format="chrome"
    ).spawn_bfs().join()
    events = json.load(open(path))  # closed file is a full JSON array
    phases = {e.get("ph") for e in events if e}
    assert "i" in phases  # instant events (waves / run brackets)
    assert "X" in phases  # duration events (phase timers)
    names = {e.get("name") for e in events if e}
    assert "run_start" in names and "run_end" in names
    assert "check_block" in names
    for e in events:
        if e.get("ph") == "X":
            assert e["dur"] > 0 and e["ts"] >= 0


def test_trace_format_validation():
    with pytest.raises(ValueError, match="chrome"):
        BinaryClock().checker().trace("/tmp/x", format="perfetto")


def test_explorer_coverage_prometheus_and_explain():
    from stateright_tpu.explorer.server import serve

    server = serve(
        TensorModelAdapter(IncrementTensorCov(2)).checker(),
        "127.0.0.1:0",
        block=False,
    )
    try:
        base = server.url.rstrip("/")

        def get(path):
            return urllib.request.urlopen(base + path)

        def get_json(path):
            with get(path) as r:
                assert r.status == 200
                return json.loads(r.read())

        # Drive the on-demand checker to completion so coverage fills in.
        req = urllib.request.Request(base + "/.runtocompletion", method="POST")
        with urllib.request.urlopen(req) as r:
            assert r.status == 200
        server.checker.join()

        body = get_json("/coverage")
        cov = body["coverage"]
        assert cov["enabled"] and sum(cov["actions"].values()) > 0
        assert get_json("/.coverage")["coverage"]["actions"] == cov["actions"]

        # Prometheus exposition: content type + stateright_ prefix.
        for path in ("/metrics?format=prometheus", "/metrics.prom"):
            with get(path) as r:
                assert r.status == 200
                ctype = r.headers.get("Content-Type", "")
                assert ctype.startswith("text/plain")
                assert "version=0.0.4" in ctype
                text = r.read().decode()
            assert "stateright_state_count" in text
            assert "stateright_engine_info" in text
        # The JSON endpoint still works with no format param.
        assert "telemetry" in get_json("/metrics")

        # Path-detail forensics over a discovered counterexample.
        status = get_json("/.status")
        discovery = next(
            enc for (_e, name, enc) in status["properties"] if name == "fin" and enc
        )
        body = get_json("/.explain/" + discovery)
        assert "narrative" in body and "explained:" in body["narrative"]
        assert body["steps"][0]["step"] == 0
        # Bad paths 404 instead of crashing the server.
        with pytest.raises(urllib.error.HTTPError):
            get("/.explain/notafingerprint")
    finally:
        server.shutdown()


def test_bench_compare_prints_delta_table(tmp_path, capsys):
    import bench

    a = tmp_path / "a.json"
    b = tmp_path / "b.json"
    a.write_text(
        json.dumps(
            {
                "value": 100.0,
                "detail": {
                    "tpc7": {
                        "states_per_sec": 100.0,
                        "telemetry": {"phase_ms": {"device_era": 50.0}},
                    }
                },
            }
        )
        + "\n"
    )
    b.write_text(
        json.dumps(
            {
                "value": 48.0,
                "detail": {
                    "tpc7": {
                        "states_per_sec": 48.0,
                        "telemetry": {"phase_ms": {"device_era": 110.0}},
                    }
                },
            }
        )
        + "\n"
    )
    assert bench.compare_bench(str(a), str(b)) == 0
    out = capsys.readouterr().out
    assert "detail.tpc7.states_per_sec" in out
    assert "-52.0%" in out
    assert "detail.tpc7.telemetry.phase_ms.device_era" in out
    assert "+120.0%" in out


def test_explorer_ui_ships_coverage_panel():
    # The SPA bundle must actually wire the coverage dashboard: panel +
    # explain view in the page, polling/render logic in the script.
    from pathlib import Path as FsPath

    ui = FsPath(__file__).parent.parent / "stateright_tpu" / "explorer" / "ui"
    html = (ui / "index.html").read_text()
    js = (ui / "app.js").read_text()
    css = (ui / "app.css").read_text()
    assert "coverage-panel" in html and "action-bars" in html
    assert "depth-hist" in html and "explain-path" in html
    assert "/coverage" in js and "pollCoverage" in js
    assert "/.explain/" in js and "renderDeadActions" in js
    assert ".cov-bar" in css and ".hist-bar" in css


def test_coverage_in_telemetry_gauges():
    c = TensorModelAdapter(IncrementTensorCov(2)).checker().spawn_bfs().join()
    t = c.telemetry()
    assert t["coverage_actions_fired"] == 4
    assert t["coverage_dead_actions"] == 0
