"""Host BFS engine tests. Mirrors src/checker/bfs.rs:411-489 test module."""

import pytest

from stateright_tpu import StateRecorder, WriteReporter
from stateright_tpu.models import LinearEquation, Panicker


def test_visits_states_in_bfs_order():
    recorder, accessor = StateRecorder.new_with_accessor()
    LinearEquation(2, 10, 14).checker().visitor(recorder).spawn_bfs().join()
    assert accessor() == [
        (0, 0),  # distance 0
        (1, 0), (0, 1),  # distance 1
        (2, 0), (1, 1), (0, 2),  # distance 2
        (3, 0), (2, 1),  # distance 3
    ]


def test_can_complete_by_enumerating_all_states():
    checker = LinearEquation(2, 4, 7).checker().spawn_bfs().join()
    assert checker.is_done()
    checker.assert_no_discovery("solvable")
    assert checker.unique_state_count() == 256 * 256


def test_can_complete_by_eliminating_properties():
    checker = LinearEquation(2, 10, 14).checker().spawn_bfs().join()
    checker.assert_properties()
    assert checker.unique_state_count() == 12

    # BFS finds the shortest example: (2*2 + 10*1) % 256 == 14.
    assert checker.discovery("solvable").into_actions() == [
        "IncreaseX", "IncreaseX", "IncreaseY",
    ]
    # ... and other solutions are also valid discoveries: (10*27) % 256 == 14.
    checker.assert_discovery("solvable", ["IncreaseY"] * 27)


def test_report_format():
    import io

    out = io.StringIO()
    LinearEquation(2, 10, 14).checker().spawn_bfs().report(WriteReporter(out))
    text = out.getvalue()
    assert text.startswith(
        "Checking. states=1, unique=1, depth=0\n"
        "Done. states=15, unique=12, depth=4, sec="
    )
    assert 'Discovered "solvable" example Path[3]:' in text
    assert "- 'IncreaseX'" in text
    assert "Fingerprint path: " in text


def test_handles_panics_gracefully():
    with pytest.raises(RuntimeError, match="reached panic state"):
        Panicker().checker().spawn_bfs().join()


def test_target_state_count_stops_early():
    checker = (
        LinearEquation(2, 4, 7).checker().target_state_count(1000).spawn_bfs().join()
    )
    assert checker.is_done()
    assert checker.state_count() >= 1000
    assert checker.unique_state_count() < 65536


def test_target_max_depth_limits_depth():
    checker = (
        LinearEquation(2, 4, 7).checker().target_max_depth(3).spawn_bfs().join()
    )
    assert checker.max_depth() == 3
    # Depth-3 jobs are popped but skipped, so generated states reach depth 3:
    # (0,0) + {(1,0),(0,1)} + {(2,0),(1,1),(0,2)} = 6 unique states.
    assert checker.unique_state_count() == 6
def test_threads_gt1_routes_or_raises_per_engine():
    from stateright_tpu.models.fixtures import BinaryClock

    # threads>1 spawn_bfs routes rich models to the multiprocessing
    # ownership-sharded engine (round 5); DFS stays single-threaded and
    # raises loudly rather than silently ignoring the setting.
    c = BinaryClock().checker().threads(2).spawn_bfs().join()
    assert c.unique_state_count() == 2
    with pytest.raises(NotImplementedError, match="single-threaded"):
        BinaryClock().checker().threads(2).spawn_dfs()

def test_threads_1_is_fine():
    from stateright_tpu.models.fixtures import BinaryClock

    c = BinaryClock().checker().threads(1).spawn_bfs().join()
    assert c.unique_state_count() == 2
