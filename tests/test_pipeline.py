"""Pipelined-vs-serial parity (ISSUE 14 tentpole): the speculative era
driver must be GOLDEN-IDENTICAL to the serial one.

A speculative era is dispatched off the still-on-device params chain
before the host has read era N's result. The device cond re-derives
every host-intervention exit from the chained params, so a speculative
era dispatched across a host-action boundary is an exact identity no-op
and the consumed stream of eras is the same either way. These tests pin
that equivalence end to end on both device engines: unique counts,
total state counts, max depth, discovery fingerprints, and coverage
histograms — with pipelining forced OFF via ``CheckerBuilder.pipeline``
against the default ON — plus the chaos path (a probe-error era with a
speculative era in flight is discarded wholesale by the checkpoint
reload and never corrupts the resumed run).
"""

import jax
import pytest

from stateright_tpu.models import TwoPhaseTensor
from stateright_tpu.tensor import TensorModelAdapter

# sync_steps=4 forces many short eras so speculative chains actually
# engage (a run that finishes in one era never reaches a chain point).
OPTS = dict(
    chunk_size=64,
    queue_capacity=1 << 12,
    table_capacity=1 << 11,
    sync_steps=4,
)


def _paxos_opts():
    return dict(
        chunk_size=1024,
        queue_capacity=1 << 16,
        table_capacity=1 << 16,
        sync_steps=64,
    )


def _fingerprint(c):
    """Everything the golden contract covers, in one comparable dict."""
    cov = c.coverage()
    fp = dict(
        unique=c.unique_state_count(),
        states=c.state_count(),
        max_depth=c.max_depth(),
        discovery_fps=dict(c._discovery_fps),
        coverage_actions=cov["actions"],
        coverage_depths=cov["depths"],
    )
    sampler = getattr(c, "_sampler", None)
    if sampler is not None and sampler.size():
        # The deterministic bottom-k sample is part of the golden
        # contract too: fusion reorders nothing the sampler can see.
        fp["sample"] = tuple(sampler.fingerprints())
    return fp


@pytest.fixture(scope="module")
def devices():
    devs = jax.devices()
    if len(devs) < 4:
        pytest.skip("needs 4 virtual devices")
    return devs[:4]


def test_tpu_bfs_parity_2pc5():
    runs = {}
    for on in (True, False):
        c = (
            TensorModelAdapter(TwoPhaseTensor(5))
            .checker()
            .coverage()
            .pipeline(on)
            .spawn_tpu_bfs(**OPTS)
            .join()
        )
        c.assert_properties()
        runs[on] = (_fingerprint(c), c.telemetry())
    fp_on, tel_on = runs[True]
    fp_off, tel_off = runs[False]
    assert fp_on["unique"] == 8832
    assert fp_on == fp_off
    # The pipelined run actually speculated; the serial run never did.
    assert tel_on.get("spec_dispatch", 0) >= 1
    assert tel_off.get("spec_dispatch", 0) == 0


def test_tpu_bfs_parity_paxos2():
    from stateright_tpu.models.paxos import PaxosTensorExhaustive

    runs = {}
    for on in (True, False):
        c = (
            TensorModelAdapter(PaxosTensorExhaustive(2))
            .checker()
            .coverage()
            .pipeline(on)
            .spawn_tpu_bfs(**_paxos_opts())
            .join()
        )
        runs[on] = (_fingerprint(c), c.telemetry())
    fp_on, tel_on = runs[True]
    fp_off, _ = runs[False]
    assert fp_on["unique"] == 16_668
    assert fp_on == fp_off
    assert "value chosen" in fp_on["discovery_fps"]
    assert tel_on.get("spec_dispatch", 0) >= 1


def test_mesh_parity_2pc5(devices):
    runs = {}
    opts = dict(
        devices=devices,
        chunk_size=64,
        queue_capacity_per_shard=1 << 11,
        table_capacity_per_shard=1 << 10,
        sync_steps=4,
    )
    for on in (True, False):
        c = (
            TensorModelAdapter(TwoPhaseTensor(5))
            .checker()
            .coverage()
            .pipeline(on)
            .spawn_sharded_bfs(**opts)
            .join()
        )
        runs[on] = (_fingerprint(c), c.telemetry())
    fp_on, tel_on = runs[True]
    fp_off, tel_off = runs[False]
    assert fp_on["unique"] == 8832
    assert fp_on == fp_off
    assert tel_on.get("spec_dispatch", 0) >= 1
    assert tel_off.get("spec_dispatch", 0) == 0


# ---------------------------------------------------------------------------
# Mega-dispatch sweep (ISSUE 19 tentpole): K-deep chains x on-device
# multi-era fusion must stay golden-identical to the serial driver.
# ---------------------------------------------------------------------------
#
# depth only changes host scheduling (no new compiled shape); fuse > 1
# compiles the inner-loop program. The sweep covers K in {1, 2, 4} and
# fused N in {1, 4} ((4, 4) exercises fusion under a deep chain, which
# subsumes the shallow-chain fused case): every config must reproduce
# the serial unique count, max depth, discovery fingerprints, coverage
# histograms, AND the deterministic bottom-k sample — and a fused run
# must retire its eras in strictly fewer dispatches.

MEGA_SWEEP = [(1, 1), (2, 1), (4, 1), (4, 4)]


@pytest.fixture(scope="module")
def serial_2pc5_solo():
    c = (
        TensorModelAdapter(TwoPhaseTensor(5))
        .checker()
        .coverage()
        .pipeline(False)
        .spawn_tpu_bfs(**OPTS)
        .join()
    )
    return _fingerprint(c)


@pytest.mark.parametrize("depth,fuse", MEGA_SWEEP)
def test_tpu_bfs_mega_sweep_2pc5(depth, fuse, serial_2pc5_solo):
    c = (
        TensorModelAdapter(TwoPhaseTensor(5))
        .checker()
        .coverage()
        .pipeline(depth=depth, fuse=fuse)
        .spawn_tpu_bfs(**OPTS)
        .join()
    )
    fp = _fingerprint(c)
    assert fp == serial_2pc5_solo
    assert fp["unique"] == 8832
    tel = c.telemetry()
    assert tel["spec_chain_depth"] <= depth
    if fuse > 1:
        # The amortization headline: strictly fewer host dispatches
        # than device eras, and the gauge reports the realized ratio.
        assert tel["dispatches"] < tel["eras"]
        assert tel["fused_eras_per_dispatch"] > 1.0
    else:
        assert tel["fused_eras_per_dispatch"] <= 1.0


@pytest.fixture(scope="module")
def serial_2pc5_mesh(devices):
    c = (
        TensorModelAdapter(TwoPhaseTensor(5))
        .checker()
        .coverage()
        .pipeline(False)
        .spawn_sharded_bfs(
            devices=devices,
            chunk_size=64,
            queue_capacity_per_shard=1 << 11,
            table_capacity_per_shard=1 << 10,
            sync_steps=4,
        )
        .join()
    )
    return _fingerprint(c)


@pytest.mark.parametrize("depth,fuse", MEGA_SWEEP)
def test_mesh_mega_sweep_2pc5(depth, fuse, devices, serial_2pc5_mesh):
    c = (
        TensorModelAdapter(TwoPhaseTensor(5))
        .checker()
        .coverage()
        .pipeline(depth=depth, fuse=fuse)
        .spawn_sharded_bfs(
            devices=devices,
            chunk_size=64,
            queue_capacity_per_shard=1 << 11,
            table_capacity_per_shard=1 << 10,
            sync_steps=4,
        )
        .join()
    )
    fp = _fingerprint(c)
    assert fp == serial_2pc5_mesh
    assert fp["unique"] == 8832
    tel = c.telemetry()
    assert tel["spec_chain_depth"] <= depth
    if fuse > 1:
        assert tel["dispatches"] < tel["eras"]
        assert tel["fused_eras_per_dispatch"] > 1.0


def test_tpu_bfs_mega_parity_paxos2():
    """The deepest config against serial on the bigger model."""
    from stateright_tpu.models.paxos import PaxosTensorExhaustive

    fps = {}
    for cfg in (None, (4, 4)):
        b = TensorModelAdapter(PaxosTensorExhaustive(2)).checker().coverage()
        if cfg is None:
            b.pipeline(False)
        else:
            b.pipeline(depth=cfg[0], fuse=cfg[1])
        fps[cfg] = _fingerprint(b.spawn_tpu_bfs(**_paxos_opts()).join())
    assert fps[(4, 4)] == fps[None]
    assert fps[None]["unique"] == 16_668


def test_mesh_mega_parity_paxos2(devices):
    from stateright_tpu.models.paxos import PaxosTensorExhaustive

    opts = dict(
        devices=devices,
        chunk_size=256,
        queue_capacity_per_shard=1 << 14,
        table_capacity_per_shard=1 << 13,
        sync_steps=64,
    )
    fps = {}
    for cfg in (None, (4, 4)):
        b = TensorModelAdapter(PaxosTensorExhaustive(2)).checker().coverage()
        if cfg is None:
            b.pipeline(False)
        else:
            b.pipeline(depth=cfg[0], fuse=cfg[1])
        fps[cfg] = _fingerprint(b.spawn_sharded_bfs(**opts).join())
    assert fps[(4, 4)] == fps[None]
    assert fps[None]["unique"] == 16_668


def test_tpu_bfs_kill_resume_under_deep_chain(tmp_path):
    """A checkpointed run killed at a boundary and resumed with a deep
    fused chain must land on the serial golden (the final checkpoint of
    a partial run is the exact stopping point, and the resumed mega-
    dispatch driver replays nothing and skips nothing)."""
    ckpt = str(tmp_path / "deep.ckpt.npz")
    part = (
        TensorModelAdapter(TwoPhaseTensor(5))
        .checker()
        .target_state_count(2_000)
        .spawn_tpu_bfs(checkpoint_path=ckpt, **OPTS)
        .join()
    )
    assert 0 < part.unique_state_count() < 8832
    c = (
        TensorModelAdapter(TwoPhaseTensor(5))
        .checker()
        .coverage()
        .pipeline(depth=4, fuse=4)
        .spawn_tpu_bfs(resume_from=ckpt, **OPTS)
        .join()
    )
    assert c.unique_state_count() == 8832
    c.assert_properties()


# ---------------------------------------------------------------------------
# Chaos: a probe-error era with a speculative era in flight
# ---------------------------------------------------------------------------
#
# The degraded-regrow path (reload last checkpoint, double the table,
# continue) must discard the WHOLE chain: the error era's unsound work
# and whatever the speculative era did. A real probe error closes the
# chained dispatch's gate (the carried P_ERR makes it an identity
# no-op); the chaos hook fakes the error host-side, so the speculative
# era may have run real work — the reload discards it wholesale either
# way, and the resumed run must still land on the exact golden.


def test_tpu_bfs_chaos_spec_discard_recovers(tmp_path):
    ckpt = str(tmp_path / "spec.ckpt.npz")
    # Seed a checkpoint generation (state-count targets run serial).
    part = (
        TensorModelAdapter(TwoPhaseTensor(5))
        .checker()
        .target_state_count(2_000)
        .spawn_tpu_bfs(checkpoint_path=ckpt, **OPTS)
        .join()
    )
    assert 0 < part.unique_state_count() < 8832
    # Resume pipelined: a long cadence keeps the chain gate open, so the
    # chaos-faked error lands while a speculative era is in flight.
    checker = (
        TensorModelAdapter(TwoPhaseTensor(5))
        .checker()
        .pipeline(depth=4, fuse=4)
        .spawn_tpu_bfs(
            resume_from=ckpt,
            checkpoint_path=ckpt,
            checkpoint_every=30.0,
            **OPTS,
        )
    )
    checker._chaos_probe_error_era = 1
    checker.join()
    assert checker.unique_state_count() == 8832
    checker.assert_properties()
    tel = checker.telemetry()
    assert tel.get("degraded_regrow", 0) == 1
    assert tel.get("spec_dispatch", 0) >= 1
    assert tel.get("spec_wasted", 0) >= 1


def test_mesh_chaos_spec_discard_recovers(tmp_path, devices):
    ckpt = str(tmp_path / "mesh-spec.ckpt.npz")
    opts = dict(
        devices=devices,
        chunk_size=64,
        queue_capacity_per_shard=1 << 11,
        table_capacity_per_shard=1 << 10,
        sync_steps=4,
    )
    part = (
        TensorModelAdapter(TwoPhaseTensor(5))
        .checker()
        .target_state_count(3_000)
        .spawn_sharded_bfs(checkpoint_path=ckpt, **opts)
        .join()
    )
    assert 0 < part.unique_state_count() < 8832
    checker = (
        TensorModelAdapter(TwoPhaseTensor(5))
        .checker()
        .pipeline(depth=4, fuse=4)
        .spawn_sharded_bfs(
            resume_from=ckpt,
            checkpoint_path=ckpt,
            checkpoint_every=30.0,
            **opts,
        )
    )
    checker._chaos_probe_error_era = 1
    checker.join()
    assert checker.unique_state_count() == 8832
    tel = checker.telemetry()
    assert tel.get("degraded_regrow", 0) == 1
    assert tel.get("spec_dispatch", 0) >= 1
    assert tel.get("spec_wasted", 0) >= 1
