"""Device-engine eventually-property matrix: dgraph twins.

Ports the host dgraph eventually cases (reference checker.rs:589-681) to
small TensorModels so the device ebits/dedup interaction is covered —
including the reference's PRESERVED false negative on cycles and DAG joins
(revisiting a state suppresses terminality; checker.rs:663-680). The
device engine must reproduce that behavior, not "fix" it, to stay
output-identical with the host engines.
"""

from typing import Dict, List, Tuple

import numpy as np

from stateright_tpu.tensor import TensorModel, TensorModelAdapter, TensorProperty


class DGraphTensor(TensorModel):
    """A directed graph on small-int states, lanes form (1 lane)."""

    state_width = 1

    def __init__(self, inits: List[int], edges: Dict[int, List[int]]):
        self.inits = sorted(inits)
        self.edges = edges
        self.max_actions = max((len(v) for v in edges.values()), default=1) or 1

    @staticmethod
    def from_paths(paths: List[List[int]]) -> "DGraphTensor":
        inits = set()
        edges: Dict[int, List[int]] = {}
        for path in paths:
            inits.add(path[0])
            for a, b in zip(path, path[1:]):
                outs = edges.setdefault(a, [])
                if b not in outs:
                    outs.append(b)
        return DGraphTensor(sorted(inits), edges)

    def init_states_array(self) -> np.ndarray:
        return np.asarray([[v] for v in self.inits], dtype=np.uint32)

    def step_lanes(self, xp, lanes):
        s = lanes[0]
        u = xp.uint32
        succs = []
        masks = []
        for a in range(self.max_actions):
            nxt = u(0) * s
            valid = s != s  # all-false, varying
            for v, outs in self.edges.items():
                if a < len(outs):
                    hit = s == u(v)
                    nxt = xp.where(hit, u(outs[a]), nxt)
                    valid = valid | hit
            succs.append((nxt,))
            masks.append(valid)
        return succs, masks

    def tensor_properties(self):
        return [
            TensorProperty.eventually(
                "odd", lambda xp, lanes: (lanes[0] & xp.uint32(1)) == xp.uint32(1)
            )
        ]


def check(paths: List[List[int]]):
    tm = DGraphTensor.from_paths(paths)
    return (
        TensorModelAdapter(tm)
        .checker()
        .spawn_tpu_bfs(chunk_size=64, queue_capacity=1 << 10, table_capacity=1 << 8)
        .join()
    )


def test_device_can_validate():
    check([[1], [2, 3], [2, 6, 7], [4, 9, 10]]).assert_properties()
    check([[1]]).assert_properties()
    check([[2, 3]]).assert_properties()
    check([[2, 6, 7]]).assert_properties()
    check([[4, 9, 10]]).assert_properties()


def test_device_can_discover_counterexample():
    # Terminal even states are eventually-"odd" counterexamples; BFS finds
    # the shortest path to each (checker.rs:612-661 ported to the device).
    path = check([[0, 1], [0, 2]]).discovery("odd")
    assert [int(s[0]) for s in path.into_states()] == [0, 2]
    path = check([[0, 1], [2, 4]]).discovery("odd")
    assert [int(s[0]) for s in path.into_states()] == [2, 4]
    path = check([[0, 1, 4, 6], [2, 4, 8]]).discovery("odd")
    assert [int(s[0]) for s in path.into_states()] == [2, 4, 6]


def test_device_fixme_can_miss_counterexample_when_revisiting_a_state():
    # Cycle: 0 -> 2 -> 4 -> 2 never satisfies "odd" but is never terminal.
    # The reference documents this false negative (checker.rs:663-680); the
    # device engine must reproduce it bit-for-bit, not repair it.
    assert check([[0, 2, 4, 2]]).discovery("odd") is None
    # DAG join: revisiting 4 suppresses terminality on the second path.
    assert check([[0, 2, 4], [1, 4, 6]]).discovery("odd") is None


def test_device_matches_host_engine_verdicts():
    # The host adapter run is the oracle for the same tensor models.
    for paths in (
        [[1], [2, 3], [2, 6, 7], [4, 9, 10]],
        [[0, 1], [0, 2]],
        [[0, 2, 4, 2]],
        [[0, 2, 4], [1, 4, 6]],
    ):
        tm = DGraphTensor.from_paths(paths)
        host = TensorModelAdapter(tm).checker().spawn_bfs().join()
        dev = (
            TensorModelAdapter(tm)
            .checker()
            .spawn_tpu_bfs(
                chunk_size=64, queue_capacity=1 << 10, table_capacity=1 << 8
            )
            .join()
        )
        assert dev.unique_state_count() == host.unique_state_count()
        assert (dev.discovery("odd") is None) == (host.discovery("odd") is None)
