"""The bench perf-regression gate (bench.py --history / --gate / --from):
summary extraction, direction/threshold logic, rolling-baseline
comparison, and the no-jax subprocess CLI path scripts/ci.sh relies on.
"""

import io
import json
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).parent.parent
sys.path.insert(0, str(REPO))

from bench import (  # noqa: E402
    GATE_BASELINE_WINDOW,
    _gate_check,
    _gate_direction,
    append_history,
    bench_summary,
    gate_bench,
    load_history,
)


def _record(rate=1000.0, p99=0.3, secs=3.0, overhead=0.5):
    return {
        "metric": "x",
        "value": rate,
        "unit": "states/sec",
        "vs_baseline": 2.0,
        "detail": {
            "tpc7": {
                "states_per_sec": rate,
                "secs_median": secs,
                "unique": 296_448,
                "golden_match": True,
                "telemetry": {"states_generated": 5, "eras": 3},
                "flight": {"device_secs": 2.9, "host_gap_secs": 0.1},
            },
            "tpc7_span_cost": {"overhead_pct": overhead},
            "service": {
                "latency": {
                    "submit_to_result": {
                        "p50": 0.1,
                        "p95": 0.2,
                        "p99": p99,
                        "count": 8,
                    }
                }
            },
        },
    }


# -- summary extraction -------------------------------------------------------


def test_summary_selects_gate_relevant_metrics_only():
    s = bench_summary(_record())
    assert s["value"] == 1000.0
    assert s["detail.tpc7.states_per_sec"] == 1000.0
    assert s["detail.tpc7.secs_median"] == 3.0
    assert s["detail.tpc7_span_cost.overhead_pct"] == 0.5
    assert s["detail.service.latency.submit_to_result.p99"] == 0.3
    # Diagnostic/environment sections stay out of the gate: telemetry
    # counters, flight wall totals, golden booleans, raw counts.
    for key in s:
        assert ".telemetry." not in key and ".flight." not in key, key
    assert "detail.tpc7.unique" not in s
    assert "detail.service.latency.submit_to_result.count" not in s
    assert "detail.tpc7.golden_match" not in s


def test_direction_inference():
    assert _gate_direction("value") == "higher"
    assert _gate_direction("detail.tpc7.states_per_sec") == "higher"
    assert _gate_direction("detail.pbfs.speedup") == "higher"
    assert _gate_direction("a.p99") == "lower"
    assert _gate_direction("a.secs_median") == "lower"
    assert _gate_direction("a.overhead_pct") == "lower"
    assert _gate_direction("detail.tpc7.unique") is None


# -- per-metric check ---------------------------------------------------------


def test_gate_check_rate_budget():
    assert _gate_check("value", 1000.0, 900.0) is None  # -10%: within
    assert _gate_check("value", 1000.0, 840.0) is not None  # -16%: trips
    assert _gate_check("value", 1000.0, 1500.0) is None  # faster is fine


def test_gate_check_latency_budget_with_noise_floor():
    key = "detail.service.latency.submit_to_result.p99"
    assert _gate_check(key, 1.0, 1.2) is None  # +20%: within
    assert _gate_check(key, 1.0, 1.3) is not None  # +30%: trips
    # Sub-floor absolute moves never trip, however large relatively.
    assert _gate_check(key, 0.01, 0.03) is None
    key = "detail.tpc7_span_cost.overhead_pct"
    assert _gate_check(key, 0.2, 0.9) is None  # < 1.0pp absolute floor
    assert _gate_check(key, 1.0, 2.5) is not None


# -- rolling baseline ---------------------------------------------------------


def test_gate_empty_history_passes(tmp_path):
    out = io.StringIO()
    assert gate_bench(str(tmp_path / "none.jsonl"), _record(), out) == 0
    assert "seed run" in out.getvalue()


def test_gate_parity_passes_and_regression_fails(tmp_path):
    hist = str(tmp_path / "h.jsonl")
    append_history(hist, _record())
    append_history(hist, _record(rate=1020.0))
    out = io.StringIO()
    assert gate_bench(hist, _record(rate=990.0), out) == 0
    out = io.StringIO()
    assert gate_bench(hist, _record(rate=700.0), out) == 1
    assert "REGRESSION value" in out.getvalue()


def test_gate_baseline_is_median_of_last_window(tmp_path):
    hist = str(tmp_path / "h.jsonl")
    # One ancient slow run, then GATE_BASELINE_WINDOW fast ones: the
    # rolling window must forget the slow outlier entirely.
    append_history(hist, _record(rate=10.0))
    for _ in range(GATE_BASELINE_WINDOW):
        append_history(hist, _record(rate=1000.0))
    assert gate_bench(hist, _record(rate=700.0), io.StringIO()) == 1
    # And a single fast outlier inside the window cannot poison the
    # median baseline.
    hist2 = str(tmp_path / "h2.jsonl")
    for rate in (1000.0, 1000.0, 5000.0, 1000.0, 1000.0):
        append_history(hist2, _record(rate=rate))
    assert gate_bench(hist2, _record(rate=950.0), io.StringIO()) == 0


def test_history_rows_are_flat_jsonl(tmp_path):
    hist = tmp_path / "h.jsonl"
    summary = append_history(str(hist), _record())
    rows = load_history(str(hist))
    assert rows == [summary]
    # Every row is a flat {dotted-key: number} dict — greppable and
    # mergeable across bench versions.
    assert all(
        isinstance(v, (int, float)) for v in rows[0].values()
    )
    # Corrupt/blank lines are skipped, not fatal.
    with open(hist, "a") as f:
        f.write("not json\n\n")
    append_history(str(hist), _record())
    assert len(load_history(str(hist))) == 2


# -- CLI: the no-jax --from path ----------------------------------------------


def _run_cli(*args):
    return subprocess.run(
        [sys.executable, str(REPO / "bench.py"), *args],
        capture_output=True,
        text=True,
        timeout=60,
        cwd=str(REPO),
    )


@pytest.fixture()
def bench_json(tmp_path):
    path = tmp_path / "BENCH.json"
    path.write_text(json.dumps(_record()) + "\n")
    return str(path)


def test_cli_from_gate_and_history_roundtrip(tmp_path, bench_json):
    hist = str(tmp_path / "hist.jsonl")
    # Seed: empty history passes and appends the baseline row.
    r = _run_cli("--from", bench_json, "--gate", hist, "--history", hist)
    assert r.returncode == 0, r.stderr
    assert "seed run" in r.stdout
    # Parity passes.
    r = _run_cli("--from", bench_json, "--gate", hist)
    assert r.returncode == 0, r.stdout + r.stderr
    # A regressed record trips the gate with a nonzero exit.
    slow = tmp_path / "SLOW.json"
    slow.write_text(json.dumps(_record(rate=700.0, p99=0.9)) + "\n")
    r = _run_cli("--from", str(slow), "--gate", hist)
    assert r.returncode == 1, r.stdout + r.stderr
    assert "REGRESSION" in r.stdout
    # The gate ran BEFORE any append: the history still has one row.
    assert len(load_history(hist)) == 1


def test_cli_from_does_not_import_jax(bench_json, tmp_path):
    # ci.sh may gate records on boxes without an accelerator stack; the
    # --from path must never import jax. A poisoned jax on sys.path
    # proves it by construction.
    trap = tmp_path / "jax"
    trap.mkdir()
    (trap / "__init__.py").write_text("raise ImportError('jax imported')\n")
    r = subprocess.run(
        [
            sys.executable,
            str(REPO / "bench.py"),
            "--from",
            bench_json,
            "--gate",
            str(tmp_path / "h.jsonl"),
        ],
        capture_output=True,
        text=True,
        timeout=60,
        cwd=str(tmp_path),
        env={"PYTHONPATH": f"{tmp_path}", "PATH": "/usr/bin:/bin"},
    )
    assert r.returncode == 0, r.stdout + r.stderr


def test_cli_from_requires_an_action(bench_json):
    r = _run_cli("--from", bench_json)
    assert r.returncode != 0
    assert "usage" in (r.stdout + r.stderr).lower()
