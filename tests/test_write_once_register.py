"""Write-once register actor kit tests: a trivial first-write-wins server
checked against the WORegister semantics via the kit's history hooks.

Role parity: the reference exercises this kit through its examples; here a
minimal server validates client sequencing (PutFail advances like PutOk,
write_once_register.rs:247-266) and the record hooks end-to-end.
"""

from stateright_tpu import Expectation
from stateright_tpu.actor import Actor, ActorModel, Network
from stateright_tpu.actor.write_once_register import (
    Get,
    GetOk,
    Put,
    PutFail,
    PutOk,
    WORegisterClient,
    record_invocations,
    record_returns,
)
from stateright_tpu.semantics import LinearizabilityTester
from stateright_tpu.semantics.write_once_register import WORegister


class FirstWriteWinsServer(Actor):
    """Accepts only the first write; later writes of other values fail."""

    def on_start(self, id, out):
        return None

    def on_msg(self, id, state, src, msg, out):
        if isinstance(msg, Put):
            if state is None or state == msg.value:
                out.send(src, PutOk(msg.request_id))
                return msg.value
            out.send(src, PutFail(msg.request_id))
            return None
        if isinstance(msg, Get):
            out.send(src, GetOk(msg.request_id, state))
            return None
        return None


def wo_model(client_count: int):
    return (
        ActorModel(init_history=LinearizabilityTester(WORegister()))
        .actor(FirstWriteWinsServer())
        .add_actors(
            WORegisterClient(put_count=1, server_count=1)
            for _ in range(client_count)
        )
        .with_init_network(Network.new_unordered_nonduplicating())
        .property(
            Expectation.ALWAYS,
            "linearizable",
            lambda model, state: state.history.serialized_history() is not None,
        )
        .property(
            Expectation.SOMETIMES,
            "a write fails",
            lambda model, state: any(
                isinstance(env.msg, PutFail)
                for env in state.network.iter_deliverable()
            ),
        )
        .with_record_msg_in(record_returns)
        .with_record_msg_out(record_invocations)
    )


def test_single_server_write_once_is_linearizable():
    checker = wo_model(2).checker().spawn_bfs().join()
    checker.assert_properties()  # linearizable + a conflicting write fails


def test_clients_advance_past_put_fail():
    # Both clients finish their op sequences even when one Put fails.
    from stateright_tpu import StateRecorder

    recorder, accessor = StateRecorder.new_with_accessor()
    wo_model(2).checker().visitor(recorder).spawn_bfs().join()
    assert any(
        all(
            getattr(s, "awaiting", "x") is None
            for s in state.actor_states[1:]
        )
        for state in accessor()
    )


def test_symmetry_representative_rewrites_wo_states():
    from stateright_tpu.fingerprint import fingerprint

    model = wo_model(2)
    init = model.init_states()[0]
    rep = init.representative()
    assert fingerprint(rep) == fingerprint(rep.representative())  # idempotent
