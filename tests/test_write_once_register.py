"""Write-once register actor kit tests: a trivial first-write-wins server
checked against the WORegister semantics via the kit's history hooks.

Role parity: the reference exercises this kit through its examples; here a
minimal server validates client sequencing (PutFail advances like PutOk,
write_once_register.rs:247-266) and the record hooks end-to-end.
"""

# The server + demo model now live in the kit itself (and back the
# `write-once-register` speclint CLI shorthand); the tests exercise the
# bundled factory.
from stateright_tpu.actor.write_once_register import (
    wo_register_model as wo_model,
)


def test_single_server_write_once_is_linearizable():
    checker = wo_model(2).checker().spawn_bfs().join()
    checker.assert_properties()  # linearizable + a conflicting write fails


def test_clients_advance_past_put_fail():
    # Both clients finish their op sequences even when one Put fails.
    from stateright_tpu import StateRecorder

    recorder, accessor = StateRecorder.new_with_accessor()
    wo_model(2).checker().visitor(recorder).spawn_bfs().join()
    assert any(
        all(
            getattr(s, "awaiting", "x") is None
            for s in state.actor_states[1:]
        )
        for state in accessor()
    )


def test_symmetry_representative_rewrites_wo_states():
    from stateright_tpu.fingerprint import fingerprint

    model = wo_model(2)
    init = model.init_states()[0]
    rep = init.representative()
    assert fingerprint(rep) == fingerprint(rep.representative())  # idempotent
