"""TPU batched-BFS engine: discovery-output equivalence with the host oracle.

The host BFS run on the same TensorModel is the correctness oracle
(SURVEY.md §7 step 2): unique-state counts, property verdicts, and the
validity of reconstructed discovery paths must agree. Runs on the virtual
CPU platform in CI; the same code path is what executes on the TPU chip.
"""

import numpy as np
import pytest

from stateright_tpu import Expectation, Property, TensorModelAdapter
from stateright_tpu.models import IncrementTensor, TwoPhaseTensor
from stateright_tpu.tensor import TensorModel, TensorProperty


def host_check(tm):
    return TensorModelAdapter(tm).checker().spawn_bfs().join()


def tpu_check(tm, **kw):
    return TensorModelAdapter(tm).checker().spawn_tpu_bfs(**kw).join()


def test_2pc3_matches_host_oracle():
    tm = TwoPhaseTensor(3)
    host = host_check(tm)
    tpu = tpu_check(tm)
    assert tpu.unique_state_count() == host.unique_state_count() == 288
    tpu.assert_properties()
    # Both sometimes-properties discovered with valid paths.
    for name in ("abort agreement", "commit agreement"):
        path = tpu.discovery(name)
        assert path is not None
        # The path must be replayable through the model (actions are real).
        assert len(path.into_actions()) >= 1


def test_2pc5():
    tm = TwoPhaseTensor(5)
    tpu = tpu_check(tm)
    assert tpu.unique_state_count() == 8832
    tpu.assert_properties()


def test_increment_race_discovered():
    tm = IncrementTensor(2)
    tpu = tpu_check(tm)
    path = tpu.discovery("fin")
    assert path is not None
    # Validate the counterexample end-to-end: final state violates "fin".
    final = tuple(
        np.asarray([v], dtype=np.uint32) for v in path.last_state()
    )
    prop = next(p for p in tm.tensor_properties() if p.name == "fin")
    assert not bool(np.asarray(prop.check(np, final))[0])
    # BFS discovers a shortest counterexample: the classic 4-step schedule.
    assert len(path.into_actions()) == 4


def test_table_growth_and_queue_spill():
    # Tiny table (forces growth) and tiny queue (forces spill) on the
    # 8832-state space: counts must still be exact.
    tm = TwoPhaseTensor(5)
    tpu = tpu_check(tm, table_capacity=1 << 8, queue_capacity=1 << 12, chunk_size=64)
    assert tpu.unique_state_count() == 8832
    tpu.assert_properties()


def test_eventually_property_tensor():
    # A 4-lane counter that counts 0..3 and stops; eventually x>=3 holds.
    class Counter(TensorModel):
        state_width = 1
        max_actions = 1

        def init_states_array(self):
            return np.zeros((1, 1), dtype=np.uint32)

        def step_lanes(self, xp, lanes):
            x = lanes[0]
            return [(xp.minimum(x + xp.uint32(1), xp.uint32(3)),)], [
                x < xp.uint32(3)
            ]

        def tensor_properties(self):
            return [
                TensorProperty.eventually(
                    "reaches3", lambda xp, lanes: lanes[0] >= xp.uint32(3)
                )
            ]

    tpu = tpu_check(Counter())
    tpu.assert_properties()  # no counterexample: every path reaches 3

    class Stuck(Counter):
        def step_lanes(self, xp, lanes):
            x = lanes[0]
            return [(xp.minimum(x + xp.uint32(1), xp.uint32(2)),)], [
                x < xp.uint32(2)
            ]

    tpu = tpu_check(Stuck())
    path = tpu.discovery("reaches3")
    assert path is not None  # terminal state 2 never satisfies the property
    assert [int(s[0]) for s in path.into_states()] == [0, 1, 2]


def test_target_state_count_and_timeout():
    tm = TwoPhaseTensor(5)
    tpu = tpu_check(tm, chunk_size=64)
    full = tpu.state_count()
    capped = (
        TensorModelAdapter(tm)
        .checker()
        .target_state_count(500)
        .spawn_tpu_bfs(chunk_size=64)
        .join()
    )
    assert 500 <= capped.state_count() < full


def test_rejects_rich_models_and_visitors():
    from stateright_tpu.models import LinearEquation

    with pytest.raises(TypeError, match="TensorModel"):
        LinearEquation(2, 10, 14).checker().spawn_tpu_bfs()
    with pytest.raises(ValueError, match="visitor"):
        TensorModelAdapter(IncrementTensor(2)).checker().visitor(
            lambda p: None
        ).spawn_tpu_bfs()


def test_telemetry_surfaces_engine_gauges():
    """Engine health (eras, steps, load factor, take_cap) must be visible
    through the public Checker.telemetry()/report surface, not just
    STPU_DEBUG (reference report.rs:66-74 role)."""
    import io

    from stateright_tpu.models import TwoPhaseTensor
    from stateright_tpu.report import WriteReporter
    from stateright_tpu.tensor import TensorModelAdapter

    c = TensorModelAdapter(TwoPhaseTensor(4)).checker().spawn_tpu_bfs(
        chunk_size=256
    )
    buf = io.StringIO()
    c.report(WriteReporter(buf))
    t = c.telemetry()
    assert t["eras"] >= 1
    assert t["steps"] >= 1
    assert 0 < t["load_factor"] < 1
    assert t["take_cap"] >= 1
    assert "Telemetry." in buf.getvalue()
