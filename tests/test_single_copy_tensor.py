"""Single-copy-register tensor twin: the toolkit's violating protocol.

With one server the system is linearizable; with two, a stale/None read
breaks it (single-copy-register.rs goldens). This is the only register-
family twin whose linearizable lane program FIRES on a real protocol, so
it pins the violation-finding path end to end.
"""

import pytest

from examples.single_copy_register import single_copy_model
from stateright_tpu.has_discoveries import HasDiscoveries
from stateright_tpu.models.single_copy import SingleCopyTensor
from stateright_tpu.tensor import TensorModelAdapter

_NEVER = HasDiscoveries.all_of(["<no such property>"])


@pytest.mark.parametrize("c", [2, 3])
def test_single_server_exhaustive_parity(c):
    """s=1 is linearizable, so no property-set-dependent early stop: the
    twin must match the actor model state-for-state to exhaustion
    (93 uniques at c=2, single-copy-register.rs parity)."""
    host = (
        single_copy_model(c, 1).checker().finish_when(_NEVER).spawn_bfs().join()
    )
    twin = (
        TensorModelAdapter(SingleCopyTensor(c, 1))
        .checker()
        .finish_when(_NEVER)
        .spawn_bfs()
        .join()
    )
    assert host.unique_state_count() == twin.unique_state_count()
    if c == 2:
        assert twin.unique_state_count() == 93
    assert twin.discovery("linearizable") is None
    assert host.discovery("linearizable") is None


def test_two_servers_violation_found_by_all_engines():
    """s=2: the None-read violation must be found by the actor model, the
    twin's host engines, AND the device engine — with a replayable trace.
    (Counts at stop are property-set/schedule dependent and are NOT
    compared; the host engine halts once every property has a discovery,
    and the twin carries an extra never-discovered capacity guard.)"""
    host = single_copy_model(2, 2).checker().spawn_bfs().join()
    assert host.discovery("linearizable") is not None

    plain = TensorModelAdapter(SingleCopyTensor(2, 2)).checker().spawn_bfs().join()
    t_plain = plain.discovery("linearizable")
    assert t_plain is not None

    vec = (
        TensorModelAdapter(SingleCopyTensor(2, 2))
        .checker()
        .threads(4)
        .spawn_bfs()
        .join()
    )
    t_vec = vec.discovery("linearizable")
    assert t_vec is not None
    # BFS engines find a SHORTEST counterexample: lengths must agree.
    assert len(t_vec.into_actions()) == len(t_plain.into_actions())

    dev = (
        TensorModelAdapter(SingleCopyTensor(2, 2))
        .checker()
        .spawn_tpu_bfs(chunk_size=128, queue_capacity=1 << 10, table_capacity=1 << 10)
        .join()
    )
    t_dev = dev.discovery("linearizable")
    assert t_dev is not None
    assert len(t_dev.into_actions()) == len(t_plain.into_actions())


def test_twin_engines_agree_exhaustively_at_two_servers():
    """Under an identical never-matching policy and the twin's own property
    set, all three twin engines enumerate the same space... except engines
    still stop when every property is discovered; the capacity guard never
    is, so these runs ARE exhaustive and comparable."""
    counts = []
    counts.append(
        TensorModelAdapter(SingleCopyTensor(2, 2))
        .checker()
        .finish_when(_NEVER)
        .spawn_bfs()
        .join()
        .unique_state_count()
    )
    counts.append(
        TensorModelAdapter(SingleCopyTensor(2, 2))
        .checker()
        .finish_when(_NEVER)
        .threads(4)
        .spawn_bfs()
        .join()
        .unique_state_count()
    )
    counts.append(
        TensorModelAdapter(SingleCopyTensor(2, 2))
        .checker()
        .finish_when(_NEVER)
        .spawn_tpu_bfs(
            chunk_size=128, queue_capacity=1 << 10, table_capacity=1 << 10
        )
        .join()
        .unique_state_count()
    )
    assert counts[0] == counts[1] == counts[2], counts
