"""ABD (linearizable-register) tensor twin: actor-model parity + device run.

The twin is built on the stateright_tpu.lanes toolkit; these tests are
what "the toolkit generalizes" means concretely: exact unique-count parity
with the host ActorModel (544 at c=2/s=2, linearizable-register.rs:287),
agreement between host and device engines, and the shared linearizable
lane program holding on the reachable space.
"""

import pytest

from examples.linearizable_register import abd_model
from stateright_tpu.models.abd import AbdTensor
from stateright_tpu.tensor import TensorModelAdapter


def test_twin_matches_actor_model_c1():
    host = abd_model(1, 2).checker().spawn_bfs().join()
    twin = TensorModelAdapter(AbdTensor(1)).checker().spawn_bfs().join()
    assert host.unique_state_count() == twin.unique_state_count() == 13
    assert twin.discovery("linearizable") is None
    assert twin.discovery("value chosen") is not None


def test_twin_matches_actor_model_c2_golden():
    host = abd_model(2, 2).checker().spawn_bfs().join()
    twin = TensorModelAdapter(AbdTensor(2)).checker().spawn_bfs().join()
    # linearizable-register.rs:287 golden
    assert host.unique_state_count() == twin.unique_state_count() == 544
    assert twin.discovery("linearizable") is None
    assert twin.discovery("value chosen") is not None


def test_device_engine_matches_host_c2():
    twin = (
        TensorModelAdapter(AbdTensor(2))
        .checker()
        .spawn_tpu_bfs(
            chunk_size=256, queue_capacity=1 << 13, table_capacity=1 << 12
        )
        .join()
    )
    assert twin.unique_state_count() == 544
    assert twin.discovery("linearizable") is None
    assert twin.discovery("value chosen") is not None


def test_device_finds_violation_in_mutant():
    """A mutant whose servers answer reads with None must be caught by the
    shared linearizable lane program, with a reconstructable trace."""

    class NoneReadAbd(AbdTensor):
        def deliver(self, xp, lanes, env):
            new_lanes, sends, changed = super().deliver(xp, lanes, env)
            u = xp.uint32

            def maul(m):
                is_gok = (m >> u(28)) == u(4)  # GETOK
                return xp.where(
                    is_gok, (m & ~u(0xFF0)) | (u(1) << u(4)), m
                )

            return new_lanes, [maul(s) for s in sends], changed

    twin = (
        TensorModelAdapter(NoneReadAbd(2))
        .checker()
        .spawn_tpu_bfs(
            chunk_size=256, queue_capacity=1 << 13, table_capacity=1 << 12
        )
        .join()
    )
    trace = twin.discovery("linearizable")
    assert trace is not None
    assert len(trace.into_actions()) >= 5  # write + full ABD round + read


def test_sharded_engine_matches_c2():
    import jax

    if len(jax.devices()) < 4:
        pytest.skip("needs 4 virtual devices")
    twin = (
        TensorModelAdapter(AbdTensor(2))
        .checker()
        .spawn_sharded_bfs(devices=jax.devices()[:4], chunk_size=64)
        .join()
    )
    assert twin.unique_state_count() == 544
