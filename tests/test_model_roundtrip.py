"""Fingerprint / decode_state round-trips for every bundled TensorModel.

Three invariants per model, checked over a breadth-first sample of its
own reachable rows (not just the inits — decode/fingerprint bugs live in
the corners the protocol actually reaches):

  - `decode_state` is total and deterministic over reachable rows (the
    Explorer and counterexample rendering depend on it);
  - `fingerprint_row` is stable, nonzero, and identical through the row
    (`hash_words_np`) and structure-of-arrays (`hash_lanes_np`) hash
    paths — the bit-for-bit host/device contract;
  - the adapter's `fingerprint_state` agrees with `fingerprint_row`, so
    host-oracle runs dedup exactly like device runs.
"""

from __future__ import annotations

import numpy as np
import pytest

from stateright_tpu.analysis import sample_states
from stateright_tpu.fingerprint import combine64, hash_lanes_np, hash_words_np
from stateright_tpu.models import (
    AbdOrderedTensor,
    AbdTensor,
    IncrementLockTensor,
    IncrementTensor,
    PaxosTensor,
    SingleCopyTensor,
    TwoPhaseTensor,
)
from stateright_tpu.tensor import TensorModelAdapter

TENSOR_MODELS = [
    pytest.param(lambda: IncrementTensor(2), id="increment-2"),
    pytest.param(lambda: IncrementLockTensor(2), id="increment-lock-2"),
    pytest.param(lambda: TwoPhaseTensor(3), id="2pc-3"),
    pytest.param(lambda: TwoPhaseTensor(5), id="2pc-5"),
    pytest.param(lambda: AbdTensor(2), id="abd-2"),
    pytest.param(lambda: AbdOrderedTensor(2), id="abd-ordered-2"),
    pytest.param(lambda: PaxosTensor(2), id="paxos-2"),
    pytest.param(lambda: SingleCopyTensor(2, 1), id="single-copy-2x1"),
]

SAMPLE = 160


def sampled_rows(tm) -> np.ndarray:
    adapter = TensorModelAdapter(tm)
    sample = sample_states(adapter, SAMPLE)
    assert sample.error is None, f"sampling raised: {sample.error!r}"
    assert sample.states, "no states sampled"
    return np.asarray(sample.states, dtype=np.uint32)


@pytest.mark.parametrize("mk", TENSOR_MODELS)
def test_decode_state_total_and_deterministic(mk):
    tm = mk()
    rows = sampled_rows(tm)
    for row in rows:
        d1 = tm.decode_state(row)
        d2 = tm.decode_state(row)
        assert repr(d1) == repr(d2)


@pytest.mark.parametrize("mk", TENSOR_MODELS)
def test_fingerprint_row_nonzero_stable_and_soa_identical(mk):
    tm = mk()
    rows = sampled_rows(tm)
    # Row path: per-row fingerprint_row == batched hash_words_np.
    h1, h2 = hash_words_np(rows)
    # SoA path: the lanes layout must hash bit-for-bit identically.
    l1, l2 = hash_lanes_np(tuple(rows[:, i] for i in range(rows.shape[1])))
    assert np.array_equal(h1, l1) and np.array_equal(h2, l2)
    for i, row in enumerate(rows):
        fp = tm.fingerprint_row(row)
        assert fp != 0
        assert fp == tm.fingerprint_row(row)  # stable
        assert fp == combine64(h1[i], h2[i])


@pytest.mark.parametrize("mk", TENSOR_MODELS)
def test_adapter_fingerprint_matches_row_fingerprint(mk):
    tm = mk()
    adapter = TensorModelAdapter(tm)
    rows = sampled_rows(tm)
    for row in rows:
        state = tuple(int(v) for v in row)
        assert adapter.fingerprint_state(state) == tm.fingerprint_row(row)


@pytest.mark.parametrize("mk", TENSOR_MODELS)
def test_distinct_sampled_rows_have_distinct_fingerprints(mk):
    """No pair collisions within the sample (the 64-bit pair would need
    a birthday miracle at these sizes; a collision here means a hashing
    regression, exactly the bug class round 4 fixed)."""
    tm = mk()
    rows = sampled_rows(tm)
    fps = {tm.fingerprint_row(row) for row in rows}
    assert len(fps) == len(rows)


@pytest.mark.parametrize("mk", TENSOR_MODELS)
def test_init_rows_decode_and_fingerprint(mk):
    """The init array itself round-trips (speclint STR203/STR204 ground)."""
    tm = mk()
    arr = np.asarray(tm.init_states_array(), dtype=np.uint32)
    assert arr.ndim == 2 and arr.shape[1] == tm.state_width
    for row in arr:
        tm.decode_state(row)
        assert tm.fingerprint_row(row) != 0
