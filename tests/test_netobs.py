"""Network flight recorder tests (obs/netobs.py): live per-actor metrics,
Lamport causal reconstruction, Chrome flow export, the /deployment view,
and schema v1/v2 compatibility.

Ports here live in the 43100-43199 range (test_conformance.py uses
43000-43099, test_spawn.py 42000-42020, the demos/CI 46xxx).

Cross-engine determinism uses a dedicated ping-pong pair whose every
application payload is unique: a seeded duplicate-only FaultPlan then
preserves per-socket FIFO, so the whole logical run — causal order,
counters, fault schedule — is identical across engines and across runs.
(The counter demo's idempotent re-replies emit byte-identical payloads,
which makes duplicate matching ambiguous under thread interleaving —
correct but not canonical, so it is not used for the identity test.)
"""

import collections
import json
import time
from dataclasses import dataclass

import pytest

from examples.increment import record_counter_demo
from stateright_tpu.actor import Actor, Id, Out
from stateright_tpu.conformance import FaultPlan, check_trace, load_trace
from stateright_tpu.obs.metrics import (
    NETOBS_SERIES_LABELS,
    render_prometheus,
)
from stateright_tpu.obs.netobs import (
    NetObs,
    as_netobs,
    assign_lamport,
    causal_order,
    causal_past,
    deployment_view,
    export_chrome_trace,
    flow_pairs,
    format_event,
)


def _engines():
    from stateright_tpu.native import runtime as native_runtime

    engines = ["python"]
    if native_runtime.is_available():
        engines.append("native")
    return engines


# -- deterministic ping-pong workload ----------------------------------------


@dataclass(frozen=True)
class Ping:
    n: int


@dataclass(frozen=True)
class Pong:
    n: int
    hits: int


class EchoServer(Actor):
    """Replies to every delivered Ping — including duplicates — with a
    Pong carrying a delivery counter, so every send payload is unique."""

    def name(self):
        return "EchoServer"

    def on_start(self, id: Id, out: Out):
        return 0

    def on_msg(self, id: Id, state, src: Id, msg, out: Out):
        if not isinstance(msg, Ping):
            return None
        hits = state + 1
        out.send(src, Pong(msg.n, hits))
        return hits


@dataclass(frozen=True)
class PingState:
    awaiting: int
    done: int


class PingClient(Actor):
    def __init__(self, server_id, max_ops: int):
        self.server_id = Id(server_id)
        self.max_ops = max_ops

    def name(self):
        return "PingClient"

    def on_start(self, id: Id, out: Out):
        out.set_timer("retry", (60.0, 60.0))  # never fires in-test
        out.send(self.server_id, Ping(1))
        return PingState(awaiting=1, done=0)

    def on_msg(self, id: Id, state, src: Id, msg, out: Out):
        if not isinstance(msg, Pong) or msg.n != state.awaiting:
            return None  # duplicate/stale Pong
        done = state.done + 1
        if done >= self.max_ops:
            return PingState(awaiting=0, done=done)
        out.send(self.server_id, Ping(done + 1))
        return PingState(awaiting=done + 1, done=done)

    def on_timeout(self, id: Id, state, timer, out: Out):
        out.set_timer("retry", (60.0, 60.0))
        return None


# Duplicate-only: drops would stall the chain, delay/reorder would break
# the per-socket FIFO the deterministic matching relies on.
PLAN = FaultPlan(seed=11, duplicate=0.35)
MAX_OPS = 12
PORT = 43100  # shared by every run: the plan's RNG keys embed the ports


def _record_pingpong(path, engine):
    from stateright_tpu.actor.spawn import (
        json_serializer,
        make_json_deserializer,
        spawn,
    )

    ids = [Id.from_addr("127.0.0.1", PORT + i) for i in range(2)]
    actors = [
        (ids[0], EchoServer()),
        (ids[1], PingClient(ids[0], max_ops=MAX_OPS)),
    ]
    nob = NetObs()
    handle = spawn(
        json_serializer,
        make_json_deserializer(Ping, Pong),
        actors,
        background=True,
        engine=engine,
        record=str(path),
        faults=PLAN,
        netobs=nob,
    )
    deadline = time.monotonic() + 8.0
    while time.monotonic() < deadline:
        if getattr(handle.state(ids[1]), "done", 0) >= MAX_OPS:
            break
        time.sleep(0.01)
    time.sleep(0.2)  # let straggler duplicates land
    handle.shutdown()
    return nob.snapshot()


def _canonical(events):
    """The engine-independent projection: causal order with the causal
    fields only (wall-clock ts/dur excluded)."""
    return [
        (
            ev["lc"],
            ev["actor"],
            ev["seq"],
            ev["kind"],
            tuple(ev.get("sent_by") or ()),
            bool(ev.get("redelivery")),
            json.dumps(ev.get("msg"), sort_keys=True),
        )
        for ev in causal_order(events)
    ]


def _counterish(snapshot):
    return {
        k: v
        for k, v in snapshot.items()
        if k.startswith(("actor_", "fault_", "net_"))
        and not k.endswith("_secs")
    }


@pytest.fixture(scope="module")
def engine_runs(tmp_path_factory):
    """One seeded faulted ping-pong run per available engine, plus a
    second python run for run-to-run determinism."""
    tmp = tmp_path_factory.mktemp("netobs")
    runs = {}
    for tag, engine in [("python", "python"), ("python2", "python")] + [
        (e, e) for e in _engines() if e != "python"
    ]:
        path = tmp / f"{tag}.jsonl"
        snapshot = _record_pingpong(path, engine)
        meta, events = load_trace(str(path))
        runs[tag] = (str(path), meta, events, snapshot)
    return runs


def test_causal_order_identical_across_engines_and_runs(engine_runs):
    _, _, base_events, base_snap = engine_runs["python"]
    base = _canonical(base_events)
    assert len(base) > 2 * MAX_OPS  # the run actually did work
    for tag, (_, _, events, snapshot) in engine_runs.items():
        assert _canonical(events) == base, f"{tag} causal order differs"
        assert _counterish(snapshot) == _counterish(base_snap), (
            f"{tag} counters differ"
        )


def test_fault_counters_match_trace_fault_lines(engine_runs):
    for tag, (_, _, events, snapshot) in engine_runs.items():
        recorded = collections.Counter(
            ev["fault"] for ev in events if ev["kind"] == "fault"
        )
        assert dict(recorded) == snapshot.get("fault_injected", {}), tag
        assert recorded, "the seeded plan injected no faults"


def test_recorded_stamps_equal_offline_reconstruction(engine_runs):
    """The recorder's live v2 stamps are exactly what assign_lamport
    recomputes offline — one matching discipline, two implementations."""
    for tag, (_, _, events, _snap) in engine_runs.items():
        recomputed = assign_lamport(events)
        for orig, new in zip(events, recomputed):
            assert orig.get("lc") == new.get("lc"), tag
            assert orig.get("sent_by") == new.get("sent_by"), tag
            assert bool(orig.get("redelivery")) == bool(
                new.get("redelivery")
            ), tag


def test_meta_carries_schema_v2_and_plan(engine_runs):
    path, meta, _events, _snap = engine_runs["python"]
    assert meta["v"] == 2
    assert meta["faults"]["seed"] == PLAN.seed
    assert meta["faults"]["duplicate"] == PLAN.duplicate
    plan = FaultPlan.from_meta(meta)
    assert plan == PLAN


def test_fault_lines_carry_replayable_seed_keys(engine_runs):
    """record_fault's seed_key + the meta plan replay the schedule from
    the trace alone: decide() on the recorded link/seq reproduces the
    recorded fault kind."""
    _path, meta, events, _snap = engine_runs["python"]
    plan = FaultPlan.from_meta(meta)
    roster = {entry["index"]: entry for entry in meta["actors"]}

    def id_of(index):
        ip, _, port = roster[index]["addr"].partition(":")
        return int(Id.from_addr(ip, int(port)))

    faults = [ev for ev in events if ev["kind"] == "fault"]
    assert faults
    for ev in faults:
        src, dst = id_of(ev["actor"]), id_of(ev["dst"])
        assert ev["seed_key"] == f"{plan.seed}|{src}|{dst}|{ev['link_seq']}"
        assert plan.decide(src, dst, ev["link_seq"]).kind == ev["fault"]


# -- chrome flow export -------------------------------------------------------


def test_chrome_flow_events_pair_exactly(tmp_path):
    """Every ``s`` has its ``f``; each pair is one matched transmission;
    drops contribute none. Uses a droppy 2-client counter run so all
    fault kinds appear."""
    path = tmp_path / "droppy.jsonl"
    record_counter_demo(
        str(path), duration=0.8, client_count=2, seed=7,
        engine="python", base_port=43110,
    )
    meta, events = load_trace(str(path))
    out = tmp_path / "trace.chrome.json"
    pair_count = export_chrome_trace((meta, events), str(out))

    records = json.loads(out.read_text())
    starts = [r for r in records if r.get("ph") == "s"]
    finishes = [r for r in records if r.get("ph") == "f"]
    assert len(starts) == pair_count == len(finishes)
    assert {r["id"] for r in starts} == {r["id"] for r in finishes}
    # Exact accounting: one pair per deliver that matched a send.
    matched = [
        ev for ev in assign_lamport(events)
        if ev["kind"] == "deliver" and "sent_by" in ev
    ]
    assert pair_count == len(matched) == len(flow_pairs(events))
    # Per-actor metadata lanes and handler slices exist.
    lanes = [r for r in records if r.get("name") == "thread_name"]
    assert len(lanes) == len(meta["actors"])
    assert any(r.get("ph") == "X" for r in records)
    assert any(
        r.get("ph") == "i" and r.get("cat") == "fault" for r in records
    )


def test_dropped_transmissions_never_pair(tmp_path):
    path = tmp_path / "dropsonly.jsonl"
    record_counter_demo(
        str(path), duration=0.6, client_count=1, engine="python",
        base_port=43114, plan=FaultPlan(seed=3, drop=0.4),
        retry_range=(0.05, 0.08),
    )
    meta, events = load_trace(str(path))
    drops = sum(
        1 for ev in events
        if ev["kind"] == "fault" and ev["fault"] == "drop"
    )
    sends = sum(1 for ev in events if ev["kind"] == "send")
    assert drops > 0
    # Every pair consumes a distinct send; dropped sends never appear.
    pairs = flow_pairs(events)
    fresh = [p for p in pairs if not p[1].get("redelivery")]
    assert len(fresh) <= sends - drops


# -- live metrics / prometheus ------------------------------------------------


def test_labeled_prometheus_series(engine_runs):
    _path, _meta, _events, snapshot = engine_runs["python"]
    text = render_prometheus(snapshot, labels=NETOBS_SERIES_LABELS)
    assert 'stateright_actor_messages_sent{actor="1"}' in text
    assert 'stateright_actor_messages_delivered{actor="0"}' in text
    assert 'stateright_fault_injected{kind="duplicate"}' in text
    assert "stateright_handler_duration_secs_count" in text
    assert "stateright_delivery_latency_secs_count" in text
    assert 'stateright_engine_info{engine="python"}' in text


def test_netobs_gauges_and_histograms(engine_runs):
    _path, _meta, _events, snapshot = engine_runs["python"]
    assert snapshot["deployment_actors"] == 2
    assert snapshot["net_transmissions"] >= 2 * MAX_OPS
    assert snapshot["net_in_flight"] >= 0
    hists = snapshot["histograms"]
    assert hists["handler_duration_secs"]["count"] > 0
    assert hists["delivery_latency_secs"]["count"] > 0
    # timer_set counted per actor (the client arms its retry timer).
    assert snapshot["actor_timer_set"]["1"] >= 1


def test_as_netobs_normalization():
    nob = NetObs()
    assert as_netobs(nob) is nob
    assert as_netobs(False) is None
    assert as_netobs(False, default=True) is None
    assert isinstance(as_netobs(True), NetObs)
    assert as_netobs(None) is None
    assert isinstance(as_netobs(None, default=True), NetObs)
    with pytest.raises(TypeError):
        as_netobs("yes")


# -- causal past / divergence forensics ---------------------------------------


def test_causal_past_walks_happened_before(engine_runs):
    _path, _meta, events, _snap = engine_runs["python"]
    # The last deliver on the client: its past must include the server's
    # send that caused it, and every entry happened-before it.
    target = [
        ev for ev in assign_lamport(events)
        if ev["kind"] == "deliver" and ev["actor"] == 1
    ][-1]
    past = causal_past(events, target["actor"], target["seq"], k=6)
    assert 0 < len(past) <= 6
    assert all(ev["lc"] <= target["lc"] for ev in past)
    sent_by = tuple(target["sent_by"])
    assert any((ev["actor"], ev["seq"]) == sent_by for ev in past)
    # And renders as one line per event.
    lines = [format_event(ev) for ev in past]
    assert all(line.startswith("lc=") for line in lines)


def test_divergence_report_carries_causal_past(tmp_path):
    path = tmp_path / "mutated.jsonl"
    record_counter_demo(
        str(path), duration=0.6, client_count=2, seed=7,
        engine="python", base_port=43116,
    )
    meta, events = load_trace(str(path))
    mutated = False
    for ev in events:
        if (
            not mutated
            and ev.get("kind") == "deliver"
            and ev.get("seq", 0) > 2
            and isinstance(ev.get("state"), list)
            and ev["state"][0] == "CounterState"
        ):
            ev["state"][1] += 100
            mutated = True
    assert mutated
    from examples.increment import Bump, BumpOk, counter_model
    from stateright_tpu.actor import Network
    from stateright_tpu.conformance import make_decoder

    report = check_trace(
        counter_model(2, Network.new_unordered_duplicating()),
        (meta, events),
        decode=make_decoder(Bump, BumpOk),
    )
    assert not report.ok
    d = report.divergences[0]
    assert d.kind == "state-mismatch"
    assert d.causal_past, "divergence carries no causal past"
    assert all(line.startswith("lc=") for line in d.causal_past)
    rendered = d.format()
    assert "causal past" in rendered
    # The causal past rides along the json report too.
    assert report.to_dict()["divergences"][0]["causal_past"]


def test_check_trace_emits_labeled_fault_kind_counters(tmp_path):
    from examples.increment import conform_counter_trace
    from stateright_tpu.obs.metrics import MetricsRegistry

    path = tmp_path / "faulty.jsonl"
    record_counter_demo(
        str(path), duration=0.6, client_count=2, seed=7,
        engine="python", base_port=43118,
    )
    _meta, events = load_trace(str(path))
    recorded = collections.Counter(
        ev["fault"] for ev in events if ev["kind"] == "fault"
    )
    metrics = MetricsRegistry()
    report, _tester = conform_counter_trace(str(path), metrics=metrics)
    snap = metrics.snapshot()
    # conformance_* counters reconcile exactly against the fault lines.
    assert snap["conformance_faults"] == sum(recorded.values())
    assert snap["conformance_fault_kinds"] == dict(recorded)
    assert report.faults == sum(recorded.values())


# -- schema v1 back-compat ----------------------------------------------------


def test_v1_trace_still_loads_and_checks(tmp_path):
    path = tmp_path / "v2.jsonl"
    record_counter_demo(
        str(path), duration=0.5, client_count=1, seed=7,
        engine="python", base_port=43120,
    )
    v1 = tmp_path / "v1.jsonl"
    with open(path) as src, open(v1, "w") as dst:
        for line in src:
            ev = json.loads(line)
            if ev.get("kind") == "meta":
                ev.pop("v", None)
                ev.pop("faults", None)
            for key in ("lc", "sent_by", "redelivery", "dur", "seed_key"):
                ev.pop(key, None)
            dst.write(json.dumps(ev) + "\n")

    meta, events = load_trace(str(v1))
    assert "v" not in meta
    assert all("lc" not in ev for ev in events)
    # The reconstructor backfills stamps; the checker still runs.
    order = causal_order(events)
    assert order and all("lc" in ev for ev in order)
    from examples.increment import conform_counter_trace

    report, _tester = conform_counter_trace(str(v1))
    assert report.ok, report.format()
    with pytest.raises(ValueError):
        FaultPlan.from_meta(meta)


# -- deployment view ----------------------------------------------------------


def test_deployment_view_topology_and_tail(engine_runs):
    path, meta, events, _snap = engine_runs["python"]
    view = deployment_view(trace_path=path, tail=10)
    assert view["v"] == 2
    assert view["engine"] == "python"
    assert view["faults_plan"]["seed"] == PLAN.seed
    assert [a["actor"] for a in view["actors"]] == [
        "EchoServer", "PingClient",
    ]
    assert view["actors"][1]["sent"] >= MAX_OPS
    edges = {(e["src"], e["dst"]): e for e in view["edges"]}
    assert edges[(1, 0)]["sent"] >= MAX_OPS
    assert edges[(0, 1)]["delivered"] >= MAX_OPS
    total_faults = sum(
        sum(e["faults"].values()) for e in view["edges"]
    )
    assert total_faults == sum(
        1 for ev in events if ev["kind"] == "fault"
    )
    assert len(view["tail"]) == 10
    assert all(isinstance(line, str) for line in view["tail"])


def test_deployment_view_requires_a_source():
    with pytest.raises(KeyError):
        deployment_view()


def test_deployment_view_merges_live_telemetry(engine_runs):
    path, _meta, _events, _snap = engine_runs["python"]

    class FakeHandle:
        def telemetry(self):
            return {"net_transmissions": 42}

    view = deployment_view(trace_path=path, handle=FakeHandle())
    assert view["telemetry"]["net_transmissions"] == 42
    assert view["actors"]


def test_explorer_serves_deployment(engine_runs, tmp_path):
    import urllib.error
    import urllib.request

    from examples.increment import counter_model
    from stateright_tpu.explorer.server import serve

    path, _meta, _events, _snap = engine_runs["python"]
    server = serve(
        counter_model(1).checker(), "127.0.0.1:0", block=False, trace=path
    )
    try:
        base = server.url.rstrip("/")
        body = json.loads(
            urllib.request.urlopen(base + "/deployment?tail=5").read()
        )
        assert body["actors"] and body["edges"]
        assert len(body["tail"]) == 5
    finally:
        server.shutdown()


def test_explorer_deployment_404_without_trace():
    import urllib.error
    import urllib.request

    from examples.increment import counter_model
    from stateright_tpu.explorer.server import serve

    server = serve(counter_model(1).checker(), "127.0.0.1:0", block=False)
    try:
        base = server.url.rstrip("/")
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(base + "/deployment")
        assert err.value.code == 404
    finally:
        server.shutdown()


# -- timers base-port footgun -------------------------------------------------


def test_timers_demo_rejects_odd_base_port(tmp_path):
    from examples.timers import record_timers_demo, spawn_info

    with pytest.raises(ValueError, match="must be even"):
        record_timers_demo(str(tmp_path / "t.jsonl"), base_port=43131)
    with pytest.raises(ValueError, match="must be even"):
        spawn_info(record=str(tmp_path / "t2.jsonl"), base_port=43133)


def test_timers_spawn_info_accepts_even_base_port(tmp_path):
    from examples.timers import conform_timers_trace, spawn_info

    path = tmp_path / "timers.jsonl"
    spawn_info(record=str(path), duration=0.2, base_port=43140)
    report, _none = conform_timers_trace(str(path))
    assert report.ok, report.format()
