"""speclint (stateright_tpu.analysis): each rule family must flag its
deliberately broken model, and every bundled example model must lint
clean (the dogfood test the CI contract hangs off)."""

from __future__ import annotations

import random
from typing import List

import numpy as np
import pytest

from stateright_tpu import SpecLintError, analyze
from stateright_tpu.analysis import AnalysisReport, Severity
from stateright_tpu.core import Model, Property
from stateright_tpu.tensor import TensorModel, TensorModelAdapter, TensorProperty


def codes(report: AnalysisReport) -> set:
    return {d.code for d in report.diagnostics}


def error_codes(report: AnalysisReport) -> set:
    return {d.code for d in report.errors}


# ---------------------------------------------------------------------------
# Broken-model fixtures, one per rule family.
# ---------------------------------------------------------------------------


class RngActionsModel(Model):
    """STR101: hidden RNG in `actions` (the classic corruption source)."""

    def init_states(self):
        return [0]

    def actions(self, state, actions: List) -> None:
        actions.append(random.randint(0, 1 << 30))

    def next_state(self, state, action):
        return (state + action) % 97 if state < 50 else None

    def properties(self):
        return [Property.always("true", lambda _m, _s: True)]


class MutatingModel(Model):
    """STR103: `next_state` edits its input state in place."""

    def init_states(self):
        return [[0, 0]]

    def actions(self, state, actions: List) -> None:
        if state[0] < 3:
            actions.append(1)

    def next_state(self, state, action):
        state[0] += action  # the bug: successor built by editing the input
        return [state[0], state[1]]

    def properties(self):
        return [Property.always("true", lambda _m, _s: True)]


class RngNextStateModel(Model):
    """STR102: `next_state` flips a hidden coin."""

    def init_states(self):
        return [0]

    def actions(self, state, actions: List) -> None:
        if state < 5:
            actions.append("go")

    def next_state(self, state, action):
        return state + random.choice([1, 2])

    def properties(self):
        return []


class UnfingerprintableModel(Model):
    """STR104: states the canonical serializer cannot encode."""

    class Opaque:
        pass

    def init_states(self):
        return [self.Opaque()]

    def actions(self, state, actions: List) -> None:
        pass

    def next_state(self, state, action):
        return None


class OverflowPackTensor(TensorModel):
    """STR207: successor values overflow the uint32 lane packing (numpy
    promotes to int64 and keeps the wide value; the device engine's cast
    would silently truncate)."""

    state_width = 1
    max_actions = 1

    def init_states_array(self) -> np.ndarray:
        return np.asarray([[0x90000000]], dtype=np.uint32)

    def step_lanes(self, xp, lanes):
        # The bug: arithmetic in a wide off-lane dtype; the wide values
        # exceed the uint32 packing and the device cast truncates them.
        nxt = lanes[0].astype(xp.int64) * 3 + 1
        return [(nxt,)], [lanes[0] >= 0]

    def tensor_properties(self):
        return [TensorProperty.always("true", lambda xp, l: l[0] == l[0])]


class UntraceableTensor(TensorModel):
    """STR201: data-dependent Python control flow in `step_lanes`."""

    state_width = 1
    max_actions = 1

    def init_states_array(self) -> np.ndarray:
        return np.zeros((1, 1), dtype=np.uint32)

    def step_lanes(self, xp, lanes):
        u = xp.uint32
        if lanes[0][0] > 5:  # the bug: concrete branch on a traced value
            nxt = lanes[0] - u(1)
        else:
            nxt = lanes[0] + u(1)
        return [(nxt,)], [lanes[0] < u(10)]

    def tensor_properties(self):
        return [TensorProperty.always("true", lambda xp, l: l[0] == l[0])]


class BadMaskTensor(TensorModel):
    """STR202: validity masks with the wrong dtype/shape."""

    state_width = 1
    max_actions = 1

    def init_states_array(self) -> np.ndarray:
        return np.zeros((1, 1), dtype=np.uint32)

    def step_lanes(self, xp, lanes):
        u = xp.uint32
        nxt = (lanes[0] + u(1)) & u(7)
        return [(nxt,)], [(lanes[0] < u(8)).astype(xp.uint32)]  # not bool

    def tensor_properties(self):
        return []


class BadDecodeTensor(TensorModel):
    """STR204: `decode_state` crashes on reachable rows."""

    state_width = 1
    max_actions = 1

    def init_states_array(self) -> np.ndarray:
        return np.zeros((1, 1), dtype=np.uint32)

    def step_lanes(self, xp, lanes):
        u = xp.uint32
        return [((lanes[0] + u(1)) & u(3),)], [lanes[0] == lanes[0]]

    def tensor_properties(self):
        return []

    def decode_state(self, row):
        return {0: "zero"}[int(row[0])]  # KeyError beyond the first row


class DupPropsModel(Model):
    """STR301: two properties sharing one name shadow each other."""

    def init_states(self):
        return [0]

    def actions(self, state, actions: List) -> None:
        if state < 3:
            actions.append(1)

    def next_state(self, state, action):
        return state + action

    def properties(self):
        return [
            Property.always("safe", lambda _m, s: s < 10),
            Property.sometimes("safe", lambda _m, s: s > 1),
        ]


class RaisingPropModel(Model):
    """STR302: a predicate that raises mid-search."""

    def init_states(self):
        return [0]

    def actions(self, state, actions: List) -> None:
        if state < 5:
            actions.append(1)

    def next_state(self, state, action):
        return state + action

    def properties(self):
        return [Property.always("broken", lambda _m, s: 1 // max(0, 2 - s) >= 0)]


class NonIdempotentRepState:
    """rep() rotates instead of sorting: rep(rep(s)) != rep(s)."""

    def __init__(self, items):
        self.items = tuple(items)

    def representative(self) -> "NonIdempotentRepState":
        return NonIdempotentRepState(self.items[1:] + self.items[:1])

    def fingerprint_key(self):
        return self.items

    def __repr__(self):
        return f"S{self.items}"


class NonIdempotentRepModel(Model):
    """STR402: canonicalization that never reaches a fixed point."""

    def init_states(self):
        return [NonIdempotentRepState((2, 0, 1))]

    def actions(self, state, actions: List) -> None:
        pass

    def next_state(self, state, action):
        return None

    def properties(self):
        return [Property.always("true", lambda _m, _s: True)]


class PropChangingRepState:
    def __init__(self, x):
        self.x = x

    def representative(self):
        return PropChangingRepState(0)  # collapses EVERYTHING to one class

    def fingerprint_key(self):
        return self.x

    def __repr__(self):
        return f"P({self.x})"


class PropChangingRepModel(Model):
    """STR403: the 'representative' changes property verdicts."""

    def init_states(self):
        return [PropChangingRepState(1)]

    def actions(self, state, actions: List) -> None:
        if state.x < 4:
            actions.append(1)

    def next_state(self, state, action):
        return PropChangingRepState(state.x + action)

    def properties(self):
        return [Property.always("positive", lambda _m, s: s.x > 0)]


class DivergentRepTensor(TensorModel):
    """STR404: representative_lanes differs between numpy and jax
    (int64 promotion under numpy vs uint32 wraparound under jax)."""

    state_width = 1
    max_actions = 1

    def init_states_array(self) -> np.ndarray:
        return np.asarray([[0xF0000000]], dtype=np.uint32)

    def step_lanes(self, xp, lanes):
        u = xp.uint32
        return [((lanes[0] ^ u(1)),)], [lanes[0] == lanes[0]]

    def tensor_properties(self):
        return []

    def representative_lanes(self, xp, lanes):
        # Wide-dtype canonicalization: numpy int64 keeps the full product,
        # jax (x64 disabled) truncates to int32 — host and device
        # canonicalize into different quotients.
        wide = lanes[0].astype(xp.int64)
        return (((wide * 5) % 4093).astype(xp.uint32),)


# ---------------------------------------------------------------------------
# Family 1: determinism / purity
# ---------------------------------------------------------------------------


def test_rng_in_actions_flagged():
    random.seed(0xC0FFEE)  # see test_rng_in_next_state_flagged
    report = analyze(RngActionsModel())
    assert "STR101" in error_codes(report)


def test_mutating_next_state_flagged():
    report = analyze(MutatingModel())
    assert "STR103" in error_codes(report)


def test_rng_in_next_state_flagged():
    # Pin the GLOBAL random stream the fixture draws from: next_state
    # replays agree with probability 1/2 per pair, so an arbitrary stream
    # position (set by whatever tests ran before) can let the
    # nondeterminism slip through a small sample by sheer luck. Seeding
    # makes the detection draw deterministic and test-order-independent.
    random.seed(0xC0FFEE)
    report = analyze(RngNextStateModel())
    assert error_codes(report) & {"STR102", "STR101"}


def test_unfingerprintable_state_flagged():
    report = analyze(UnfingerprintableModel())
    assert "STR104" in error_codes(report)


# ---------------------------------------------------------------------------
# Family 2: device compatibility
# ---------------------------------------------------------------------------


def test_overflowing_field_pack_flagged():
    report = analyze(OverflowPackTensor())
    assert "STR207" in error_codes(report)


def test_untraceable_step_lanes_flagged():
    report = analyze(UntraceableTensor())
    assert "STR201" in error_codes(report)


def test_bad_mask_dtype_flagged():
    report = analyze(BadMaskTensor())
    assert "STR202" in error_codes(report)


def test_bad_decode_state_flagged():
    report = analyze(BadDecodeTensor())
    assert "STR204" in error_codes(report)


# ---------------------------------------------------------------------------
# Family 3: property well-formedness
# ---------------------------------------------------------------------------


def test_duplicate_property_names_flagged():
    report = analyze(DupPropsModel())
    assert "STR301" in error_codes(report)


def test_raising_predicate_flagged():
    report = analyze(RaisingPropModel())
    assert "STR302" in error_codes(report)


def test_no_properties_warns():
    class NoProps(Model):
        def init_states(self):
            return [0]

        def actions(self, state, actions):
            pass

        def next_state(self, state, action):
            return None

    report = analyze(NoProps())
    assert "STR305" in codes(report)
    assert report.ok  # warning, not error


# ---------------------------------------------------------------------------
# Family 4: symmetry soundness
# ---------------------------------------------------------------------------


def test_non_idempotent_representative_flagged():
    report = analyze(NonIdempotentRepModel())
    assert "STR402" in error_codes(report)


def test_property_changing_representative_flagged():
    report = analyze(PropChangingRepModel())
    assert "STR403" in error_codes(report)


def test_divergent_representative_lanes_flagged():
    report = analyze(DivergentRepTensor())
    assert error_codes(report) & {"STR404", "STR402"}


# ---------------------------------------------------------------------------
# Dogfood: every bundled example model lints clean (zero errors).
# ---------------------------------------------------------------------------

BUNDLED_MODELS = [
    pytest.param(lambda: __import__("stateright_tpu.models", fromlist=[n]).__dict__[n](*args), id=f"{n}{args}")
    for n, args in [
        ("Increment", (2,)),
        ("IncrementTensor", (2,)),
        ("IncrementLock", (2,)),
        ("IncrementLockTensor", (2,)),
        ("TwoPhaseSys", (3,)),
        ("TwoPhaseTensor", (3,)),
        ("AbdTensor", (2,)),
        ("AbdOrderedTensor", (2,)),
        ("PaxosTensor", (2,)),
        ("SingleCopyTensor", (2, 1)),
    ]
]


@pytest.mark.parametrize("mk", BUNDLED_MODELS)
def test_bundled_models_lint_clean(mk):
    model = mk()
    report = analyze(model, samples=96)
    assert report.ok, report.format()


# ---------------------------------------------------------------------------
# Wire-in: builder.lint / strict mode / telemetry / CLI
# ---------------------------------------------------------------------------


def test_builder_lint_and_telemetry():
    from stateright_tpu.models import IncrementTensor

    builder = TensorModelAdapter(IncrementTensor(2)).checker()
    report = builder.lint(samples=64)
    assert report.ok
    checker = builder.spawn_bfs().join()
    t = checker.telemetry()
    assert t["lint_errors"] == 0
    assert checker.unique_state_count() == 13


def test_strict_mode_refuses_broken_model():
    with pytest.raises(SpecLintError) as exc:
        RngActionsModel().checker().strict().spawn_bfs()
    assert "STR101" in str(exc.value)


def test_strict_mode_launches_clean_model():
    from stateright_tpu.models import IncrementTensor

    checker = (
        TensorModelAdapter(IncrementTensor(2))
        .checker()
        .strict()
        .spawn_bfs()
        .join()
    )
    assert checker.unique_state_count() == 13
    assert checker.telemetry()["lint_errors"] == 0


def test_strict_mode_refuses_device_engine_launch():
    """The pre-flight guards the DEVICE engines too (that is its point:
    a shape bug otherwise surfaces inside a jitted program)."""
    adapter = TensorModelAdapter(OverflowPackTensor())
    with pytest.raises(SpecLintError):
        adapter.checker().strict().spawn_tpu_bfs(
            chunk_size=64, queue_capacity=1 << 10, table_capacity=1 << 10
        )


def test_cli_main_clean_and_broken(capsys):
    from stateright_tpu.analysis.__main__ import main

    assert main(["increment:2", "--samples", "64"]) == 0
    out = capsys.readouterr().out
    assert "IncrementTensor" in out

    assert main(["tests.test_speclint:DupPropsModel", "--json"]) == 1
    out = capsys.readouterr().out
    assert "STR301" in out


def test_report_format_and_dict_round_trip():
    report = analyze(DupPropsModel())
    d = report.to_dict()
    assert d["ok"] is False
    assert d["counts_by_code"].get("STR301", 0) >= 1
    assert "STR301" in report.format()
    assert any(x["severity"] == "error" for x in d["diagnostics"])


def test_severity_partition():
    report = analyze(MutatingModel())
    assert not report.ok
    for d in report.errors:
        assert d.severity is Severity.ERROR
