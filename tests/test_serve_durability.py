"""Serve-layer durability: journal-backed restart recovery, transparent
retry of transient failures, circuit-breaker fast-fail, worker-crash
replacement, and the admin retry endpoint (ISSUE 9 tentpole b).

Restart tests build a service, kill it (shutdown -- equivalent to a
crash AFTER the relevant journal appends, which are fsynced before any
state change is acknowledged), and assert a fresh service on the same
journal loses nothing and duplicates nothing.
"""

import json
import time
import urllib.error
import urllib.request

import pytest

from stateright_tpu.serve.durability import (
    CircuitBreaker,
    JobJournal,
    RetryPolicy,
)
from stateright_tpu.serve.service import RunService

_FAST_RETRY = RetryPolicy(base_delay=0.01, max_delay=0.05, max_attempts=3)


def _wait(svc, job_id, timeout=60.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        job = svc.job(job_id)
        if job is not None and job.status in ("done", "failed", "cancelled"):
            return job
        time.sleep(0.01)
    state = svc.job(job_id).view() if svc.job(job_id) else None
    raise AssertionError(f"timeout waiting on job {job_id}: {state}")


def _svc(tmp_path, **kw):
    kw.setdefault("workers", 1)
    kw.setdefault("journal_path", str(tmp_path / "journal.jsonl"))
    kw.setdefault("results_dir", str(tmp_path / "results"))
    kw.setdefault("retry", _FAST_RETRY)
    kw.setdefault("guard_interval", 0.05)
    return RunService(**kw)


# ---------------------------------------------------------------------------
# Restart recovery from the journal
# ---------------------------------------------------------------------------


def test_restart_recovers_queued_jobs(tmp_path):
    svc = _svc(tmp_path)
    svc.pause()
    ids = []
    for _ in range(3):
        code, body = svc.submit({"spec": "increment:2", "engine": "bfs"})
        assert code == 202
        ids.append(body["job_id"])
    svc.shutdown()  # killed with everything still queued

    svc2 = _svc(tmp_path)
    try:
        # Every queued job re-enqueued, nothing lost, nothing duplicated.
        assert len(svc2.jobs()) == 3
        for jid in ids:
            job = _wait(svc2, jid)
            assert job.status == "done", job.error
            assert job.result["unique_state_count"] == 13
        assert svc2.telemetry().get("journal_recovered_queued") == 3
    finally:
        svc2.shutdown()


def test_restart_retries_interrupted_running_job(tmp_path):
    # Forge the journal of a service killed MID-JOB: a start record with
    # no result record after it.
    path = str(tmp_path / "journal.jsonl")
    j = JobJournal(path)
    j.submit({"id": "deadbeef0001", "tenant": "t", "spec": "increment:2",
              "engine": "bfs", "priority": 0, "options": {},
              "submitted_at": time.time()})
    j.start("deadbeef0001", 1)
    j.close()

    svc = _svc(tmp_path)
    try:
        job = _wait(svc, "deadbeef0001")
        assert job.status == "done", job.error
        assert job.result["unique_state_count"] == 13
        # First attempt died with the old process; this was the second.
        assert job.attempts == 2
        assert svc.telemetry().get("journal_recovered_running") == 1
    finally:
        svc.shutdown()


def test_restart_serves_finished_results_without_rerunning(tmp_path):
    svc = _svc(tmp_path)
    code, body = svc.submit({"spec": "increment:2", "engine": "bfs"})
    assert code == 202
    done = _wait(svc, body["job_id"])
    assert done.status == "done"
    result = done.result
    svc.shutdown()

    svc2 = _svc(tmp_path)
    try:
        job = svc2.job(body["job_id"])
        assert job is not None and job.status == "done"
        # The persisted payload IS the wire form (one JSON roundtrip:
        # int coverage-histogram keys become strings, as over HTTP).
        assert job.result == json.loads(json.dumps(result))
        assert svc2.telemetry().get("journal_recovered_done") == 1
        assert svc2.telemetry().get("serve_completed", 0) == 0
    finally:
        svc2.shutdown()


def test_restart_fails_unresolvable_spec_loudly(tmp_path):
    path = str(tmp_path / "journal.jsonl")
    j = JobJournal(path)
    j.submit({"id": "feedface0001", "tenant": "t", "spec": "no-such:9",
              "engine": "bfs", "priority": 0, "options": {},
              "submitted_at": time.time()})
    j.close()
    svc = _svc(tmp_path)
    try:
        job = svc.job("feedface0001")
        assert job.status == "failed"
        assert "unresolvable after restart" in job.error
    finally:
        svc.shutdown()


# ---------------------------------------------------------------------------
# Transparent retry + escalation
# ---------------------------------------------------------------------------


def test_transient_failure_retries_transparently(tmp_path):
    svc = _svc(tmp_path)
    orig = svc._run_solo
    blown = []

    def flaky(job):
        if not blown:
            blown.append(job.id)
            raise RuntimeError(
                "visited-table probe budget exhausted despite headroom"
            )
        orig(job)

    svc._run_solo = flaky
    try:
        code, body = svc.submit({"spec": "increment:2", "engine": "bfs"})
        assert code == 202
        job = _wait(svc, body["job_id"])
        # The client sees success; the failure existed only in telemetry.
        assert job.status == "done", job.error
        assert job.attempts == 2
        assert blown == [job.id]
        tel = svc.telemetry()
        assert tel.get("retry_scheduled") == 1
        assert tel.get("serve_failed", 0) == 0
    finally:
        svc.shutdown()


def test_lane_budget_failure_escalates_to_solo_engine(tmp_path):
    svc = _svc(tmp_path)

    def lane_wall(jobs):
        raise RuntimeError(
            "lane 0 did not complete within the lane budget (frontier=9, "
            "unique=65000); raise queue_capacity/table_capacity or run it "
            "solo via spawn_tpu_bfs"
        )

    svc._run_multiplex_batch = lane_wall
    try:
        code, body = svc.submit({"spec": "increment:2"})  # auto -> multiplex
        assert code == 202
        job = _wait(svc, body["job_id"])
        assert job.status == "done", job.error
        assert job.engine == "tpu_bfs"  # escalated off the lane shape
        assert job.result["unique_state_count"] == 13
        assert svc.telemetry().get("retry_escalated_solo") == 1
    finally:
        svc.shutdown()


def test_permanent_failure_exhausts_attempts(tmp_path):
    svc = _svc(tmp_path)

    def wall(job):
        raise RuntimeError(
            "visited-table probe budget exhausted despite headroom"
        )

    svc._run_solo = wall
    try:
        code, body = svc.submit({"spec": "increment:2", "engine": "bfs"})
        job = _wait(svc, body["job_id"])
        assert job.status == "failed"
        assert "probe budget" in job.error
        assert job.attempts == _FAST_RETRY.max_attempts
        assert svc.telemetry().get("retry_exhausted") == 1
    finally:
        svc.shutdown()


def test_non_transient_failure_does_not_retry(tmp_path):
    svc = _svc(tmp_path)

    def bug(job):
        raise AssertionError("model invariant violated in expand()")

    svc._run_solo = bug
    try:
        code, body = svc.submit({"spec": "increment:2", "engine": "bfs"})
        job = _wait(svc, body["job_id"])
        assert job.status == "failed"
        assert job.attempts == 1  # no retries for deterministic bugs
        assert svc.telemetry().get("retry_scheduled", 0) == 0
    finally:
        svc.shutdown()


# ---------------------------------------------------------------------------
# Circuit breaker + worker crash replacement
# ---------------------------------------------------------------------------


def test_breaker_fast_fails_repeated_failures(tmp_path):
    svc = _svc(
        tmp_path,
        breaker=CircuitBreaker(threshold=1, cooldown=3600.0),
    )

    def bug(job):
        raise AssertionError("model invariant violated")

    svc._run_solo = bug
    try:
        svc.pause()
        code, b1 = svc.submit({"spec": "increment:2", "engine": "bfs"})
        code, b2 = svc.submit({"spec": "increment:2", "engine": "bfs"})
        svc.resume()
        j1 = _wait(svc, b1["job_id"])
        j2 = _wait(svc, b2["job_id"])
        assert j1.status == "failed" and "invariant" in j1.error
        assert j2.status == "failed" and "circuit breaker open" in j2.error
        assert svc.telemetry().get("serve_breaker_fastfail") == 1
        assert svc.stats()["breaker"]["open_keys"]  # visible in /stats
    finally:
        svc.shutdown()


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning"
)
def test_dead_worker_thread_is_replaced(tmp_path):
    svc = _svc(tmp_path)
    orig = svc._pop_batch
    crashed = []

    def explode():
        if not crashed:
            crashed.append(1)
            raise SystemError("synthetic worker crash in the pop path")
        return orig()

    svc._pop_batch = explode
    try:
        code, body = svc.submit({"spec": "increment:2", "engine": "bfs"})
        assert code == 202
        # The sole worker dies popping; the guard must replace it or this
        # job would hang queued forever.
        job = _wait(svc, body["job_id"])
        assert job.status == "done", job.error
        assert svc.telemetry().get("serve_worker_crashes") == 1
    finally:
        svc.shutdown()


# ---------------------------------------------------------------------------
# Admin retry + HTTP surface
# ---------------------------------------------------------------------------


def test_admin_retry_requeues_failed_job(tmp_path):
    svc = _svc(tmp_path)
    orig = svc._run_solo

    def bug(job):
        raise AssertionError("transient-looking only to a human")

    svc._run_solo = bug
    try:
        code, body = svc.submit({"spec": "increment:2", "engine": "bfs"})
        job = _wait(svc, body["job_id"])
        assert job.status == "failed"

        svc._run_solo = orig  # "operator fixed it"
        code, view = svc.retry_job(job.id)
        assert code == 200 and view["status"] == "queued"
        job = _wait(svc, job.id)
        assert job.status == "done"
        assert job.result["unique_state_count"] == 13

        assert svc.retry_job("nope")[0] == 404
        assert svc.retry_job(job.id)[0] == 409  # done jobs don't retry
    finally:
        svc.shutdown()


def _req(server, method, path, payload=None):
    url = server.url.rstrip("/") + path
    data = json.dumps(payload).encode() if payload is not None else None
    req = urllib.request.Request(url, data=data, method=method)
    if data is not None:
        req.add_header("Content-Type", "application/json")
    try:
        with urllib.request.urlopen(req, timeout=30) as resp:
            return resp.status, json.loads(resp.read().decode())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode())


def test_http_retry_endpoint_and_durability_stats(tmp_path):
    from stateright_tpu.serve.http import ServeServer

    svc = _svc(tmp_path)

    def bug(job):
        raise AssertionError("broken until retried")

    orig = svc._run_solo
    svc._run_solo = bug
    server = ServeServer(svc, "127.0.0.1:0").serve_in_background()
    try:
        code, body = _req(
            server, "POST", "/submit",
            {"spec": "increment:2", "engine": "bfs"},
        )
        assert code == 202
        jid = body["job_id"]
        job = _wait(svc, jid)
        assert job.status == "failed"

        svc._run_solo = orig
        code, view = _req(server, "POST", f"/jobs/{jid}/retry")
        assert code == 200 and view["status"] == "queued"
        _wait(svc, jid)
        code, res = _req(server, "GET", f"/jobs/{jid}/result")
        assert code == 200
        assert res["result"]["unique_state_count"] == 13
        # Admin retry resets the attempt budget: this run was attempt 1.
        assert res["job"]["attempts"] == 1

        code, stats = _req(server, "GET", "/stats")
        assert code == 200
        assert stats["retry"]["max_attempts"] == _FAST_RETRY.max_attempts
        assert "journal" in stats and stats["journal"]["bytes"] > 0
        assert "results" in stats and stats["results"]["results"] >= 1
        assert "breaker" in stats

        code, missing = _req(server, "POST", "/jobs/zzz/retry")
        assert code == 404
    finally:
        server.shutdown()


def test_result_gc_prunes_jobs_and_journal(tmp_path):
    svc = _svc(tmp_path, result_ttl=1e9)
    try:
        code, body = svc.submit({"spec": "increment:2", "engine": "bfs"})
        job = _wait(svc, body["job_id"])
        assert job.status == "done"
        assert svc.gc_results() == []  # fresh: nothing expires
        # Force expiry: rewind the store clock far past the TTL.
        svc._results.ttl = 1e-6
        expired = svc.gc_results()
        assert expired == [job.id]
        assert svc.job(job.id) is None  # pruned from the job table
        assert JobJournal.replay(svc._journal.path) == {}  # and the WAL
    finally:
        svc.shutdown()


# ---------------------------------------------------------------------------
# Trace continuity (PR 12): one trace per job, across crashes and retries
# ---------------------------------------------------------------------------


def test_trace_id_survives_journal_replay(tmp_path):
    svc = _svc(tmp_path)
    svc.pause()
    code, body = svc.submit({"spec": "increment:2", "engine": "bfs"})
    assert code == 202
    jid, tid = body["job_id"], body["trace_id"]
    assert len(tid) == 32
    root = svc.job(jid).root_span_id
    svc.shutdown()  # crash with the job still queued

    svc2 = _svc(tmp_path)
    try:
        job = svc2.job(jid)
        # Identity restored from the journal, not regenerated.
        assert job.trace_id == tid
        assert job.root_span_id == root
        done = _wait(svc2, jid)
        assert done.status == "done", done.error

        trace = svc2.spans.trace(tid)
        names = [s["name"] for s in trace]
        # The restart is visible IN the original trace, followed by the
        # post-restart lifecycle — one continuous waterfall.
        assert "restart_recovery" in names
        for leg in ("queue_wait", "execute", "job"):
            assert leg in names, names
        assert all(s["trace_id"] == tid for s in trace)
        (recovery,) = [s for s in trace if s["name"] == "restart_recovery"]
        assert recovery["parent_id"] == root
        (root_span,) = [s for s in trace if s["name"] == "job"]
        assert root_span["span_id"] == root
        assert root_span["attributes"]["final_status"] == "done"
    finally:
        svc2.shutdown()


def test_retry_backoff_and_reexecution_share_the_trace(tmp_path):
    svc = _svc(tmp_path)
    orig = svc._run_solo
    blown = []

    def flaky(job):
        if not blown:
            blown.append(job.id)
            raise RuntimeError(
                "visited-table probe budget exhausted despite headroom"
            )
        orig(job)

    svc._run_solo = flaky
    try:
        code, body = svc.submit({"spec": "increment:2", "engine": "bfs"})
        assert code == 202
        job = _wait(svc, body["job_id"])
        assert job.status == "done", job.error
        assert job.attempts == 2

        trace = svc.spans.trace(job.trace_id)
        executes = [s for s in trace if s["name"] == "execute"]
        # Attempt 1 (failed) AND attempt 2 (succeeded) are both spans of
        # the SAME trace, each tagged with its attempt number.
        assert len(executes) == 2, [s["name"] for s in trace]
        by_attempt = {s["attributes"]["attempt"]: s for s in executes}
        assert by_attempt[1]["status"] == "error"
        assert "probe budget" in by_attempt[1]["attributes"]["error"]
        assert by_attempt[2]["status"] == "ok"
        # The backoff window between them is a span too.
        (backoff,) = [s for s in trace if s["name"] == "backoff_wait"]
        assert backoff["attributes"]["attempt"] == 1
        assert by_attempt[1]["end"] <= backoff["end"] <= by_attempt[2]["start"]
        # Two queue waits: the original admission and the re-enqueue.
        queue_waits = [s for s in trace if s["name"] == "queue_wait"]
        assert len(queue_waits) == 2
        (root_span,) = [s for s in trace if s["name"] == "job"]
        assert root_span["attributes"]["attempts"] == 2
    finally:
        svc.shutdown()


def test_escalation_links_multiplex_and_solo_executions(tmp_path):
    svc = _svc(tmp_path)

    def lane_wall(jobs):
        raise RuntimeError(
            "lane 0 did not complete within the lane budget (frontier=9, "
            "unique=65000); raise queue_capacity/table_capacity or run it "
            "solo via spawn_tpu_bfs"
        )

    svc._run_multiplex_batch = lane_wall
    try:
        code, body = svc.submit({"spec": "increment:2"})  # auto -> multiplex
        assert code == 202
        job = _wait(svc, body["job_id"])
        assert job.status == "done", job.error
        assert job.engine == "tpu_bfs"

        trace = svc.spans.trace(job.trace_id)
        executes = sorted(
            (s for s in trace if s["name"] == "execute"),
            key=lambda s: s["attributes"]["attempt"],
        )
        # The failed lane attempt and the solo re-run are siblings under
        # one root: the escalation reads straight off the waterfall.
        assert len(executes) == 2
        assert executes[0]["status"] == "error"
        assert executes[0]["attributes"]["engine"] == "multiplex"
        assert executes[1]["status"] == "ok"
        assert executes[1]["attributes"]["engine"] == "tpu_bfs"
        assert executes[0]["trace_id"] == executes[1]["trace_id"]
        (root_span,) = [s for s in trace if s["name"] == "job"]
        assert all(s["parent_id"] == root_span["span_id"] for s in executes)
        (backoff,) = [s for s in trace if s["name"] == "backoff_wait"]
        assert backoff["attributes"]["next_engine"] == "tpu_bfs"
    finally:
        svc.shutdown()
