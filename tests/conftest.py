"""Test configuration: run JAX on a virtual 8-device CPU mesh.

Multi-chip TPU hardware is unavailable in CI; sharding correctness is
validated on a forced 8-device CPU platform (the driver separately dry-runs
the multi-chip path via __graft_entry__.dryrun_multichip).

NOTE: in this environment a sitecustomize hook imports jax at interpreter
boot and pins jax_platforms to the axon TPU backend — setting JAX_PLATFORMS
in the environment here is too late. Overriding the jax config directly
(before any backend is initialized) is what actually keeps tests off the
TPU tunnel.
"""

import os
import sys

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache: the suite's wall-clock is dominated by XLA
# compiles (every engine-option variation builds a fresh loop); caching them
# across runs keeps CI honest as coverage grows. Safe to share: entries key
# on the full HLO + compile options.
_CACHE_DIR = os.environ.get(
    "STPU_JAX_CACHE", os.path.join(os.path.dirname(__file__), ".jax_cache")
)
jax.config.update("jax_compilation_cache_dir", _CACHE_DIR)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running golden-count integration tests"
    )
