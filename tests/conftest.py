"""Test configuration: run JAX on a virtual 8-device CPU mesh.

Multi-chip TPU hardware is unavailable in CI; sharding correctness is
validated on a forced 8-device CPU platform (the driver separately dry-runs
the multi-chip path via __graft_entry__.dryrun_multichip).

NOTE: in this environment a sitecustomize hook imports jax at interpreter
boot and pins jax_platforms to the axon TPU backend — setting JAX_PLATFORMS
in the environment here is too late. Overriding the jax config directly
(before any backend is initialized) is what actually keeps tests off the
TPU tunnel.
"""

import os
import sys

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running golden-count integration tests"
    )
