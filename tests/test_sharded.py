"""Sharded multi-device BFS on the virtual 8-device CPU mesh.

Validates that fingerprint-ownership sharding over a jax.sharding.Mesh
explores exactly the same state space as the host oracle and the
single-device engine.
"""

import jax
import pytest

from stateright_tpu.models import IncrementTensor, TwoPhaseTensor
from stateright_tpu.parallel import ShardedBfs


@pytest.fixture(scope="module")
def devices():
    devs = jax.devices()
    assert len(devs) >= 8, "conftest should force 8 virtual CPU devices"
    return devs[:8]


def test_2pc3_sharded_exact_count(devices):
    sb = ShardedBfs(TwoPhaseTensor(3), devices, chunk_size=128).run()
    assert sb.unique_state_count == 288
    assert set(sb.discovery_fps) == {"abort agreement", "commit agreement"}
    assert "consistent" not in sb.discovery_fps  # no counterexample


def test_2pc5_sharded_exact_count(devices):
    sb = ShardedBfs(TwoPhaseTensor(5), devices, chunk_size=256).run()
    assert sb.unique_state_count == 8832
    assert "consistent" not in sb.discovery_fps


def test_increment_race_sharded(devices):
    sb = ShardedBfs(IncrementTensor(2), devices, chunk_size=64).run()
    assert "fin" in sb.discovery_fps


def test_two_shards_also_exact(devices):
    sb = ShardedBfs(TwoPhaseTensor(3), devices[:2], chunk_size=128).run()
    assert sb.unique_state_count == 288
