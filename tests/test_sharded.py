"""Sharded multi-device BFS on the virtual 8-device CPU mesh.

Validates that fingerprint-ownership sharding over a jax.sharding.Mesh —
with the owner-routed all_to_all candidate exchange — explores exactly the
same state space as the host oracle and the single-device engine, and that
counterexample paths reconstruct across shard tables.
"""

import jax
import pytest

from stateright_tpu.models import IncrementTensor, TwoPhaseTensor
from stateright_tpu.parallel import ShardedBfs
from stateright_tpu.tensor import TensorModelAdapter


@pytest.fixture(scope="module")
def devices():
    devs = jax.devices()
    assert len(devs) >= 8, "conftest should force 8 virtual CPU devices"
    return devs[:8]


def test_2pc3_sharded_exact_count(devices):
    sb = ShardedBfs(TwoPhaseTensor(3), devices, chunk_size=128).run()
    assert sb.unique_state_count == 288
    assert set(sb.discovery_fps) == {"abort agreement", "commit agreement"}
    assert "consistent" not in sb.discovery_fps  # no counterexample


def test_2pc5_sharded_exact_count(devices):
    sb = ShardedBfs(TwoPhaseTensor(5), devices, chunk_size=256).run()
    assert sb.unique_state_count == 8832
    assert "consistent" not in sb.discovery_fps


def test_2pc5_sharded_with_spill_and_growth(devices):
    # Tiny rings + tables force the spill and grow paths; counts must stay
    # exact (mirrors the single-device growth/spill test).
    sb = ShardedBfs(
        TwoPhaseTensor(5),
        devices,
        chunk_size=64,
        queue_capacity_per_shard=1 << 11,
        table_capacity_per_shard=1 << 10,
    ).run()
    assert sb.unique_state_count == 8832


def test_increment_race_sharded(devices):
    sb = ShardedBfs(IncrementTensor(2), devices, chunk_size=64).run()
    assert "fin" in sb.discovery_fps


def test_two_shards_also_exact(devices):
    sb = ShardedBfs(TwoPhaseTensor(3), devices[:2], chunk_size=128).run()
    assert sb.unique_state_count == 288


def test_paxos2_sharded_golden(devices):
    # Register family on the mesh: the paxos twin's 16,668-state space
    # (examples/paxos.rs:327) must survive fingerprint-ownership sharding
    # and the all_to_all exchange exactly — same golden as the host
    # oracle and the single-device engine.
    from stateright_tpu.models.paxos import PaxosTensorExhaustive

    sb = ShardedBfs(PaxosTensorExhaustive(2), devices, chunk_size=256).run()
    assert sb.unique_state_count == 16_668


def test_abd2_sharded_golden(devices):
    # linearizable-register check 2 (ABD, unordered) on the mesh: 544
    # states (linearizable-register.rs:287), linearizability holds — no
    # counterexample may appear from cross-shard routing.
    from stateright_tpu.models.abd import AbdTensor

    sb = ShardedBfs(AbdTensor(2), devices, chunk_size=128).run()
    assert sb.unique_state_count == 544
    assert "linearizable" not in sb.discovery_fps


def test_checker_api_and_cross_shard_paths(devices):
    # The full Checker interface: spawn via the builder, reconstruct a
    # discovery Path across shard tables, and replay it through the model.
    checker = (
        TensorModelAdapter(IncrementTensor(2))
        .checker()
        .spawn_sharded_bfs(devices=devices, chunk_size=64)
        .join()
    )
    path = checker.discovery("fin")
    assert path is not None
    # BFS shortest counterexample: the classic 4-step lost-update schedule.
    assert len(path.into_actions()) == 4
    checker.assert_any_discovery("fin")


def test_sharded_matches_single_device_engine(devices):
    tm = TwoPhaseTensor(4)
    single = TensorModelAdapter(tm).checker().spawn_tpu_bfs(
        chunk_size=128, queue_capacity=1 << 13, table_capacity=1 << 13
    ).join()
    sharded = (
        TensorModelAdapter(tm)
        .checker()
        .spawn_sharded_bfs(devices=devices, chunk_size=128)
        .join()
    )
    assert sharded.unique_state_count() == single.unique_state_count()


def test_sharded_checkpoint_resume_golden(tmp_path):
    """Kill/resume on the 8-shard mesh: a target-capped run checkpoints
    (including per-shard rings, spill lists, and take_caps); a fresh
    checker resumes it to the exact full-space golden."""
    import jax

    from stateright_tpu.models import TwoPhaseTensor
    from stateright_tpu.tensor import TensorModelAdapter

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    ck = str(tmp_path / "shard.npz")
    devices = jax.devices()[:8]
    opts = dict(
        devices=devices,
        chunk_size=64,
        queue_capacity_per_shard=1 << 11,
        table_capacity_per_shard=1 << 10,
    )
    part = (
        TensorModelAdapter(TwoPhaseTensor(5))
        .checker()
        .target_state_count(3000)
        .spawn_sharded_bfs(checkpoint_path=ck, **opts)
        .join()
    )
    assert part.unique_state_count() < 8832
    resumed = (
        TensorModelAdapter(TwoPhaseTensor(5))
        .checker()
        .spawn_sharded_bfs(resume_from=ck, **opts)
        .join()
    )
    assert resumed.unique_state_count() == 8832, resumed.unique_state_count()
    assert resumed.discovery("consistent") is None


def test_sharded_checkpoint_rejects_mismatched_model(tmp_path):
    import jax

    from stateright_tpu.models import TwoPhaseTensor
    from stateright_tpu.tensor import TensorModelAdapter

    if len(jax.devices()) < 4:
        pytest.skip("needs 4 virtual devices")
    ck = str(tmp_path / "shard.npz")
    devices = jax.devices()[:4]
    opts = dict(devices=devices, chunk_size=64)
    TensorModelAdapter(TwoPhaseTensor(4)).checker().target_state_count(
        500
    ).spawn_sharded_bfs(checkpoint_path=ck, **opts).join()
    with pytest.raises(ValueError):
        TensorModelAdapter(TwoPhaseTensor(5)).checker().spawn_sharded_bfs(
            resume_from=ck, **opts
        ).join()
