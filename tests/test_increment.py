"""Increment race goldens (reference: examples/increment.rs doc comment:
13 unique states at 2 threads, 8 with symmetry reduction; the "fin"
invariant has a counterexample)."""

from stateright_tpu import Property, TensorModelAdapter
from stateright_tpu.models import Increment, IncrementTensor
from stateright_tpu.tensor import TensorProperty


class IncrementFull(Increment):
    """Increment plus an unsatisfiable sometimes-property.

    Once every property has a discovery, the engines drain the queue without
    expanding (reference bfs.rs:278-280) — so the full 13/8-state spaces from
    the reference's doc comment are only observable when at least one
    property stays undiscovered. The impossible property forces exhaustion.
    """

    def properties(self):
        return super().properties() + [
            Property.sometimes("unreachable", lambda _m, _s: False)
        ]


class IncrementTensorFull(IncrementTensor):
    def tensor_properties(self):
        return super().tensor_properties() + [
            TensorProperty.sometimes(
                "unreachable",
                lambda xp, lanes: xp.zeros(lanes[0].shape, dtype=bool),
            )
        ]


def test_race_found_and_state_count():
    checker = IncrementFull(2).checker().spawn_bfs().join()
    assert checker.unique_state_count() == 13
    path = checker.discovery("fin")
    assert path is not None  # the lost-update interleaving exists
    # Classic schedule: both threads read 0, then both write 1.
    final = path.last_state()
    assert final.i != sum(1 for (_t, pc) in final.s if pc == 3)


def test_symmetry_reduction_golden():
    checker = IncrementFull(2).checker().symmetry().spawn_dfs().join()
    assert checker.unique_state_count() == 8
    assert checker.discovery("fin") is not None


def test_tensor_model_matches_host():
    host = IncrementFull(2).checker().spawn_bfs().join()
    tensor = TensorModelAdapter(IncrementTensorFull(2)).checker().spawn_bfs().join()
    assert tensor.unique_state_count() == host.unique_state_count() == 13
    assert tensor.discovery("fin") is not None


def test_three_threads():
    host = IncrementFull(3).checker().spawn_bfs().join()
    tensor = TensorModelAdapter(IncrementTensorFull(3)).checker().spawn_bfs().join()
    assert host.unique_state_count() == tensor.unique_state_count()
    assert host.discovery("fin") is not None
