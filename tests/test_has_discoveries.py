"""device_masks must agree with matches() for every policy and bitmask.

ADVICE r4: the finish-policy lowering drives the on-device early-exit
gate in both device engines; a mismatch against matches() would cause
premature or missed era exits and was only caught indirectly by engine
goldens. This exhaustively checks the predicate over every discovery
bitmask for every policy kind, including the edge cases (all_of with a
missing name, all_failures with zero failure-expectation properties).
"""

import itertools

import pytest

from stateright_tpu.core import Expectation
from stateright_tpu.has_discoveries import HasDiscoveries


class _Prop:
    def __init__(self, name, expectation):
        self.name = name
        self.expectation = expectation


def _prop_sets():
    a = _Prop("always_ok", Expectation.ALWAYS)
    s = _Prop("some_hit", Expectation.SOMETIMES)
    e = _Prop("event_done", Expectation.EVENTUALLY)
    a2 = _Prop("always_2", Expectation.ALWAYS)
    yield [a, s, e]
    yield [s]  # zero failure-expectation properties
    yield [a, a2, e]  # zero sometimes
    yield []


def _policies(props):
    names = [p.name for p in props]
    yield HasDiscoveries.ALL
    yield HasDiscoveries.ANY
    yield HasDiscoveries.ANY_FAILURES
    yield HasDiscoveries.ALL_FAILURES
    for r in range(len(names) + 1):
        for combo in itertools.combinations(names, r):
            yield HasDiscoveries.all_of(combo)
            yield HasDiscoveries.any_of(combo)
    # Policies naming a property that does not exist.
    yield HasDiscoveries.all_of(["no_such_prop"])
    yield HasDiscoveries.all_of([*names, "no_such_prop"])
    yield HasDiscoveries.any_of(["no_such_prop"])


def _device_fires(rec, masks):
    any_mask, all_mask, all_en = masks
    return (rec & any_mask) != 0 or (all_en and (rec & all_mask) == all_mask)


@pytest.mark.parametrize("props", list(_prop_sets()), ids=lambda ps: "+".join(p.name for p in ps) or "empty")
def test_device_masks_equal_matches(props):
    names = [p.name for p in props]
    for policy in _policies(props):
        masks = policy.device_masks(props)
        for rec in range(1 << len(props)):
            discovered = {names[i] for i in range(len(props)) if (rec >> i) & 1}
            want = policy.matches(discovered, props)
            got = _device_fires(rec, masks)
            if policy._kind == "all_of" and not all(
                n in names for n in policy._names
            ):
                # Documented divergence: a policy naming a missing property
                # can never match; the device gate is disabled, and both
                # sides must agree it never fires.
                assert not want and not got, (policy, rec)
            else:
                assert want == got, (policy, rec, masks)
