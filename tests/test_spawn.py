"""UDP spawn runtime tests: the checked actor code runs over real sockets.

Role parity: the reference's spawn runtime is smoke-tested by hand
(SURVEY.md §4.4); here the background-handle capability makes it properly
testable: a ping-pong pair converges over loopback UDP, and timers fire.
"""

import time

import pytest

from stateright_tpu.actor import Actor, Id, Out
from stateright_tpu.actor.spawn import (
    json_serializer,
    make_json_deserializer,
    spawn,
)
from stateright_tpu.actor.test_util import Ping, PingPongActor, Pong


def _wait_until(predicate, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(0.02)
    return predicate()


def _engines():
    from stateright_tpu.native import runtime as native_runtime

    engines = ["python"]
    if native_runtime.is_available():
        engines.append("native")
    return engines


@pytest.mark.parametrize("engine", _engines())
def test_ping_pong_over_udp(engine):
    base = 42000 + (10 if engine == "native" else 0)
    a = Id.from_addr("127.0.0.1", base)
    b = Id.from_addr("127.0.0.1", base + 1)
    handle = spawn(
        json_serializer,
        make_json_deserializer(Ping, Pong),
        [(a, PingPongActor(serve_to=b)), (b, PingPongActor())],
        background=True,
        engine=engine,
    )
    try:
        # Counters climb as the pair bounces Ping/Pong over loopback.
        assert _wait_until(
            lambda: (handle.state(a) or 0) >= 3 and (handle.state(b) or 0) >= 3
        )
    finally:
        handle.shutdown()


@pytest.mark.parametrize("engine", _engines())
def test_timers_fire(engine):
    class TickActor(Actor):
        def on_start(self, id, out):
            out.set_timer("tick", (0.01, 0.02))
            return 0

        def on_timeout(self, id, state, timer, out):
            out.set_timer("tick", (0.01, 0.02))
            return state + 1

    addr = Id.from_addr("127.0.0.1", 42020 + (1 if engine == "native" else 0))
    handle = spawn(
        json_serializer,
        make_json_deserializer(),
        [(addr, TickActor())],
        background=True,
        engine=engine,
    )
    try:
        assert _wait_until(lambda: (handle.state(addr) or 0) >= 3)
    finally:
        handle.shutdown()


def test_random_choice_resolves():
    class RandomActor(Actor):
        def on_start(self, id, out):
            out.choose_random("pick", ["x", "y"])
            return None

        def on_random(self, id, state, random, out):
            return random

    addr = Id.from_addr("127.0.0.1", 42011)
    handle = spawn(
        json_serializer,
        make_json_deserializer(),
        [(addr, RandomActor())],
        background=True,
    )
    try:
        # ChooseRandom schedules the pick up to 10s out (spawn.rs:222-231);
        # just assert the actor is running and the loop handles the queue.
        time.sleep(0.1)
        assert handle.state(addr) in (None, "x", "y")
    finally:
        handle.shutdown()
