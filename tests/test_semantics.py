"""Semantics-layer tests. Mirrors the test modules of
src/semantics/{register,write_once_register,vec,linearizability,
sequential_consistency}.rs."""

import pytest

from stateright_tpu.semantics import (
    LinearizabilityTester,
    SequentialConsistencyTester,
)
from stateright_tpu.semantics import register as reg
from stateright_tpu.semantics import vec
from stateright_tpu.semantics import write_once_register as wor


# -- reference objects -------------------------------------------------------

def test_register_models_expected_semantics():
    r = reg.Register("A")
    assert r.invoke(reg.READ) == reg.ReadOk("A")
    assert r.invoke(reg.Write("B")) == reg.WRITE_OK
    assert r.invoke(reg.READ) == reg.ReadOk("B")


def test_register_accepts_valid_histories():
    assert reg.Register("A").is_valid_history([])
    assert reg.Register("A").is_valid_history([
        (reg.READ, reg.ReadOk("A")),
        (reg.Write("B"), reg.WRITE_OK),
        (reg.READ, reg.ReadOk("B")),
        (reg.Write("C"), reg.WRITE_OK),
        (reg.READ, reg.ReadOk("C")),
    ])


def test_register_rejects_invalid_histories():
    assert not reg.Register("A").is_valid_history([
        (reg.READ, reg.ReadOk("B")),
        (reg.Write("B"), reg.WRITE_OK),
    ])
    assert not reg.Register("A").is_valid_history([
        (reg.Write("B"), reg.WRITE_OK),
        (reg.READ, reg.ReadOk("A")),
    ])


def test_write_once_register_semantics():
    r = wor.WORegister()
    assert r.invoke(wor.Write("A")) == wor.WRITE_OK
    assert r.invoke(wor.READ) == wor.ReadOk("A")
    assert r.invoke(wor.Write("B")) == wor.WRITE_FAIL
    assert r.invoke(wor.READ) == wor.ReadOk("A")

    assert wor.WORegister().is_valid_history([
        (wor.READ, wor.ReadOk(None)),
        (wor.Write("A"), wor.WRITE_OK),
        (wor.READ, wor.ReadOk("A")),
        (wor.Write("B"), wor.WRITE_FAIL),
        (wor.READ, wor.ReadOk("A")),
        (wor.Write("C"), wor.WRITE_FAIL),
        (wor.READ, wor.ReadOk("A")),
    ])
    assert not wor.WORegister("A").is_valid_history([
        (wor.READ, wor.ReadOk("A")),
        (wor.Write("B"), wor.WRITE_OK),
    ])
    assert not wor.WORegister().is_valid_history([
        (wor.READ, wor.ReadOk("A")),
        (wor.Write("A"), wor.WRITE_OK),
    ])
    assert not wor.WORegister().is_valid_history([
        (wor.READ, wor.ReadOk(None)),
        (wor.Write("A"), wor.WRITE_OK),
        (wor.Write("B"), wor.WRITE_OK),
    ])


def test_vec_semantics():
    v = vec.VecSpec(["A"])
    assert v.invoke(vec.Push("B")) == vec.PUSH_OK
    assert v.invoke(vec.LEN) == vec.LenOk(2)
    assert v.invoke(vec.POP) == vec.PopOk("B")
    assert v.invoke(vec.POP) == vec.PopOk("A")
    assert v.invoke(vec.POP) == vec.PopOk(None)
    assert v.invoke(vec.LEN) == vec.LenOk(0)


# -- linearizability (linearizability.rs:305-470) ----------------------------

def test_rejects_invalid_history():
    t = LinearizabilityTester(reg.Register("A"))
    t.on_invoke(99, reg.Write("B"))
    assert t.is_valid_history
    t.on_invoke(99, reg.Write("C"))
    assert not t.is_valid_history
    assert "already has an operation in flight" in t.last_error
    assert not t.is_consistent()

    t = LinearizabilityTester(reg.Register("A"))
    t.on_invret(99, reg.Write("B"), reg.WRITE_OK)
    t.on_invret(99, reg.Write("C"), reg.WRITE_OK)
    t.on_return(99, reg.WRITE_OK)
    assert not t.is_valid_history
    assert "no in-flight invocation" in t.last_error


def test_identifies_linearizable_register_history():
    t = LinearizabilityTester(reg.Register("A"))
    t.on_invoke(0, reg.Write("B")).on_invret(1, reg.READ, reg.ReadOk("A"))
    assert t.serialized_history() == [(reg.READ, reg.ReadOk("A"))]

    t = LinearizabilityTester(reg.Register("A"))
    t.on_invoke(0, reg.READ).on_invoke(1, reg.Write("B")).on_return(
        0, reg.ReadOk("B")
    )
    assert t.serialized_history() == [
        (reg.Write("B"), reg.WRITE_OK),
        (reg.READ, reg.ReadOk("B")),
    ]


def test_identifies_unlinearizable_register_history():
    t = LinearizabilityTester(reg.Register("A"))
    t.on_invret(0, reg.READ, reg.ReadOk("B"))
    assert t.serialized_history() is None

    # SC but not linearizable: the write was invoked after the read returned.
    t = LinearizabilityTester(reg.Register("A"))
    t.on_invret(0, reg.READ, reg.ReadOk("B")).on_invoke(1, reg.Write("B"))
    assert t.serialized_history() is None


def test_identifies_linearizable_vec_history():
    t = LinearizabilityTester(vec.VecSpec())
    t.on_invoke(0, vec.Push(10))
    assert t.serialized_history() == []

    t = LinearizabilityTester(vec.VecSpec())
    t.on_invoke(0, vec.Push(10)).on_invret(1, vec.POP, vec.PopOk(None))
    assert t.serialized_history() == [(vec.POP, vec.PopOk(None))]

    t = LinearizabilityTester(vec.VecSpec())
    t.on_invoke(0, vec.Push(10)).on_invret(1, vec.POP, vec.PopOk(10))
    assert t.serialized_history() == [
        (vec.Push(10), vec.PUSH_OK),
        (vec.POP, vec.PopOk(10)),
    ]

    t = LinearizabilityTester(vec.VecSpec())
    (
        t.on_invret(0, vec.Push(10), vec.PUSH_OK)
        .on_invoke(0, vec.Push(20))
        .on_invret(1, vec.LEN, vec.LenOk(1))
        .on_invret(1, vec.POP, vec.PopOk(20))
        .on_invret(1, vec.POP, vec.PopOk(10))
    )
    assert t.serialized_history() == [
        (vec.Push(10), vec.PUSH_OK),
        (vec.LEN, vec.LenOk(1)),
        (vec.Push(20), vec.PUSH_OK),
        (vec.POP, vec.PopOk(20)),
        (vec.POP, vec.PopOk(10)),
    ]

    t = LinearizabilityTester(vec.VecSpec())
    (
        t.on_invret(0, vec.Push(10), vec.PUSH_OK)
        .on_invoke(0, vec.Push(20))
        .on_invret(1, vec.LEN, vec.LenOk(1))
        .on_invret(1, vec.POP, vec.PopOk(10))
        .on_invret(1, vec.POP, vec.PopOk(20))
    )
    assert t.serialized_history() == [
        (vec.Push(10), vec.PUSH_OK),
        (vec.LEN, vec.LenOk(1)),
        (vec.POP, vec.PopOk(10)),
        (vec.Push(20), vec.PUSH_OK),
        (vec.POP, vec.PopOk(20)),
    ]

    t = LinearizabilityTester(vec.VecSpec())
    (
        t.on_invret(0, vec.Push(10), vec.PUSH_OK)
        .on_invoke(0, vec.Push(20))
        .on_invret(1, vec.LEN, vec.LenOk(2))
        .on_invret(1, vec.POP, vec.PopOk(20))
        .on_invret(1, vec.POP, vec.PopOk(10))
    )
    assert t.serialized_history() == [
        (vec.Push(10), vec.PUSH_OK),
        (vec.Push(20), vec.PUSH_OK),
        (vec.LEN, vec.LenOk(2)),
        (vec.POP, vec.PopOk(20)),
        (vec.POP, vec.PopOk(10)),
    ]

    t = LinearizabilityTester(vec.VecSpec())
    (
        t.on_invret(0, vec.Push(10), vec.PUSH_OK)
        .on_invoke(1, vec.LEN)
        .on_invoke(0, vec.Push(20))
        .on_return(1, vec.LenOk(1))
    )
    assert t.serialized_history() == [
        (vec.Push(10), vec.PUSH_OK),
        (vec.LEN, vec.LenOk(1)),
    ]

    t = LinearizabilityTester(vec.VecSpec())
    (
        t.on_invret(0, vec.Push(10), vec.PUSH_OK)
        .on_invoke(1, vec.LEN)
        .on_invoke(0, vec.Push(20))
        .on_return(1, vec.LenOk(2))
    )
    assert t.serialized_history() == [
        (vec.Push(10), vec.PUSH_OK),
        (vec.Push(20), vec.PUSH_OK),
        (vec.LEN, vec.LenOk(2)),
    ]


def test_identifies_unlinearizable_vec_history():
    # SC but not linearizable.
    t = LinearizabilityTester(vec.VecSpec())
    t.on_invret(0, vec.Push(10), vec.PUSH_OK).on_invret(1, vec.POP, vec.PopOk(None))
    assert t.serialized_history() is None

    t = LinearizabilityTester(vec.VecSpec())
    (
        t.on_invret(0, vec.Push(10), vec.PUSH_OK)
        .on_invoke(1, vec.LEN)
        .on_invoke(0, vec.Push(20))
        .on_return(1, vec.LenOk(0))
    )
    assert t.serialized_history() is None

    t = LinearizabilityTester(vec.VecSpec())
    (
        t.on_invret(0, vec.Push(10), vec.PUSH_OK)
        .on_invoke(0, vec.Push(20))
        .on_invret(1, vec.LEN, vec.LenOk(2))
        .on_invret(1, vec.POP, vec.PopOk(10))
        .on_invret(1, vec.POP, vec.PopOk(20))
    )
    assert t.serialized_history() is None


# -- sequential consistency --------------------------------------------------

def test_sc_accepts_what_linearizability_rejects():
    # Stale read after a completed write: SC yes, linearizable no.
    lin = LinearizabilityTester(reg.Register("A"))
    lin.on_invret(0, reg.Write("B"), reg.WRITE_OK).on_invret(
        1, reg.READ, reg.ReadOk("A")
    )
    assert lin.serialized_history() is None

    sc = SequentialConsistencyTester(reg.Register("A"))
    sc.on_invret(0, reg.Write("B"), reg.WRITE_OK).on_invret(
        1, reg.READ, reg.ReadOk("A")
    )
    assert sc.serialized_history() == [
        (reg.READ, reg.ReadOk("A")),
        (reg.Write("B"), reg.WRITE_OK),
    ]


def test_sc_still_requires_per_thread_order():
    sc = SequentialConsistencyTester(reg.Register("A"))
    sc.on_invret(0, reg.READ, reg.ReadOk("B")).on_invret(
        0, reg.Write("B"), reg.WRITE_OK
    )
    assert sc.serialized_history() is None


def test_testers_are_value_objects():
    from stateright_tpu import fingerprint

    t1 = LinearizabilityTester(reg.Register("A"))
    t1.on_invoke(0, reg.Write("B"))
    t2 = t1.copy()
    assert t1 == t2
    assert fingerprint(t1) == fingerprint(t2)
    t2.on_return(0, reg.WRITE_OK)
    assert t1 != t2
    assert fingerprint(t1) != fingerprint(t2)
    assert len(t1) == 1 and len(t2) == 1
