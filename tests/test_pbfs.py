"""Parallel host BFS for rich models (engines/pbfs.py).

The multiprocessing ownership-sharded engine must agree with the
single-threaded host engine on unique counts, verdicts, and produce
valid reconstructable discovery paths — for plain Models AND for actor
models assembled from closures (shipped via cloudpickle).
"""

from stateright_tpu.models.two_phase_commit import TwoPhaseSys


def test_2pc3_golden_and_paths():
    c = TwoPhaseSys(3).checker().threads(2).spawn_bfs().join()
    assert c.unique_state_count() == 288  # examples/2pc.rs:154
    assert c.discovery("consistent") is None
    for name in ("abort agreement", "commit agreement"):
        p = c.discovery(name)
        assert p is not None
        # Path.from_fingerprints re-executes the model: a non-None path
        # proves the cross-shard parent chain reconstructed validly.
        assert len(p.into_states()) >= 2


def test_2pc5_golden():
    c = TwoPhaseSys(5).checker().threads(4).spawn_bfs().join()
    assert c.unique_state_count() == 8832  # examples/2pc.rs:159
    assert c.discovery("consistent") is None


def test_closure_built_actor_model():
    # Actor models are assembled from lambdas/closures; plain pickle
    # rejects them — cloudpickle shipping must handle it.
    from examples.linearizable_register import abd_model

    c = abd_model(2, 2).checker().threads(2).spawn_bfs().join()
    assert c.unique_state_count() == 544  # linearizable-register.rs:287
    assert c.discovery("linearizable") is None


def test_target_state_count_stops_early():
    c = (
        TwoPhaseSys(5)
        .checker()
        .threads(2)
        .target_state_count(500)
        .spawn_bfs()
        .join()
    )
    assert c.state_count() >= 500
    assert c.unique_state_count() < 8832
