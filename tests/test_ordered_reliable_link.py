"""Ordered-reliable-link tests. Mirrors the test module of
src/actor/ordered_reliable_link.rs:230-330."""

from stateright_tpu import Expectation
from stateright_tpu.actor import Actor, ActorModel, Deliver, Id, Network
from stateright_tpu.actor.ordered_reliable_link import (
    DeliverMsg,
    OrderedReliableLink,
)


class Sender(Actor):
    def __init__(self, receiver_id):
        self.receiver_id = receiver_id

    def on_start(self, id, out):
        out.send(self.receiver_id, 42)
        out.send(self.receiver_id, 43)
        return ()

    def on_msg(self, id, state, src, msg, out):
        return state + ((src, msg),)


class Receiver(Sender):
    def __init__(self):
        pass

    def on_start(self, id, out):
        return ()


def model():
    def received(state):
        return state.actor_states[1].wrapped_state

    return (
        ActorModel()
        .actor(OrderedReliableLink.with_default_timeout(Sender(Id(1))))
        .actor(OrderedReliableLink.with_default_timeout(Receiver()))
        .with_init_network(Network.new_unordered_duplicating())
        .with_lossy_network(True)
        .property(
            Expectation.ALWAYS,
            "no redelivery",
            lambda m, s: sum(1 for _, v in received(s) if v == 42) < 2
            and sum(1 for _, v in received(s) if v == 43) < 2,
        )
        .property(
            Expectation.ALWAYS,
            "ordered",
            lambda m, s: all(
                a[1] <= b[1] for a, b in zip(received(s), received(s)[1:])
            ),
        )
        .property(
            Expectation.SOMETIMES,
            "delivered",
            lambda m, s: received(s) == ((Id(0), 42), (Id(0), 43)),
        )
        .with_within_boundary(lambda cfg, s: len(s.network) < 4)
    )


def test_messages_are_not_delivered_twice():
    model().checker().spawn_bfs().join().assert_no_discovery("no redelivery")


def test_messages_are_delivered_in_order():
    model().checker().spawn_bfs().join().assert_no_discovery("ordered")


def test_messages_are_eventually_delivered():
    checker = model().checker().spawn_bfs().join()
    checker.assert_discovery(
        "delivered",
        [
            Deliver(src=Id(0), dst=Id(1), msg=DeliverMsg(1, 42)),
            Deliver(src=Id(0), dst=Id(1), msg=DeliverMsg(2, 43)),
        ],
    )
