"""ActorModel tests. Mirrors src/actor/model.rs:765-1431 test module."""

from typing import Optional

import pytest

from stateright_tpu import Expectation, PathRecorder, StateRecorder
from stateright_tpu.actor import (
    Actor,
    ActorModel,
    ActorModelState,
    Crash,
    Deliver,
    Drop,
    Envelope,
    Id,
    Network,
    Out,
    RandomChoices,
    Timers,
    model_timeout,
)
from stateright_tpu.actor.test_util import Ping, PingPongCfg, Pong, ping_pong_model


def states_and_network(states, envelopes, last_msg=None):
    """Helper to build expected ping_pong system states (model.rs:779-796)."""
    return ActorModelState(
        actor_states=list(states),
        network=Network.new_unordered_duplicating_with_last_msg(envelopes, last_msg),
        timers_set=[Timers() for _ in states],
        random_choices=[RandomChoices() for _ in states],
        crashed=[False] * len(states),
        history=(0, 0),
    )


def test_visits_expected_states():
    recorder, accessor = StateRecorder.new_with_accessor()
    checker = (
        ping_pong_model(PingPongCfg(maintains_history=False, max_nat=1))
        .with_lossy_network(True)
        .checker()
        .visitor(recorder)
        .spawn_bfs()
        .join()
    )
    assert checker.unique_state_count() == 14

    state_space = accessor()
    assert len(state_space) == 14
    p01 = Envelope(Id(0), Id(1), Ping(0))
    q10 = Envelope(Id(1), Id(0), Pong(0))
    p11 = Envelope(Id(0), Id(1), Ping(1))
    expected = [
        # When the network loses no messages...
        states_and_network([0, 0], [p01]),
        states_and_network([0, 1], [p01, q10], p01),
        states_and_network([1, 1], [p01, q10, p11], q10),
        # When the network loses the message for pinger-ponger state (0, 0)...
        states_and_network([0, 0], []),
        # When the network loses a message for pinger-ponger state (0, 1)...
        states_and_network([0, 1], [q10], p01),
        states_and_network([0, 1], [p01], p01),
        states_and_network([0, 1], [], p01),
        # When the network loses a message for pinger-ponger state (1, 1)...
        states_and_network([1, 1], [q10, p11], q10),
        states_and_network([1, 1], [p01, p11], q10),
        states_and_network([1, 1], [p01, q10], q10),
        states_and_network([1, 1], [p11], q10),
        states_and_network([1, 1], [q10], q10),
        states_and_network([1, 1], [p01], q10),
        states_and_network([1, 1], [], q10),
    ]
    assert set(state_space) == set(expected)


def test_no_op_depends_on_network():
    class MyClient(Actor):
        def __init__(self, server):
            self.server = server

        def on_start(self, id, out):
            out.send(self.server, "Ignored")
            out.send(self.server, "Interesting")
            return "Awaiting an interesting message."

        def on_msg(self, id, state, src, msg, out):
            if msg == "Interesting":
                return "Got an interesting message."
            return None

    class MyServer(MyClient):
        def __init__(self):
            pass

        def on_start(self, id, out):
            return "Awaiting an interesting message."

    def build(network):
        return (
            ActorModel()
            .actor(MyClient(server=Id(1)))
            .actor(MyServer())
            .with_lossy_network(False)
            .with_init_network(network)
            .property(Expectation.ALWAYS, "Check everything", lambda m, s: True)
        )

    # initial and delivery of Interesting
    for name in ("unordered_duplicating", "unordered_nonduplicating"):
        checker = build(Network.from_name(name)).checker().spawn_bfs().join()
        assert checker.unique_state_count() == 2, name
    # initial, delivery of Uninteresting, and subsequent delivery of Interesting
    checker = build(Network.new_ordered()).checker().spawn_bfs().join()
    assert checker.unique_state_count() == 3


def test_maintains_fixed_delta_despite_lossy_duplicating_network():
    checker = (
        ping_pong_model(PingPongCfg(maintains_history=False, max_nat=5))
        .with_lossy_network(True)
        .checker()
        .spawn_bfs()
        .join()
    )
    assert checker.unique_state_count() == 4_094
    checker.assert_no_discovery("delta within 1")


def test_may_never_reach_max_on_lossy_network():
    checker = (
        ping_pong_model(PingPongCfg(maintains_history=False, max_nat=5))
        .with_lossy_network(True)
        .checker()
        .spawn_bfs()
        .join()
    )
    assert checker.unique_state_count() == 4_094
    # can lose the first message and get stuck, for example
    checker.assert_discovery(
        "must reach max", [Drop(Envelope(Id(0), Id(1), Ping(0)))]
    )


def test_eventually_reaches_max_on_perfect_delivery_network():
    checker = (
        ping_pong_model(PingPongCfg(maintains_history=False, max_nat=5))
        .with_init_network(Network.new_unordered_nonduplicating())
        .with_lossy_network(False)
        .checker()
        .spawn_bfs()
        .join()
    )
    assert checker.unique_state_count() == 11
    checker.assert_no_discovery("must reach max")


def test_can_reach_max():
    checker = (
        ping_pong_model(PingPongCfg(maintains_history=False, max_nat=5))
        .with_lossy_network(False)
        .checker()
        .spawn_bfs()
        .join()
    )
    assert checker.unique_state_count() == 11
    assert checker.discovery("can reach max").last_state().actor_states == [4, 5]


def test_might_never_reach_beyond_max():
    # Exercises a falsifiable liveness property (eventually must exceed max),
    # which fails due to the state-space boundary.
    checker = (
        ping_pong_model(PingPongCfg(maintains_history=False, max_nat=5))
        .with_init_network(Network.new_unordered_nonduplicating())
        .with_lossy_network(False)
        .checker()
        .spawn_bfs()
        .join()
    )
    assert checker.unique_state_count() == 11
    assert checker.discovery("must exceed max").last_state().actor_states == [5, 5]


def test_handles_undeliverable_messages():
    class Noop(Actor):
        def on_start(self, id, out):
            return ()

    checker = (
        ActorModel()
        .actor(Noop())
        .property(Expectation.ALWAYS, "unused", lambda m, s: True)
        .with_init_network(
            Network.new_unordered_duplicating([Envelope(Id(0), Id(99), ())])
        )
        .checker()
        .spawn_bfs()
        .join()
    )
    assert checker.unique_state_count() == 1


def test_handles_ordered_network_flag():
    class OrderedNetworkActor(Actor):
        def on_start(self, id, out):
            if id == Id(0):
                out.send(Id(1), 2)  # count down
                out.send(Id(1), 1)
            return ()

        def on_msg(self, id, state, src, msg, out):
            return state + (msg,)

    def recipient_states(network):
        recorder, accessor = StateRecorder.new_with_accessor()
        (
            ActorModel()
            .actor(OrderedNetworkActor())
            .actor(OrderedNetworkActor())
            .property(Expectation.ALWAYS, "", lambda m, s: True)
            .with_init_network(network)
            .checker()
            .visitor(recorder)
            .spawn_bfs()
            .join()
        )
        return {s.actor_states[1] for s in accessor()}

    # Fewer states if network is ordered.
    assert recipient_states(Network.new_ordered()) == {(), (2,), (2, 1)}
    # More states if network is not ordered.
    assert recipient_states(Network.new_unordered_nonduplicating()) == {
        (),
        (1,),
        (2,),
        (1, 2),
        (2, 1),
    }


def enumerate_action_sequences(lossy, init_network):
    """Two actors; the first sends the same two messages; the second counts.

    Reference: model.rs:1163-1215.
    """

    class A(Actor):
        def on_start(self, id, out):
            if id == Id(0):
                out.send(Id(1), ())
                out.send(Id(1), ())
            return 0

        def on_msg(self, id, state, src, msg, out):
            return state + 1

    recorder, accessor = PathRecorder.new_with_accessor()
    (
        ActorModel()
        .actor(A())
        .actor(A())
        .with_init_network(init_network)
        .with_lossy_network(lossy)
        .property(Expectation.ALWAYS, "force visiting all states", lambda m, s: True)
        .with_within_boundary(lambda cfg, s: s.actor_states[1] < 4)
        .checker()
        .visitor(recorder)
        .spawn_dfs()
        .join()
    )
    return {tuple(p.into_actions()) for p in accessor()}


def test_unordered_network_has_a_bug():
    deliver = Deliver(src=Id(0), dst=Id(1), msg=())
    drop = Drop(Envelope(src=Id(0), dst=Id(1), msg=()))

    # Ordered networks can deliver/drop both messages.
    ordered_lossless = enumerate_action_sequences(False, Network.new_ordered())
    assert (deliver, deliver) in ordered_lossless
    assert (deliver, deliver, deliver) not in ordered_lossless
    ordered_lossy = enumerate_action_sequences(True, Network.new_ordered())
    assert (deliver, deliver) in ordered_lossy
    assert (deliver, drop) in ordered_lossy  # same state as "drop, deliver"
    assert (drop, drop) in ordered_lossy

    # Unordered duplicating networks can deliver/drop duplicates. Dropping
    # means "never deliver again" (model.rs:1246-1249).
    unord_dup_lossless = enumerate_action_sequences(
        False, Network.new_unordered_duplicating()
    )
    assert (deliver, deliver, deliver) in unord_dup_lossless
    unord_dup_lossy = enumerate_action_sequences(
        True, Network.new_unordered_duplicating()
    )
    assert (deliver, deliver, deliver) in unord_dup_lossy
    assert (deliver, deliver, drop) in unord_dup_lossy
    assert (deliver, drop) in unord_dup_lossy
    assert (drop,) in unord_dup_lossy
    assert (drop, deliver) not in unord_dup_lossy

    # Unordered nonduplicating networks can deliver/drop both messages.
    unord_nondup_lossless = enumerate_action_sequences(
        False, Network.new_unordered_nonduplicating()
    )
    assert (deliver, deliver) in unord_nondup_lossless
    unord_nondup_lossy = enumerate_action_sequences(
        True, Network.new_unordered_nonduplicating()
    )
    assert (deliver, drop) in unord_nondup_lossy
    assert (drop, drop) in unord_nondup_lossy


def test_resets_timer():
    class TestActor(Actor):
        def on_start(self, id, out):
            out.set_timer("t", model_timeout())
            return ()

    # Init state with timer, followed by next state without timer.
    checker = (
        ActorModel()
        .actor(TestActor())
        .property(Expectation.ALWAYS, "unused", lambda m, s: True)
        .checker()
        .spawn_bfs()
        .join()
    )
    assert checker.unique_state_count() == 2


def test_choose_random():
    class TestActor(Actor):
        def on_start(self, id, out):
            out.choose_random("key1", ["Choice1", "Choice2", "Choice3"])
            return None

        def on_random(self, id, state, random, out):
            return random

    # Init state with a random choice, followed by 3 possible next states.
    checker = (
        ActorModel()
        .actor(TestActor())
        .property(Expectation.ALWAYS, "unused", lambda m, s: True)
        .checker()
        .spawn_bfs()
        .join()
    )
    assert checker.unique_state_count() == 4


def test_overwrite_choose_random():
    class TestActor(Actor):
        def on_start(self, id, out):
            out.choose_random("key1", ["Choice1"])
            out.choose_random("key2", ["Choice2", "Choice3"])
            return ()

        def on_random(self, id, state, random, out):
            if random == "Choice1":
                out.choose_random("key2", ["Choice3"])
            return state + (random,)

    #      /-> key1:Choice1 -> key2:Choice3
    # Init --> key2:Choice2 -> key1:Choice1 -> key2:Choice3
    #      \-> key2:Choice3 -> key1:Choice1 -> key2:Choice3
    checker = (
        ActorModel()
        .actor(TestActor())
        .property(Expectation.ALWAYS, "unused", lambda m, s: True)
        .checker()
        .spawn_bfs()
        .join()
    )
    assert checker.unique_state_count() == 9


def test_crash_requires_timer_or_random_to_differ():
    # `crashed` is excluded from the fingerprint (model_state.rs:134-145), so
    # crashing an actor with no timers/randoms dedups against its parent.
    class Idle(Actor):
        def on_start(self, id, out):
            return ()

    checker = (
        ActorModel()
        .actor(Idle())
        .with_max_crashes(1)
        .property(Expectation.ALWAYS, "unused", lambda m, s: True)
        .checker()
        .spawn_bfs()
        .join()
    )
    assert checker.unique_state_count() == 1

    class WithTimer(Actor):
        def on_start(self, id, out):
            out.set_timer("tick", model_timeout())
            return ()

    # init (timer set) -> timeout fires (timer gone) / crash (timers cleared);
    # the crashed state and the post-timeout state collapse into one entry.
    checker = (
        ActorModel()
        .actor(WithTimer())
        .with_max_crashes(1)
        .property(Expectation.ALWAYS, "unused", lambda m, s: True)
        .checker()
        .spawn_bfs()
        .join()
    )
    assert checker.unique_state_count() == 2


def test_script_actor_round_trip():
    from stateright_tpu.actor import ScriptActor

    class Echo(Actor):
        def on_start(self, id, out):
            return 0

        def on_msg(self, id, state, src, msg, out):
            out.send(src, msg)
            return state + 1

    checker = (
        ActorModel()
        .actor(ScriptActor([(Id(1), "a"), (Id(1), "b")]))
        .actor(Echo())
        .with_init_network(Network.new_ordered())
        .property(
            Expectation.SOMETIMES,
            "script finishes",
            lambda m, s: s.actor_states[0] == 2 and s.actor_states[1] == 2,
        )
        .checker()
        .spawn_bfs()
        .join()
    )
    checker.assert_properties()
