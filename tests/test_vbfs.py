"""Vectorized threaded host engine: parity with the reference engine.

`.threads(n).spawn_bfs()` on a tensor-backed checker routes to the
vectorized engine (native claim set + numpy lane batches); these tests pin
its semantics to the single-threaded reference engine on every observable:
unique counts, generated counts, discoveries, shortest paths, eventually
properties, targets, and depth limits.
"""

import numpy as np
import pytest

from stateright_tpu.models import IncrementTensor, TwoPhaseTensor
from stateright_tpu.models.abd import AbdTensor
from stateright_tpu.tensor import TensorModel, TensorModelAdapter, TensorProperty


def both(tm_factory, configure=lambda c: c, threads=4):
    plain = configure(TensorModelAdapter(tm_factory()).checker()).spawn_bfs().join()
    vec = (
        configure(TensorModelAdapter(tm_factory()).checker())
        .threads(threads)
        .spawn_bfs()
        .join()
    )
    return plain, vec


def test_counts_and_discoveries_2pc5():
    plain, vec = both(lambda: TwoPhaseTensor(5))
    assert vec.unique_state_count() == plain.unique_state_count() == 8832
    assert vec.state_count() == plain.state_count()
    assert vec.max_depth() == plain.max_depth()
    assert (vec.discovery("consistent") is None) == (
        plain.discovery("consistent") is None
    )


def test_shortest_counterexample_increment_race():
    plain, vec = both(lambda: IncrementTensor(2))
    tp, tv = plain.discovery("fin"), vec.discovery("fin")
    assert tv is not None
    assert len(tv.into_actions()) == len(tp.into_actions()) == 4
    # the trace replays through the model
    assert tv.into_actions()


def test_abd_golden():
    plain, vec = both(lambda: AbdTensor(2))
    assert vec.unique_state_count() == plain.unique_state_count() == 544
    assert vec.discovery("linearizable") is None


def test_eventually_terminal_discoveries():
    class Counter(TensorModel):
        """Counts 0..3; 'reaches 5' eventually-property must be discovered
        at the terminal state (3) with the bit still pending."""

        state_width = 1
        max_actions = 1

        def init_states_array(self):
            return np.zeros((1, 1), dtype=np.uint32)

        def step_lanes(self, xp, lanes):
            u = xp.uint32
            return [(lanes[0] + u(1),)], [lanes[0] < u(3)]

        def tensor_properties(self):
            return [
                TensorProperty.eventually(
                    "reaches 5", lambda xp, l: l[0] == xp.uint32(5)
                )
            ]

    plain, vec = both(Counter)
    assert vec.unique_state_count() == plain.unique_state_count() == 4
    tp, tv = plain.discovery("reaches 5"), vec.discovery("reaches 5")
    assert tv is not None and tp is not None
    assert len(tv.into_actions()) == len(tp.into_actions()) == 3


def test_target_state_count_and_depth():
    _plain, vec = both(
        lambda: TwoPhaseTensor(5), lambda c: c.target_state_count(2000)
    )
    assert vec.state_count() >= 2000
    _plain, vec2 = both(
        lambda: TwoPhaseTensor(5), lambda c: c.target_max_depth(3)
    )
    assert vec2.max_depth() <= 3


def test_visited_set_growth():
    from stateright_tpu.native.vset import VisitedSet

    vs = VisitedSet(1 << 10)
    rng = np.random.default_rng(7)
    keys = rng.integers(1, 2**63, size=5000, dtype=np.uint64)
    new1 = vs.insert_batch(keys, 4)  # forces several growths
    assert len(vs) == len(np.unique(keys)) == new1.sum()
    new2 = vs.insert_batch(keys, 4)
    assert not new2.any()


def test_rich_host_models_route_to_parallel_engine():
    from stateright_tpu.engines.pbfs import ParallelBfsChecker
    from stateright_tpu.models.fixtures import BinaryClock

    c = BinaryClock().checker().threads(4).spawn_bfs()
    assert isinstance(c, ParallelBfsChecker)
    assert c.join().unique_state_count() == 2


def test_tpc7_exact_row_golden():
    """2pc-7's TRUE count is 296,448 — derived by exact-row-identity BFS,
    independent of any fingerprint hash. (Rounds 1-3 reported 296,447: one
    64-bit pair collision under the old correlated hash halves silently
    merged two distinct states.) The fingerprint-based engines must now
    agree with the exact count."""
    vec = (
        TensorModelAdapter(TwoPhaseTensor(7)).checker().threads(8).spawn_bfs().join()
    )
    assert vec.unique_state_count() == 296_448, vec.unique_state_count()
