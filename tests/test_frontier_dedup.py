"""Property tests for the claim-based in-batch dedup (ops/frontier.py).

claim_dedup is APPROXIMATE by contract: distinct-key scratch collisions may
retain extra duplicates (the visited-set insert arbitrates them exactly),
but it must never be unsound. The invariants that matter:

  1. every distinct valid key keeps at least one representative,
  2. no invalid row survives,
  3. with a collision-free scratch (cap >> batch), exactly one
     representative survives per distinct key.
"""

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")

from stateright_tpu.ops.frontier import claim_dedup


def _exact_first_occurrence_count(h1, h2, valid):
    keys = {(int(a), int(b)) for a, b, v in zip(h1, h2, valid) if v}
    return len(keys)


@pytest.mark.parametrize("seed", range(20))
def test_claim_dedup_invariants(seed):
    rng = np.random.default_rng(seed)
    n = 512
    # Heavy duplication: keys drawn from a small pool.
    pool = rng.integers(1, 2**32, size=(24, 2), dtype=np.uint32)
    pick = rng.integers(0, len(pool), n)
    h1 = jnp.asarray(pool[pick, 0])
    h2 = jnp.asarray(pool[pick, 1])
    valid = jnp.asarray(rng.random(n) < 0.7)

    mask = np.asarray(claim_dedup(h1, h2, valid, 4096))
    h1n, h2n, vn = np.asarray(h1), np.asarray(h2), np.asarray(valid)

    # (2) no invalid survivor
    assert not np.any(mask & ~vn)
    # (1) coverage: every distinct valid key has a representative
    valid_keys = {(a, b) for a, b, v in zip(h1n, h2n, vn) if v}
    surviving_keys = {(a, b) for a, b, m in zip(h1n, h2n, mask) if m}
    assert surviving_keys == valid_keys


@pytest.mark.parametrize("seed", range(5))
def test_claim_dedup_exact_when_collision_free(seed):
    rng = np.random.default_rng(100 + seed)
    n = 256
    pool = rng.integers(1, 2**32, size=(16, 2), dtype=np.uint32)
    pick = rng.integers(0, len(pool), n)
    h1 = jnp.asarray(pool[pick, 0])
    h2 = jnp.asarray(pool[pick, 1])
    valid = jnp.asarray(np.ones(n, dtype=bool))
    # Scratch vastly larger than the key pool: collisions vanishingly rare,
    # so the mask must be minimal (one survivor per key).
    mask = np.asarray(claim_dedup(h1, h2, valid, 1 << 20))
    assert mask.sum() == _exact_first_occurrence_count(
        np.asarray(h1), np.asarray(h2), np.asarray(valid)
    )
