"""Flight recorder (stateright_tpu/obs/flight.py): ring semantics, the
per-era device/host-gap wall split, the engine integrations (single
device, simulation, sharded mesh with per-shard labeled metrics), and
the export surfaces (JSONL, Chrome counter tracks, /flight).
"""

import json

import jax
import pytest

from stateright_tpu import TensorModelAdapter
from stateright_tpu.models import TwoPhaseTensor
from stateright_tpu.obs.flight import FlightRecorder
from stateright_tpu.obs.metrics import SHARD_SERIES_LABELS, render_prometheus
from stateright_tpu.parallel import ShardedBfs


@pytest.fixture(scope="module")
def devices():
    devs = jax.devices()
    assert len(devs) >= 8, "conftest should force 8 virtual CPU devices"
    return devs[:8]


# -- recorder unit semantics --------------------------------------------------


def test_capacity_validation():
    with pytest.raises(ValueError, match="capacity"):
        FlightRecorder(capacity=0)


def test_device_gap_wall_identity_per_record():
    fr = FlightRecorder()
    fr.start(t=100.0)
    fr.record(device_era_secs=0.2, t=100.5)  # 0.3s of host gap
    fr.record(device_era_secs=0.4, t=101.0)  # 0.1s of host gap
    recs = fr.records()
    assert [r["era"] for r in recs] == [1, 2]
    for r in recs:
        # The load-bearing overlap-aware identity, exact per record.
        assert r["device_era_secs"] - r["overlap_secs"] + r[
            "host_gap_secs"
        ] == pytest.approx(r["wall_secs"])
        assert r["overlap_secs"] == 0.0  # serial eras: no overlap
    assert recs[0]["host_gap_secs"] == pytest.approx(0.3)
    assert recs[1]["host_gap_secs"] == pytest.approx(0.1)
    s = fr.summary()
    assert s["eras"] == 2
    assert s["device_secs"] == pytest.approx(0.6)
    assert s["host_gap_secs"] == pytest.approx(0.4)
    assert s["overlap_secs"] == 0.0
    assert s["wall_secs"] == pytest.approx(1.0)
    assert s["host_gap_pct"] == pytest.approx(40.0)


def test_overlap_booked_when_device_exceeds_wall():
    # A pipelined engine can report a device span larger than the wall
    # delta since the previous readback (its dispatch overlapped the
    # previous era's host work). The excess is booked as overlap_secs —
    # not silently clamped — so device - overlap + gap == wall stays
    # exact and the run-level totals reconcile with the external clock.
    fr = FlightRecorder()
    fr.start(t=0.0)
    fr.record(device_era_secs=2.0, t=1.0)
    fr.record(device_era_secs=0.5, t=2.0)  # serial era afterwards
    recs = fr.records()
    assert recs[0]["host_gap_secs"] == 0.0
    assert recs[0]["overlap_secs"] == pytest.approx(1.0)
    assert recs[0]["device_era_secs"] - recs[0]["overlap_secs"] + recs[0][
        "host_gap_secs"
    ] == pytest.approx(recs[0]["wall_secs"])
    # Exactly one of gap/overlap is nonzero per record.
    assert recs[1]["overlap_secs"] == 0.0
    assert recs[1]["host_gap_secs"] == pytest.approx(0.5)
    s = fr.summary()
    assert s["overlap_secs"] == pytest.approx(1.0)
    assert s["device_secs"] - s["overlap_secs"] + s[
        "host_gap_secs"
    ] == pytest.approx(s["wall_secs"])


def test_lazy_anchor_without_start():
    # An engine that skips start(): the first record's wall time equals
    # its device time (zero gap) instead of measuring from the epoch.
    fr = FlightRecorder()
    fr.record(device_era_secs=0.25, t=50.0)
    rec = fr.records()[0]
    assert rec["wall_secs"] == pytest.approx(0.25)
    assert rec["host_gap_secs"] == 0.0


def test_ring_eviction_keeps_summary_exact():
    fr = FlightRecorder(capacity=4)
    fr.start(t=0.0)
    for i in range(10):
        fr.record(device_era_secs=0.1, t=float(i + 1))
    assert len(fr) == 4
    recs = fr.records()
    assert [r["era"] for r in recs] == [7, 8, 9, 10]  # oldest evicted
    s = fr.summary()
    assert s["eras"] == 10
    assert s["recorded"] == 4
    assert s["dropped"] == 6
    # Totals accumulate across the WHOLE run, not just the retained ring.
    assert s["wall_secs"] == pytest.approx(10.0)
    assert s["device_secs"] == pytest.approx(1.0)


def test_export_jsonl_and_chrome_shapes(tmp_path):
    fr = FlightRecorder(engine="TestEngine")
    fr.start(t=0.0)
    fr.record(device_era_secs=0.1, frontier=10, load_factor=0.5, t=0.2)
    jpath = tmp_path / "f.jsonl"
    fr.export_jsonl(str(jpath))
    lines = [json.loads(ln) for ln in jpath.read_text().splitlines()]
    assert lines[0]["era"] == 1
    assert lines[-1]["summary"]["eras"] == 1
    assert lines[-1]["engine"] == "TestEngine"

    events = fr.chrome_counter_events()
    assert {e["name"] for e in events} == {
        "flight era (ms)",
        "flight frontier",
        "flight load_factor",
    }
    assert all(e["ph"] == "C" for e in events)
    cpath = tmp_path / "f.trace.json"
    fr.export_chrome(str(cpath))
    assert json.loads(cpath.read_text()) == events


# -- builder surface ----------------------------------------------------------


def test_builder_flight_format_validation():
    with pytest.raises(ValueError, match="format"):
        TensorModelAdapter(TwoPhaseTensor(3)).checker().flight(
            path="x.jsonl", format="xml"
        )


# -- device-engine integration ------------------------------------------------


def test_device_run_records_flight_by_default(tmp_path):
    path = str(tmp_path / "run.flight.jsonl")
    c = (
        TensorModelAdapter(TwoPhaseTensor(3))
        .checker()
        .flight(path=path)
        .spawn_tpu_bfs(chunk_size=128)
        .join()
    )
    assert c.unique_state_count() == 288
    recs = c.flight()
    assert recs, "device run recorded no flight records"
    tel = c.telemetry()
    assert len(recs) == tel["eras"]
    for r in recs:
        # Overlap-aware identity: with pipelining ON (the default) a
        # chained era's device span can overlap the previous host gap.
        assert r["device_era_secs"] - r["overlap_secs"] + r[
            "host_gap_secs"
        ] == pytest.approx(r["wall_secs"])
        assert r["take_cap"] >= 1
    # The last record reconciles with the engine's own counters.
    assert recs[-1]["unique"] == c.unique_state_count()
    assert sum(r["generated"] for r in recs) == tel["states_generated"]
    assert sum(r["steps"] for r in recs) == tel["steps"]
    # Summary rides telemetry, plus the flat Prometheus-visible gauges.
    fsum = tel["flight"]
    assert fsum["eras"] == len(recs)
    assert fsum["device_secs"] - fsum["overlap_secs"] + fsum[
        "host_gap_secs"
    ] == pytest.approx(fsum["wall_secs"], rel=1e-6, abs=1e-6)
    assert tel["flight_eras"] == fsum["eras"]
    assert tel["flight_device_era_secs"] == pytest.approx(
        fsum["device_secs"]
    )
    # The JSONL export landed at run end: records + summary line.
    lines = [json.loads(ln) for ln in open(path)]
    assert [r["era"] for r in lines[:-1]] == [r["era"] for r in recs]
    assert lines[-1]["summary"]["eras"] == fsum["eras"]


def test_flight_disabled_is_clean():
    c = (
        TensorModelAdapter(TwoPhaseTensor(3))
        .checker()
        .flight(False)
        .spawn_tpu_bfs(chunk_size=128)
        .join()
    )
    assert c.unique_state_count() == 288
    assert c.flight() == []
    assert "flight" not in c.telemetry()


def test_flight_counter_tracks_ride_chrome_trace(tmp_path):
    path = str(tmp_path / "run.trace.json")
    c = (
        TensorModelAdapter(TwoPhaseTensor(3))
        .checker()
        .trace(path, format="chrome")
        .spawn_tpu_bfs(chunk_size=128)
        .join()
    )
    assert c.unique_state_count() == 288
    events = json.loads(open(path).read())
    counters = [e for e in events if e.get("ph") == "C"]
    assert len(counters) == 3 * len(c.flight())
    assert {"flight era (ms)", "flight frontier"} <= {
        e["name"] for e in counters
    }


def test_simulation_engine_records_flight():
    from stateright_tpu.models import IncrementTensor

    c = (
        TensorModelAdapter(IncrementTensor(2))
        .checker()
        .target_state_count(100)
        .spawn_tpu_simulation(7, walks=32, walk_cap=16)
        .join()
    )
    recs = c.flight()
    assert recs
    assert recs[0]["frontier"] == 32  # the walk batch width
    assert "era_secs" in c.telemetry().get("histograms", {})


# -- sharded mesh: per-shard labeled metrics ----------------------------------


def _shard_sum(tel, name):
    series = tel[name]
    assert isinstance(series, dict) and len(series) == tel["n_shards"]
    return sum(series.values())


def test_sharded_flight_and_labeled_sums_abd2(devices):
    from stateright_tpu.models.abd import AbdTensor

    sb = ShardedBfs(AbdTensor(2), devices, chunk_size=256).run()
    c = sb.checker
    assert c.unique_state_count() == 544
    tel = c.telemetry()
    # The mesh readback rows carry PER-ERA step/gen counts, so the
    # labeled per-shard series sum EXACTLY to the engine totals.
    assert _shard_sum(tel, "shard_steps") == tel["steps"]
    assert _shard_sum(tel, "shard_states_generated") == (
        tel["states_generated"]
    )
    # Exchange accounting: on a clean run every unique state was
    # accepted by exactly one shard, so the sum is the unique count.
    assert _shard_sum(tel, "shard_exchange_rows") == 544
    assert "shard_frontier_rows" in tel and "shard_load_factor" in tel
    assert tel["shard_imbalance"] >= 1.0
    # Flight records carry the per-shard breakdown.
    recs = c.flight()
    assert recs and "shards" in recs[-1]
    assert len(recs[-1]["shards"]) == len(devices)
    assert sum(
        s["exchange_rows"] for r in recs for s in r["shards"].values()
    ) == 544


def test_sharded_multi_era_identity_2pc5(devices):
    # sync_steps=4 forces many short eras; the per-era exchange deltas
    # must still sum exactly across records AND shards.
    sb = ShardedBfs(
        TwoPhaseTensor(5), devices, chunk_size=256, sync_steps=4
    ).run()
    c = sb.checker
    assert c.unique_state_count() == 8832
    tel = c.telemetry()
    assert tel["eras"] > 1, "sync_steps=4 should force a multi-era run"
    assert len(c.flight()) == tel["eras"]
    assert _shard_sum(tel, "shard_exchange_rows") == 8832
    assert _shard_sum(tel, "shard_steps") == tel["steps"]


def test_sharded_labeled_sums_paxos2(devices):
    from stateright_tpu.models.paxos import PaxosTensorExhaustive

    sb = ShardedBfs(PaxosTensorExhaustive(2), devices, chunk_size=256).run()
    c = sb.checker
    assert c.unique_state_count() == 16_668
    tel = c.telemetry()
    assert _shard_sum(tel, "shard_exchange_rows") == 16_668
    assert _shard_sum(tel, "shard_steps") == tel["steps"]
    assert _shard_sum(tel, "shard_states_generated") == (
        tel["states_generated"]
    )
    assert tel["shard_imbalance"] >= 1.0


def test_sharded_prometheus_renders_shard_series(devices):
    from stateright_tpu.models import IncrementTensor

    sb = ShardedBfs(IncrementTensor(2), devices, chunk_size=64).run()
    text = render_prometheus(
        sb.checker.telemetry(), labels=SHARD_SERIES_LABELS
    )
    assert 'stateright_shard_exchange_rows{shard="0"}' in text
    assert 'stateright_shard_frontier_rows{shard="7"}' in text
    assert "stateright_shard_imbalance" in text


# -- Explorer /flight ---------------------------------------------------------


def test_explorer_flight_endpoint():
    import urllib.request

    from stateright_tpu.explorer.server import serve
    from stateright_tpu.models.fixtures import BinaryClock

    # The Explorer drives an on-demand HOST checker, so its live /flight
    # is well-formed but empty — the panel only lights up for device
    # runs (the populated view is covered below via _flight_view).
    server = serve(BinaryClock().checker(), "127.0.0.1:0", block=False)
    try:
        with urllib.request.urlopen(
            server.url.rstrip("/") + "/flight"
        ) as r:
            assert r.status == 200
            body = json.loads(r.read())
        assert body["records"] == []
        assert body["summary"] == {}
        assert "ts" in body and "done" in body
    finally:
        server.shutdown()


def test_flight_view_populated_for_device_checker():
    from stateright_tpu.explorer.server import _flight_view

    checker = (
        TensorModelAdapter(TwoPhaseTensor(3))
        .checker()
        .spawn_tpu_bfs(chunk_size=128)
        .join()
    )
    view = _flight_view(checker)
    assert view["done"] is True
    assert view["records"] == checker.flight() and view["records"]
    assert view["summary"]["eras"] == len(view["records"])


def test_explorer_ui_ships_flight_panel():
    from pathlib import Path

    ui = Path(__file__).parent.parent / "stateright_tpu" / "explorer" / "ui"
    assert "flight-panel" in (ui / "index.html").read_text()
    js = (ui / "app.js").read_text()
    assert "/flight" in js and "pollFlight" in js
