"""Explorer server tests: handler-level golden JSON plus a live-socket
smoke test of the bundled SPA.

Reference parity: the reference drives its HTTP handler functions directly
with golden JSON, including serialized SVG (src/checker/explorer.rs:322-597);
this repo's `states_views`/`_status_view` were written to be testable the
same way.
"""

import json
import urllib.request

import pytest

from stateright_tpu.explorer.server import (
    ExplorerServer,
    _status_view,
    serve,
    states_views,
)
from stateright_tpu.models.fixtures import BinaryClock
from stateright_tpu.actor.test_util import PingPongActor, ping_pong_model


def _on_demand(model):
    return model.checker().spawn_on_demand()


def test_states_views_init_states():
    checker = _on_demand(BinaryClock())
    views = states_views(checker, "")
    # Two init states (0 and 1), each with a fingerprint and per-property
    # verdict triples (explorer.rs:224-320).
    assert len(views) == 2
    assert [v["state"] for v in views] == ["0", "1"]
    for v in views:
        assert int(v["fingerprint"]) != 0
        assert v["properties"] == [["always", "in [0, 1]", None]]
    checker.run_to_completion()
    checker.join()


def test_states_views_walks_fingerprint_path():
    checker = _on_demand(BinaryClock())
    model = checker.model()
    init_fp = model.fingerprint_state(0)
    views = states_views(checker, f"/{init_fp}")
    # From state 0 the only action is GoHigh, leading to state 1.
    assert len(views) == 1
    assert views[0]["action"] == "'GoHigh'"
    assert views[0]["state"] == "1"
    assert int(views[0]["fingerprint"]) == model.fingerprint_state(1)
    checker.run_to_completion()
    checker.join()


def test_states_views_rejects_garbage():
    checker = _on_demand(BinaryClock())
    with pytest.raises(KeyError, match="Unable to parse fingerprints"):
        states_views(checker, "/not-a-fingerprint")
    with pytest.raises(KeyError, match="Unable to find state"):
        states_views(checker, "/12345")  # no such fingerprint
    checker.run_to_completion()
    checker.join()


def test_states_views_includes_actor_svg():
    # Actor models render sequence diagrams for the walked path
    # (model.rs:550-754 / explorer.rs golden includes the SVG).
    from stateright_tpu.actor.test_util import PingPongCfg

    model = ping_pong_model(PingPongCfg(max_nat=2))
    checker = model.checker().spawn_on_demand()
    init_fp = model.fingerprint_state(model.init_states()[0])
    views = states_views(checker, f"/{init_fp}")
    assert any("svg" in v for v in views if "fingerprint" in v)
    checker.run_to_completion()
    checker.join()


def test_status_view_shape():
    from stateright_tpu.explorer.server import _Snapshot

    checker = _on_demand(BinaryClock())
    checker.run_to_completion()
    checker.join()
    view = _status_view(checker, checker.model(), _Snapshot())
    assert view["done"] is True
    assert view["model"] == "BinaryClock"
    assert view["unique_state_count"] == 2
    assert view["properties"] == [["always", "in [0, 1]", None]]


def test_live_server_serves_ui_and_api():
    server = serve(BinaryClock().checker(), "127.0.0.1:0", block=False)
    try:
        base = server.url

        def get(path):
            with urllib.request.urlopen(base.rstrip("/") + path) as r:
                return r.status, r.read()

        status, body = get("/")
        assert status == 200 and b"Explorer" in body
        status, body = get("/app.js")
        assert status == 200 and b"fingerprint" in body
        status, body = get("/app.css")
        assert status == 200
        status, body = get("/.status")
        st = json.loads(body)
        assert st["model"] == "BinaryClock"
        status, body = get("/.states/")
        assert len(json.loads(body)) == 2

        req = urllib.request.Request(
            base.rstrip("/") + "/.runtocompletion", method="POST"
        )
        with urllib.request.urlopen(req) as r:
            assert r.status == 200
        server.checker.join()
        _, body = get("/.status")
        assert json.loads(body)["done"] is True
    finally:
        server.shutdown()
