"""Memory observability (obs/memory.py): ledger parity, forecaster,
capacity planner, and the serve/speclint/reporter integrations.

The load-bearing invariant is EXACT parity: the analytic ledger must
equal ``sum(arr.nbytes)`` over the live device buffers on every engine,
including across table growth and queue spill — an approximate ledger
is worse than none, because operators size hardware off it. The planner
is the same arithmetic run before dispatch, so plan == ledger at equal
geometry is also exact, not approximate.
"""

import io
import json

import pytest

from stateright_tpu import Model, TensorModelAdapter
from stateright_tpu.has_discoveries import HasDiscoveries
from stateright_tpu.models import IncrementTensor, TwoPhaseTensor
from stateright_tpu.obs.memory import (
    Forecaster,
    MemoryRecorder,
    device_memory_bytes,
    main as plan_main,
    max_lanes_for_budget,
    plan,
    recommend_engine,
)

# ---------------------------------------------------------------------------
# Shared runs (module-scoped: the growth/spill space is 8832 states)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def growth_checker():
    """Tiny table (forces growth) + tiny queue (forces spill) on the
    2pc-5 space — the ledger must track every regrow and spill block."""
    return (
        TensorModelAdapter(TwoPhaseTensor(5))
        .checker()
        .spawn_tpu_bfs(table_capacity=1 << 8, queue_capacity=1 << 10, chunk_size=64)
        .join()
    )


@pytest.fixture(scope="module")
def bfs3_checker():
    """2pc-3 at a fixed no-growth geometry (the grow trigger reserves
    max_actions*chunk rows, so the table must be comfortably larger than
    that), mirrored by the planner test."""
    return (
        TensorModelAdapter(TwoPhaseTensor(3))
        .checker()
        .spawn_tpu_bfs(
            table_capacity=1 << 15, queue_capacity=1 << 12, chunk_size=256
        )
        .join()
    )


def _device_component_bytes(snap):
    return {
        name: c["bytes"]
        for name, c in snap["components"].items()
        if c["kind"] == "device"
    }


# ---------------------------------------------------------------------------
# Ledger parity on all three device engines
# ---------------------------------------------------------------------------


def test_tpu_bfs_ledger_parity_across_growth_and_spill(growth_checker):
    c = growth_checker
    assert c.unique_state_count() == 8832
    snap = c.telemetry()["memory"]
    # EXACT: analytic bytes == nbytes over the live buffers, after growth.
    assert snap["total_bytes"] == c._memory.ledger.live_nbytes()
    assert snap["total_bytes"] > 0
    events = snap["events"]
    kinds = {e["event"] for e in events}
    resizes = [
        e
        for e in events
        if e["event"] == "resize" and e["component"] == "visited_table"
    ]
    assert resizes, "the 1<<8 table must have regrown on 8832 states"
    for e in resizes:
        assert e["to_bytes"] > e["from_bytes"]
    # The 1<<12 queue must have spilled to host staging and refilled.
    assert "spill" in kinds and "refill" in kinds
    assert snap["peak_bytes"] >= snap["total_bytes"]


def test_flight_records_carry_memory(growth_checker):
    records = growth_checker.flight()
    assert records
    for rec in records:
        mem = rec["memory"]
        assert mem["total_bytes"] > 0
        assert mem["by_component"]["visited_table"] > 0


def test_memory_snapshot_is_json_serializable(growth_checker):
    json.dumps(growth_checker.telemetry()["memory"])


def test_tpu_simulation_ledger_parity():
    c = (
        TensorModelAdapter(IncrementTensor(2))
        .checker()
        .finish_when(HasDiscoveries.any_of(["fin"]))
        .spawn_tpu_simulation(7, walks=64, walk_cap=64)
        .join()
    )
    snap = c.telemetry()["memory"]
    assert snap["total_bytes"] == c._memory.ledger.live_nbytes()
    comps = _device_component_bytes(snap)
    assert comps["walk_lanes"] > 0
    assert comps["path_fps"] > 0


def test_sharded_ledger_parity():
    c = (
        TensorModelAdapter(TwoPhaseTensor(3))
        .checker()
        .spawn_sharded_bfs(
            chunk_size=128,
            queue_capacity_per_shard=1 << 12,
            table_capacity_per_shard=1 << 10,
        )
        .join()
    )
    assert c.unique_state_count() == 288
    snap = c.telemetry()["memory"]
    assert snap["total_bytes"] == c._memory.ledger.live_nbytes()
    assert snap["total_bytes"] > 0


def test_memory_off_builder():
    c = (
        TensorModelAdapter(TwoPhaseTensor(3))
        .checker()
        .memory(False)
        .spawn_tpu_bfs()
        .join()
    )
    assert "memory" not in c.telemetry()


# ---------------------------------------------------------------------------
# Planner: plan == ledger at equal geometry
# ---------------------------------------------------------------------------


def test_plan_matches_ledger_exactly(bfs3_checker):
    snap = bfs3_checker.telemetry()["memory"]
    assert not any(e["event"] == "resize" for e in snap["events"])
    p = plan(
        TensorModelAdapter(TwoPhaseTensor(3)),
        engine="tpu_bfs",
        chunk=256,
        queue_capacity=1 << 12,
        table_capacity=1 << 15,
    )
    planned = {name: c["bytes"] for name, c in p["components"].items()}
    assert _device_component_bytes(snap) == planned
    assert snap["total_bytes"] == p["total_bytes"]


def test_plan_engine_aliases_and_fit():
    m = TensorModelAdapter(TwoPhaseTensor(3))
    assert plan(m, engine="mesh")["components"] == plan(m, engine="sharded")[
        "components"
    ]
    assert plan(m, engine="bfs")["engine"] == plan(m, engine="tpu_bfs")["engine"]
    p = plan(m, engine="tpu_bfs", device_limit_bytes=1000)
    assert p["fits"] is False and p["headroom_bytes"] < 0
    p2 = plan(m, engine="tpu_bfs", device_limit_bytes=p["total_bytes"])
    assert p2["fits"] is True
    # Per-lane arithmetic on the multiplex engine.
    pm = plan(m, engine="multiplex", lanes=4)
    assert pm["per_lane_bytes"] == pm["total_bytes"] // 4


def test_plan_rejects_host_only_models():
    class HostOnly(Model):
        def init_states(self):
            return [0]

        def actions(self, state, actions):
            pass

        def next_state(self, state, action):
            return state

        def properties(self):
            return []

    with pytest.raises(TypeError):
        plan(HostOnly())


def test_recommend_engine_order_and_budget():
    m = TensorModelAdapter(TwoPhaseTensor(3))
    totals = {
        e: plan(m, engine=e)["total_bytes"]
        for e in ("tpu_bfs", "sharded", "tpu_simulation")
    }
    big = max(totals.values())
    assert recommend_engine(m, big) == "tpu_bfs"
    assert recommend_engine(m, 100) is None  # nothing fits in 100 bytes
    if totals["sharded"] <= big:
        assert recommend_engine(m, big, exclude=("tpu_bfs",)) == "sharded"


def test_max_lanes_for_budget():
    m = TensorModelAdapter(IncrementTensor(2))
    per_lane = plan(m, engine="multiplex", lanes=1)["total_bytes"]
    # No known limit -> the configured lane count, untouched.
    assert max_lanes_for_budget(m, None) == 32
    assert max_lanes_for_budget(m, None, lanes=8) == 8
    # A budget under one lane still grants one (the job must run somewhere).
    assert max_lanes_for_budget(m, per_lane) == 1
    # Plenty of budget -> capped at the configured lanes.
    assert max_lanes_for_budget(m, per_lane * 100, lanes=8) == 8


def test_plan_cli(capsys):
    assert plan_main(["2pc:3", "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["fits"] is None or doc["fits"] is True  # no limit known on CPU
    assert doc["total_bytes"] > 0

    assert plan_main(["2pc:3", "--limit-bytes", "1000"]) == 3
    out = capsys.readouterr().out
    assert "DOES NOT FIT" in out

    with pytest.raises(SystemExit):
        plan_main(["no-such-model:1"])


# ---------------------------------------------------------------------------
# Forecaster
# ---------------------------------------------------------------------------


def test_forecaster_geometric_growth_and_exhaustion():
    f = Forecaster()
    for u in (10, 30, 70, 150, 310):
        f.observe(u)
    r, d = f.fit()
    assert r == pytest.approx(2.0)
    assert d == 160
    base = dict(
        unique=310,
        rows=4096,
        max_load=0.25,
        reserve_rows=0,
        table_bytes=4096 * 8,
    )
    fc = f.forecast(**base)
    # 310 -> 470 -> 790 -> 1430 crosses 0.25*4096=1024 at era 3.
    assert fc["eras_to_grow"] == 3
    assert fc["eras_to_exhaustion"] is None
    assert fc["projected_unique"] is None  # diverging (r >= 1)
    fc = f.forecast(**base, device_limit=40_000)
    # The era-3 doubling (32768 -> 65536 bytes) crosses the 40k limit.
    assert fc["eras_to_exhaustion"] == 3


def test_forecaster_plateau():
    f = Forecaster()
    for u in (100, 180, 220, 240):
        f.observe(u)
    r, d = f.fit()
    assert r == pytest.approx(0.5)
    assert d == 20
    fc = f.forecast(
        unique=240,
        rows=1 << 20,
        max_load=0.9,
        reserve_rows=0,
        table_bytes=8 << 20,
        device_limit=1 << 30,
    )
    # Decaying deltas converge: u + d*r/(1-r) = 240 + 20 = 260.
    assert fc["projected_unique"] == 260
    assert fc["eras_to_grow"] is None
    assert fc["eras_to_exhaustion"] is None
    assert fc["projected_table_bytes"] == 8 << 20


def test_forecaster_load_frac_is_measured_not_simulated():
    """`load_frac` reports how much of the grow trigger CURRENT occupancy
    has consumed — the proactive-reshard gate's self-limiting input: a
    doubling of `rows` halves it regardless of the fitted ratio."""
    f = Forecaster()
    for u in (10, 30, 70, 150, 310):
        f.observe(u)  # diverging fit (r == 2)
    base = dict(
        unique=512, max_load=0.25, reserve_rows=0, table_bytes=4096 * 8
    )
    fc = f.forecast(rows=4096, **base)
    assert fc["load_frac"] == pytest.approx(0.5)
    # Same fit, doubled table: the measured fraction halves even though
    # the simulated projection still diverges.
    fc2 = f.forecast(rows=8192, **base)
    assert fc2["load_frac"] == pytest.approx(0.25)
    # Reserve rows count against the trigger just like the engines' own
    # grow check does.
    fc3 = f.forecast(rows=4096, **{**base, "reserve_rows": 512})
    assert fc3["load_frac"] == pytest.approx(1.0)


def test_forecaster_needs_three_observations():
    f = Forecaster()
    f.observe(10)
    f.observe(20)
    assert f.fit() == (None, None)
    fc = f.forecast(
        unique=20, rows=64, max_load=0.5, reserve_rows=0, table_bytes=512
    )
    assert fc["ratio"] is None and fc["eras_to_grow"] is None


def test_recorder_one_shot_warning():
    rec = MemoryRecorder(engine="TpuBfsChecker", device_limit_bytes=100_000)
    rec.ledger.register("visited_table", nbytes=60_000)
    rec.on_era(unique=10, load_factor=0.1)
    # Headroom (40k) cannot fit the next table doubling (60k) -> warn.
    first = rec.warning
    assert first is not None
    assert "device memory pressure" in first
    assert "regrow now" in first
    rec.on_era(unique=20, load_factor=0.2)
    assert rec.warning is first  # one-shot: never rewritten


# ---------------------------------------------------------------------------
# Serve integration: 413 admission, lane right-sizing, OOM post-mortem
# ---------------------------------------------------------------------------


def test_serve_memory_admission_413():
    from stateright_tpu.serve import RunService

    svc = RunService(workers=1, lint_samples=16, device_memory_bytes=1024)
    try:
        svc.pause()
        code, body = svc.submit({"spec": "2pc:3"})
        assert code == 413, body
        assert body["predicted_bytes"] > body["available_bytes"] == 1024
        assert body["engine"] == "multiplex"
        assert svc.metrics.snapshot()["serve_rejected_memory"] == 1
    finally:
        svc.shutdown()


def test_serve_lane_rightsizing():
    from stateright_tpu.serve import RunService

    m = TensorModelAdapter(IncrementTensor(2))
    per_lane = plan(
        m,
        engine="multiplex",
        lanes=1,
        chunk=256,
        queue_capacity=1 << 13,
        table_capacity=1 << 16,
    )["total_bytes"]
    # A budget that fits exactly two lanes (after the 0.9 safety factor).
    limit = int(per_lane * 2 / 0.9) + 2
    svc = RunService(
        workers=1, lanes=8, lint_samples=16, device_memory_bytes=limit
    )
    try:
        svc.pause()
        for _ in range(4):
            code, body = svc.submit({"spec": "increment:2"})
            assert code == 202, body
        with svc._cv:
            batch = svc._pop_batch()
        # 4 same-signature lanes queued, but only 2 fit the budget.
        assert len(batch) == 2
        snap = svc.metrics.snapshot()
        assert snap["serve_lane_budget"] == 2
        assert snap["serve_lanes_rightsized"] >= 1
    finally:
        svc.shutdown()


def test_is_oom_classifier():
    from stateright_tpu.serve.durability import is_oom

    assert is_oom("RuntimeError: RESOURCE_EXHAUSTED: out of memory")
    assert is_oom("XlaRuntimeError: Out of memory allocating 123 bytes")
    assert not is_oom("ValueError: bad spec")
    assert not is_oom("TimeoutError: deadline")


def test_oom_postmortem_journal(tmp_path):
    from stateright_tpu.serve import RunService
    from stateright_tpu.serve.durability import RetryPolicy

    journal = str(tmp_path / "serve.journal")
    svc = RunService(
        workers=1,
        lint_samples=16,
        journal_path=journal,
        retry=RetryPolicy(max_attempts=1),
    )
    try:
        svc.pause()
        code, body = svc.submit({"spec": "2pc:3"})
        assert code == 202, body
        job_id = body["job_id"]
        job = svc._jobs[job_id]
        job.attempts = 1  # out of attempts -> the failure is terminal
        svc._handle_failure(
            [job], RuntimeError("RESOURCE_EXHAUSTED: out of memory")
        )
        assert job.status == "failed"
        mem = job.memory_at_failure
        assert mem is not None
        assert mem["source"] == "plan" and mem["total_bytes"] > 0
        assert job.view()["memory_at_failure"] == mem
    finally:
        svc.shutdown()

    # The post-mortem must survive a service restart via the journal.
    svc2 = RunService(workers=1, lint_samples=16, journal_path=journal)
    try:
        restored = svc2._jobs[job_id]
        assert restored.status == "failed"
        assert restored.memory_at_failure["source"] == "plan"
        assert restored.memory_at_failure["total_bytes"] == mem["total_bytes"]
    finally:
        svc2.shutdown()


# ---------------------------------------------------------------------------
# Explorer, Prometheus, reporter, speclint
# ---------------------------------------------------------------------------


def test_explorer_memory_view_and_prom_series(bfs3_checker):
    from stateright_tpu.explorer.server import _memory_view, _metrics_prometheus

    view = _memory_view(bfs3_checker)
    assert view["memory"]["components"]["visited_table"]["bytes"] > 0
    prom = _metrics_prometheus(bfs3_checker)
    assert 'memory_bytes{component="visited_table"}' in prom


def test_write_reporter_memory_line():
    from stateright_tpu.report import ReportData, WriteReporter

    buf = io.StringIO()
    reporter = WriteReporter(buf)
    reporter.report_checking(
        ReportData(
            total_states=10,
            unique_states=5,
            max_depth=3,
            duration_secs=1.0,
            done=True,
            telemetry={
                "eras": 3,
                "memory": {
                    "total_bytes": 1000,
                    "peak_bytes": 1200,
                    "host_bytes": 64,
                    "headroom_bytes": 500,
                    "forecast": {"eras_to_exhaustion": 7},
                    "warning": "device memory pressure: test",
                },
            },
        )
    )
    out = buf.getvalue()
    assert "Memory. resident_bytes=1000, peak_bytes=1200" in out
    assert "host_bytes=64" in out
    assert "eta_exhaustion_eras=7" in out
    assert "Warning. device memory pressure" in out
    # The nested snapshot must NOT bloat the flat telemetry pairs line.
    telemetry_line = next(l for l in out.splitlines() if l.startswith("Telemetry."))
    assert "total_bytes" not in telemetry_line


def test_speclint_str208_footprint(monkeypatch):
    from stateright_tpu.analysis import analyze

    monkeypatch.setenv("STPU_DEVICE_MEMORY_BYTES", "4096")
    assert device_memory_bytes() == 4096
    report = analyze(TwoPhaseTensor(3))
    assert "STR208" in report.counts_by_code()
    assert report.ok  # a warning, not an error

    monkeypatch.delenv("STPU_DEVICE_MEMORY_BYTES")
    if device_memory_bytes() is None:  # CPU hosts: no limit, no finding
        report = analyze(TwoPhaseTensor(3))
        assert "STR208" not in report.counts_by_code()
