"""Kill-and-resume chaos tests: durability under crashes, corruption,
signals, and resource exhaustion (ISSUE 9 tentpole).

The contract under test, for every device engine:

* a run killed at an era boundary resumes from its checkpoint to the
  EXACT golden counts (2pc-5: 8,832; paxos-2: 16,668);
* a corrupt/truncated newest checkpoint falls back to the previous
  rolling generation instead of losing the run;
* visited-table probe-budget exhaustion degrades gracefully (reload the
  last checkpoint, regrow the table, continue) instead of aborting —
  injected here via the engines' private `_chaos_probe_error_era` hook,
  because the proactive-growth invariant makes the real thing
  unreachable by construction;
* a SIGTERM/SIGINT mid-run flushes a final checkpoint before exit;
* a multiplexed sweep resumes from its per-batch snapshots without
  re-dispatching completed batches.
"""

import os
import signal

import pytest

from stateright_tpu.models import IncrementTensor, TwoPhaseTensor
from stateright_tpu.tensor import TensorModelAdapter

OPTS = dict(chunk_size=64, queue_capacity=1 << 12, table_capacity=1 << 11)


def _paxos_opts():
    return dict(
        chunk_size=1024, queue_capacity=1 << 16, table_capacity=1 << 16
    )


# ---------------------------------------------------------------------------
# Kill/resume goldens (2pc-5 lives in test_checkpoint.py / test_sharded.py)
# ---------------------------------------------------------------------------


def test_tpu_bfs_kill_resume_paxos2_golden(tmp_path):
    from stateright_tpu.models.paxos import PaxosTensorExhaustive

    ckpt = str(tmp_path / "paxos.ckpt.npz")
    part = (
        TensorModelAdapter(PaxosTensorExhaustive(2))
        .checker()
        .target_state_count(4_000)
        .spawn_tpu_bfs(checkpoint_path=ckpt, **_paxos_opts())
        .join()
    )
    assert 0 < part.unique_state_count() < 16_668
    resumed = (
        TensorModelAdapter(PaxosTensorExhaustive(2))
        .checker()
        .spawn_tpu_bfs(resume_from=ckpt, **_paxos_opts())
        .join()
    )
    assert resumed.unique_state_count() == 16_668
    path = resumed.discovery("value chosen")
    assert path is not None and len(path.into_actions()) == 8


def test_mesh_kill_resume_paxos2_golden(tmp_path):
    import jax

    from stateright_tpu.models.paxos import PaxosTensorExhaustive

    if len(jax.devices()) < 4:
        pytest.skip("needs 4 virtual devices")
    ckpt = str(tmp_path / "paxos-mesh.ckpt.npz")
    opts = dict(
        devices=jax.devices()[:4],
        chunk_size=256,
        queue_capacity_per_shard=1 << 15,
        table_capacity_per_shard=1 << 15,
    )
    part = (
        TensorModelAdapter(PaxosTensorExhaustive(2))
        .checker()
        .target_state_count(4_000)
        .spawn_sharded_bfs(checkpoint_path=ckpt, **opts)
        .join()
    )
    assert 0 < part.unique_state_count() < 16_668
    resumed = (
        TensorModelAdapter(PaxosTensorExhaustive(2))
        .checker()
        .spawn_sharded_bfs(resume_from=ckpt, **opts)
        .join()
    )
    assert resumed.unique_state_count() == 16_668


# ---------------------------------------------------------------------------
# Corruption fallback on the mesh (tpu_bfs version in test_checkpoint.py)
# ---------------------------------------------------------------------------


def test_mesh_corrupt_checkpoint_falls_back(tmp_path):
    import jax

    if len(jax.devices()) < 4:
        pytest.skip("needs 4 virtual devices")
    ckpt = str(tmp_path / "mesh-gen.ckpt.npz")
    opts = dict(
        devices=jax.devices()[:4],
        chunk_size=64,
        queue_capacity_per_shard=1 << 11,
        table_capacity_per_shard=1 << 10,
    )
    (
        TensorModelAdapter(TwoPhaseTensor(5))
        .checker()
        .target_state_count(3_000)
        .spawn_sharded_bfs(
            checkpoint_path=ckpt, checkpoint_every=1e-4,
            keep_checkpoints=3, **opts
        )
        .join()
    )
    assert os.path.exists(ckpt) and os.path.exists(ckpt + ".1")
    size = os.path.getsize(ckpt)
    with open(ckpt, "r+b") as f:
        f.truncate(size // 2)
    resumed = (
        TensorModelAdapter(TwoPhaseTensor(5))
        .checker()
        .spawn_sharded_bfs(resume_from=ckpt, **opts)
        .join()
    )
    assert resumed.unique_state_count() == 8832
    assert resumed.telemetry().get("checkpoint_fallbacks", 0) == 1


# ---------------------------------------------------------------------------
# Graceful degradation: probe-budget exhaustion -> checkpoint + regrow
# ---------------------------------------------------------------------------


def test_tpu_bfs_degraded_regrow_recovers(tmp_path):
    ckpt = str(tmp_path / "regrow.ckpt.npz")
    checker = (
        TensorModelAdapter(TwoPhaseTensor(5))
        .checker()
        .spawn_tpu_bfs(checkpoint_path=ckpt, checkpoint_every=1e-4, **OPTS)
    )
    # The engine thread is still compiling its first era; arm the chaos
    # hook that fakes one probe-budget-exhausted era result once eras >= 1
    # (by then the 1e-4s cadence has written a pre-era checkpoint).
    checker._chaos_probe_error_era = 1
    checker.join()
    assert checker.unique_state_count() == 8832
    checker.assert_properties()
    tel = checker.telemetry()
    assert tel.get("degraded_regrow", 0) == 1
    assert tel.get("table_growths", 0) >= 1


def test_tpu_bfs_exhaustion_without_checkpoint_still_aborts():
    """Without a checkpoint the consumed frontier rows are gone: the
    original loud abort is the only sound behavior."""
    checker = (
        TensorModelAdapter(TwoPhaseTensor(5)).checker().spawn_tpu_bfs(**OPTS)
    )
    checker._chaos_probe_error_era = 1
    with pytest.raises(RuntimeError, match="probe budget"):
        checker.join()


def test_mesh_degraded_regrow_recovers(tmp_path):
    import jax

    if len(jax.devices()) < 4:
        pytest.skip("needs 4 virtual devices")
    ckpt = str(tmp_path / "mesh-regrow.ckpt.npz")
    checker = (
        TensorModelAdapter(TwoPhaseTensor(5))
        .checker()
        .spawn_sharded_bfs(
            checkpoint_path=ckpt,
            checkpoint_every=1e-4,
            devices=jax.devices()[:4],
            chunk_size=64,
            queue_capacity_per_shard=1 << 11,
            table_capacity_per_shard=1 << 10,
        )
    )
    checker._chaos_probe_error_era = 1
    checker.join()
    assert checker.unique_state_count() == 8832
    assert checker.telemetry().get("degraded_regrow", 0) == 1


# ---------------------------------------------------------------------------
# Graceful-stop flush: explicit request and real OS signal
# ---------------------------------------------------------------------------


def test_request_checkpoint_stop_flushes_resumable_checkpoint(tmp_path):
    ckpt = str(tmp_path / "stop.ckpt.npz")
    checker = (
        TensorModelAdapter(TwoPhaseTensor(5))
        .checker()
        .spawn_tpu_bfs(checkpoint_path=ckpt, **OPTS)
    )
    # Requested while the first era is still compiling: the engine observes
    # it at the first era boundary, flushes, and exits early.
    checker.request_checkpoint_stop()
    checker.join()
    tel = checker.telemetry()
    assert tel.get("interrupted") == 1
    assert checker.unique_state_count() < 8832
    assert os.path.exists(ckpt)
    resumed = (
        TensorModelAdapter(TwoPhaseTensor(5))
        .checker()
        .spawn_tpu_bfs(resume_from=ckpt, **OPTS)
        .join()
    )
    assert resumed.unique_state_count() == 8832


def test_kill_resume_under_pipelining(tmp_path):
    """Kill/resume with the speculative era driver engaged (ISSUE 14):
    the partial run stops gracefully mid-pipeline (no checkpoint cadence,
    so the chain gate stays open until the stop request closes it), and
    the resumed run — also pipelined, with many short eras — must land
    on the exact golden. A stop that arrives while a speculative era is
    in flight either discards it (identity no-op) or consumes its real,
    sound work; both end at a resumable era boundary."""
    ckpt = str(tmp_path / "pipe.ckpt.npz")
    opts = dict(OPTS, sync_steps=4)
    checker = (
        TensorModelAdapter(TwoPhaseTensor(5))
        .checker()
        .spawn_tpu_bfs(checkpoint_path=ckpt, **opts)
    )
    checker.request_checkpoint_stop()
    checker.join()
    assert checker.telemetry().get("interrupted") == 1
    assert checker.unique_state_count() < 8832
    assert os.path.exists(ckpt)
    resumed = (
        TensorModelAdapter(TwoPhaseTensor(5))
        .checker()
        .spawn_tpu_bfs(resume_from=ckpt, **opts)
        .join()
    )
    assert resumed.unique_state_count() == 8832
    # The resumed run actually exercised the speculative driver.
    assert resumed.telemetry().get("spec_dispatch", 0) >= 1


def test_sigterm_flushes_final_checkpoint(tmp_path):
    """The real kill path: SIGTERM to our own process while a checkpointing
    engine runs. The installed handler asks the engine to stop, the engine
    flushes at the next era boundary, join() returns normally, and the run
    resumes to the exact golden."""
    ckpt = str(tmp_path / "sig.ckpt.npz")
    prev = signal.getsignal(signal.SIGTERM)
    try:
        checker = (
            TensorModelAdapter(TwoPhaseTensor(5))
            .checker()
            .spawn_tpu_bfs(checkpoint_path=ckpt, **OPTS)
        )
        os.kill(os.getpid(), signal.SIGTERM)
        checker.join()
        assert checker.telemetry().get("interrupted") == 1
        assert os.path.exists(ckpt)
    finally:
        signal.signal(signal.SIGTERM, prev)
    resumed = (
        TensorModelAdapter(TwoPhaseTensor(5))
        .checker()
        .spawn_tpu_bfs(resume_from=ckpt, **OPTS)
        .join()
    )
    assert resumed.unique_state_count() == 8832


# ---------------------------------------------------------------------------
# Multiplexed sweep: per-batch snapshots, resume never re-dispatches
# ---------------------------------------------------------------------------


def test_multiplex_snapshot_resume_skips_dispatch(tmp_path, monkeypatch):
    from stateright_tpu.engines import multiplex
    from stateright_tpu.engines.multiplex import run_multiplexed

    base = str(tmp_path / "mux.ckpt.npz")

    def builders():
        return [
            TensorModelAdapter(IncrementTensor(2)).checker() for _ in range(5)
        ]

    first = run_multiplexed(builders(), lanes=4, checkpoint_path=base)
    assert [c.unique_state_count() for c in first] == [13] * 5
    # 5 builders over 4 lanes = two batches, one snapshot each.
    assert os.path.exists(base + ".batch0.npz")
    assert os.path.exists(base + ".batch4.npz")

    # Resume must rebuild every lane from the snapshots WITHOUT compiling
    # or dispatching anything: poison the program builder to prove it.
    def boom(*a, **k):
        raise AssertionError("resume re-dispatched a completed batch")

    monkeypatch.setattr(multiplex, "_build_lane_program", boom)
    resumed = run_multiplexed(builders(), lanes=4, resume_from=base)
    assert [c.unique_state_count() for c in resumed] == [13] * 5
    for lane in resumed:
        # Discovery paths reconstruct from the snapshotted lane tables.
        assert "fin" in lane.discoveries()
        assert lane.discoveries()["fin"].explain(lane.model())


def test_multiplex_corrupt_snapshot_reruns_batch(tmp_path):
    """Snapshots are an optimization, never a correctness dependency: a
    corrupt batch snapshot silently re-runs that batch."""
    from stateright_tpu.engines.multiplex import run_multiplexed

    base = str(tmp_path / "mux2.ckpt.npz")
    bs = [TensorModelAdapter(IncrementTensor(2)).checker() for _ in range(5)]
    run_multiplexed(bs, lanes=4, checkpoint_path=base)
    snap = base + ".batch0.npz"
    with open(snap, "r+b") as f:
        f.truncate(os.path.getsize(snap) // 2)
    bs2 = [TensorModelAdapter(IncrementTensor(2)).checker() for _ in range(5)]
    resumed = run_multiplexed(bs2, lanes=4, resume_from=base)
    assert [c.unique_state_count() for c in resumed] == [13] * 5
