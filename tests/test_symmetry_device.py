"""Device symmetry reduction (SURVEY §7 step 8).

Semantics note, derived by measurement on 2pc-5 (and documented in
models/two_phase_commit.py): with the reference's IMPERFECT canonicalizer
(stable sort by rm_state only, examples/2pc.rs:203-229), the symmetry-
reduced "unique count" is traversal-defined, not semantic — the reference
itself gets 8,832 from its BFS (which ignores symmetry), 665 from its
sequential DFS (expand-original, dedup-by-rep, DFS order), and an
expand-original BFS gets 508. All variants soundly cover the same
equivalence classes (rep(s) == rep(t) implies s ~ t, and successor sets
of equivalent states are equivalent). The device engine explores the
CANONICAL CLOSURE (expand representatives), the only order-independent
variant a batched level-synchronous BFS can define: deterministically
1,092 representatives for 2pc-5 — an 8.1x reduction over the full space,
with identical property verdicts.
"""

import numpy as np
import pytest

from stateright_tpu import TensorModelAdapter
from stateright_tpu.models import TwoPhaseTensor

TPC5_SYM_CLOSURE = 1_092  # deterministic canonical-closure golden
TPC5_FULL = 8_832  # examples/2pc.rs:159


def _spawn(tm, symmetry):
    b = TensorModelAdapter(tm).checker()
    if symmetry:
        b = b.symmetry()
    return b.spawn_tpu_bfs(
        chunk_size=512, queue_capacity=1 << 13, table_capacity=1 << 14
    ).join()


def test_2pc5_device_symmetry_closure_golden():
    full = _spawn(TwoPhaseTensor(5), symmetry=False)
    sym = _spawn(TwoPhaseTensor(5), symmetry=True)
    assert full.unique_state_count() == TPC5_FULL
    assert sym.unique_state_count() == TPC5_SYM_CLOSURE
    # Identical verdicts, with VALID reconstructed discovery paths.
    for name in ("abort agreement", "commit agreement"):
        assert full.discovery(name) is not None
        p = sym.discovery(name)
        assert p is not None and len(p.into_states()) >= 2
    assert sym.discovery("consistent") is None


def test_canonicalizer_matches_host_representative_2pc4():
    """The lane canonicalizer must agree with the rich host model's
    representative() on every reachable state (same stable-sort rule)."""
    from collections import deque

    from stateright_tpu.models.two_phase_commit import TwoPhaseState

    n = 4
    tm = TwoPhaseTensor(n)
    ad = TensorModelAdapter(tm)
    seen = set()
    q = deque(ad.init_states())
    seen.update(q)
    while q:
        s = q.popleft()
        acts = []
        ad.actions(s, acts)
        for a in acts:
            ns = ad.next_state(s, a)
            if ns is not None and ns not in seen:
                seen.add(ns)
                q.append(ns)

    def to_host(row):
        lane0, lane1, lane2 = row
        return TwoPhaseState(
            rm_state=tuple((lane1 >> (2 * i)) & 3 for i in range(n)),
            tm_state=lane0 & 3,
            tm_prepared=tuple(bool((lane0 >> (2 + i)) & 1) for i in range(n)),
            msgs=frozenset(
                [i for i in range(n) if (lane2 >> i) & 1]
                + ([-1] if (lane2 >> 30) & 1 else [])
                + ([-2] if (lane2 >> 31) & 1 else [])
            ),
        )

    def from_host(s):
        lane0 = s.tm_state | sum(
            (1 << (2 + i)) for i in range(n) if s.tm_prepared[i]
        )
        lane1 = sum((s.rm_state[i] & 3) << (2 * i) for i in range(n))
        lane2 = sum(1 << m for m in s.msgs if m >= 0)
        if -1 in s.msgs:
            lane2 |= 1 << 30
        if -2 in s.msgs:
            lane2 |= 1 << 31
        return (lane0, lane1, lane2)

    for st in seen:
        hrep = from_host(to_host(st).representative())
        crep = ad.representative_state(st)
        assert hrep == crep, st


def test_symmetry_without_canonicalizer_raises():
    from stateright_tpu.models import IncrementTensor

    with pytest.raises(ValueError, match="representative_lanes"):
        TensorModelAdapter(IncrementTensor(2)).checker().symmetry().spawn_tpu_bfs()
