"""Checking-as-a-service: run-server lifecycle, quotas, the executable
cache, cancellation, the speclint admission gate, and multiplexed-lane
parity with the host oracle.

The HTTP tests run one module-scoped in-process server (workers=1,
lanes=8) on an ephemeral port — the scheduler `pause()`/`resume()` hook
makes the batching deterministic, and the shared server keeps the CPU
compile budget to one lane program. The parity tests drive
`run_multiplexed` directly: per-lane results must match an individual
host `spawn_bfs` on the seed goldens (increment:2 = 13 unique,
2pc-3 = 288 unique).
"""

import json
import random
import time
import urllib.error
import urllib.request
from typing import List

import numpy as np
import pytest

from stateright_tpu import Model, Property, TensorModelAdapter
from stateright_tpu.engines.compiled import (
    ExecutableCache,
    intern_model,
    model_signature,
)
from stateright_tpu.engines.multiplex import run_multiplexed
from stateright_tpu.models import IncrementTensor, TwoPhaseTensor
from stateright_tpu.serve import RunService, ServeServer


# ---------------------------------------------------------------------------
# HTTP fixture + helpers
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def server():
    svc = RunService(workers=1, lanes=8, lint_samples=32)
    srv = ServeServer(svc, "127.0.0.1:0").serve_in_background()
    yield srv
    srv.shutdown()


def _req(server, method, path, body=None):
    data = json.dumps(body).encode() if body is not None else None
    request = urllib.request.Request(
        server.url.rstrip("/") + path, data=data, method=method
    )
    try:
        with urllib.request.urlopen(request) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _await_done(server, job_id, timeout=300.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        code, view = _req(server, "GET", f"/jobs/{job_id}")
        assert code == 200, view
        if view["status"] not in ("queued", "running"):
            return view
        time.sleep(0.1)
    raise AssertionError(f"job {job_id} did not finish")


# ---------------------------------------------------------------------------
# Lifecycle over REST
# ---------------------------------------------------------------------------


def test_submit_status_result_lifecycle(server):
    _req(server, "POST", "/scheduler/pause")
    ids = []
    for _ in range(4):
        code, body = _req(
            server, "POST", "/submit", {"spec": "increment:2", "tenant": "acme"}
        )
        assert code == 202 and body["status"] == "queued", body
        ids.append(body["job_id"])
    code, body = _req(
        server, "POST", "/submit", {"spec": "2pc:3", "tenant": "acme"}
    )
    assert code == 202
    two_phase = body["job_id"]
    # Still queued while paused.
    assert _req(server, "GET", f"/jobs/{ids[0]}")[1]["status"] == "queued"
    code, body = _req(server, "GET", f"/jobs/{ids[0]}/result")
    assert code == 409  # no result yet
    _req(server, "POST", "/scheduler/resume")

    for job_id in ids:
        assert _await_done(server, job_id)["status"] == "done"
    assert _await_done(server, two_phase)["status"] == "done"

    # Results carry the seed goldens + Path.explain forensics.
    code, body = _req(server, "GET", f"/jobs/{ids[0]}/result")
    assert code == 200
    result = body["result"]
    assert result["engine"] == "multiplex"
    assert result["unique_state_count"] == 13
    assert result["max_depth"] == 5
    fin = result["discoveries"]["fin"]
    assert fin["expectation"] == "always"  # "fin" counterexample
    assert fin["depth"] == 4
    assert "explained" in fin["explain"]
    assert fin["encoded"].count("/") == 4

    code, body = _req(server, "GET", f"/jobs/{two_phase}/result")
    assert body["result"]["unique_state_count"] == 288
    assert set(body["result"]["discoveries"]) == {
        "abort agreement",
        "commit agreement",
    }

    # The 4 increment lanes shared ONE multiplexed batch + executable.
    telemetry = _req(server, "GET", "/metrics")[1]
    assert telemetry["serve_multiplexed_jobs"] >= 4
    assert telemetry["serve_completed"] >= 5

    # /jobs filters by tenant.
    jobs = _req(server, "GET", "/jobs?tenant=acme")[1]["jobs"]
    assert {j["job_id"] for j in jobs} >= set(ids) | {two_phase}
    assert _req(server, "GET", "/jobs?tenant=nobody")[1]["jobs"] == []


def test_exec_cache_hit_on_second_same_shape_submit(server):
    before = _req(server, "GET", "/stats")[1]["cache"]
    code, body = _req(server, "POST", "/submit", {"spec": "increment:2"})
    assert code == 202
    assert _await_done(server, body["job_id"])["status"] == "done"
    after = _req(server, "GET", "/stats")[1]["cache"]
    # Same shape signature as the lifecycle test's lanes: warm executable,
    # zero new compiles.
    assert after["hits"] == before["hits"] + 1
    assert after["misses"] == before["misses"]


def test_cancellation(server):
    _req(server, "POST", "/scheduler/pause")
    code, body = _req(server, "POST", "/submit", {"spec": "increment:2"})
    assert code == 202
    job_id = body["job_id"]
    code, body = _req(server, "POST", f"/jobs/{job_id}/cancel")
    assert code == 200 and body["status"] == "cancelled"
    # Cancelled jobs never run, re-cancelling conflicts, results 409.
    code, _ = _req(server, "POST", f"/jobs/{job_id}/cancel")
    assert code == 409
    code, _ = _req(server, "GET", f"/jobs/{job_id}/result")
    assert code == 409
    _req(server, "POST", "/scheduler/resume")
    assert _req(server, "GET", f"/jobs/{job_id}")[1]["status"] == "cancelled"
    code, _ = _req(server, "POST", "/jobs/nope/cancel")
    assert code == 404


def test_submit_rejects_malformed(server):
    assert _req(server, "POST", "/submit", {})[0] == 400
    assert _req(server, "POST", "/submit", {"spec": "no-such-model"})[0] == 400
    assert (
        _req(server, "POST", "/submit", {"spec": "increment:2", "engine": "warp"})[0]
        == 400
    )
    # Device engines need tensor models.
    code, body = _req(
        server, "POST", "/submit",
        {"spec": "increment-host:2", "engine": "multiplex"},
    )
    assert code == 400 and "tensor" in body["error"]


def test_tenant_labels_in_prometheus(server):
    raw = urllib.request.urlopen(
        server.url.rstrip("/") + "/metrics.prom"
    ).read().decode()
    assert 'stateright_serve_tenant_requests{tenant="acme"}' in raw
    assert "stateright_serve_exec_cache_hits" in raw


# ---------------------------------------------------------------------------
# Quotas (service-level; a paused scheduler keeps everything queued so no
# engine work happens)
# ---------------------------------------------------------------------------


def test_quota_max_active_returns_429():
    svc = RunService(workers=1, quota_max_active=2)
    svc.pause()
    try:
        for _ in range(2):
            code, _ = svc.submit({"spec": "increment:2", "tenant": "greedy"})
            assert code == 202
        code, body = svc.submit({"spec": "increment:2", "tenant": "greedy"})
        assert code == 429 and "quota" in body["error"]
        # Other tenants are unaffected.
        code, _ = svc.submit({"spec": "increment:2", "tenant": "polite"})
        assert code == 202
        assert svc.metrics.get("serve_rejected_quota") == 1
    finally:
        svc.shutdown()


def test_rate_limit_returns_429():
    svc = RunService(workers=1, quota_per_minute=3)
    svc.pause()
    try:
        ids = []
        for _ in range(3):
            code, body = svc.submit({"spec": "increment:2", "tenant": "t"})
            assert code == 202
            ids.append(body["job_id"])
        # Active-job quota is NOT the limiter here: cancel them all.
        for job_id in ids:
            assert svc.cancel(job_id)[0] == 200
        code, body = svc.submit({"spec": "increment:2", "tenant": "t"})
        assert code == 429 and "minute" in body["error"]
    finally:
        svc.shutdown()


# ---------------------------------------------------------------------------
# Speclint admission gate
# ---------------------------------------------------------------------------


class RngNextStateModel(Model):
    """STR1xx fixture: `next_state` flips a hidden coin."""

    def init_states(self):
        return [0]

    def actions(self, state, actions: List) -> None:
        actions.append("step")

    def next_state(self, state, action):
        return (state + random.randint(0, 1 << 30)) % 97

    def properties(self):
        return [Property.always("true", lambda _m, _s: True)]


def test_lint_admission_gate_rejects_with_strxxx_codes(server, monkeypatch):
    from stateright_tpu.analysis import __main__ as registry

    monkeypatch.setitem(registry.BUNDLED, "broken", RngNextStateModel)
    code, body = _req(server, "POST", "/submit", {"spec": "broken"})
    assert code == 422
    assert "speclint" in body["error"]
    codes = {d["code"] for d in body["diagnostics"]["diagnostics"]}
    assert codes & {"STR101", "STR102"}
    telemetry = _req(server, "GET", "/metrics")[1]
    assert telemetry["serve_rejected_lint"] >= 1


class CallbackIncrementTensor(IncrementTensor):
    """STR601 fixture: a host callback hidden inside the hot-loop program.

    The state-space families cannot see it (numpy and jax evaluations
    agree — the probe is multiplied by zero); only the STR6xx program
    lint, which scans the traced jaxpr, catches it.
    """

    def step_lanes(self, xp, lanes):
        succs, masks = super().step_lanes(xp, lanes)
        if xp is not np:
            import jax

            probe = jax.pure_callback(
                lambda x: np.asarray(x, dtype=np.uint32),
                jax.ShapeDtypeStruct(lanes[0].shape, np.uint32),
                lanes[0],
            )
            first = list(succs[0])
            first[0] = first[0] + probe * xp.uint32(0)
            succs = [tuple(first)] + list(succs[1:])
        return succs, masks


def test_proglint_admission_gate_rejects_compiled_program(server, monkeypatch):
    """A spec whose COMPILED program is broken is refused before any
    compile, and the refusal is attributed to the program family."""
    from stateright_tpu.analysis import __main__ as registry

    monkeypatch.setitem(
        registry.BUNDLED, "callback-broken", CallbackIncrementTensor
    )
    code, body = _req(server, "POST", "/submit", {"spec": "callback-broken:2"})
    assert code == 422
    assert "speclint" in body["error"]
    codes = {d["code"] for d in body["diagnostics"]["diagnostics"]}
    assert "STR601" in codes
    telemetry = _req(server, "GET", "/metrics")[1]
    assert telemetry["serve_rejected_proglint"] >= 1
    assert telemetry["serve_rejected_lint"] >= 1


# ---------------------------------------------------------------------------
# Multiplexed lanes: parity with individual host spawn_bfs runs
# ---------------------------------------------------------------------------


def _host(tm):
    return TensorModelAdapter(tm).checker().spawn_bfs().join()


@pytest.mark.parametrize(
    "factory,golden_unique",
    [(lambda: IncrementTensor(2), 13), (lambda: TwoPhaseTensor(3), 288)],
    ids=["increment", "2pc-3"],
)
def test_multiplexed_lanes_match_spawn_bfs(factory, golden_unique):
    host = _host(factory())
    builders = [TensorModelAdapter(factory()).checker() for _ in range(4)]
    lanes = run_multiplexed(builders, lanes=4)
    assert len(lanes) == 4
    for lane in lanes:
        assert lane.unique_state_count() == host.unique_state_count()
        assert lane.unique_state_count() == golden_unique
        assert lane.state_count() == host.state_count()
        assert lane.max_depth() == host.max_depth()
        assert sorted(lane.discoveries()) == sorted(host.discoveries())
        for name, path in lane.discoveries().items():
            # BFS finds shallowest counterexamples: depths must agree
            # (the tie-broken path itself may differ).
            assert len(path) == len(host.discoveries()[name])
            assert path.explain(lane.model())  # replayable forensics
        telemetry = lane.telemetry()
        assert telemetry["eras"] == 1
        assert "small_workload_hint" not in telemetry


def test_multiplexed_batch_wider_than_lanes_dispatches_twice():
    builders = [
        TensorModelAdapter(IncrementTensor(2)).checker() for _ in range(5)
    ]
    lanes = run_multiplexed(builders, lanes=4)
    assert [c.unique_state_count() for c in lanes] == [13] * 5


def test_multiplexed_rejects_unsupported_options():
    builder = TensorModelAdapter(IncrementTensor(2)).checker().timeout(1.0)
    with pytest.raises(ValueError, match="timeouts"):
        run_multiplexed([builder], lanes=4)


def test_mixed_signatures_rejected():
    builders = [
        TensorModelAdapter(IncrementTensor(2)).checker(),
        TensorModelAdapter(IncrementTensor(3)).checker(),
    ]
    with pytest.raises(ValueError, match="signature"):
        run_multiplexed(builders, lanes=4)


# ---------------------------------------------------------------------------
# Small-workload guard: multiplexed lanes ARE the intended small path
# ---------------------------------------------------------------------------


def test_small_workload_hint_suppressed_for_multiplexed_lane(capsys):
    checker = (
        TensorModelAdapter(TwoPhaseTensor(3))
        .checker()
        .multiplex_lane()
        .spawn_tpu_bfs(
            chunk_size=64, queue_capacity=1 << 10, table_capacity=1 << 10
        )
        .join()
    )
    # Same 288-state run that fires the hint in test_stage_profile.py —
    # flagged as a multiplexed lane it must stay silent.
    assert checker.unique_state_count() == 288
    assert "small_workload_hint" not in checker.telemetry()
    assert "small workload" not in capsys.readouterr().err


# ---------------------------------------------------------------------------
# Build/run split primitives
# ---------------------------------------------------------------------------


def test_model_signature_stable_across_instances():
    assert model_signature(IncrementTensor(2)) == model_signature(
        IncrementTensor(2)
    )
    assert model_signature(IncrementTensor(2)) != model_signature(
        IncrementTensor(3)
    )
    tm_a, sig = intern_model(IncrementTensor(2))
    tm_b, _ = intern_model(IncrementTensor(2))
    assert tm_a is tm_b  # one canonical instance -> id(tm) jit caches hit


def test_executable_cache_keys_by_shape_and_options():
    cache = ExecutableCache(capacity=4)
    a, hit_a = cache.get(IncrementTensor(2), "multiplex", lanes=4, chunk=64)
    assert not hit_a
    b, hit_b = cache.get(IncrementTensor(2), "multiplex", lanes=4, chunk=64)
    assert hit_b and b is a
    _, hit_c = cache.get(IncrementTensor(2), "multiplex", lanes=8, chunk=64)
    assert not hit_c  # different shape options = different executable
    stats = cache.stats()
    assert stats == {"hits": 1, "misses": 2, "size": 2, "capacity": 4}
