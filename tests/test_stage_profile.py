"""Stage-attributed era profiling (obs/stageprof.py + the engines'
`_build_stage_kernels`).

`CheckerBuilder.stage_profile()` decomposes each device engine's opaque
era wall time across its pipeline stages by microbenching every stage in
isolation at the era's exact shapes, then attributing the measured
`device_era` phase proportionally. The contract under test:

  - `stage_<name>` phase timers appear in `Checker.telemetry()` and sum
    to the `device_era` phase within 10% (by construction — proportional
    attribution; the raw isolated costs stay in `stage_us_per_step`);
  - each engine reports its own architecture's stage set;
  - profiling never changes verdicts or counts;
  - the small-workload guard (a hint, not a profiler feature, but wired
    through the same telemetry) fires one gauge + one stderr line.
"""

import pytest

from stateright_tpu.models import IncrementTensor, TwoPhaseTensor
from stateright_tpu.obs import STAGE_ORDER, stage_rows
from stateright_tpu.tensor import TensorModelAdapter


def _stage_phases(telemetry):
    phase_ms = telemetry.get("phase_ms", {})
    return {k: v for k, v in phase_ms.items() if k.startswith("stage_")}


def test_tpu_bfs_stage_breakdown_reconciles():
    c = (
        TensorModelAdapter(TwoPhaseTensor(3))
        .checker()
        .stage_profile(iters=4)
        .spawn_tpu_bfs(chunk_size=64, queue_capacity=1 << 10, table_capacity=1 << 10)
        .join()
    )
    assert c.unique_state_count() == 288  # profiling must not perturb counts
    tel = c.telemetry()
    assert "stage_profile_error" not in tel, tel.get("stage_profile_error")
    stages = _stage_phases(tel)
    # The single-device BFS pipeline: every stage materializes.
    for name in ("expand", "hash", "probe", "claim", "compact", "ring"):
        assert f"stage_{name}" in stages, (name, sorted(stages))
    era = tel["phase_ms"]["device_era"]
    total = sum(stages.values())
    assert era > 0
    assert abs(total - era) <= 0.1 * era, (total, era)
    # Raw isolated measurements ride alongside the attribution.
    assert set(tel["stage_us_per_step"]) == {
        k[len("stage_"):] for k in stages
    }
    assert tel["stage_profile_iters"] == 4
    assert tel["stage_profile_model_pct"] > 0
    # stage_rows orders for display without dropping anything.
    rows = stage_rows(tel["phase_ms"])
    assert [n for n, _ in rows if n in STAGE_ORDER] == [n for n, _ in rows]
    assert len(rows) == len(stages)


def test_stage_profile_off_by_default():
    c = (
        TensorModelAdapter(TwoPhaseTensor(3))
        .checker()
        .spawn_tpu_bfs(chunk_size=64, queue_capacity=1 << 10, table_capacity=1 << 10)
        .join()
    )
    assert not _stage_phases(c.telemetry())


def test_tpu_simulation_stage_breakdown():
    c = (
        TensorModelAdapter(IncrementTensor(2))
        .checker()
        .stage_profile(iters=4)
        .target_state_count(2000)
        .spawn_tpu_simulation(7, walks=64, walk_cap=16)
        .join()
    )
    tel = c.telemetry()
    assert "stage_profile_error" not in tel, tel.get("stage_profile_error")
    stages = _stage_phases(tel)
    # The simulation engine's walk pipeline, not the BFS one.
    for name in ("hash", "cycle", "record", "expand", "choose"):
        assert f"stage_{name}" in stages, (name, sorted(stages))
    era = tel["phase_ms"]["device_era"]
    total = sum(stages.values())
    assert era > 0 and abs(total - era) <= 0.1 * era, (total, era)


def test_sharded_stage_breakdown_includes_exchange():
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    c = (
        TensorModelAdapter(TwoPhaseTensor(3))
        .checker()
        .stage_profile(iters=2)
        .spawn_sharded_bfs(
            devices=jax.devices()[:8],
            chunk_size=64,
            queue_capacity_per_shard=1 << 11,
            table_capacity_per_shard=1 << 10,
        )
        .join()
    )
    assert c.unique_state_count() == 288
    tel = c.telemetry()
    assert "stage_profile_error" not in tel, tel.get("stage_profile_error")
    stages = _stage_phases(tel)
    # The mesh adds the owner-routed all_to_all exchange stage.
    for name in ("expand", "hash", "probe", "exchange", "ring"):
        assert f"stage_{name}" in stages, (name, sorted(stages))
    era = tel["phase_ms"]["device_era"]
    total = sum(stages.values())
    assert era > 0 and abs(total - era) <= 0.1 * era, (total, era)


def test_small_workload_hint_fires(capsys):
    # 2pc-3 explores 288 states, far below the ~10k crossover where the
    # device engine's dispatch overhead stops paying for itself.
    c = (
        TensorModelAdapter(TwoPhaseTensor(3))
        .checker()
        .spawn_tpu_bfs(chunk_size=64, queue_capacity=1 << 10, table_capacity=1 << 10)
        .join()
    )
    assert c.telemetry().get("small_workload_hint") == 288
    err = capsys.readouterr().err
    assert "spawn_bfs() on the host" in err
    # One line only, even though both the spawn-time and run-end checks see
    # a small number.
    assert err.count("small workload") == 1
