"""Oracle validation of the device-side "linearizable" lane program.

`PaxosTensor.linearizable_lanes` claims that for this workload (each client
invokes a unique-valued write at time zero, then reads after its own write
completes) linearizability reduces to acyclicity of a write-precedence
digraph. This test validates that claim semantically: generate random
client event interleavings, replay them BOTH into the repo's real
backtracking `LinearizabilityTester` (the same component the host actor
model uses, examples/paxos.py:216-230) and into the lane encoding, and
require identical verdicts — including deliberately wrong read values,
which reachable paxos states never produce.
"""

import numpy as np
import pytest

from stateright_tpu.semantics.register import Read, ReadOk, Write, WRITE_OK
from stateright_tpu.models.paxos import PaxosTensor
from stateright_tpu.semantics import LinearizabilityTester
from stateright_tpu.semantics.register import Register


def replay(c, events):
    """Replay an event list into (tester verdict, client lanes).

    events: list of ("putok", i) / ("getok", i, val) with val None or a
    writer index. Mirrors the model's client handler exactly: PutOk
    completes the write AND invokes the read in the same atomic step,
    snapshotting every peer's phase (models/paxos.py client handler).
    """
    tester = LinearizabilityTester(Register(None))
    for i in range(c):
        tester.on_invoke(i, Write(i))
    phase = [0] * c
    val = [0] * c
    counters = [[0] * c for _ in range(c)]
    for ev in events:
        if ev[0] == "putok":
            i = ev[1]
            assert phase[i] == 0
            tester.on_return(i, WRITE_OK)
            tester.on_invoke(i, Read())
            phase[i] = 1
            for p in range(c):
                if p != i:
                    counters[i][p] = phase[p]
        else:
            _, i, v = ev
            assert phase[i] == 1
            tester.on_return(i, ReadOk(None if v is None else v))
            phase[i] = 2
            val[i] = 1 if v is None else 2 + v

    lanes = []
    for i in range(c):
        cl = phase[i] | (val[i] << 2)
        for p in range(c):
            if p != i:
                cl |= counters[i][p] << (6 + 2 * p)
        lanes.append(cl)
    return tester.serialized_history() is not None, lanes


def lane_verdict(c, client_lanes):
    tm = PaxosTensor(c)
    row = np.zeros(tm.state_width, dtype=np.uint32)
    for i, cl in enumerate(client_lanes):
        row[6 + i] = cl
    full = tuple(np.asarray([v], dtype=np.uint32) for v in row)
    return bool(np.asarray(tm.linearizable_lanes(np, full))[0])


def random_history(rng, c):
    """A random interleaving of putok/getok events (possibly truncated),
    with read values drawn adversarially (any writer, or None)."""
    pending = [["putok", "getok"] for _ in range(c)]
    events = []
    while any(pending[i] for i in range(c)):
        live = [i for i in range(c) if pending[i]]
        i = int(rng.choice(live))
        kind = pending[i].pop(0)
        if kind == "putok":
            events.append(("putok", i))
        else:
            v = int(rng.integers(-1, c))
            events.append(("getok", i, None if v < 0 else v))
    cut = int(rng.integers(0, len(events) + 1))
    return events[:cut]


@pytest.mark.parametrize("c", [2, 3, 4, 5, 6, 7])
def test_lane_program_matches_backtracking_tester(c):
    """c runs to 7: the counter packing tops out at bit 19 and the closure
    first needs 3 relaxation rounds at c=5 — both must be exercised at the
    supported maximum (the reference bench runs c=6)."""
    rng = np.random.default_rng(42 + c)
    checked = 0
    n_cases = 400 if c <= 4 else 250
    for _ in range(n_cases):
        events = random_history(rng, c)
        expected, lanes = replay(c, events)
        got = lane_verdict(c, lanes)
        assert got == expected, (events, lanes, expected, got)
        checked += 1
    assert checked == n_cases


def test_known_cases():
    # Stale read: client 0 reads v1 (forcing w0 < w1), then client 1 —
    # invoking its read AFTER read_0 completed — reads v0, which would
    # have to linearize before w1, i.e. before read_0. Unserializable.
    events = [
        ("putok", 0),
        ("getok", 0, 1),
        ("putok", 1),  # snapshots phase_0 == 2: read_0 completed first
        ("getok", 1, 0),
    ]
    expected, lanes = replay(2, events)
    assert expected is False
    assert lane_verdict(2, lanes) is False

    # Same schedule with consistent read values is linearizable.
    events = [
        ("putok", 0),
        ("putok", 1),
        ("getok", 0, 1),
        ("getok", 1, 1),
    ]
    expected, lanes = replay(2, events)
    assert expected is True
    assert lane_verdict(2, lanes) is True

    # A completed read returning None is never linearizable (its own
    # write precedes it).
    events = [("putok", 0), ("getok", 0, None)]
    expected, lanes = replay(1, events)
    assert expected is False
    assert lane_verdict(1, lanes) is False


def test_reachable_space_has_no_violation():
    """Device twin c=1: the 'linearizable' always-property must hold on
    every reachable state (paxos IS linearizable), and exploring with it
    enabled must not perturb the 265-state golden."""
    from stateright_tpu.tensor import TensorModelAdapter

    c = TensorModelAdapter(PaxosTensor(1)).checker().spawn_bfs().join()
    assert c.unique_state_count() == 265
    assert c.discovery("linearizable") is None
    assert c.discovery("value chosen") is not None
