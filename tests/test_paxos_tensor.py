"""Paxos tensor twin: host-oracle equivalence and reference goldens.

The host `examples/paxos.py` ActorModel is the correctness oracle (its own
golden, 16,668 uniques at 2 clients, matches examples/paxos.rs:327). The
tensor twin must agree on unique-state counts — which requires its lane
encoding to capture the FULL host state identity, including the
linearizability tester's thread histories and real-time snapshots.
"""


import pytest

from stateright_tpu.models.paxos import (
    PaxosTensorExhaustive as PaxosTensorFull,
)
from stateright_tpu.tensor import TensorModelAdapter


def test_c1_twin_matches_host_actor_model():
    from examples.paxos import paxos_model

    host = paxos_model(1, 3).checker().spawn_bfs().join()
    host.assert_properties()
    twin = TensorModelAdapter(PaxosTensorFull(1)).checker().spawn_bfs().join()
    assert twin.unique_state_count() == host.unique_state_count() == 265
    assert twin.discovery("value chosen") is not None


def test_c1_device_engine_matches():
    twin = (
        TensorModelAdapter(PaxosTensorFull(1))
        .checker()
        .spawn_tpu_bfs(chunk_size=256, queue_capacity=1 << 14, table_capacity=1 << 12)
        .join()
    )
    assert twin.unique_state_count() == 265
    path = twin.discovery("value chosen")
    assert path is not None
    # BFS finds a shortest example; every prefix action must replay.
    assert len(path.into_actions()) >= 1


def test_c2_device_engine_reference_golden():
    # The reference's headline golden: 16,668 unique states at 2 clients
    # (examples/paxos.rs:327), with an 8-step "value chosen" discovery
    # (paxos.rs:330-340). Default-on since round 4: the era-loop engine +
    # the persistent compilation cache make this affordable in CI (the
    # round-3 block engine needed several minutes on CPU).
    twin = (
        TensorModelAdapter(PaxosTensorFull(2))
        .checker()
        .spawn_tpu_bfs(
            chunk_size=1024, queue_capacity=1 << 16, table_capacity=1 << 16
        )
        .join()
    )
    assert twin.unique_state_count() == 16_668
    path = twin.discovery("value chosen")
    assert path is not None
    assert len(path.into_actions()) == 8


def test_c2_threaded_host_oracle_golden():
    """The vectorized threaded host engine re-derives the reference golden
    in under a second — the live oracle bench.py uses."""
    twin = (
        TensorModelAdapter(PaxosTensorFull(2))
        .checker()
        .threads(4)
        .spawn_bfs()
        .join()
    )
    assert twin.unique_state_count() == 16_668
    assert twin.discovery("linearizable") is None


def test_c2_sharded_engine_agrees():
    """Single-device and sharded engines must agree on the paxos golden
    (the scale-capability criterion: the same program that runs paxos-3 on
    one chip shards over the mesh)."""
    import jax

    if len(jax.devices()) < 4:
        pytest.skip("needs 4 virtual devices")
    twin = (
        TensorModelAdapter(PaxosTensorFull(2))
        .checker()
        .spawn_sharded_bfs(
            devices=jax.devices()[:4],
            chunk_size=256,
            queue_capacity_per_shard=1 << 15,
            table_capacity_per_shard=1 << 15,
        )
        .join()
    )
    assert twin.unique_state_count() == 16_668
