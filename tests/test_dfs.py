"""Host DFS engine tests. Mirrors src/checker/dfs.rs:404-585 test module."""

import io

import pytest

from stateright_tpu import StateRecorder, WriteReporter
from stateright_tpu.models import LinearEquation, Panicker


def test_visits_states_in_dfs_order():
    recorder, accessor = StateRecorder.new_with_accessor()
    LinearEquation(2, 10, 14).checker().visitor(recorder).spawn_dfs().join()
    # Successors push X-result then Y-result; LIFO pops Y first, so DFS dives
    # down the y axis until (0, 27) solves (10*27) % 256 == 14.
    assert accessor() == [(0, y) for y in range(28)]


def test_can_complete_by_eliminating_properties():
    checker = LinearEquation(2, 10, 14).checker().spawn_dfs().join()
    checker.assert_properties()
    assert checker.discovery("solvable").into_actions() == ["IncreaseY"] * 27


def test_report_format():
    out = io.StringIO()
    LinearEquation(2, 10, 14).checker().spawn_dfs().report(WriteReporter(out))
    text = out.getvalue()
    assert text.startswith(
        "Checking. states=1, unique=1, depth=0\n"
        "Done. states=55, unique=55, depth=28, sec="
    )
    assert 'Discovered "solvable" example Path[27]:' in text


def test_handles_panics_gracefully():
    with pytest.raises(RuntimeError, match="reached panic state"):
        Panicker().checker().spawn_dfs().join()


def test_full_enumeration_matches_bfs():
    dfs = LinearEquation(2, 4, 7).checker().spawn_dfs().join()
    assert dfs.is_done()
    dfs.assert_no_discovery("solvable")
    assert dfs.unique_state_count() == 256 * 256
