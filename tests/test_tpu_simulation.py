"""Batched device simulation engine (engines/tpu_simulation.py).

Runs on the CPU backend (conftest pins JAX_PLATFORMS=cpu); the engine is
platform-agnostic JAX. Covers: counterexample discovery with a VALID
replayable path, seed determinism, cycle-detection-driven walk restart,
sometimes-example discovery, and the host engine's .threads(n) support.
"""

import numpy as np
import pytest

from stateright_tpu import TensorModelAdapter
from stateright_tpu.has_discoveries import HasDiscoveries
from stateright_tpu.models import IncrementTensor, TwoPhaseTensor
from stateright_tpu.tensor import TensorModel, TensorProperty


def test_increment_race_found_with_valid_path():
    tm = IncrementTensor(2)
    c = (
        TensorModelAdapter(tm)
        .checker()
        .finish_when(HasDiscoveries.any_of(["fin"]))
        .spawn_tpu_simulation(7, walks=64, walk_cap=32)
        .join()
    )
    path = c.discovery("fin")
    assert path is not None
    # Path.from_fingerprints re-executes the model along the chain, so a
    # non-None path IS the validity proof; "fin" is an always-property,
    # so its discovery is a counterexample whose final state VIOLATES it.
    final = path.last_state()
    prop = c.model().property("fin")
    assert not prop.condition(c.model(), final)


def test_seed_determinism():
    tm = IncrementTensor(2)

    def run(seed):
        c = (
            TensorModelAdapter(tm)
            .checker()
            .finish_when(HasDiscoveries.any_of(["fin"]))
            .spawn_tpu_simulation(seed, walks=32, walk_cap=32)
            .join()
        )
        return c.discovery("fin").encode(c.model()), c.state_count()

    a = run(123)
    b = run(123)
    assert a == b
    c = run(321)
    assert a != c  # different seed explores differently (overwhelmingly)


class TinyClock(TensorModel):
    """1-lane 2-state cycle: 0 -> 1 -> 0 -> ... — every walk cycles."""

    state_width = 1
    max_actions = 1

    def init_states_array(self):
        return np.zeros((1, 1), dtype=np.uint32)

    def step_lanes(self, xp, lanes):
        (v,) = lanes
        return [(xp.uint32(1) - v,)], [v == v]

    def tensor_properties(self):
        return [
            TensorProperty.sometimes(
                "is one", lambda xp, lanes: lanes[0] == xp.uint32(1)
            )
        ]


def test_cycle_detection_restarts_walks():
    tm = TinyClock()
    c = (
        TensorModelAdapter(tm)
        .checker()
        .spawn_tpu_simulation(5, walks=8, walk_cap=16)
        .join()
    )
    # Walks loop after 2 states; the engine must still terminate (cycle
    # detection ends each walk) and find the sometimes example.
    assert c.discovery("is one") is not None
    tel = c.telemetry()
    assert tel["steps"] >= 2


def test_2pc_sometimes_found_always_holds():
    tm = TwoPhaseTensor(3)
    c = (
        TensorModelAdapter(tm)
        .checker()
        .finish_when(
            HasDiscoveries.all_of(["abort agreement", "commit agreement"])
        )
        .spawn_tpu_simulation(11, walks=128, walk_cap=64)
        .join()
    )
    assert c.discovery("abort agreement") is not None
    assert c.discovery("commit agreement") is not None
    assert c.discovery("consistent") is None  # always-property holds


def test_target_state_count_bounds_run():
    tm = TinyClock()
    c = (
        TensorModelAdapter(tm)
        .checker()
        .finish_when(HasDiscoveries.all_of(["no such property"]))
        .target_state_count(5_000)
        .spawn_tpu_simulation(1, walks=16, walk_cap=8)
        .join()
    )
    assert c.state_count() >= 5_000


class ChainFork(TensorModel):
    """0 -(+1|+2)-> ... until v >= N (terminal). The SOMETIMES property
    freezes any walk that lands on 1; the EVENTUALLY property is satisfied
    at every terminal state, so an honest run can never produce an
    EVENTUALLY counterexample."""

    state_width = 1
    max_actions = 2
    N = 6

    def init_states_array(self):
        return np.zeros((1, 1), dtype=np.uint32)

    def step_lanes(self, xp, lanes):
        (v,) = lanes
        ok = v < xp.uint32(self.N)
        return [(v + xp.uint32(1),), (v + xp.uint32(2),)], [ok, ok]

    def tensor_properties(self):
        return [
            TensorProperty.sometimes(
                "at one", lambda xp, l: l[0] == xp.uint32(1)
            ),
            TensorProperty.eventually(
                "reaches end", lambda xp, l: l[0] >= xp.uint32(self.N)
            ),
        ]


def test_frozen_walks_cannot_fake_eventually_counterexamples():
    """Regression: a walk freezes when it records a discovery, with its
    current state already in its own path buffer. The old code dropped the
    frozen lane at the era boundary, so the walk thawed next era, matched
    ITSELF in the cycle check, and the fake cycle's surviving
    eventually-bits were reported as an EVENTUALLY counterexample. Small
    sync_steps forces many era boundaries while walks sit frozen."""
    tm = ChainFork()
    c = (
        TensorModelAdapter(tm)
        .checker()
        .target_state_count(3_000)
        .timeout(60)  # safety net only; the run ends on the target
        .spawn_tpu_simulation(13, walks=64, walk_cap=32, sync_steps=4)
        .join()
    )
    assert c.discovery("at one") is not None
    # Every terminal satisfies the eventually property, so any reported
    # counterexample is fabricated.
    assert c.discovery("reaches end") is None
    assert c.state_count() >= 3_000  # frozen walks restart; no starvation


def test_host_simulation_threads():
    # .threads(n) on the host engine runs n seed streams (reference
    # simulation.rs:138-201) instead of raising.
    from stateright_tpu.models.two_phase_commit import TwoPhaseSys

    c = (
        TwoPhaseSys(3)
        .checker()
        .threads(4)
        .finish_when(
            HasDiscoveries.all_of(["abort agreement", "commit agreement"])
        )
        .spawn_simulation(3)
        .join()
    )
    assert c.discovery("abort agreement") is not None
    assert c.discovery("commit agreement") is not None
