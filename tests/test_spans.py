"""Run-ledger span tests: the recorder primitive (obs/spans.py), the
latency histogram (obs/metrics.py), the structured logger (obs/log.py),
engine span wiring, Chrome/OTel exports, and the servers' /events SSE
stream. Serve-side trace *continuity* across crashes/retries lives in
tests/test_serve_durability.py.
"""

import json
import queue
import time
import urllib.error
import urllib.request

import pytest

from stateright_tpu.models.fixtures import BinaryClock
from stateright_tpu.obs.log import configure, get_logger
from stateright_tpu.obs.metrics import Histogram, MetricsRegistry, render_prometheus
from stateright_tpu.obs.spans import (
    SpanRecorder,
    attach_phase_spans,
    new_span_id,
    new_trace_id,
    spans_to_chrome,
)


# ---------------------------------------------------------------------------
# SpanRecorder primitive
# ---------------------------------------------------------------------------


def test_ids_are_otel_width_hex():
    t, s = new_trace_id(), new_span_id()
    assert len(t) == 32 and int(t, 16) >= 0
    assert len(s) == 16 and int(s, 16) >= 0


def test_record_and_trace_query_sorted_by_start():
    rec = SpanRecorder()
    tid = new_trace_id()
    rec.record("b", start=2.0, end=3.0, trace_id=tid)
    rec.record("a", start=1.0, end=4.0, trace_id=tid)
    rec.record("other", start=0.0, end=1.0)  # different trace
    trace = rec.trace(tid)
    assert [s["name"] for s in trace] == ["a", "b"]
    assert len(rec.spans()) == 3
    assert len(rec.spans(tid)) == 2
    assert rec.trace_ids()[-1] == tid or tid in rec.trace_ids()


def test_record_clamps_negative_durations():
    rec = SpanRecorder()
    span = rec.record("x", start=5.0, end=4.0)
    assert span["end"] == span["start"] == 5.0


def test_capacity_bounds_the_ledger():
    rec = SpanRecorder(capacity=4)
    for i in range(10):
        rec.record(f"s{i}", start=float(i), end=float(i) + 0.5)
    names = [s["name"] for s in rec.spans()]
    assert names == ["s6", "s7", "s8", "s9"]


def test_open_span_context_manager_and_events():
    rec = SpanRecorder()
    tid = new_trace_id()
    with rec.start_span("op", trace_id=tid, attributes={"k": 1}) as span:
        span.add_event("milestone", detail="halfway")
    (s,) = rec.spans(tid)
    assert s["status"] == "ok" and s["attributes"]["k"] == 1
    assert s["events"][0]["name"] == "milestone"
    assert s["end"] >= s["start"]


def test_open_span_records_error_status_on_exception():
    rec = SpanRecorder()
    tid = new_trace_id()
    with pytest.raises(RuntimeError):
        with rec.start_span("boom", trace_id=tid):
            raise RuntimeError("kaput")
    (s,) = rec.spans(tid)
    assert s["status"] == "error"
    assert "kaput" in s["attributes"]["error"]


def test_subscriber_feed_receives_completions_and_drops_when_full():
    rec = SpanRecorder()
    sub = rec.subscribe(maxsize=2)
    rec.record("one", start=1.0, end=2.0)
    assert sub.get_nowait()["name"] == "one"
    rec.record("a", start=1.0, end=2.0)
    rec.record("b", start=1.0, end=2.0)
    rec.record("dropped", start=1.0, end=2.0)  # full queue: must not block
    got = [sub.get_nowait()["name"], sub.get_nowait()["name"]]
    assert got == ["a", "b"]
    with pytest.raises(queue.Empty):
        sub.get_nowait()
    rec.unsubscribe(sub)
    rec.record("after", start=1.0, end=2.0)
    with pytest.raises(queue.Empty):
        sub.get_nowait()


def test_metrics_registry_counts_recorded_spans():
    m = MetricsRegistry()
    rec = SpanRecorder(metrics=m)
    rec.record("x", start=1.0, end=2.0)
    rec.record("y", start=1.0, end=2.0)
    assert m.snapshot()["spans_recorded"] == 2


# ---------------------------------------------------------------------------
# Exports: OTel JSONL + Chrome trace events
# ---------------------------------------------------------------------------


def test_otel_jsonl_export_shape(tmp_path):
    rec = SpanRecorder()
    tid = new_trace_id()
    root = new_span_id()
    rec.record("parent", start=1.0, end=2.0, trace_id=tid, span_id=root,
               attributes={"job": "j1"})
    rec.record("child", start=1.2, end=1.8, trace_id=tid, parent_id=root,
               status="error")
    path = tmp_path / "spans.jsonl"
    assert rec.export_jsonl(str(path)) == 2
    rows = [json.loads(line) for line in path.read_text().splitlines()]
    parent, child = rows
    assert parent["traceId"] == tid and parent["spanId"] == root
    assert parent["parentSpanId"] == ""
    assert parent["startTimeUnixNano"] == int(1.0 * 1e9)
    assert parent["status"] == {"code": "OK"}
    assert parent["attributes"] == [
        {"key": "job", "value": {"stringValue": "j1"}}
    ]
    assert child["parentSpanId"] == root
    assert child["status"] == {"code": "ERROR"}


def test_chrome_export_balanced_and_nested(tmp_path):
    rec = SpanRecorder()
    tid = new_trace_id()
    # Same start: the longer (outer) span must open first and close last.
    rec.record("inner", start=1.0, end=1.5, trace_id=tid)
    rec.record("outer", start=1.0, end=2.0, trace_id=tid)
    path = tmp_path / "trace.json"
    assert rec.export_chrome(str(path)) == 4
    events = json.loads(path.read_text())
    assert [(e["name"], e["ph"]) for e in events] == [
        ("outer", "B"), ("inner", "B"), ("inner", "E"), ("outer", "E"),
    ]
    begins = [e for e in events if e["ph"] == "B"]
    ends = [e for e in events if e["ph"] == "E"]
    assert len(begins) == len(ends) == 2
    assert all(e["tid"] == f"trace:{tid[:8]}" for e in events)
    assert begins[0]["args"]["trace_id"] == tid


def test_spans_to_chrome_ends_before_begins_at_ties():
    tid = new_trace_id()
    spans = [
        {"name": "first", "trace_id": tid, "span_id": new_span_id(),
         "parent_id": None, "start": 1.0, "end": 2.0, "status": "ok"},
        {"name": "second", "trace_id": tid, "span_id": new_span_id(),
         "parent_id": None, "start": 2.0, "end": 3.0, "status": "ok"},
    ]
    events = spans_to_chrome(spans)
    # At ts=2.0 the E of "first" must precede the B of "second".
    assert [(e["name"], e["ph"]) for e in events] == [
        ("first", "B"), ("first", "E"), ("second", "B"), ("second", "E"),
    ]


def test_attach_phase_spans_widths_and_alignment():
    rec = SpanRecorder()
    tid, parent = new_trace_id(), new_span_id()
    made = attach_phase_spans(
        rec,
        {"device_era": 100.0, "readback": 25.0, "idle": 0.0},
        trace_id=tid, parent_id=parent, end=10.0,
        attributes={"engine": "X"},
    )
    assert [s["name"] for s in made] == ["phase:device_era", "phase:readback"]
    for s in made:
        assert s["end"] == 10.0 and s["parent_id"] == parent
        assert s["attributes"]["engine"] == "X"
    era = made[0]
    assert era["end"] - era["start"] == pytest.approx(0.1)


# ---------------------------------------------------------------------------
# Histogram + Prometheus exposition
# ---------------------------------------------------------------------------


def test_histogram_quantiles_and_buckets():
    h = Histogram()
    for v in [0.001, 0.002, 0.004, 0.008, 0.5]:
        h.observe(v)
    assert h.count == 5
    assert h.sum == pytest.approx(0.515)
    assert 0.0 < h.quantile(0.5) <= 0.008
    # The top quantile clamps to the observed max, not a bucket bound.
    assert h.quantile(0.99) <= 0.5
    buckets = h.buckets()
    assert buckets[-1][0] == float("inf") and buckets[-1][1] == 5
    # Cumulative counts never decrease.
    counts = [c for _, c in buckets]
    assert counts == sorted(counts)
    snap = h.snapshot()
    assert snap["count"] == 5 and "p99" in snap and "p50" in snap
    assert snap["buckets"][-1][0] == "+Inf"


def test_histogram_empty_is_sane():
    h = Histogram()
    assert h.count == 0 and h.quantile(0.99) == 0.0
    assert h.snapshot()["p50"] == 0.0


def test_registry_histogram_rides_snapshot_and_prometheus():
    m = MetricsRegistry()
    m.observe("submit_to_result_secs", 0.004)
    m.observe("submit_to_result_secs", 0.1)
    snap = m.snapshot()
    hist = snap["histograms"]["submit_to_result_secs"]
    assert hist["count"] == 2
    text = render_prometheus(snap)
    assert 'submit_to_result_secs_bucket{le="+Inf"} 2' in text
    assert "submit_to_result_secs_count 2" in text
    assert "submit_to_result_secs_sum" in text
    assert "# TYPE stateright_submit_to_result_secs histogram" in text


# ---------------------------------------------------------------------------
# Structured logger
# ---------------------------------------------------------------------------


def test_logger_threshold_and_list_sink():
    records = []
    configure(level="info", sink=records)
    try:
        log = get_logger("test.component")
        log.debug("too quiet", x=1)
        log.info("hello", trace_id="abc123")
        log.error("bad", code=7)
        assert [r["msg"] for r in records] == ["hello", "bad"]
        assert records[0]["component"] == "test.component"
        assert records[0]["level"] == "info"
        assert records[0]["trace_id"] == "abc123"
        assert records[1]["code"] == 7
        assert all("ts" in r for r in records)
    finally:
        configure()  # reset to env-driven defaults


def test_logger_force_bypasses_threshold():
    records = []
    configure(level="off", sink=records)
    try:
        get_logger("gated").force("debug", "explicitly requested", n=1)
        assert len(records) == 1 and records[0]["level"] == "debug"
    finally:
        configure()


def test_logger_records_are_json_lines(tmp_path):
    path = tmp_path / "log.jsonl"
    configure(level="warning", sink=str(path))
    try:
        get_logger("c").warning("to file", k="v")
        rec = json.loads(path.read_text().splitlines()[0])
        assert rec["msg"] == "to file" and rec["k"] == "v"
    finally:
        configure()


def test_configure_rejects_unknown_level():
    with pytest.raises(ValueError, match="unknown log level"):
        configure(level="loud")
    configure()


# ---------------------------------------------------------------------------
# Engine wiring: CheckerBuilder.spans()
# ---------------------------------------------------------------------------


def test_host_engine_records_run_span_with_phases():
    rec = SpanRecorder()
    checker = BinaryClock().checker().spans(rec).spawn_bfs().join()
    assert checker.is_done()
    tids = rec.trace_ids()
    assert len(tids) == 1
    trace = rec.trace(tids[0])
    runs = [s for s in trace if s["name"] == "run"]
    assert len(runs) == 1
    run = runs[0]
    assert run["parent_id"] is None and run["status"] == "ok"
    assert run["attributes"]["states"] == checker.state_count()
    # Engine phase timers become child spans under the run span.
    phases = [s for s in trace if s["name"].startswith("phase:")]
    assert phases and all(s["parent_id"] == run["span_id"] for s in phases)
    # Progress spans (waves) parent into the run span too.
    waves = [s for s in trace if s["name"] == "wave"]
    assert waves and all(s["parent_id"] == run["span_id"] for s in waves)


def test_engine_span_ids_flow_from_builder():
    rec = SpanRecorder()
    tid, parent = new_trace_id(), new_span_id()
    BinaryClock().checker().spans(rec, trace_id=tid, parent_id=parent) \
        .spawn_bfs().join()
    trace = rec.trace(tid)
    assert trace, "engine must record into the provided trace"
    (run,) = [s for s in trace if s["name"] == "run"]
    assert run["trace_id"] == tid and run["parent_id"] == parent


def test_chrome_trace_embeds_spans(tmp_path):
    # Satellite: .trace(path, format="chrome") + .spans() => ONE Perfetto
    # file carrying engine phases AND the run's spans on aligned clocks.
    path = tmp_path / "run.chrome.json"
    rec = SpanRecorder()
    BinaryClock().checker().trace(str(path), format="chrome") \
        .spans(rec).spawn_bfs().join()
    events = json.loads(path.read_text())
    names = {e.get("name") for e in events}
    assert "run" in names, "span events must be embedded in the trace file"
    span_events = [e for e in events if "trace_id" in (e.get("args") or {})]
    begins = sum(1 for e in span_events if e["ph"] == "B")
    span_names = {s["name"] for s in rec.spans()}
    ends = sum(
        1 for e in events
        if e.get("ph") == "E" and e.get("name") in span_names
    )
    assert begins and begins == ends


# ---------------------------------------------------------------------------
# /events SSE stream (Explorer; the serve server shares the handler)
# ---------------------------------------------------------------------------


def _sse_blocks(url):
    raw = urllib.request.urlopen(url).read().decode()
    return [b for b in raw.strip().split("\n\n") if b]


def test_explorer_events_stream_spans_and_metric_deltas():
    from stateright_tpu.explorer.server import serve

    server = serve(BinaryClock().checker(), "127.0.0.1:0", block=False)
    try:
        base = server.url.rstrip("/")
        server.checker.run_to_completion()
        server.checker.join()
        blocks = _sse_blocks(f"{base}/events?replay=50&limit=5&duration=4")
        spans = [json.loads(b.split("data: ", 1)[1]) for b in blocks
                 if b.startswith("event: span")]
        assert spans, blocks
        assert "run" in {s["name"] for s in spans}
        metrics = [json.loads(b.split("data: ", 1)[1]) for b in blocks
                   if b.startswith("event: metrics")]
        assert metrics and all("changed" in m for m in metrics)
        # Limit bounds the span count even with a bigger replay buffer.
        assert len(spans) <= 5
    finally:
        server.shutdown()


def test_explorer_ui_ships_waterfall_panel():
    from stateright_tpu.explorer.server import serve

    server = serve(BinaryClock().checker(), "127.0.0.1:0", block=False)
    try:
        base = server.url.rstrip("/")
        html = urllib.request.urlopen(f"{base}/").read().decode()
        assert "spans-panel" in html and 'id="waterfall"' in html
        js = urllib.request.urlopen(f"{base}/app.js").read().decode()
        assert "EventSource" in js and "startSpanStream" in js
        css = urllib.request.urlopen(f"{base}/app.css").read().decode()
        assert ".wf-bar" in css
        server.checker.run_to_completion()
        server.checker.join()
    finally:
        server.shutdown()


def test_serve_events_and_job_trace_endpoint():
    from stateright_tpu.serve import RunService, ServeServer

    svc = RunService(workers=1, lint_samples=32)
    server = ServeServer(svc, "127.0.0.1:0").serve_in_background()
    try:
        base = server.url.rstrip("/")
        req = urllib.request.Request(
            base + "/submit",
            data=json.dumps({"spec": "increment:2", "engine": "bfs"}).encode(),
        )
        body = json.load(urllib.request.urlopen(req))
        jid, tid = body["job_id"], body["trace_id"]
        assert len(tid) == 32
        deadline = time.time() + 60
        while time.time() < deadline:
            view = json.load(urllib.request.urlopen(f"{base}/jobs/{jid}"))
            if view["status"] not in ("queued", "running"):
                break
            time.sleep(0.05)
        assert view["status"] == "done", view
        assert view["trace_id"] == tid

        ledger = json.load(urllib.request.urlopen(f"{base}/jobs/{jid}/trace"))
        assert ledger["trace_id"] == tid
        names = [s["name"] for s in ledger["spans"]]
        for expected in ("admission", "queue_wait", "execute", "job"):
            assert expected in names, names
        (root,) = [s for s in ledger["spans"] if s["name"] == "job"]
        assert root["parent_id"] is None
        assert root["attributes"]["final_status"] == "done"
        # Every other span hangs off the job's trace; lifecycle legs
        # parent to the root.
        for s in ledger["spans"]:
            if s["name"] in ("admission", "queue_wait", "execute"):
                assert s["parent_id"] == root["span_id"], s

        blocks = _sse_blocks(f"{base}/events?replay=20&limit=4&duration=4")
        spans = [json.loads(b.split("data: ", 1)[1]) for b in blocks
                 if b.startswith("event: span")]
        assert spans, blocks

        stats = json.load(urllib.request.urlopen(f"{base}/stats"))
        lat = stats["latency"]["submit_to_result"]
        assert lat["count"] >= 1 and lat["p99"] > 0.0
        assert set(lat) >= {"count", "p50", "p95", "p99"}

        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(f"{base}/jobs/nope/trace")
        assert err.value.code == 404
    finally:
        server.shutdown()
