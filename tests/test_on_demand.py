"""On-demand engine tests. Reference: src/checker/on_demand.rs:500-540 —
the engine idles until driven by fingerprint or run_to_completion."""

from stateright_tpu.models.fixtures import BinaryClock, LinearEquation


def test_idles_until_driven():
    checker = LinearEquation(2, 4, 7).checker().spawn_on_demand()
    # Only the single init state is known; nothing has been expanded.
    assert checker.unique_state_count() == 1
    assert checker.state_count() == 1
    assert not checker.is_done()


def test_check_fingerprint_expands_one_node():
    model = LinearEquation(2, 4, 7)
    checker = model.checker().spawn_on_demand()
    init_fp = model.fingerprint_state((0, 0))
    checker.check_fingerprint(init_fp)
    # (0,0) expands to (1,0) and (0,1).
    assert checker.unique_state_count() == 3
    # Unknown fingerprints are ignored.
    checker.check_fingerprint(12345)
    assert checker.unique_state_count() == 3
    # Expanding a frontier successor works too.
    checker.check_fingerprint(model.fingerprint_state((1, 0)))
    assert checker.unique_state_count() == 5  # adds (2,0) and (1,1)


def test_run_to_completion_enumerates_full_space():
    # 2x + 4y = 7 (mod 256) has no solution, so the full 256*256 space is
    # explored (reference golden: on_demand.rs:522).
    checker = LinearEquation(2, 4, 7).checker().spawn_on_demand()
    checker.run_to_completion()
    checker.join()
    assert checker.is_done()
    assert checker.unique_state_count() == 256 * 256
    checker.assert_no_discovery("solvable")


def test_run_to_completion_binary_clock():
    checker = BinaryClock().checker().spawn_on_demand()
    checker.run_to_completion()
    checker.join()
    # Reference golden: 2 unique states (on_demand.rs:532 asserts 12 for the
    # 12-state fixture; the analogous exact-count check here).
    assert checker.unique_state_count() == 2
    checker.assert_no_discovery("in [0, 1]")
