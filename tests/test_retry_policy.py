"""Pure-unit tests for the serve durability primitives
(serve/durability.py): retry backoff determinism, failure
classification, circuit-breaker state machine, journal fold/compaction,
and result-store TTL — no engines, no threads, no service."""

import json
import os

import pytest

from stateright_tpu.serve.durability import (
    CircuitBreaker,
    JobJournal,
    ResultStore,
    RetryPolicy,
    classify_failure,
)


# ---------------------------------------------------------------------------
# RetryPolicy
# ---------------------------------------------------------------------------


def test_backoff_is_exponential_and_capped():
    p = RetryPolicy(base_delay=0.1, max_delay=1.0, jitter=0.0)
    assert p.delay(1) == pytest.approx(0.1)
    assert p.delay(2) == pytest.approx(0.2)
    assert p.delay(3) == pytest.approx(0.4)
    assert p.delay(8) == pytest.approx(1.0)  # capped at max_delay


def test_jitter_is_deterministic_per_seed_and_key():
    a = RetryPolicy(seed=7, jitter=0.5)
    b = RetryPolicy(seed=7, jitter=0.5)
    c = RetryPolicy(seed=8, jitter=0.5)
    assert a.delay(2, key="job-1") == b.delay(2, key="job-1")
    assert a.delay(2, key="job-1") != a.delay(2, key="job-2")
    assert a.delay(2, key="job-1") != c.delay(2, key="job-1")
    base = RetryPolicy(jitter=0.0).delay(2)
    d = a.delay(2, key="job-1")
    assert base <= d <= base * 1.5  # jitter fraction in [0, 0.5]


def test_policy_validates_configuration():
    with pytest.raises(ValueError, match="max_attempts"):
        RetryPolicy(max_attempts=0)
    with pytest.raises(ValueError, match="base_delay"):
        RetryPolicy(base_delay=0)
    with pytest.raises(ValueError, match="jitter"):
        RetryPolicy(jitter=2.0)
    with pytest.raises(ValueError, match="1-based"):
        RetryPolicy().delay(0)


def test_classify_failure():
    transient, escalate = classify_failure(
        "RuntimeError: lane 3 did not complete within the lane budget "
        "(frontier=9, unique=70000); raise queue_capacity/table_capacity "
        "or run it solo via spawn_tpu_bfs"
    )
    assert transient and escalate
    transient, escalate = classify_failure(
        "RuntimeError: visited-table probe budget exhausted despite headroom"
    )
    assert transient and escalate
    transient, escalate = classify_failure(
        "XlaRuntimeError: RESOURCE_EXHAUSTED: out of memory allocating ..."
    )
    assert transient and not escalate
    transient, escalate = classify_failure(
        "ValueError: unknown model spec 'nope:1'"
    )
    assert not transient and not escalate
    assert classify_failure("AssertionError: model bug") == (False, False)


# ---------------------------------------------------------------------------
# CircuitBreaker (with a fake clock: fully deterministic)
# ---------------------------------------------------------------------------


class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def test_breaker_opens_after_threshold_and_cools_down():
    clock = _Clock()
    br = CircuitBreaker(threshold=3, cooldown=10.0, clock=clock)
    for _ in range(2):
        br.record_failure("sig")
        assert br.allow("sig")  # still closed below threshold
    br.record_failure("sig")
    assert br.state("sig") == "open"
    assert not br.allow("sig")  # fast-fail during cooldown
    clock.t = 9.9
    assert not br.allow("sig")
    clock.t = 10.0
    assert br.allow("sig")  # ONE half-open trial admitted
    assert br.state("sig") == "half-open"
    assert not br.allow("sig")  # ...and only one


def test_breaker_half_open_success_closes_failure_reopens():
    clock = _Clock()
    br = CircuitBreaker(threshold=1, cooldown=5.0, clock=clock)
    br.record_failure("sig")
    clock.t = 5.0
    assert br.allow("sig")
    br.record_success("sig")
    assert br.state("sig") == "closed"
    assert br.allow("sig")

    br.record_failure("sig")  # open again (threshold=1)
    clock.t = 10.0
    assert br.allow("sig")  # trial
    br.record_failure("sig")  # trial failed -> re-open immediately
    assert br.state("sig") == "open"
    assert not br.allow("sig")


def test_breaker_keys_are_independent():
    br = CircuitBreaker(threshold=1, cooldown=100.0, clock=_Clock())
    br.record_failure("bad-sig")
    assert not br.allow("bad-sig")
    assert br.allow("good-sig")
    assert br.snapshot()["open_keys"] == ["bad-sig"]


# ---------------------------------------------------------------------------
# JobJournal: fold rules, torn-tail tolerance, compaction
# ---------------------------------------------------------------------------


def _fields(jid, **over):
    f = {"id": jid, "tenant": "t", "spec": "increment:2", "engine": "bfs",
         "priority": 0, "options": {}, "submitted_at": 1.0}
    f.update(over)
    return f


def test_journal_folds_lifecycle(tmp_path):
    path = str(tmp_path / "jobs.jsonl")
    j = JobJournal(path)
    j.submit(_fields("aaa"))
    j.submit(_fields("bbb"))
    j.submit(_fields("ccc"))
    j.submit(_fields("ddd"))
    j.start("aaa", 1)
    j.result("aaa", "done")
    j.start("bbb", 1)  # interrupted: no result record follows
    j.cancel("ccc")
    j.start("ddd", 1)
    j.result("ddd", "failed", error="boom")
    j.retry("ddd")
    j.close()

    folded = JobJournal.replay(path)
    assert folded["aaa"]["status"] == "done"
    assert folded["bbb"]["status"] == "running"
    assert folded["bbb"]["attempts"] == 1
    assert folded["ccc"]["status"] == "cancelled"
    assert folded["ddd"]["status"] == "queued"  # retried after failure
    assert folded["ddd"]["error"] is None


def test_journal_tolerates_torn_tail(tmp_path):
    path = str(tmp_path / "torn.jsonl")
    j = JobJournal(path)
    j.submit(_fields("aaa"))
    j.result("aaa", "done")
    j.close()
    with open(path, "a", encoding="utf-8") as fh:
        fh.write('{"rec": "submit", "job": {"id": "bb')  # kill mid-append
    folded = JobJournal.replay(path)
    assert list(folded) == ["aaa"]
    assert folded["aaa"]["status"] == "done"


def test_journal_compaction_preserves_fold_and_shrinks(tmp_path):
    path = str(tmp_path / "compact.jsonl")
    j = JobJournal(path)
    j.submit(_fields("aaa"))
    for attempt in range(1, 20):
        j.start("aaa", attempt)
        j.retry("aaa")
    j.start("aaa", 20)
    j.result("aaa", "done")
    j.submit(_fields("bbb"))
    before = os.path.getsize(path)
    folded = JobJournal.replay(path)
    j.compact(folded)
    assert os.path.getsize(path) < before
    assert JobJournal.replay(path) == folded
    # The journal stays appendable after compaction swapped the file.
    j.submit(_fields("ccc"))
    j.close()
    assert "ccc" in JobJournal.replay(path)


def test_journal_ignores_records_for_unknown_jobs(tmp_path):
    path = str(tmp_path / "unknown.jsonl")
    with open(path, "w", encoding="utf-8") as fh:
        fh.write(json.dumps({"rec": "result", "job_id": "ghost",
                             "status": "done"}) + "\n")
    assert JobJournal.replay(path) == {}


# ---------------------------------------------------------------------------
# ResultStore: persistence + TTL GC
# ---------------------------------------------------------------------------


def test_result_store_roundtrip_and_ttl(tmp_path):
    clock = _Clock()
    store = ResultStore(str(tmp_path / "results"), ttl=100.0, clock=clock)
    store.put("aaa", {"unique_state_count": 13})
    assert store.get("aaa") == {"unique_state_count": 13}
    clock.t = 99.0
    assert store.get("aaa") is not None
    clock.t = 101.0
    assert store.get("aaa") is None  # expired reads return nothing
    assert store.gc() == ["aaa"]  # ...and GC removes the file
    assert store.stats()["results"] == 0
    assert store.gc() == []


def test_result_store_gc_only_expires_old_entries(tmp_path):
    clock = _Clock()
    store = ResultStore(str(tmp_path / "r"), ttl=50.0, clock=clock)
    store.put("old", {"n": 1})
    clock.t = 40.0
    store.put("new", {"n": 2})
    clock.t = 60.0
    assert store.gc() == ["old"]
    assert store.get("new") == {"n": 2}


def test_result_store_rejects_bad_ttl(tmp_path):
    with pytest.raises(ValueError, match="ttl"):
        ResultStore(str(tmp_path / "x"), ttl=0)
