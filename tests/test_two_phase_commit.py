"""Two-phase commit integration goldens (reference: examples/2pc.rs:149-170)
plus host-model/tensor-model equivalence."""

from stateright_tpu import TensorModelAdapter
from stateright_tpu.models import TwoPhaseSys, TwoPhaseTensor


def test_bfs_3_rms_golden():
    checker = TwoPhaseSys(3).checker().spawn_bfs().join()
    assert checker.unique_state_count() == 288
    checker.assert_properties()


def test_dfs_5_rms_golden():
    checker = TwoPhaseSys(5).checker().spawn_dfs().join()
    assert checker.unique_state_count() == 8832
    checker.assert_properties()


def test_dfs_5_rms_symmetry_golden():
    checker = TwoPhaseSys(5).checker().symmetry().spawn_dfs().join()
    assert checker.unique_state_count() == 665
    checker.assert_properties()


def test_tensor_model_matches_host_model():
    # The dense tensor encoding explores the same state space as the rich
    # host model: identical unique-state counts and property verdicts.
    host = TwoPhaseSys(3).checker().spawn_bfs().join()
    tensor = TensorModelAdapter(TwoPhaseTensor(3)).checker().spawn_bfs().join()
    assert tensor.unique_state_count() == host.unique_state_count() == 288
    tensor.assert_properties()


def test_tensor_model_5_rms():
    tensor = TensorModelAdapter(TwoPhaseTensor(5)).checker().spawn_dfs().join()
    assert tensor.unique_state_count() == 8832
    tensor.assert_properties()
