"""Fingerprint stability and host/device hash agreement.

Mirrors the reference's determinism requirements (stable seeds,
src/lib.rs:369-387) and the order-insensitive hashing regression tests for
HashableHashSet/Map (src/util.rs:219-268).
"""

import numpy as np

from stateright_tpu.fingerprint import (
    canonical_bytes,
    combine64,
    fingerprint,
    hash_words_jnp,
    hash_words_np,
)


def test_fingerprint_nonzero_and_stable():
    assert fingerprint((0, 0)) != 0
    assert fingerprint((0, 0)) == fingerprint((0, 0))
    assert fingerprint((0, 0)) != fingerprint((0, 1))


def test_fingerprint_pinned_values():
    # Pinned goldens: if these change, every stored fingerprint path breaks.
    assert fingerprint((0, 0)) == 5786581936300015565
    assert fingerprint("hello") == 13198642188457316447
    assert fingerprint(frozenset({1, 2, 3})) == 16332772150987862064


def test_set_and_dict_hash_order_insensitive():
    assert canonical_bytes({1, 2, 3}) == canonical_bytes({3, 2, 1})
    assert canonical_bytes({"a": 1, "b": 2}) == canonical_bytes({"b": 2, "a": 1})
    assert fingerprint({frozenset({1, 2}): [3, 4]}) == fingerprint(
        {frozenset({2, 1}): [3, 4]}
    )


def test_nested_collections_roundtrip():
    v1 = {"k": [frozenset({(1, 2), (3, 4)}), {"x": None}]}
    v2 = {"k": [frozenset({(3, 4), (1, 2)}), {"x": None}]}
    assert fingerprint(v1) == fingerprint(v2)


def test_word_hash_np_jnp_agree():
    rng = np.random.default_rng(0)
    words = rng.integers(0, 2**32, size=(64, 7), dtype=np.uint32)
    h1n, h2n = hash_words_np(words)
    h1j, h2j = hash_words_jnp(words)
    np.testing.assert_array_equal(h1n, np.asarray(h1j))
    np.testing.assert_array_equal(h2n, np.asarray(h2j))


def test_word_hash_distinct_rows_distinct_hashes():
    # All 2**16 two-lane states with small values: no collisions expected.
    xs, ys = np.meshgrid(np.arange(256, dtype=np.uint32), np.arange(256, dtype=np.uint32))
    words = np.stack([xs.ravel(), ys.ravel()], axis=-1)
    h1, h2 = hash_words_np(words)
    combined = (h1.astype(np.uint64) << np.uint64(32)) | h2.astype(np.uint64)
    assert len(np.unique(combined)) == 65536


def test_word_hash_pinned_values():
    # Pinned for fingerprint-path stability (role of the reference's fixed
    # ahash seeds, lib.rs:374-378). Re-pinned in round 4 when the hash pair
    # was fixed: the original seed-only-differentiated halves were
    # correlated and the 64-bit pair behaved like ~35 bits on structured
    # states (see fingerprint.py's mix note).
    h1, h2 = hash_words_np(np.array([[0, 0, 0]], dtype=np.uint32))
    assert combine64(h1[0], h2[0]) == 4517466826452767667


def test_hash_pair_halves_are_decorrelated():
    """The regression that motivated the round-4 re-pin: among random
    sparse structured rows, h1-collisions must NOT predict h2-collisions.
    With the old seed-only variant, ~1 in 8 h1-collisions also collided in
    h2; with independent halves the expected pair-collision count over any
    corpus this size is ~0."""
    rng = np.random.default_rng(99)
    # structured sparse rows, like model states: smallish ints, few lanes
    # (range 2**10 keeps the corpus genuinely ~2M distinct rows — a 64-range
    # pool would collapse to 262k and under-power the test)
    rows = rng.integers(0, 1024, size=(2_200_000, 3), dtype=np.uint32)
    rows = np.unique(rows, axis=0)
    assert len(rows) > 2_000_000
    h1, h2 = hash_words_np(rows)
    keys = (h1.astype(np.uint64) << np.uint64(32)) | h2.astype(np.uint64)
    n_pair_collisions = len(rows) - len(np.unique(keys))
    assert n_pair_collisions == 0, n_pair_collisions
