"""Trace conformance tests: record real loopback runs, inject faults,
and check the recordings against the models that verified them.

Ports here live in the 43xxx range (test_spawn.py uses 42000-42020, the
demos/CI 46xxx) so parallel invocations never collide.
"""

import json

import pytest

from examples.increment import conform_counter_trace, record_counter_demo
from examples.linearizable_register import conform_abd_trace, record_abd_demo
from examples.timers import conform_timers_trace, record_timers_demo
from stateright_tpu.conformance import (
    TRACE_VERSION,
    FaultInjector,
    FaultPlan,
    TraceError,
    check_trace,
    load_trace,
    register_history,
)
from stateright_tpu.semantics import LinearizabilityTester
from stateright_tpu.semantics.register import (
    READ,
    WRITE_OK,
    ReadOk,
    Register,
    Write,
)


def _engines():
    from stateright_tpu.native import runtime as native_runtime

    engines = ["python"]
    if native_runtime.is_available():
        engines.append("native")
    return engines


# -- fault-plan determinism ---------------------------------------------------


def test_fault_plan_decide_is_pure():
    plan = FaultPlan(seed=3, drop=0.2, duplicate=0.2, delay=0.2, reorder=0.2)
    grid = [
        (src, dst, n) for src in (0, 1, 7) for dst in (0, 2) for n in range(50)
    ]
    first = [plan.decide(*cell) for cell in grid]
    again = [plan.decide(*cell) for cell in grid]
    assert first == again
    # Every kind occurs somewhere on a grid this size, and a different
    # seed produces a different schedule.
    assert {d.kind for d in first} == {
        "drop", "duplicate", "delay", "reorder", "deliver",
    }
    other = FaultPlan(seed=4, drop=0.2, duplicate=0.2, delay=0.2, reorder=0.2)
    assert [other.decide(*cell) for cell in grid] != first


def test_fault_plan_validates_and_parses():
    with pytest.raises(ValueError):
        FaultPlan(drop=0.7, duplicate=0.7)
    plan = FaultPlan.from_spec("7,0.05,0.1")
    assert plan == FaultPlan(seed=7, drop=0.05, duplicate=0.1)
    with pytest.raises(ValueError):
        FaultPlan.from_spec("not-a-seed")


def test_injector_schedule_matches_plan():
    # drop/duplicate only: every decision resolves synchronously inside
    # transmit(), so the send counts are exactly the plan's schedule.
    plan = FaultPlan(seed=11, drop=0.3, duplicate=0.3)
    for _round in range(2):  # identical across injector instances
        injector = FaultInjector(plan)
        sends = []
        for n in range(40):
            injector.transmit(5, 9, b"%d" % n, sends.append)
        injector.close()
        expected = []
        for n in range(40):
            kind = plan.decide(5, 9, n).kind
            copies = {"drop": 0, "duplicate": 2}.get(kind, 1)
            expected.extend([b"%d" % n] * copies)
        assert sends == expected


def test_injector_close_flushes_pending():
    plan = FaultPlan(seed=0, delay=1.0, delay_range=(5.0, 6.0))
    injector = FaultInjector(plan)
    sends = []
    injector.transmit(0, 1, b"slow", sends.append)
    assert sends == []  # scheduled seconds out
    injector.close()  # must not wait for the deadline
    assert sends == [b"slow"]


# -- record -> conform, both engines ------------------------------------------


@pytest.fixture(scope="module", params=_engines())
def counter_trace(request, tmp_path_factory):
    engine = request.param
    base = 43000 + (10 if engine == "native" else 0)
    path = tmp_path_factory.mktemp("conf") / f"counter_{engine}.jsonl"
    record_counter_demo(
        str(path), duration=0.7, seed=7, base_port=base, client_count=2,
        engine=engine,
    )
    return engine, str(path)


def test_counter_record_conform_divergence_free(counter_trace):
    _engine, path = counter_trace
    report, tester = conform_counter_trace(path, client_count=2)
    assert report.ok, report.format()
    assert report.events > 0 and report.steps > 0
    assert report.faults > 0  # the seeded plan actually injected faults
    assert len(tester) > 0
    assert tester.serialized_history() is not None


def test_mutated_trace_is_rejected_with_field_diff(counter_trace):
    _engine, path = counter_trace
    meta, events = load_trace(path)
    mutated = False
    for ev in events:
        if (
            not mutated
            and ev.get("kind") == "deliver"
            and isinstance(ev.get("state"), list)
            and ev["state"][0] == "CounterState"
        ):
            ev["state"][1] += 100  # corrupt the recorded counter value
            mutated = True
    assert mutated, "trace has no CounterState deliver event to corrupt"
    from examples.increment import counter_model
    from stateright_tpu.actor import Network
    from stateright_tpu.conformance import make_decoder
    from examples.increment import Bump, BumpOk

    report = check_trace(
        counter_model(2, Network.new_unordered_duplicating()),
        (meta, events),
        decode=make_decoder(Bump, BumpOk),
    )
    assert not report.ok
    d = report.divergences[0]
    assert d.kind == "state-mismatch"
    # Field-level forensics: the diff names the corrupted field, and the
    # narrative is the same Path.explain rendering counterexamples get.
    assert any("value" in key for key in d.diff)
    (pair,) = [v for k, v in d.diff.items() if "value" in k]
    assert pair[1] == pair[0] + 100
    assert "Path[" in d.narrative
    assert "state-mismatch" in report.format()


@pytest.mark.parametrize("engine", _engines())
def test_abd_record_conform_and_linearizability(engine, tmp_path):
    path = tmp_path / "abd.jsonl"
    base = 43020 + (10 if engine == "native" else 0)
    record_abd_demo(
        str(path), duration=0.6, seed=3, base_port=base, client_count=2,
        engine=engine,
    )
    report, tester = conform_abd_trace(str(path), client_count=2)
    assert report.ok, report.format()
    assert report.steps > 0
    assert len(tester) > 0
    assert tester.serialized_history() is not None


@pytest.mark.parametrize("engine", _engines())
def test_timers_record_conform_ordered(engine, tmp_path):
    path = tmp_path / "timers.jsonl"
    base = 43040 + (10 if engine == "native" else 0)  # EVEN: parity peers
    record_timers_demo(str(path), duration=0.25, engine=engine, base_port=base)
    report, _ = conform_timers_trace(str(path))
    assert report.ok, report.format()
    assert report.events > 0
    # NoOp timers re-arm only; the model prunes them, the checker stutters.
    assert report.stutters > 0


# -- trace schema -------------------------------------------------------------


@pytest.mark.parametrize("engine", _engines())
def test_trace_schema(engine, tmp_path):
    path = tmp_path / "schema.jsonl"
    base = 43060 + (10 if engine == "native" else 0)
    record_counter_demo(
        str(path), duration=0.4, seed=None, base_port=base, client_count=1,
        engine=engine,
    )
    lines = [ln for ln in open(path).read().splitlines() if ln.strip()]
    meta = json.loads(lines[0])
    assert meta["kind"] == "meta" and meta["v"] == TRACE_VERSION
    assert meta["engine"] == engine
    assert [a["index"] for a in meta["actors"]] == [0, 1]
    assert meta["actors"][0]["actor"] == "CounterActor"
    assert all(":" in a["addr"] and a["id"] >= 2**16 for a in meta["actors"])

    _meta, events = load_trace(str(path))
    # Per-actor seqs are monotonic from 0 with no gaps (commands included).
    seqs = {}
    for ev in events:
        if ev["kind"] == "fault":
            continue
        seqs.setdefault(ev["actor"], []).append(ev["seq"])
    for actor, got in seqs.items():
        assert got == list(range(len(got))), f"actor {actor} seqs {got}"
    # Every actor's first event is its init, and causal file order holds:
    # a deliver's payload was previously put on the wire by a send.
    first = {}
    for ev in events:
        first.setdefault(ev["actor"], ev["kind"])
    assert set(first.values()) == {"init"}
    sent = []
    for ev in events:
        if ev["kind"] == "send":
            sent.append((ev["actor"], ev["dst"], ev["msg"]))
        elif ev["kind"] == "deliver":
            assert (ev["src"], ev["actor"], ev["msg"]) in sent
    # Command children name their (earlier) parent handler event.
    by_seq = {(e["actor"], e["seq"]): e for e in events if e["kind"] != "fault"}
    for ev in events:
        if "cause" in ev:
            parent = by_seq[(ev["actor"], ev["cause"])]
            assert parent["kind"] in ("init", "deliver", "timeout", "random")
            assert parent["seq"] < ev["seq"]


def test_load_trace_errors(tmp_path):
    p = tmp_path / "bad.jsonl"
    p.write_text("")
    with pytest.raises(TraceError):
        load_trace(str(p))
    p.write_text('{"kind": "meta", "v": 1, "actors": []}\nnot json\n{"kind": "x"}\n')
    with pytest.raises(TraceError):
        load_trace(str(p))
    # A torn FINAL line (killed deployment) is tolerated.
    p.write_text('{"kind": "meta", "v": 1, "actors": []}\n{"kind": "init", "ac')
    meta, events = load_trace(str(p))
    assert meta["v"] == 1 and events == []


# -- history extraction -------------------------------------------------------


def _send(actor, msg):
    return {"kind": "send", "actor": actor, "seq": 0, "msg": msg}


def _deliver(actor, msg):
    return {"kind": "deliver", "actor": actor, "seq": 0, "msg": msg}


def test_register_history_parity_with_semantics_fixtures():
    # Mirrors tests/test_semantics.py::test_identifies_linearizable_register_history
    # via synthetic trace events instead of direct tester calls.
    t = register_history(
        [_send(0, ["Put", 1, "B"]), _send(1, ["Get", 1]),
         _deliver(1, ["GetOk", 1, "A"])],
        tester=LinearizabilityTester(Register("A")),
    )
    assert t.serialized_history() == [(READ, ReadOk("A"))]

    t = register_history(
        [_send(0, ["Get", 1]), _send(1, ["Put", 1, "B"]),
         _deliver(0, ["GetOk", 1, "B"])],
        tester=LinearizabilityTester(Register("A")),
    )
    assert t.serialized_history() == [
        (Write("B"), WRITE_OK), (READ, ReadOk("B")),
    ]

    # ...and the unlinearizable fixture still rejects.
    t = register_history(
        [_send(0, ["Get", 1]), _deliver(0, ["GetOk", 1, "B"])],
        tester=LinearizabilityTester(Register("A")),
    )
    assert t.serialized_history() is None


def test_history_extraction_dedups_retries_and_duplicates():
    events = [
        _send(0, ["Put", 1, "X"]),
        _send(0, ["Put", 1, "X"]),  # retransmission while in flight
        _deliver(0, ["PutOk", 1]),
        _deliver(0, ["PutOk", 1]),  # duplicated response
        _send(0, ["Get", 2]),
        _deliver(0, ["GetOk", 1, "X"]),  # stale rid: ignored
        _deliver(0, ["GetOk", 2, "X"]),
    ]
    t = register_history(events)
    assert len(t) == 2
    assert t.serialized_history() == [
        (Write("X"), WRITE_OK), (READ, ReadOk("X")),
    ]


# -- speclint STR5xx ----------------------------------------------------------


def test_speclint_flags_unserializable_messages():
    from dataclasses import dataclass
    from typing import FrozenSet

    from stateright_tpu import Expectation
    from stateright_tpu.actor import Actor, ActorModel, Id
    from stateright_tpu.analysis import analyze

    @dataclass(frozen=True)
    class SetMsg:
        items: FrozenSet[int]

    class SetActor(Actor):
        def on_start(self, id, out):
            out.send(Id(1 - int(id)), SetMsg(frozenset({1, 2})))
            return 0

        def on_msg(self, id, state, src, msg, out):
            return None

    model = (
        ActorModel()
        .add_actors(SetActor() for _ in range(2))
        .property(Expectation.ALWAYS, "t", lambda m, s: True)
    )
    report = analyze(model, samples=32)
    assert "spawn" in report.families_run
    assert [d.code for d in report.diagnostics] == ["STR501"]
    assert "SetMsg" in report.diagnostics[0].location


def test_speclint_spawn_family_clean_on_abd():
    from examples.linearizable_register import abd_model
    from stateright_tpu.analysis import analyze

    report = analyze(abd_model(1, 2), samples=64)
    assert "spawn" in report.families_run
    assert not [d for d in report.diagnostics if d.code.startswith("STR5")]
