"""Observability layer (stateright_tpu/obs): registry semantics, the JSONL
trace schema, reporter rate/ETA math, the uniform Checker.telemetry()
surface across every engine, and the Explorer /metrics endpoint.
"""

import io
import json
import urllib.request

import pytest

from stateright_tpu import TensorModelAdapter
from stateright_tpu.models import IncrementTensor, TwoPhaseTensor
from stateright_tpu.models.fixtures import BinaryClock, LinearEquation
from stateright_tpu.models.two_phase_commit import TwoPhaseSys
from stateright_tpu.obs.metrics import MetricsRegistry
from stateright_tpu.report import ReportData, WriteReporter

REQUIRED_KEYS = {"ts", "seq", "engine", "event"}
PROGRESS_KEYS = {"states", "unique", "frontier", "max_depth", "phase_ms"}


# -- registry -----------------------------------------------------------------


def test_registry_counters_gauges_phases():
    m = MetricsRegistry()
    m.inc("eras")
    m.inc("eras")
    m.inc("steps", 5)
    m.set_gauge("take_cap", 128)
    m.set_gauge("take_cap", 64)  # gauges overwrite
    with m.phase("device_era"):
        pass
    with m.phase("device_era"):
        pass
    m.add_phase("readback", 0.25)
    snap = m.snapshot()
    assert snap["eras"] == 2
    assert snap["steps"] == 5
    assert snap["take_cap"] == 64
    assert snap["phase_ms"]["readback"] == 250.0
    assert snap["phase_ms"]["device_era"] >= 0.0
    assert m.get("eras") == 2
    assert m.get("missing", 7) == 7
    # phase_ms() is cumulative and sorted by name
    assert list(m.phase_ms()) == ["device_era", "readback"]


def test_registry_snapshot_is_a_copy():
    m = MetricsRegistry()
    m.inc("eras")
    snap = m.snapshot()
    snap["eras"] = 999
    assert m.snapshot()["eras"] == 1


# -- trace JSONL --------------------------------------------------------------


def _parse_trace(path):
    with open(path) as f:
        lines = [json.loads(line) for line in f]  # every line must parse
    assert lines, "trace is empty"
    assert [rec["seq"] for rec in lines] == list(range(len(lines)))
    for rec in lines:
        assert REQUIRED_KEYS <= set(rec), rec
    assert lines[0]["event"] == "run_start"
    assert lines[-1]["event"] == "run_end"
    return lines


def test_trace_jsonl_schema_device_engine(tmp_path):
    """Acceptance: CheckerBuilder.trace(path) on a 2pc-3 run produces valid
    JSONL with per-era phase timings."""
    path = str(tmp_path / "run.jsonl")
    c = (
        TensorModelAdapter(TwoPhaseTensor(3))
        .checker()
        .trace(path)
        .spawn_tpu_bfs(chunk_size=128)
        .join()
    )
    assert c.unique_state_count() == 288
    lines = _parse_trace(path)
    eras = [rec for rec in lines if rec["event"] == "era"]
    assert eras, "device run emitted no era events"
    for rec in eras:
        assert PROGRESS_KEYS <= set(rec), rec
        assert {"load_factor", "take_cap", "steps", "generated",
                "spill_rows"} <= set(rec)
        assert "device_era" in rec["phase_ms"]
        assert rec["phase_ms"]["device_era"] >= 0.0
    # Final event reconciles with the checker's own counters.
    assert lines[-1]["states"] == c.state_count()
    assert lines[-1]["unique"] == c.unique_state_count()


def test_trace_jsonl_schema_host_engine(tmp_path):
    path = str(tmp_path / "host.jsonl")
    c = (
        TensorModelAdapter(TwoPhaseTensor(3))
        .checker()
        .trace(path)
        .spawn_bfs()
        .join()
    )
    lines = _parse_trace(path)
    waves = [rec for rec in lines if rec["event"] == "wave"]
    assert waves
    for rec in waves:
        assert PROGRESS_KEYS <= set(rec)
        assert "check_block" in rec["phase_ms"]


def test_profile_option_is_harmless(tmp_path):
    # jax.profiler may or may not be usable on this backend; .profile()
    # must never break the run either way.
    c = (
        TensorModelAdapter(TwoPhaseTensor(3))
        .checker()
        .profile(str(tmp_path / "prof"))
        .spawn_bfs()
        .join()
    )
    assert c.unique_state_count() == 288


# -- reporter rate / ETA math -------------------------------------------------


def test_reporter_rate_moving_average_and_eta():
    out = io.StringIO()
    r = WriteReporter(out)
    mk = lambda states, secs: ReportData(
        total_states=states,
        unique_states=states,
        max_depth=1,
        duration_secs=secs,
        done=False,
        target_states=1_000,
    )
    r.report_checking(mk(0, 0.0))
    r.report_checking(mk(100, 1.0))
    r.report_checking(mk(300, 2.0))
    lines = out.getvalue().splitlines()
    # First sample: reference-compatible line, no rate suffix yet.
    assert lines[0] == "Checking. states=0, unique=0, depth=1"
    # Second: rate == avg == 100/s; eta = (1000-100)/100 = 9s.
    assert "rate=100/s" in lines[1]
    assert "avg=100/s" in lines[1]
    assert "eta=9s" in lines[1]
    # Third: instantaneous (300-100)/1 = 200/s, window avg 300/2 = 150/s,
    # eta = (1000-300)/150 = 4s.
    assert "rate=200/s" in lines[2]
    assert "avg=150/s" in lines[2]
    assert "eta=4s" in lines[2]


def test_reporter_done_line_unchanged_and_rate_appended():
    out = io.StringIO()
    r = WriteReporter(out)
    r.report_checking(
        ReportData(
            total_states=1_000,
            unique_states=900,
            max_depth=7,
            duration_secs=2.0,
            done=True,
            telemetry={"eras": 3},
        )
    )
    text = out.getvalue()
    assert text.startswith("Done. states=1000, unique=900, depth=7, sec=2\n")
    assert "Rate. states_per_sec=500.0" in text
    assert "Telemetry. eras=3" in text


def test_reporter_rate_units():
    from stateright_tpu.report import _fmt_rate

    assert _fmt_rate(12.0) == "12/s"
    assert _fmt_rate(4_200.0) == "4.2k/s"
    assert _fmt_rate(2_500_000.0) == "2.50M/s"


def test_reporter_eta_damps_shrinking_era_jitter():
    """Regression: near the end of a run eras shrink and polls can land
    milliseconds apart; the one-interval instantaneous rate over such a
    sliver whipsawed the rate and ETA. The trailing span now reaches
    back until it covers MIN_RATE_SPAN."""
    out = io.StringIO()
    r = WriteReporter(out)
    mk = lambda states, secs: ReportData(
        total_states=states,
        unique_states=states,
        max_depth=1,
        duration_secs=secs,
        done=False,
        target_states=10_000,
    )
    r.report_checking(mk(0, 0.0))
    r.report_checking(mk(1000, 1.0))
    r.report_checking(mk(1500, 1.02))  # 20ms after the previous poll
    lines = out.getvalue().splitlines()
    # Undamped this would read (1500-1000)/0.02 = "25.0k/s"; reaching
    # back to a >= 0.25s span reads (1500-0)/1.02 ≈ 1.5k/s instead.
    assert "rate=1.5k/s" in lines[2], lines[2]
    assert "25.0k/s" not in lines[2]
    assert "eta=5s" in lines[2], lines[2]


def test_reporter_eta_never_negative():
    out = io.StringIO()
    r = WriteReporter(out)
    mk = lambda states, secs: ReportData(
        total_states=states,
        unique_states=states,
        max_depth=1,
        duration_secs=secs,
        done=False,
        target_states=1_000,
    )
    r.report_checking(mk(0, 0.0))
    r.report_checking(mk(1500, 1.0))  # overshot the target
    r.report_checking(mk(1400, 2.0))  # synthetic counter retreat
    lines = out.getvalue().splitlines()
    # Past the target: the ETA is omitted rather than negative.
    assert "rate=" in lines[1] and "eta=" not in lines[1], lines[1]
    # A retreating count floors the instantaneous rate at zero.
    assert "rate=0/s" in lines[2] and "eta=" not in lines[2], lines[2]


# -- Histogram.merge edge cases -----------------------------------------------


def test_histogram_merge_empty_and_populated():
    from stateright_tpu.obs.metrics import Histogram

    a = Histogram()
    for v in (0.001, 0.01, 0.5):
        a.observe(v)
    before = a.snapshot()
    a.merge(Histogram())  # merging an empty histogram is a no-op
    assert a.snapshot() == before
    b = Histogram()
    b.merge(a)  # populated into empty: exact copy
    assert b.snapshot() == before


def test_histogram_merge_mismatched_bounds_raises():
    from stateright_tpu.obs.metrics import Histogram

    a = Histogram(bounds=[0.1, 1.0, 10.0])
    b = Histogram(bounds=[0.2, 2.0])
    b.observe(0.15)
    with pytest.raises(ValueError, match="bucket bounds"):
        a.merge(b)
    assert a.count == 0  # the refused merge left no partial counts


def test_histogram_self_merge_doubles_counts():
    from stateright_tpu.obs.metrics import Histogram

    h = Histogram(bounds=[1.0, 2.0, 4.0])
    h.observe(0.5)
    h.observe(3.0)
    h.merge(h)  # sequential locking: self-merge must not deadlock
    assert h.count == 4
    assert h.sum == pytest.approx(7.0)
    assert h.buckets()[-1][1] == 4


def test_histogram_single_observation_quantiles():
    from stateright_tpu.obs.metrics import Histogram

    h = Histogram(bounds=[1.0, 2.0, 4.0])
    h.observe(1.5)
    # With one observation every quantile IS that observation: the
    # in-bucket interpolation clamps to the observed max instead of
    # reporting a fictitious bucket-edge latency.
    for q in (0.5, 0.95, 0.99, 1.0):
        assert h.quantile(q) == 1.5
    snap = h.snapshot()
    assert snap["p50"] == snap["p99"] == 1.5


# -- Prometheus labeled series ------------------------------------------------


def test_render_prometheus_labeled_dict_counters():
    from stateright_tpu.obs.metrics import render_prometheus

    snap = {
        "engine": "TestEngine",
        "shard_exchange_rows": {"1": 7, "0": 5, "10": 2},
        "serve_tenant_requests": {'we"ird\\ten': 3},
        "plain": 4,
        "unlabeled": {"x": 1},
    }
    text = render_prometheus(
        snap,
        labels={
            "shard_exchange_rows": "shard",
            "serve_tenant_requests": "tenant",
        },
    )
    # One series per label value, lexicographically ordered.
    i0 = text.index('stateright_shard_exchange_rows{shard="0"} 5')
    i1 = text.index('stateright_shard_exchange_rows{shard="1"} 7')
    i10 = text.index('stateright_shard_exchange_rows{shard="10"} 2')
    assert i0 < i1 < i10
    # Backslashes and quotes in label values are escaped.
    assert (
        'stateright_serve_tenant_requests{tenant="we\\"ird\\\\ten"} 3'
        in text
    )
    # Plain numerics render flat; dict metrics WITHOUT a label mapping
    # are skipped entirely (JSON-only gauges).
    assert "stateright_plain 4" in text
    assert "unlabeled" not in text


# -- Checker.telemetry() non-empty for EVERY engine ---------------------------


def _assert_live_telemetry(checker):
    t = checker.telemetry()
    assert isinstance(t, dict) and t, t
    assert t.get("engine")
    # More than just the engine tag: the registry actually got populated.
    assert len(t) > 1, t
    return t


def test_telemetry_spawn_bfs():
    _assert_live_telemetry(
        LinearEquation(2, 10, 14).checker().spawn_bfs().join()
    )


def test_telemetry_spawn_dfs():
    _assert_live_telemetry(
        LinearEquation(2, 10, 14).checker().spawn_dfs().join()
    )


def test_telemetry_spawn_on_demand():
    c = BinaryClock().checker().spawn_on_demand()
    _assert_live_telemetry(c)  # non-empty even before it is driven
    c.run_to_completion()
    c.join()
    t = _assert_live_telemetry(c)
    assert t["waves"] >= 1


def test_telemetry_spawn_simulation():
    c = (
        TwoPhaseSys(3)
        .checker()
        .target_state_count(200)
        .spawn_simulation(7)
        .join()
    )
    t = _assert_live_telemetry(c)
    assert t["traces"] >= 1
    assert "walk" in t["phase_ms"]


def test_telemetry_spawn_parallel_bfs():
    c = TwoPhaseSys(3).checker().threads(2).spawn_parallel_bfs().join()
    t = _assert_live_telemetry(c)
    assert t["workers"] == 2
    assert t["rounds"] >= 1


def test_telemetry_spawn_vbfs():
    c = (
        TensorModelAdapter(TwoPhaseTensor(3))
        .checker()
        .threads(2)
        .spawn_vbfs()
        .join()
    )
    t = _assert_live_telemetry(c)
    assert t["waves"] >= 1
    for phase in ("property_eval", "expand", "hash", "visited_insert"):
        assert phase in t["phase_ms"]


def test_telemetry_spawn_tpu_bfs():
    c = (
        TensorModelAdapter(TwoPhaseTensor(3))
        .checker()
        .spawn_tpu_bfs(chunk_size=128)
        .join()
    )
    t = _assert_live_telemetry(c)
    assert t["eras"] >= 1 and t["steps"] >= 1
    assert "device_era" in t["phase_ms"]


def test_telemetry_spawn_tpu_simulation():
    c = (
        TensorModelAdapter(IncrementTensor(2))
        .checker()
        .target_state_count(100)
        .spawn_tpu_simulation(7, walks=32, walk_cap=16)
        .join()
    )
    t = _assert_live_telemetry(c)
    assert t["eras"] >= 1
    assert t["walks"] == 32


def test_telemetry_spawn_sharded_bfs():
    try:
        from jax import shard_map  # noqa: F401
    except ImportError:
        pytest.skip("jax.shard_map unavailable on this jax version")
    c = (
        TensorModelAdapter(TwoPhaseTensor(3))
        .checker()
        .spawn_sharded_bfs(
            chunk_size=128,
            queue_capacity_per_shard=1 << 12,
            table_capacity_per_shard=1 << 12,
        )
        .join()
    )
    t = _assert_live_telemetry(c)
    assert t["eras"] >= 1
    assert t["n_shards"] >= 1


# -- Explorer /metrics --------------------------------------------------------


def test_explorer_metrics_endpoint():
    from stateright_tpu.explorer.server import serve

    server = serve(BinaryClock().checker(), "127.0.0.1:0", block=False)
    try:
        base = server.url.rstrip("/")

        def get_json(path):
            with urllib.request.urlopen(base + path) as r:
                assert r.status == 200
                return json.loads(r.read())

        m = get_json("/metrics")
        for key in ("ts", "done", "state_count", "unique_state_count",
                    "max_depth", "telemetry"):
            assert key in m, m
        assert m["telemetry"], "telemetry must be non-empty"
        # The dot-prefixed alias matches the other API routes.
        assert get_json("/.metrics")["telemetry"]

        req = urllib.request.Request(base + "/.runtocompletion", method="POST")
        with urllib.request.urlopen(req) as r:
            assert r.status == 200
        server.checker.join()
        m2 = get_json("/metrics")
        assert m2["done"] is True
        assert m2["unique_state_count"] == 2
        assert m2["telemetry"]["waves"] >= 1
    finally:
        server.shutdown()


def test_explorer_ui_ships_metrics_panel():
    # The SPA bundle must actually wire the dashboard: panel in the page,
    # polling + sparkline logic in the script.
    from pathlib import Path

    ui = Path(__file__).parent.parent / "stateright_tpu" / "explorer" / "ui"
    html = (ui / "index.html").read_text()
    js = (ui / "app.js").read_text()
    assert "metrics-panel" in html and "sparkline" in html
    assert "/metrics" in js and "pollMetrics" in js
