"""proglint (analysis/program.py): each STR6xx detector must flag its
deliberately broken device program, the bundled models must pass the
light tier clean, and the CLI exit-status contract (0/1/2) plus the
bundled-model registry stay honest."""

from __future__ import annotations

import copy
import io
import json
import os
import warnings

import numpy as np
import pytest

from stateright_tpu.analysis import AnalysisReport, analyze
from stateright_tpu.analysis import program as proglint
from stateright_tpu.models import IncrementTensor
from stateright_tpu.tensor import TensorModel, TensorProperty


def codes(report: AnalysisReport) -> set:
    return {d.code for d in report.diagnostics}


def error_codes(report: AnalysisReport) -> set:
    return {d.code for d in report.errors}


def run_program_family(tm, **kw) -> AnalysisReport:
    report = AnalysisReport(type(tm).__name__)
    proglint.run(tm, report, **kw)
    return report


# ---------------------------------------------------------------------------
# Broken-model fixtures, one per detector.
# ---------------------------------------------------------------------------


class CallbackTensor(TensorModel):
    """STR601: a host callback inside `step_lanes` — every era would pay
    a device->host round-trip."""

    state_width = 1
    max_actions = 1

    def init_states_array(self) -> np.ndarray:
        return np.zeros((1, 1), dtype=np.uint32)

    def step_lanes(self, xp, lanes):
        u = xp.uint32
        nxt = (lanes[0] + u(1)) & u(7)
        if xp is not np:  # keep the host-oracle replay pure
            import jax

            nxt = jax.pure_callback(
                lambda a: a, jax.ShapeDtypeStruct(nxt.shape, nxt.dtype), nxt
            )
        return [(nxt,)], [lanes[0] < u(8)]

    def tensor_properties(self):
        return [TensorProperty.always("true", lambda xp, l: l[0] == l[0])]


class WideLaneTensor(TensorModel):
    """STR603: `step_lanes` emits an off-contract lane dtype (the int64
    cast lands as int32 under disabled x64 — still not uint32)."""

    state_width = 1
    max_actions = 1

    def init_states_array(self) -> np.ndarray:
        return np.zeros((1, 1), dtype=np.uint32)

    def step_lanes(self, xp, lanes):
        u = xp.uint32
        nxt = ((lanes[0] + u(1)) & u(7)).astype(xp.int64)  # the bug
        return [(nxt,)], [lanes[0] < u(8)]

    def tensor_properties(self):
        return [TensorProperty.always("true", lambda xp, l: l[0] == l[0])]


class UnstableSignatureTensor(TensorModel):
    """STR605: `config_digest` leaks the instance address, so an
    equal-config twin gets a different compile signature and every
    signature-keyed cache (intern pool, executables, lint) misses."""

    state_width = 1
    max_actions = 1

    def init_states_array(self) -> np.ndarray:
        return np.zeros((1, 1), dtype=np.uint32)

    def config_digest(self) -> str:
        return hex(id(self))  # the bug

    def step_lanes(self, xp, lanes):
        u = xp.uint32
        return [(((lanes[0] + u(1)) & u(7)),)], [lanes[0] < u(8)]

    def tensor_properties(self):
        return [TensorProperty.always("true", lambda xp, l: l[0] == l[0])]


# ---------------------------------------------------------------------------
# STR601 — transfers/callbacks in hot-loop programs
# ---------------------------------------------------------------------------


def test_callback_in_step_lanes_flagged():
    report = run_program_family(CallbackTensor())
    assert "STR601" in error_codes(report)


# ---------------------------------------------------------------------------
# STR602 — broken/missed buffer donation
# ---------------------------------------------------------------------------


def test_missing_donation_attrs_flagged():
    report = AnalysisReport("x")
    proglint.check_donation_text(
        IncrementTensor(2), "era_loop", "module @jit_loop { }", 2, report
    )
    assert "STR602" in error_codes(report)


def test_satisfied_donation_is_clean():
    report = AnalysisReport("x")
    text = (
        "%arg0 {tf.aliasing_output = 0 : i32}, "
        "%arg1 {tf.aliasing_output = 1 : i32}"
    )
    proglint.check_donation_text(
        IncrementTensor(2), "era_loop", text, 2, report
    )
    assert "STR602" not in codes(report)


def test_disabled_donation_degrades_to_info():
    report = AnalysisReport("x")
    proglint.check_donation_text(
        IncrementTensor(2), "era_loop", "module @jit_loop { }", 0, report
    )
    assert report.ok  # info only — the backend opted out, not the model
    assert "STR602" in codes(report)


def test_real_lowering_with_broken_donation_flagged():
    # A donated buffer whose output shape differs cannot alias: XLA drops
    # the donation (UserWarning) and the StableHLO carries no aliasing
    # attr — exactly what the detector keys on.
    import jax
    import jax.numpy as jnp

    def bad(buf):
        return jnp.zeros((buf.shape[0] + 1,), buf.dtype)

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        text = (
            jax.jit(bad, donate_argnums=(0,))
            .lower(jax.ShapeDtypeStruct((8,), jnp.uint32))
            .as_text()
        )
    report = AnalysisReport("x")
    proglint.check_donation_text(
        IncrementTensor(2), "era_loop", text, 1, report
    )
    assert "STR602" in error_codes(report)


# ---------------------------------------------------------------------------
# STR603 — dtype drift
# ---------------------------------------------------------------------------


def test_off_contract_lane_dtype_flagged():
    report = run_program_family(WideLaneTensor())
    assert "STR603" in error_codes(report)


# ---------------------------------------------------------------------------
# STR604 — the op-count budget gate
# ---------------------------------------------------------------------------


def _perturbed_budgets(tmp_path, delta: int) -> str:
    """The committed budget file with IncrementTensor(2)'s tpu_bfs entry
    shifted by `delta` ops."""
    from stateright_tpu.engines.compiled import model_signature

    with open(proglint.BUDGETS_PATH) as fh:
        doc = json.load(fh)
    key = f"tpu_bfs|{model_signature(IncrementTensor(2))}"
    assert key in doc["entries"], sorted(doc["entries"])
    doc["entries"][key]["ops"] += delta
    path = os.path.join(str(tmp_path), f"budgets{delta:+d}.json")
    with open(path, "w") as fh:
        json.dump(doc, fh)
    return path


def test_op_count_growth_over_budget_is_error(tmp_path):
    # Budget one op BELOW the measured count: the trace "grew" past it.
    report = run_program_family(
        IncrementTensor(2), budgets_path=_perturbed_budgets(tmp_path, -1)
    )
    assert "STR604" in error_codes(report)


def test_op_count_shrink_under_budget_is_ratchet_warning(tmp_path):
    report = run_program_family(
        IncrementTensor(2), budgets_path=_perturbed_budgets(tmp_path, +1)
    )
    assert report.ok  # warning, not error
    assert any(
        d.code == "STR604" for d in report.warnings
    ), report.format()


def test_exact_budget_match_is_silent():
    report = run_program_family(IncrementTensor(2))
    assert "STR604" not in codes(report)


def test_fusion_factor_keys_distinct_budget_rows():
    """Two fusion factors are two different compiled artifacts: each gets
    its own committed budget row (`tpu_bfs|...` vs `tpu_bfs+f4|...`) with
    the fusion factor pinned in the geometry, so neither ratchet can
    silently absorb the other's growth."""
    from stateright_tpu.engines.compiled import model_signature

    sig = model_signature(IncrementTensor(2))
    with open(proglint.BUDGETS_PATH) as fh:
        entries = json.load(fh)["entries"]
    f = proglint.FUSED_LINT_FACTOR
    for base in ("tpu_bfs", "sharded"):
        classic = entries[f"{base}|{sig}"]
        fused = entries[f"{proglint._engine_key(base, f)}|{sig}"]
        assert classic["geometry"]["fuse"] == 1
        assert fused["geometry"]["fuse"] == f
        # The fused program carries the inner loop + fusion tail: it can
        # never be the same artifact as the classic one.
        assert fused["ops"] != classic["ops"]
    assert proglint._engine_key("tpu_bfs", 1) == "tpu_bfs"
    assert proglint._engine_key("tpu_bfs", 4) == "tpu_bfs+f4"


# ---------------------------------------------------------------------------
# STR605 — compile-signature instability
# ---------------------------------------------------------------------------


def test_unstable_config_digest_flagged():
    report = run_program_family(UnstableSignatureTensor())
    assert "STR605" in error_codes(report)


# ---------------------------------------------------------------------------
# STR606 — the cost model / predicted roofline
# ---------------------------------------------------------------------------


def test_deep_tier_produces_predicted_roofline():
    summary = proglint.program_summary(IncrementTensor(2), cost=True)
    cost = summary["cost"]
    assert cost["bytes_per_step"] > 0
    assert cost["predicted_states_per_sec"] > 0
    # The deep tier lowered every device program, not just the era loop.
    for name in (
        "era_loop", "seed_loop", "visited_insert", "visited_rehash",
        "mux_expand", "sharded_era",
    ):
        assert summary["programs"][name]["ops"] > 0, name


def test_device_run_telemetry_carries_program_snapshot():
    from stateright_tpu import TensorModelAdapter
    from stateright_tpu.engines.compiled import model_signature

    tm = IncrementTensor(2)
    proglint.program_summary(tm, cost=True)  # prime the summary cache
    assert proglint.cached_summary(model_signature(tm)) is not None
    checker = (
        TensorModelAdapter(tm)
        .checker()
        .spawn_tpu_bfs(
            chunk_size=64, queue_capacity=1 << 10, table_capacity=1 << 10
        )
        .join()
    )
    snap = checker.telemetry()["program"]
    assert snap["signature"] == model_signature(tm)
    assert snap["era_ops"] > 0
    assert snap["predicted_states_per_sec"] > 0
    if snap.get("measured_states_per_sec"):
        assert snap["attribution_ratio"] > 0


def test_write_reporter_prints_program_recap():
    from stateright_tpu.report import ReportData, WriteReporter

    out = io.StringIO()
    WriteReporter(out).report_checking(
        ReportData(
            total_states=100,
            unique_states=100,
            max_depth=3,
            duration_secs=1.0,
            done=True,
            telemetry={
                "steps": 5,
                "program": {
                    "predicted_states_per_sec": 2_000_000.0,
                    "measured_states_per_sec": 500_000.0,
                    "attribution_ratio": 0.25,
                    "era_ops": 1400,
                },
            },
        )
    )
    text = out.getvalue()
    assert "Program. predicted=2.00M/s" in text
    assert "attribution=0.25" in text
    assert "program" not in text.split("Telemetry.")[1].split("\n")[0]


# ---------------------------------------------------------------------------
# count_ops / cache mechanics
# ---------------------------------------------------------------------------


def test_count_ops_recurses_into_control_flow():
    import jax
    import jax.numpy as jnp

    def f(x):
        def body(c, _):
            return c + jnp.uint32(1), None

        y, _ = jax.lax.scan(body, x, None, length=3)
        return y * jnp.uint32(2)

    prims, dtypes = proglint.count_ops(jax.make_jaxpr(f)(jnp.uint32(0)))
    assert prims["scan"] == 1
    assert prims["add"] >= 1  # the body's add, behind the scan param
    assert any(np.dtype(d) == np.uint32 for d in dtypes)


def test_cached_program_pass_replays_identical_diags():
    tm = IncrementTensor(2)
    first = run_program_family(tm)
    second = run_program_family(tm)  # summary-cache hit
    assert codes(first) == codes(second)
    assert "program" in second.families_run


# ---------------------------------------------------------------------------
# The default lint tier includes the family; bundled models stay clean.
# ---------------------------------------------------------------------------


def test_default_analyze_runs_program_family_clean():
    report = analyze(IncrementTensor(2), samples=64)
    assert "program" in report.families_run
    assert report.ok, report.format()


# ---------------------------------------------------------------------------
# CLI contract: exit statuses 0/1/2, --json shape, --program.
# ---------------------------------------------------------------------------


def test_cli_exit_zero_on_clean_model(capsys):
    from stateright_tpu.analysis.__main__ import main

    assert main(["increment:2", "--samples", "32"]) == 0
    assert "IncrementTensor" in capsys.readouterr().out


def test_cli_exit_one_on_error_findings(capsys):
    from stateright_tpu.analysis.__main__ import main

    assert main(["tests.test_proglint:WideLaneTensor", "--samples", "32"]) == 1
    assert "STR603" in capsys.readouterr().out


def test_cli_exit_two_on_unknown_shorthand(capsys):
    from stateright_tpu.analysis.__main__ import main

    with pytest.raises(SystemExit) as exc:
        main(["no-such-model:3"])
    assert exc.value.code == 2
    assert "unknown model" in capsys.readouterr().err


def test_cli_exit_two_on_broken_dotted_path(capsys):
    from stateright_tpu.analysis.__main__ import main

    with pytest.raises(SystemExit) as exc:
        main(["no.such.module:Thing"])
    assert exc.value.code == 2

    capsys.readouterr()
    with pytest.raises(SystemExit) as exc:
        main(["tests.test_proglint:NoSuchFactory"])
    assert exc.value.code == 2
    assert "cannot resolve" in capsys.readouterr().err


def test_cli_json_shape_includes_program_family(capsys):
    from stateright_tpu.analysis.__main__ import main

    assert main(["increment:2", "--samples", "32", "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    for key in (
        "model", "ok", "errors", "warnings", "counts_by_code",
        "families_run", "diagnostics",
    ):
        assert key in doc, key
    assert doc["ok"] is True
    assert "program" in doc["families_run"]


def test_cli_program_flag_runs_deep_tier(capsys):
    from stateright_tpu.analysis.__main__ import main

    assert main(["increment:2", "--samples", "32", "--program"]) == 0
    capsys.readouterr()


# ---------------------------------------------------------------------------
# The bundled-model registry and the models package stay in sync.
# ---------------------------------------------------------------------------

# One constructing spec per registered shorthand (the arg tuples the CI
# dogfood stage uses).
BUNDLED_SPECS = [
    "2pc:3",
    "2pc-host:3",
    "abd:2",
    "abd-ordered:2",
    "binary-clock",
    "increment:2",
    "increment-host:2",
    "increment-lock:2",
    "increment-lock-host:2",
    "linear-equation:1,2,20",
    "linearizable-register:2,2",
    "lww-register:2",
    "paxos:2",
    "single-copy:2,2",
    "write-once-register:2",
]

# models.__all__ entries that are deliberately NOT lintable demo models:
# broken-by-design lint fixtures exercised by the speclint test suite.
LINT_FIXTURES = {"DGraph", "Panicker"}


def test_every_bundled_shorthand_constructs():
    from stateright_tpu.analysis.__main__ import (
        BUNDLED,
        _register,
        resolve_model,
    )

    _register()
    assert {s.split(":")[0] for s in BUNDLED_SPECS} == set(BUNDLED)
    for spec in BUNDLED_SPECS:
        assert resolve_model(spec) is not None, spec


def test_models_package_is_fully_registered():
    import stateright_tpu.models as models_pkg
    from stateright_tpu.analysis.__main__ import BUNDLED, _register

    _register()
    registered_classes = {v for v in BUNDLED.values() if isinstance(v, type)}
    for name in models_pkg.__all__:
        if name in LINT_FIXTURES:
            continue
        assert getattr(models_pkg, name) in registered_classes, (
            f"models.{name} has no bundled lint shorthand "
            "(stateright_tpu/analysis/__main__.py BUNDLED)"
        )


def test_signature_stable_across_deepcopy_for_bundled_model():
    # The positive control for STR605: the bundled models' signatures
    # must survive the very probe the detector uses.
    from stateright_tpu.engines.compiled import model_signature

    tm = IncrementTensor(2)
    assert model_signature(tm) == model_signature(copy.deepcopy(tm))
