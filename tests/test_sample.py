"""Deterministic bottom-k state sampling (obs/sample.py).

The tentpole contract: a state is sampled iff its 64-bit fingerprint is
among the k smallest in the EXPLORED SET, so the sample is a pure
function of that set — independent of engine, visitation order, shard
layout, and pipelining. These tests lock the strongest form of that
claim: exact sample-set equality between the host oracle, the
single-device engine (pipelined and serial), and the sharded mesh; exact
field sketches against exhaustive enumeration when k covers the space;
and survival of the sample across a kill/resume checkpoint round-trip.
"""

import jax
import numpy as np
import pytest

from stateright_tpu.models import IncrementTensor, TwoPhaseTensor
from stateright_tpu.obs.sample import (
    SpaceSampler,
    build_space_profile,
    detect_saturation,
)
from stateright_tpu.tensor import TensorModelAdapter

OPTS = dict(chunk_size=64, queue_capacity=1 << 12, table_capacity=1 << 11)


@pytest.fixture(scope="module")
def devices():
    devs = jax.devices()
    assert len(devs) >= 8, "conftest should force 8 virtual CPU devices"
    return devs[:8]


def _host_fps(tm, k):
    c = TensorModelAdapter(tm).checker().sample(k=k).spawn_bfs().join()
    return c._sampler.fingerprints()


# -- sampler unit behavior ----------------------------------------------------


def test_bottom_k_keeps_exactly_the_k_smallest():
    s = SpaceSampler(k=4)
    fps = [90, 10, 50, 70, 30, 20, 60]
    for fp in fps:
        s.offer(fp, depth=1)
    assert s.fingerprints() == sorted(fps)[:4]
    # Threshold is the k-th smallest, exclusive: offers at/above reject.
    assert s.threshold() == 50
    assert not s.offer(50, depth=1)
    assert not s.offer(51, depth=1)
    assert s.offer(5, depth=1)
    assert s.fingerprints() == [5, 10, 20, 30]


def test_offer_dedups_and_backfills_richer_fields():
    s = SpaceSampler(k=4)
    assert s.offer(10, depth=3)
    assert not s.offer(10, depth=3)  # duplicate fp: one sample
    assert len(s.fingerprints()) == 1
    s.offer(10, depth=3, state=(1, 2, 3))  # later offer backfills state
    (rec,) = s.records()
    assert rec["state"] == (1, 2, 3)


def test_kmv_estimate_exact_below_k():
    s = SpaceSampler(k=64)
    for fp in range(1, 14):
        s.offer(fp, depth=1)
    # Below k the sample IS the population.
    assert s.estimated_states() == 13


def test_drain_slab_tie_cut_discards_boundary_h1_group():
    # occupied > drained means the slab was truncated on device: entries
    # AT the boundary h1 may be an incomplete tie group and must go.
    s = SpaceSampler(k=2)
    fp1 = np.array([1, 2, 2], dtype=np.uint64)
    fp2 = np.array([5, 6, 7], dtype=np.uint64)
    dep = np.array([1, 1, 1], dtype=np.uint64)
    ok = np.array([1, 1, 1], dtype=np.uint64)
    s.drain_slab(fp1, fp2, dep, ok, occupied=5)
    # Only h1=1 survives (h1=2 is the boundary group), and keeping fewer
    # than k flags the sample as degraded.
    assert s.fingerprints() == [(1 << 32) | 5]
    assert s.degraded
    # exact=False (revisit-prone engines): duplicates, not truncation —
    # the cut is skipped and nothing is flagged.
    s2 = SpaceSampler(k=2)
    s2.drain_slab(fp1, fp2, dep, ok, occupied=5, exact=False)
    assert len(s2.fingerprints()) == 2
    assert not s2.degraded


def test_detect_saturation_flags_boundary_lanes():
    rows = np.zeros((8, 3), dtype=np.uint64)
    rows[:, 1] = np.arange(8)
    rows[3, 1] = 255  # lane 1 tops out exactly at 2^8 - 1
    rows[:, 2] = 12
    (hit,) = detect_saturation(rows)
    assert hit == {"lane": 1, "bits": 8, "max": 255, "hits": 1}
    assert detect_saturation(rows[:, [0, 2]]) == []


# -- cross-engine determinism -------------------------------------------------


def test_sample_identical_host_vs_device_increment():
    tm = IncrementTensor(2)
    host = _host_fps(tm, k=8)
    dev = (
        TensorModelAdapter(tm)
        .checker()
        .sample(k=8)
        .spawn_tpu_bfs(**OPTS)
        .join()
    )
    assert dev._sampler.fingerprints() == host


def test_sample_identical_host_vs_device_2pc4_pipelined_and_serial():
    tm = TwoPhaseTensor(4)
    host = _host_fps(tm, k=64)
    for pipelined in (True, False):
        dev = (
            TensorModelAdapter(tm)
            .checker()
            .sample(k=64)
            .pipeline(pipelined)
            .spawn_tpu_bfs(**OPTS)
            .join()
        )
        assert dev.unique_state_count() == 1568
        assert dev._sampler.fingerprints() == host, f"pipeline={pipelined}"
        assert not dev._sampler.degraded


def test_sample_identical_host_vs_sharded_mesh(devices):
    tm = TwoPhaseTensor(4)
    host = _host_fps(tm, k=64)
    mesh = (
        TensorModelAdapter(tm)
        .checker()
        .sample(k=64)
        .spawn_sharded_bfs(devices=devices[:4], chunk_size=64)
        .join()
    )
    assert mesh.unique_state_count() == 1568
    assert mesh._sampler.fingerprints() == host
    # Device slabs drain fingerprint-only; the profile resolves every
    # sampled state via cross-shard path reconstruction.
    profile = mesh.space_profile()
    assert profile["unresolved"] == 0
    assert profile["fields"]


def test_sample_survives_kill_and_resume(tmp_path):
    tm = TwoPhaseTensor(5)
    golden = _host_fps(tm, k=32)
    ckpt = str(tmp_path / "sample.ckpt.npz")
    partial = (
        TensorModelAdapter(tm)
        .checker()
        .sample(k=32)
        .target_state_count(2_000)
        .spawn_tpu_bfs(checkpoint_path=ckpt, **OPTS)
        .join()
    )
    assert 0 < partial.unique_state_count() < 8832
    resumed = (
        TensorModelAdapter(tm)
        .checker()
        .sample(k=32)
        .spawn_tpu_bfs(resume_from=ckpt, **OPTS)
        .join()
    )
    assert resumed.unique_state_count() == 8832
    # The checkpoint carries the sampler (threshold + records): the
    # resumed run's sample equals an uninterrupted run's exactly.
    assert resumed._sampler.fingerprints() == golden


# -- sketch exactness against exhaustive enumeration --------------------------


def test_sketches_exact_when_k_covers_the_space():
    tm = IncrementTensor(2)
    adapter = TensorModelAdapter(tm)
    checker = adapter.checker().sample(k=64).spawn_bfs().join()
    sampler = checker._sampler
    # k=64 >= 13 reachable states: the sample IS the space.
    assert len(sampler.fingerprints()) == 13
    assert sampler.estimated_states() == 13

    profile = checker.space_profile()
    # Exhaustive oracle: decode every sampled state row and flatten the
    # same way the profile does; sketches must agree exactly.
    fields = profile["fields"]
    assert fields
    for name, sk in fields.items():
        assert sk["count"] == 13, name
    # Depth exemplars partition the sample: counts sum to the space.
    assert sum(d["count"] for d in profile["depths"].values()) == 13
    # Every non-init sample carries its generating action exemplar.
    n_inits = len(np.asarray(tm.init_states_array()))
    assert sum(a["count"] for a in profile["actions"].values()) == 13 - n_inits


def test_profile_exposed_via_telemetry_and_gauges():
    checker = (
        TensorModelAdapter(IncrementTensor(2))
        .checker()
        .sample(k=8)
        .spawn_bfs()
        .join()
    )
    tel = checker.telemetry()
    space = tel["space"]
    assert space["samples"] == 8
    # Flat gauge twins for Prometheus/SSE sit beside the nested doc.
    assert tel["space_samples"] == 8
    assert tel["space_sample_k"] == 8
    assert tel["space_est_states"] > 0


def test_sample_disabled_is_clean():
    checker = (
        TensorModelAdapter(IncrementTensor(2))
        .checker()
        .sample(False)
        .spawn_tpu_bfs(**OPTS)
        .join()
    )
    assert checker.space_profile() == {}
    assert "space" not in checker.telemetry()


def test_build_space_profile_counts_unresolved_rows():
    s = SpaceSampler(k=4)
    s.offer(10, depth=1)  # no state row, no resolver: stays unresolved
    profile = build_space_profile(
        TensorModelAdapter(IncrementTensor(2)), s, resolver=None
    )
    assert profile["unresolved"] == 1
