"""Checkpoint/resume of the device engine — a capability the reference
lacks (its runs are in-memory only; SURVEY.md §5 flags this as the natural
new capability of the dense table/ring layout).

The kill/resume contract: stop a run mid-exploration (here via a
state-count target, which exits a block boundary exactly like a kill
would), resume from the checkpoint in a NEW checker, and land on exactly
the same final counts as an uninterrupted run.
"""

from stateright_tpu.models import TwoPhaseTensor
from stateright_tpu.tensor import TensorModelAdapter

OPTS = dict(chunk_size=64, queue_capacity=1 << 12, table_capacity=1 << 11)


def test_kill_and_resume_reproduces_golden(tmp_path):
    ckpt = str(tmp_path / "run.ckpt.npz")

    # Phase 1: explore part of 2pc-5, then stop; the final checkpoint
    # captures the mid-exploration frontier + visited table.
    partial = (
        TensorModelAdapter(TwoPhaseTensor(5))
        .checker()
        .target_state_count(2_000)
        .spawn_tpu_bfs(checkpoint_path=ckpt, **OPTS)
        .join()
    )
    assert 0 < partial.unique_state_count() < 8832

    # Phase 2: a fresh checker resumes and finishes the space exactly.
    resumed = (
        TensorModelAdapter(TwoPhaseTensor(5))
        .checker()
        .spawn_tpu_bfs(resume_from=ckpt, **OPTS)
        .join()
    )
    assert resumed.unique_state_count() == 8832
    resumed.assert_properties()
    # Discoveries found before the kill survive the round-trip, and paths
    # reconstruct from the resumed table.
    for name in ("abort agreement", "commit agreement"):
        assert resumed.discovery(name) is not None


def test_resume_rejects_wrong_model(tmp_path):
    """A checkpoint records its model identity; resuming it under a
    different model (even one sharing state_width) must fail loudly
    instead of silently reusing the wrong table."""
    import pytest

    from stateright_tpu.models import IncrementTensor

    ckpt = str(tmp_path / "idmix.ckpt.npz")
    (
        TensorModelAdapter(TwoPhaseTensor(4))
        .checker()
        .spawn_tpu_bfs(checkpoint_path=ckpt, **OPTS)
        .join()
    )
    # IncrementTensor(1) also encodes into 3 lanes — same state_width.
    other = TensorModelAdapter(IncrementTensor(1)).checker()
    assert IncrementTensor(1).state_width == TwoPhaseTensor(4).state_width
    with pytest.raises(ValueError, match="model"):
        other.spawn_tpu_bfs(resume_from=ckpt, **OPTS).join()


def test_periodic_checkpoint_written(tmp_path):
    ckpt = str(tmp_path / "periodic.ckpt.npz")
    checker = (
        TensorModelAdapter(TwoPhaseTensor(4))
        .checker()
        .spawn_tpu_bfs(checkpoint_path=ckpt, checkpoint_every=0.0, **OPTS)
        .join()
    )
    full = checker.unique_state_count()
    # Resuming a COMPLETED run is a no-op that reports the same counts.
    resumed = (
        TensorModelAdapter(TwoPhaseTensor(4))
        .checker()
        .spawn_tpu_bfs(resume_from=ckpt, **OPTS)
        .join()
    )
    assert resumed.unique_state_count() == full
