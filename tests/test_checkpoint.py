"""Checkpoint/resume of the device engine — a capability the reference
lacks (its runs are in-memory only; SURVEY.md §5 flags this as the natural
new capability of the dense table/ring layout).

The kill/resume contract: stop a run mid-exploration (here via a
state-count target, which exits a block boundary exactly like a kill
would), resume from the checkpoint in a NEW checker, and land on exactly
the same final counts as an uninterrupted run.
"""

from stateright_tpu.models import TwoPhaseTensor
from stateright_tpu.tensor import TensorModelAdapter

OPTS = dict(chunk_size=64, queue_capacity=1 << 12, table_capacity=1 << 11)


def test_kill_and_resume_reproduces_golden(tmp_path):
    ckpt = str(tmp_path / "run.ckpt.npz")

    # Phase 1: explore part of 2pc-5, then stop; the final checkpoint
    # captures the mid-exploration frontier + visited table.
    partial = (
        TensorModelAdapter(TwoPhaseTensor(5))
        .checker()
        .target_state_count(2_000)
        .spawn_tpu_bfs(checkpoint_path=ckpt, **OPTS)
        .join()
    )
    assert 0 < partial.unique_state_count() < 8832

    # Phase 2: a fresh checker resumes and finishes the space exactly.
    resumed = (
        TensorModelAdapter(TwoPhaseTensor(5))
        .checker()
        .spawn_tpu_bfs(resume_from=ckpt, **OPTS)
        .join()
    )
    assert resumed.unique_state_count() == 8832
    resumed.assert_properties()
    # Discoveries found before the kill survive the round-trip, and paths
    # reconstruct from the resumed table.
    for name in ("abort agreement", "commit agreement"):
        assert resumed.discovery(name) is not None


def test_resume_rejects_wrong_model(tmp_path):
    """A checkpoint records its model identity; resuming it under a
    different model (even one sharing state_width) must fail loudly
    instead of silently reusing the wrong table."""
    import pytest

    from stateright_tpu.models import IncrementTensor

    ckpt = str(tmp_path / "idmix.ckpt.npz")
    (
        TensorModelAdapter(TwoPhaseTensor(4))
        .checker()
        .spawn_tpu_bfs(checkpoint_path=ckpt, **OPTS)
        .join()
    )
    # IncrementTensor(1) also encodes into 3 lanes — same state_width.
    other = TensorModelAdapter(IncrementTensor(1)).checker()
    assert IncrementTensor(1).state_width == TwoPhaseTensor(4).state_width
    with pytest.raises(ValueError, match="model"):
        other.spawn_tpu_bfs(resume_from=ckpt, **OPTS).join()


def test_periodic_checkpoint_written(tmp_path):
    ckpt = str(tmp_path / "periodic.ckpt.npz")
    checker = (
        TensorModelAdapter(TwoPhaseTensor(4))
        .checker()
        # checkpoint_every is wall-clock seconds; a tiny positive cadence
        # checkpoints at (almost) every era boundary.
        .spawn_tpu_bfs(checkpoint_path=ckpt, checkpoint_every=1e-4, **OPTS)
        .join()
    )
    full = checker.unique_state_count()
    # Resuming a COMPLETED run is a no-op that reports the same counts.
    resumed = (
        TensorModelAdapter(TwoPhaseTensor(4))
        .checker()
        .spawn_tpu_bfs(resume_from=ckpt, **OPTS)
        .join()
    )
    assert resumed.unique_state_count() == full
    tel = checker.telemetry()
    assert tel.get("checkpoint_saves", 0) >= 1
    assert tel.get("checkpoint_bytes", 0) > 0


def test_checkpoint_every_must_be_positive(tmp_path):
    """checkpoint_every is wall-clock SECONDS; non-positive values are a
    configuration error at builder time, not "checkpoint constantly"."""
    import pytest

    ckpt = str(tmp_path / "bad.ckpt.npz")
    builder = TensorModelAdapter(TwoPhaseTensor(3)).checker()
    for bad in (0, 0.0, -1.0):
        with pytest.raises(ValueError, match="wall-clock seconds"):
            builder.spawn_tpu_bfs(
                checkpoint_path=ckpt, checkpoint_every=bad, **OPTS
            )
    with pytest.raises(ValueError, match="checkpoint_path"):
        builder.spawn_tpu_bfs(checkpoint_every=1.0, **OPTS)
    with pytest.raises(ValueError, match="keep_checkpoints"):
        builder.spawn_tpu_bfs(checkpoint_path=ckpt, keep_checkpoints=0, **OPTS)


def test_corrupt_checkpoint_falls_back_to_previous_generation(tmp_path):
    """Truncating the newest checkpoint must not lose the run: the loader
    rejects it on its content digest and resumes from the previous rolling
    generation (keep_checkpoints), still landing on the exact golden."""
    import os

    ckpt = str(tmp_path / "gen.ckpt.npz")
    (
        TensorModelAdapter(TwoPhaseTensor(5))
        .checker()
        .target_state_count(2_000)
        .spawn_tpu_bfs(
            checkpoint_path=ckpt, checkpoint_every=1e-4,
            keep_checkpoints=3, **OPTS
        )
        .join()
    )
    assert os.path.exists(ckpt) and os.path.exists(ckpt + ".1")
    # Truncate the newest generation mid-file — a classic kill-mid-write.
    size = os.path.getsize(ckpt)
    with open(ckpt, "r+b") as f:
        f.truncate(size // 2)
    resumed = (
        TensorModelAdapter(TwoPhaseTensor(5))
        .checker()
        .spawn_tpu_bfs(resume_from=ckpt, **OPTS)
        .join()
    )
    assert resumed.unique_state_count() == 8832
    assert resumed.telemetry().get("checkpoint_fallbacks", 0) == 1
    assert resumed.telemetry().get("checkpoint_corrupt_rejected", 0) == 1


def test_corrupt_only_checkpoint_rejected_loudly(tmp_path):
    """With every generation corrupt, resume must fail with a clear
    CheckpointCorruptError instead of resuming from garbage."""
    import pytest

    from stateright_tpu.engines.common import CheckpointCorruptError

    ckpt = str(tmp_path / "solo.ckpt.npz")
    (
        TensorModelAdapter(TwoPhaseTensor(4))
        .checker()
        .spawn_tpu_bfs(checkpoint_path=ckpt, keep_checkpoints=1, **OPTS)
        .join()
    )
    # Flip bytes in the zip payload: digest verification must catch it.
    with open(ckpt, "r+b") as f:
        f.seek(200)
        f.write(b"\xde\xad\xbe\xef" * 8)
    with pytest.raises(CheckpointCorruptError, match="corrupt|digest"):
        (
            TensorModelAdapter(TwoPhaseTensor(4))
            .checker()
            .spawn_tpu_bfs(resume_from=ckpt, **OPTS)
            .join()
        )
