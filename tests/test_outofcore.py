"""Out-of-core state spaces (ISSUE 20): tiered frontier spill, delta
checkpoints, and forecast-triggered proactive resharding.

The contract under test, for every device engine:

* a spill stack bounded by `STPU_SPILL_HOST_BUDGET_BYTES` demotes its
  oldest blocks to npz disk segments and promotes them back newest-first
  — LIFO order preserved across tiers, so counts stay EXACT goldens
  (2pc-5: 8,832);
* checkpoints past the first save write table DELTAS (rows inserted
  since the base), folding back onto the base at load; a corrupt delta
  falls back to the previous link; a resumed run hits the golden;
* under `STPU_DEVICE_MEMORY_BYTES` the forecaster's projection triggers
  a proactive table doubling (`reshard_proactive`) at a host-owned era
  boundary, output-identical to the uncapped run;
* the one-shot memory warning re-arms after a growth/reshard.
"""

import os

import numpy as np
import pytest

from stateright_tpu.models import TwoPhaseTensor
from stateright_tpu.tensor import TensorModelAdapter

OPTS = dict(chunk_size=64, queue_capacity=1 << 12, table_capacity=1 << 11)
# Small ring + chunk: 2pc-5 crosses the high-water mark and spills.
SPILL_OPTS = dict(chunk_size=32, queue_capacity=1 << 10, table_capacity=1 << 11)


# ---------------------------------------------------------------------------
# TieredSpillStore unit tests (ops/tiering.py)
# ---------------------------------------------------------------------------


def _blk(tag, rows=8, width=4):
    return np.full((rows, width), tag, dtype=np.uint32)


def test_tiering_unbudgeted_is_plain_lifo():
    from stateright_tpu.ops.tiering import TieredSpillStore

    st = TieredSpillStore()
    for t in range(5):
        st.append(_blk(t))
    assert len(st) == 5 and st.segments() == 0
    assert st.peek_rows() == 8
    out = [int(st.pop()[0, 0]) for _ in range(5)]
    assert out == [4, 3, 2, 1, 0]
    assert not st


def test_tiering_budget_demotes_oldest_and_preserves_lifo(tmp_path):
    from stateright_tpu.ops.tiering import TieredSpillStore

    moves = []
    st = TieredSpillStore(
        host_budget_bytes=2 * _blk(0).nbytes,
        spool_dir=str(tmp_path),
        on_tier=lambda d, r, b, db: moves.append((d, r)),
    )
    for t in range(6):
        st.append(_blk(t))
    # Oldest blocks demoted to disk; newest always stays in RAM.
    assert st.segments() >= 1
    assert st.disk_bytes() > 0
    assert st.host_bytes() <= 2 * _blk(0).nbytes
    assert st.rows() == 6 * 8
    assert moves and moves[0][0] == "ram_to_disk"
    # iter_blocks walks oldest-first without consuming anything.
    tags = [int(b[0, 0]) for b in st.iter_blocks()]
    assert tags == [0, 1, 2, 3, 4, 5]
    assert len(st) == 6
    # pop returns strict LIFO across the RAM/disk boundary.
    out = [int(st.pop()[0, 0]) for _ in range(6)]
    assert out == [5, 4, 3, 2, 1, 0]
    assert any(d == "disk_to_ram" for d, _ in moves)
    assert st.disk_bytes() == 0


def test_tiering_reset_and_clear_remove_segments(tmp_path):
    from stateright_tpu.ops.tiering import TieredSpillStore

    st = TieredSpillStore(
        host_budget_bytes=_blk(0).nbytes, spool_dir=str(tmp_path)
    )
    for t in range(4):
        st.append(_blk(t))
    assert st.segments() >= 1
    st.reset([_blk(9)])
    assert len(st) == 1 and int(st.pop()[0, 0]) == 9
    for t in range(4):
        st.append(_blk(t))
    st.clear()
    assert not st and st.disk_bytes() == 0
    assert not any(f.endswith(".npz") for f in os.listdir(tmp_path))
    with pytest.raises(IndexError):
        st.peek_rows()


def test_spill_host_budget_env(monkeypatch):
    from stateright_tpu.ops.tiering import spill_host_budget_bytes

    monkeypatch.delenv("STPU_SPILL_HOST_BUDGET_BYTES", raising=False)
    assert spill_host_budget_bytes() is None
    monkeypatch.setenv("STPU_SPILL_HOST_BUDGET_BYTES", "4096")
    assert spill_host_budget_bytes() == 4096
    monkeypatch.setenv("STPU_SPILL_HOST_BUDGET_BYTES", "0")
    assert spill_host_budget_bytes() is None
    monkeypatch.setenv("STPU_SPILL_HOST_BUDGET_BYTES", "nope")
    assert spill_host_budget_bytes() is None


# ---------------------------------------------------------------------------
# Disk-tier spill parity on the engines
# ---------------------------------------------------------------------------


def test_tpu_bfs_disk_spill_golden(monkeypatch):
    """A host budget far below the spill volume forces the disk tier;
    the run must still land on the exact golden with every demoted row
    promoted back."""
    monkeypatch.setenv("STPU_SPILL_HOST_BUDGET_BYTES", str(1 << 13))
    checker = (
        TensorModelAdapter(TwoPhaseTensor(5))
        .checker()
        .spawn_tpu_bfs(**SPILL_OPTS)
    )
    checker.join()
    assert checker.unique_state_count() == 8832
    checker.assert_properties()
    tel = checker.telemetry()
    assert tel.get("spill_rows", 0) > 0
    assert tel.get("spill_tier_rows", 0) > 0
    # Every demoted row came back up.
    assert tel.get("spill_tier_refill_rows", 0) == tel["spill_tier_rows"]
    assert tel.get("spill_disk_bytes") == 0  # drained by run end


def test_tpu_bfs_kill_resume_mid_spill_with_deltas(tmp_path, monkeypatch):
    """Kill at a spilling era boundary with a delta-chain checkpoint on
    disk (base + >=1 delta), resume, land on the golden."""
    monkeypatch.setenv("STPU_SPILL_HOST_BUDGET_BYTES", str(1 << 13))
    ckpt = str(tmp_path / "oc.ckpt.npz")
    part = (
        TensorModelAdapter(TwoPhaseTensor(5))
        .checker()
        .target_state_count(4_000)
        .spawn_tpu_bfs(
            checkpoint_path=ckpt, checkpoint_every=1e-4, **SPILL_OPTS
        )
        .join()
    )
    assert 0 < part.unique_state_count() < 8832
    tel = part.telemetry()
    assert tel.get("checkpoint_saves", 0) >= 1
    assert tel.get("checkpoint_delta_saves", 0) >= 1
    assert os.path.exists(ckpt + ".d1")
    # The partial run actually checkpointed mid-spill at least once: its
    # final save carries staged spill blocks.
    resumed = (
        TensorModelAdapter(TwoPhaseTensor(5))
        .checker()
        .spawn_tpu_bfs(resume_from=ckpt, **SPILL_OPTS)
        .join()
    )
    assert resumed.unique_state_count() == 8832
    resumed.assert_properties()


def test_tpu_bfs_corrupt_delta_falls_back_to_previous_link(
    tmp_path, monkeypatch
):
    """Truncating the newest delta must fall back to the previous chain
    link (or the base) and still resume to the golden."""
    ckpt = str(tmp_path / "cd.ckpt.npz")
    part = (
        TensorModelAdapter(TwoPhaseTensor(5))
        .checker()
        .target_state_count(4_000)
        .spawn_tpu_bfs(checkpoint_path=ckpt, checkpoint_every=1e-4, **OPTS)
        .join()
    )
    tel = part.telemetry()
    assert tel.get("checkpoint_delta_saves", 0) >= 1
    from stateright_tpu.engines.common import delta_chain_paths

    chain = delta_chain_paths(ckpt)
    assert chain
    newest = chain[-1]
    with open(newest, "r+b") as f:
        f.truncate(os.path.getsize(newest) // 2)
    resumed = (
        TensorModelAdapter(TwoPhaseTensor(5))
        .checker()
        .spawn_tpu_bfs(resume_from=ckpt, **OPTS)
        .join()
    )
    assert resumed.unique_state_count() == 8832
    rtel = resumed.telemetry()
    assert rtel.get("checkpoint_corrupt_rejected", 0) >= 1
    assert rtel.get("checkpoint_fallbacks", 0) >= 1


def test_delta_chain_compacts_to_new_base(tmp_path):
    """A chain longer than DELTA_CHAIN_MAX rolls up: the next save is a
    full base and the stale chain is cleared."""
    from stateright_tpu.engines.common import (
        DELTA_CHAIN_MAX,
        delta_chain_paths,
        load_checkpoint_folded,
        save_checkpoint_tiered,
    )

    path = str(tmp_path / "chain.ckpt.npz")
    tcap = 64
    t0 = np.zeros(tcap, dtype=np.uint32)
    t1 = np.zeros(tcap, dtype=np.uint32)
    t2 = np.zeros(tcap, dtype=np.uint32)
    t3 = np.zeros(tcap, dtype=np.uint32)
    state = None
    n_saves = DELTA_CHAIN_MAX + 2
    for i in range(n_saves):
        # Insert one new row per save.
        t0[i] = i + 1
        t1[i] = 100 + i
        arrays = {
            "table0": t0.copy(), "table1": t1.copy(),
            "table2": t2.copy(), "table3": t3.copy(),
            "extra": np.asarray([i], dtype=np.int64),
        }
        state = save_checkpoint_tiered(
            path, {"tick": i}, arrays, state=state, tcap=tcap
        )
    # Saves: full, d1..dMAX, then compaction -> full again.
    assert len(delta_chain_paths(path)) == 0
    data, meta = load_checkpoint_folded(path)
    assert meta["tick"] == n_saves - 1
    np.testing.assert_array_equal(data["table0"], t0)
    np.testing.assert_array_equal(data["table1"], t1)
    assert int(data["extra"][0]) == n_saves - 1


def test_delta_fold_reconstructs_exact_table(tmp_path):
    """base + newest delta == the full state at the newest save, bit for
    bit, including non-table arrays taken from the delta only."""
    from stateright_tpu.engines.common import (
        delta_chain_paths,
        load_checkpoint_folded,
        save_checkpoint_tiered,
    )

    path = str(tmp_path / "fold.ckpt.npz")
    rng = np.random.default_rng(7)
    tcap = 4096
    lanes = [np.zeros(tcap, dtype=np.uint32) for _ in range(4)]
    occ_idx = rng.choice(tcap, size=600, replace=False)
    for i in occ_idx[:400]:
        for t, lane in enumerate(lanes):
            lane[i] = rng.integers(1, 1 << 30)
    state = save_checkpoint_tiered(
        path, {"n": 400},
        {f"table{t}": l.copy() for t, l in enumerate(lanes)},
        state=None, tcap=tcap,
    )
    for i in occ_idx[400:]:
        for t, lane in enumerate(lanes):
            lane[i] = rng.integers(1, 1 << 30)
    arrays = {f"table{t}": l.copy() for t, l in enumerate(lanes)}
    arrays["spill0"] = np.arange(12, dtype=np.uint32)
    save_checkpoint_tiered(
        path, {"n": 600}, arrays, state=state, tcap=tcap
    )
    assert len(delta_chain_paths(path)) == 1
    data, meta = load_checkpoint_folded(path)
    assert meta["n"] == 600
    for t, lane in enumerate(lanes):
        np.testing.assert_array_equal(data[f"table{t}"], lane)
    np.testing.assert_array_equal(
        data["spill0"], np.arange(12, dtype=np.uint32)
    )
    # The delta (200 inserted rows) is smaller than a FULL save of the
    # same final arrays would be.  The base itself isn't a fair yardstick:
    # npz compression deflates its zero rows to almost nothing, while the
    # delta carries only incompressible inserted values.
    full_path = str(tmp_path / "full.ckpt.npz")
    save_checkpoint_tiered(
        full_path, {"n": 600}, arrays, state=None, tcap=tcap
    )
    full_bytes = os.path.getsize(full_path)
    delta_bytes = os.path.getsize(delta_chain_paths(path)[0])
    assert delta_bytes < full_bytes


def test_tcap_change_forces_full_base(tmp_path):
    from stateright_tpu.engines.common import (
        delta_chain_paths,
        save_checkpoint_tiered,
    )

    path = str(tmp_path / "grow.ckpt.npz")
    arrays = lambda cap: {  # noqa: E731
        f"table{t}": np.zeros(cap, dtype=np.uint32) for t in range(4)
    }
    state = save_checkpoint_tiered(
        path, {}, arrays(64), state=None, tcap=64
    )
    state = save_checkpoint_tiered(
        path, {}, arrays(64), state=state, tcap=64
    )
    assert len(delta_chain_paths(path)) == 1
    # Growth doubled the table: rows moved, deltas are meaningless.
    state = save_checkpoint_tiered(
        path, {}, arrays(128), state=state, tcap=128
    )
    assert state["seq"] == 0
    assert len(delta_chain_paths(path)) == 0


# ---------------------------------------------------------------------------
# Proactive reshard parity (solo + mesh, pipelined+fused)
# ---------------------------------------------------------------------------


def test_tpu_bfs_proactive_reshard_parity(monkeypatch):
    """Capped run must proactively double the table off the forecast and
    still match the uncapped golden exactly."""
    monkeypatch.setenv("STPU_DEVICE_MEMORY_BYTES", "300000")
    checker = (
        TensorModelAdapter(TwoPhaseTensor(5))
        .checker()
        .pipeline(depth=4, fuse=4)
        .spawn_tpu_bfs(
            chunk_size=64, queue_capacity=1 << 12, table_capacity=1 << 8
        )
    )
    checker.join()
    assert checker.unique_state_count() == 8832
    checker.assert_properties()
    tel = checker.telemetry()
    assert tel.get("reshard_proactive", 0) >= 1
    assert tel.get("table_growths", 0) >= tel["reshard_proactive"]


def test_mesh_proactive_reshard_parity(monkeypatch):
    import jax

    if len(jax.devices()) < 4:
        pytest.skip("needs 4 virtual devices")
    monkeypatch.setenv("STPU_DEVICE_MEMORY_BYTES", "2000000")
    checker = (
        TensorModelAdapter(TwoPhaseTensor(5))
        .checker()
        .pipeline(depth=4, fuse=4)
        .spawn_sharded_bfs(
            devices=jax.devices()[:4],
            chunk_size=64,
            queue_capacity_per_shard=1 << 11,
            table_capacity_per_shard=1 << 8,
        )
    )
    checker.join()
    assert checker.unique_state_count() == 8832
    tel = checker.telemetry()
    assert tel.get("reshard_proactive", 0) >= 1


def test_mesh_disk_spill_golden(tmp_path, monkeypatch):
    import jax

    if len(jax.devices()) < 4:
        pytest.skip("needs 4 virtual devices")
    monkeypatch.setenv("STPU_SPILL_HOST_BUDGET_BYTES", str(1 << 13))
    checker = (
        TensorModelAdapter(TwoPhaseTensor(5))
        .checker()
        .spawn_sharded_bfs(
            devices=jax.devices()[:4],
            chunk_size=64,
            queue_capacity_per_shard=1 << 10,
            table_capacity_per_shard=1 << 10,
        )
    )
    checker.join()
    assert checker.unique_state_count() == 8832
    tel = checker.telemetry()
    if tel.get("spill_rows", 0):  # ring pressure is config-dependent
        assert tel.get("spill_tier_rows", 0) >= 0


@pytest.mark.slow
def test_mesh_paxos2_outofcore_parity(monkeypatch):
    """ISSUE 20 acceptance shape: paxos-2 on the full 8-device virtual
    mesh under a device cap + spill budget, pipelined and fused, must be
    bit-identical to the unconstrained mesh run."""
    import jax

    from stateright_tpu.models.paxos import PaxosTensorExhaustive

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    mesh_opts = dict(
        chunk_size=256,
        queue_capacity_per_shard=1 << 11,
        table_capacity_per_shard=1 << 8,
    )
    ref = (
        TensorModelAdapter(PaxosTensorExhaustive(2))
        .checker()
        .spawn_sharded_bfs(devices=jax.devices()[:8], **mesh_opts)
        .join()
    )
    assert ref.unique_state_count() == 16_668
    monkeypatch.setenv("STPU_DEVICE_MEMORY_BYTES", "1000000")
    monkeypatch.setenv("STPU_SPILL_HOST_BUDGET_BYTES", str(1 << 13))
    capped = (
        TensorModelAdapter(PaxosTensorExhaustive(2))
        .checker()
        .pipeline(depth=4, fuse=4)
        .spawn_sharded_bfs(devices=jax.devices()[:8], **mesh_opts)
        .join()
    )
    assert capped.unique_state_count() == ref.unique_state_count()
    assert capped.state_count() == ref.state_count()
    assert dict(capped._discovery_fps) == dict(ref._discovery_fps)


# ---------------------------------------------------------------------------
# Forecaster warning re-arm (satellite)
# ---------------------------------------------------------------------------


def test_memory_warning_rearms_after_growth():
    from stateright_tpu.obs.memory import MemoryRecorder

    rec = MemoryRecorder("t", device_limit_bytes=1 << 20)
    rec.ledger.register("visited_table", nbytes=900_000, kind="device")
    rec.set_geometry(rows=1 << 10, max_load=0.25, reserve_rows=64)
    rec.on_era(unique=200)
    assert rec.warning is not None  # headroom below the next doubling
    # Growth doubles the rows: the warning must re-arm...
    rec.set_geometry(rows=1 << 11, max_load=0.25, reserve_rows=64)
    assert rec.warning is None
    # ...so a second approach to the (new) wall warns again.
    rec.on_era(unique=400)
    assert rec.warning is not None
    # Same-size geometry updates do NOT re-arm.
    w = rec.warning
    rec.set_geometry(rows=1 << 11, max_load=0.25, reserve_rows=64)
    assert rec.warning == w


def test_rearm_warning_is_idempotent():
    from stateright_tpu.obs.memory import MemoryRecorder

    rec = MemoryRecorder("t", device_limit_bytes=None)
    rec.rearm_warning()  # nothing armed: no-op, no events
    assert rec.warning is None


# ---------------------------------------------------------------------------
# Auto-N fusion pick (satellite)
# ---------------------------------------------------------------------------


def test_fuse_auto_n_backs_off_on_low_gap():
    from stateright_tpu.engines.common import HostEngineBase

    class _Metrics:
        def __init__(self):
            self.gauges = {}
            self.eras = 0

        def get(self, k):
            return self.eras if k == "eras" else 0

        def set_gauge(self, k, v):
            self.gauges[k] = v

    class _Flight:
        def __init__(self, eras, gap):
            self._s = {"eras": eras, "host_gap_pct": gap}

        def summary(self):
            return dict(self._s)

    class _Host:
        _fuse_auto_n = HostEngineBase._fuse_auto_n

    h = _Host()
    h._metrics = _Metrics()
    # Amortized gap -> halve the factor (floor 2 keeps fusion engaged).
    h._flight = _Flight(eras=32, gap=0.5)
    assert h._fuse_auto_n(8) == 4
    assert h._metrics.gauges["fuse_auto_n"] == 4
    h2 = _Host()
    h2._metrics = _Metrics()
    h2._flight = _Flight(eras=32, gap=0.5)
    assert h2._fuse_auto_n(4) == 2
    # Gap still material -> keep the configured factor.
    h3 = _Host()
    h3._metrics = _Metrics()
    h3._flight = _Flight(eras=32, gap=25.0)
    assert h3._fuse_auto_n(4) == 4
    # Too little history -> keep the configured factor.
    h4 = _Host()
    h4._metrics = _Metrics()
    h4._flight = _Flight(eras=2, gap=0.5)
    assert h4._fuse_auto_n(4) == 4
    # No flight recorder -> keep the configured factor.
    h5 = _Host()
    h5._metrics = _Metrics()
    h5._flight = None
    assert h5._fuse_auto_n(4) == 4


def test_fuse_auto_n_result_is_cached_between_rechecks():
    from stateright_tpu.engines.common import (
        FUSE_AUTO_RECHECK_ERAS,
        HostEngineBase,
    )

    calls = []

    class _Metrics:
        def __init__(self):
            self.eras = 0

        def get(self, k):
            return self.eras

        def set_gauge(self, k, v):
            pass

    class _Flight:
        def summary(self):
            calls.append(1)
            return {"eras": 32, "host_gap_pct": 0.5}

    class _Host:
        _fuse_auto_n = HostEngineBase._fuse_auto_n

    h = _Host()
    h._metrics = _Metrics()
    h._flight = _Flight()
    assert h._fuse_auto_n(8) == 4
    h._metrics.eras += FUSE_AUTO_RECHECK_ERAS - 1
    assert h._fuse_auto_n(8) == 4
    assert len(calls) == 1  # cached: summary() not re-walked
    h._metrics.eras += 1
    assert h._fuse_auto_n(8) == 4
    assert len(calls) == 2
