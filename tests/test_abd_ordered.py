"""ABD over the ORDERED network: device twin vs host actor-model oracle.

Reference workload: `linearizable-register check N ordered` (bench.sh:33;
Ordered semantics network.rs:62-68, head-of-flow rule model.rs:269-275).
The device encoding carries per-flow FIFO ranks in the envelope words
(lanes.net_step_ordered), so per-flow SEQUENCES — not multisets — define
state identity, matching the host's BTreeMap<(src,dst), VecDeque> network.
"""

from examples.linearizable_register import abd_model
from stateright_tpu import TensorModelAdapter
from stateright_tpu.actor import Network
from stateright_tpu.models import AbdOrderedTensor

ORDERED_C3_GOLDEN = 46_516  # exhaustive host actor-model run (this repo)


def test_ordered_c2_device_matches_live_host_oracle():
    host = (
        abd_model(2, 2, Network.new_ordered())
        .checker()
        .spawn_bfs()
        .join()
    )
    assert host.discovery("linearizable") is None

    dev = (
        TensorModelAdapter(AbdOrderedTensor(2))
        .checker()
        .spawn_tpu_bfs(
            chunk_size=256, queue_capacity=1 << 12, table_capacity=1 << 12
        )
        .join()
    )
    assert dev.unique_state_count() == host.unique_state_count() == 620
    assert dev.discovery("linearizable") is None


def test_ordered_more_states_than_unordered():
    # The ordered network distinguishes flow ORDER, so its space is larger
    # than the multiset network's (620 vs 544 at c=2) — a quick guard that
    # the rank encoding actually changes state identity.
    from stateright_tpu.models import AbdTensor

    dev_u = (
        TensorModelAdapter(AbdTensor(2))
        .checker()
        .spawn_tpu_bfs(
            chunk_size=256, queue_capacity=1 << 12, table_capacity=1 << 12
        )
        .join()
    )
    assert dev_u.unique_state_count() == 544


def test_ordered_send_rank_field_is_masked_before_rank_insertion():
    # A handler payload that strays into the rank nibble (bits 16-19) must
    # not pre-load a bogus FIFO rank: net_step_ordered masks sends down to
    # their ORDERED_PAY_MASK payload before OR-ing in the real flow depth.
    import numpy as np

    from stateright_tpu.lanes import (
        ORDERED_PAY_MASK,
        RANK_FIELD,
        RANK_SHIFT,
        env_word,
        net_step_ordered,
    )

    u = np.uint32
    K = 3
    # One in-flight rank-0 envelope on flow (1 -> 2); two empty slots.
    head = env_word(np, 1, u(1), u(2), u(0x7))
    net = [np.array([0], dtype=np.uint32),
           np.array([0], dtype=np.uint32),
           np.array([head], dtype=np.uint32)]
    # Deliver slot 2 (the head) and send a reply on the SAME flow whose
    # payload has rank-field bits set (a buggy 20-bit payload).
    dirty_pay = u(0x3) << u(RANK_SHIFT) | u(0x5)
    send = env_word(np, 2, u(1), u(2), dirty_pay)
    out = net_step_ordered(np, net, np.array([2], dtype=np.uint32), [send])
    inserted = [int(lane[0]) for lane in out if int(lane[0]) != 0]
    assert len(inserted) == 1
    word = inserted[0]
    # The flow was emptied by the delivery, so the inserted send must sit
    # at rank 0 with only its masked 16-bit payload surviving.
    assert (word & RANK_FIELD) >> RANK_SHIFT == 0
    assert word & ORDERED_PAY_MASK == 0x5


def test_ordered_c3_device_golden():
    dev = (
        TensorModelAdapter(AbdOrderedTensor(3))
        .checker()
        .spawn_tpu_bfs(
            chunk_size=2048, queue_capacity=1 << 15, table_capacity=1 << 18
        )
        .join()
    )
    assert dev.unique_state_count() == ORDERED_C3_GOLDEN
    assert dev.discovery("linearizable") is None
