"""ABD over the ORDERED network: device twin vs host actor-model oracle.

Reference workload: `linearizable-register check N ordered` (bench.sh:33;
Ordered semantics network.rs:62-68, head-of-flow rule model.rs:269-275).
The device encoding carries per-flow FIFO ranks in the envelope words
(lanes.net_step_ordered), so per-flow SEQUENCES — not multisets — define
state identity, matching the host's BTreeMap<(src,dst), VecDeque> network.
"""

from examples.linearizable_register import abd_model
from stateright_tpu import TensorModelAdapter
from stateright_tpu.actor import Network
from stateright_tpu.models import AbdOrderedTensor

ORDERED_C3_GOLDEN = 46_516  # exhaustive host actor-model run (this repo)


def test_ordered_c2_device_matches_live_host_oracle():
    host = (
        abd_model(2, 2, Network.new_ordered())
        .checker()
        .spawn_bfs()
        .join()
    )
    assert host.discovery("linearizable") is None

    dev = (
        TensorModelAdapter(AbdOrderedTensor(2))
        .checker()
        .spawn_tpu_bfs(
            chunk_size=256, queue_capacity=1 << 12, table_capacity=1 << 12
        )
        .join()
    )
    assert dev.unique_state_count() == host.unique_state_count() == 620
    assert dev.discovery("linearizable") is None


def test_ordered_more_states_than_unordered():
    # The ordered network distinguishes flow ORDER, so its space is larger
    # than the multiset network's (620 vs 544 at c=2) — a quick guard that
    # the rank encoding actually changes state identity.
    from stateright_tpu.models import AbdTensor

    dev_u = (
        TensorModelAdapter(AbdTensor(2))
        .checker()
        .spawn_tpu_bfs(
            chunk_size=256, queue_capacity=1 << 12, table_capacity=1 << 12
        )
        .join()
    )
    assert dev_u.unique_state_count() == 544


def test_ordered_c3_device_golden():
    dev = (
        TensorModelAdapter(AbdOrderedTensor(3))
        .checker()
        .spawn_tpu_bfs(
            chunk_size=2048, queue_capacity=1 << 15, table_capacity=1 << 18
        )
        .join()
    )
    assert dev.unique_state_count() == ORDERED_C3_GOLDEN
    assert dev.discovery("linearizable") is None
