"""Scratch: interleaved-flat pair access vs two separate arrays (round 5)."""
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

u = jnp.uint32
K = 30
CAP = 1 << 22
W = 75776
iota = jnp.arange(W, dtype=u)

a1 = jnp.arange(CAP, dtype=u) * u(0x9E3779B9)
a2 = jnp.arange(CAP, dtype=u) * u(0x85EBCA6B)
il = jnp.stack([a1, a2], axis=1).reshape(-1)  # kk[2i]=a1[i], kk[2i+1]=a2[i]


def mix(x, salt):
    x = (x ^ u(salt)) * u(0x9E3779B9)
    return x ^ (x >> u(16))


def timeit(name, fn, donate=()):
    f = jax.jit(fn, donate_argnums=donate)
    np.asarray(f())
    t0 = time.perf_counter()
    s = np.asarray(f())
    dt = time.perf_counter() - t0
    print(f"{name:48s} {dt/K*1000:8.2f} ms/iter  sum={s}", flush=True)


def f_sep():
    def body(i, acc):
        idx = mix(iota + i * u(W), 3) & u(CAP - 1)
        return acc ^ a1[idx].sum(dtype=u) ^ a2[idx].sum(dtype=u)
    return lax.fori_loop(u(0), u(K), body, u(0))
timeit("gathers: 2 separate 16MB arrays", f_sep)


def f_il():
    def body(i, acc):
        idx = mix(iota + i * u(W), 3) & u(CAP - 1)
        return acc ^ il[2 * idx].sum(dtype=u) ^ il[2 * idx + 1].sum(dtype=u)
    return lax.fori_loop(u(0), u(K), body, u(0))
timeit("gathers: interleaved flat 32MB", f_il)


def f_scat_sep():
    def run():
        def body(i, st):
            b1, b2, acc = st
            idx = mix(iota + i * u(W), 7) & u(CAP - 1)
            b1 = b1.at[idx].set(iota, mode="drop", unique_indices=False)
            b2 = b2.at[idx].set(iota, mode="drop", unique_indices=False)
            return b1, b2, acc ^ b1[0] ^ b2[0]
        out = lax.fori_loop(u(0), u(K), body,
                            (jnp.zeros(CAP, u), jnp.zeros(CAP, u), u(0)))
        return out[2]
    return run()
timeit("scatters: 2 separate 16MB arrays", f_scat_sep)


def f_scat_il():
    def run():
        def body(i, st):
            b, acc = st
            idx = mix(iota + i * u(W), 7) & u(CAP - 1)
            b = b.at[2 * idx].set(iota, mode="drop", unique_indices=False)
            b = b.at[2 * idx + 1].set(iota, mode="drop", unique_indices=False)
            return b, acc ^ b[0]
        out = lax.fori_loop(u(0), u(K), body, (jnp.zeros(2 * CAP, u), u(0)))
        return out[1]
    return run()
timeit("scatters: interleaved flat 32MB", f_scat_il)
