"""Core model-checking abstractions: Model, Property, Expectation.

Reference parity: the `Model` trait (src/lib.rs:158-257), `Property`
(src/lib.rs:264-317), and `Expectation` (src/lib.rs:319-338).

A `Model` describes a nondeterministic transition system:
  - `init_states()` returns the initial states,
  - `actions(state, actions)` appends the enabled actions,
  - `next_state(state, action)` returns the successor (or None for no-ops),
  - `properties()` declares always/sometimes/eventually predicates,
  - `within_boundary(state)` prunes the explored space.

States may be any Python values with canonical fingerprints (see
`stateright_tpu.fingerprint`); they do not need to be Python-hashable.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import Any, Callable, List, Optional, Tuple

from . import fingerprint as _fp_mod

fingerprint = _fp_mod.fingerprint


class Expectation(enum.Enum):
    """Whether a property must hold always, eventually, or sometimes.

    Reference: src/lib.rs:319-328.
    """

    ALWAYS = "always"
    EVENTUALLY = "eventually"
    SOMETIMES = "sometimes"

    @property
    def discovery_is_failure(self) -> bool:
        """Reference: src/lib.rs:330-338."""
        return self in (Expectation.ALWAYS, Expectation.EVENTUALLY)


@dataclass
class Property:
    """A named predicate over (model, state). Reference: src/lib.rs:264-317."""

    expectation: Expectation
    name: str
    condition: Callable[["Model", Any], bool]

    @staticmethod
    def always(name: str, condition: Callable[["Model", Any], bool]) -> "Property":
        """A safety property; the checker looks for a counterexample."""
        return Property(Expectation.ALWAYS, name, condition)

    @staticmethod
    def eventually(name: str, condition: Callable[["Model", Any], bool]) -> "Property":
        """A liveness property; the checker looks for a counterexample path
        from an initial state to a terminal state that never satisfies it.

        Like the reference (src/lib.rs:286-290), this only works correctly on
        acyclic paths: a path ending in a cycle is not seen as terminating, a
        documented false-negative.
        """
        return Property(Expectation.EVENTUALLY, name, condition)

    @staticmethod
    def sometimes(name: str, condition: Callable[["Model", Any], bool]) -> "Property":
        """A reachability property; the checker looks for an example."""
        return Property(Expectation.SOMETIMES, name, condition)


class Model:
    """The primary abstraction: a nondeterministic transition system.

    Reference: the `Model` trait, src/lib.rs:158-257. Subclasses implement
    `init_states`, `actions`, and `next_state`; optionally `properties`,
    `within_boundary`, formatting hooks, and `fingerprint_state`.
    """

    # -- required interface -------------------------------------------------

    def init_states(self) -> List[Any]:
        raise NotImplementedError

    def actions(self, state: Any, actions: List[Any]) -> None:
        """Append the actions enabled in `state` to `actions`."""
        raise NotImplementedError

    def next_state(self, last_state: Any, action: Any) -> Optional[Any]:
        """Successor of `last_state` under `action`; None means no-op."""
        raise NotImplementedError

    # -- optional interface -------------------------------------------------

    def properties(self) -> List[Property]:
        return []

    def within_boundary(self, state: Any) -> bool:
        return True

    def format_action(self, action: Any) -> str:
        return repr(action)

    def format_step(self, last_state: Any, action: Any) -> Optional[str]:
        next_state = self.next_state(last_state, action)
        return None if next_state is None else repr(next_state)

    def as_svg(self, path) -> Optional[str]:
        """SVG rendering of a Path (used by the Explorer); None by default."""
        return None

    def fingerprint_state(self, state: Any) -> int:
        """Stable nonzero 64-bit fingerprint of `state`.

        Engines call this instead of hashing directly so that models backed
        by tensor encodings can guarantee host/device hash agreement.
        """
        return fingerprint(state)

    # -- derived helpers ----------------------------------------------------

    def next_steps(self, last_state: Any) -> List[Tuple[Any, Any]]:
        """(action, next_state) pairs that follow `last_state`.

        Reference: src/lib.rs:199-213.
        """
        actions: List[Any] = []
        self.actions(last_state, actions)
        steps = []
        for action in actions:
            nxt = self.next_state(last_state, action)
            if nxt is not None:
                steps.append((action, nxt))
        return steps

    def next_states(self, last_state: Any) -> List[Any]:
        actions: List[Any] = []
        self.actions(last_state, actions)
        out = []
        for action in actions:
            nxt = self.next_state(last_state, action)
            if nxt is not None:
                out.append(nxt)
        return out

    def property(self, name: str) -> Property:
        """Look up a property by name; raises if absent (src/lib.rs:232-242)."""
        for p in self.properties():
            if p.name == name:
                return p
        available = [p.name for p in self.properties()]
        raise KeyError(f"Unknown property. requested={name}, available={available}")

    def checker(self) -> "CheckerBuilder":
        from .checker import CheckerBuilder

        return CheckerBuilder(self)
