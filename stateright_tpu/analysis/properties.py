"""STR3xx — property well-formedness.

Properties are the point of a checking run; a malformed one wastes the
whole search. Duplicate names shadow each other in the discovery map, a
predicate that raises kills the engine mid-run (or worse, at depth
10^7), and an `eventually` property over a space with no reachable
terminal states can never produce a counterexample (the checker's
documented acyclic-path semantics) — the run silently proves nothing.

Codes:
  STR301  duplicate property names
  STR302  a predicate raises on a sampled state
  STR303  a predicate is constant over the entire sample (info; a
          `sometimes` that is never satisfied, or an `always` that is
          false on EVERY sampled state, usually means a typo)
  STR304  `eventually` property, but no terminal state is reachable
          (warning when the sample exhausted the space: counterexamples
          are impossible by construction)
  STR305  the model declares no properties at all (warning)
  STR306  an action slot is never enabled on any sampled state (warning
          when the sample exhausted the space: the action is DEAD — a
          mis-modeled guard or unreachable transition; the run verifies
          a smaller system than the one modeled). Static twin of the
          runtime dead-action detection in obs/coverage.py; only models
          with a statically known action universe (TensorModels) are
          checked.
"""

from __future__ import annotations

from typing import List

from ..core import Expectation, Model, Property
from .diagnostics import AnalysisReport, Severity
from .sampling import Sample


def _loc(model: Model, prop: Property) -> str:
    return f"{type(model).__name__}.properties[{prop.name!r}]"


def run(model: Model, sample: Sample, report: AnalysisReport) -> None:
    report.families_run.append("properties")
    _check_dead_actions(model, sample, report)
    try:
        props = list(model.properties())
    except BaseException as e:  # noqa: BLE001
        report.add(
            "STR302",
            Severity.ERROR,
            f"properties() raised {type(e).__name__}: {e}",
            f"{type(model).__name__}.properties",
            "property declaration must not depend on run state",
        )
        return

    if not props:
        report.add(
            "STR305",
            Severity.WARNING,
            "the model declares no properties; the checker would only "
            "count states",
            f"{type(model).__name__}.properties",
            "declare at least one always/sometimes/eventually property",
        )
        return

    seen = {}
    for p in props:
        if p.name in seen:
            report.add(
                "STR301",
                Severity.ERROR,
                f"duplicate property name {p.name!r} "
                f"({seen[p.name].expectation.value} and "
                f"{p.expectation.value}); discoveries key on the name, so "
                "one silently shadows the other",
                _loc(model, p),
                "give every property a unique name",
            )
        else:
            seen[p.name] = p

    has_eventually = any(
        p.expectation == Expectation.EVENTUALLY for p in props
    )
    if has_eventually and not sample.terminal_states:
        sev = Severity.WARNING if sample.exhausted else Severity.INFO
        report.add(
            "STR304",
            sev,
            "eventually-properties only produce counterexamples at "
            "TERMINAL states, and "
            + (
                "the reachable space has none (it is exhausted and every "
                "state has successors): counterexamples are impossible by "
                "construction"
                if sample.exhausted
                else f"none were reachable within the {sample.info().states}"
                "-state sample"
            ),
            f"{type(model).__name__}.properties",
            "add a within_boundary / target_max_depth so paths terminate, "
            "or model explicit completion states",
        )

    for p in seen.values():
        _check_predicate(model, p, sample, report)


def _check_dead_actions(
    model: Model, sample: Sample, report: AnalysisReport
) -> None:
    """STR306: action slots never enabled across the sampled space.

    Only models with a statically known action universe (TensorModels,
    whose actions are the `max_actions` index slots) can be checked —
    a rich model's action space is not enumerable without running it.
    """
    from ..tensor import TensorModelAdapter

    if not isinstance(model, TensorModelAdapter) or not sample.states:
        return
    tm = model.tm
    n_actions = tm.max_actions
    fired: set = set()
    for state in sample.states:
        try:
            acts: List[int] = []
            model.actions(state, acts)
        except BaseException:  # noqa: BLE001 - reported by STR1xx rules
            return
        fired.update(acts)
        if len(fired) == n_actions:
            return
    dead = [a for a in range(n_actions) if a not in fired]
    if not dead:
        return
    labels = ", ".join(tm.format_action(a) for a in dead)
    if sample.exhausted:
        report.add(
            "STR306",
            Severity.WARNING,
            f"action slot(s) {labels} are never enabled on ANY reachable "
            "state (the sample exhausted the space): dead transitions or "
            "mis-modeled guards — the checker verifies a smaller system "
            "than the one modeled",
            f"{type(tm).__name__}.step_lanes",
            "fix the guard, or remove the action slot if the transition "
            "is intentionally impossible",
            dead_actions=[int(a) for a in dead],
        )
    else:
        report.add(
            "STR306",
            Severity.INFO,
            f"action slot(s) {labels} never enabled within the "
            f"{len(sample.states)}-state sample (may still fire deeper); "
            "run-time coverage (Checker.coverage) settles it",
            f"{type(tm).__name__}.step_lanes",
            "",
            dead_actions=[int(a) for a in dead],
        )


def _check_predicate(
    model: Model, p: Property, sample: Sample, report: AnalysisReport
) -> None:
    values: List[bool] = []
    for state in sample.states:
        try:
            values.append(bool(p.condition(model, state)))
        except BaseException as e:  # noqa: BLE001
            report.add(
                "STR302",
                Severity.ERROR,
                f"predicate raised {type(e).__name__} on sampled state "
                f"{state!r}: {e}",
                _loc(model, p),
                "predicates must be total over reachable states "
                "(initial states included)",
            )
            return
    if len(values) < 2:
        return
    if all(values) and p.expectation == Expectation.SOMETIMES:
        report.add(
            "STR303",
            Severity.INFO,
            f"sometimes-property is satisfied by EVERY one of the "
            f"{len(values)} sampled states; it can only ever produce a "
            "trivial example",
            _loc(model, p),
            "a reachability property should start unsatisfied",
        )
    elif not any(values):
        if p.expectation == Expectation.ALWAYS:
            report.add(
                "STR303",
                Severity.WARNING,
                f"always-property is FALSE on every one of the "
                f"{len(values)} sampled states, including the initial "
                "states; the first processed state is a counterexample",
                _loc(model, p),
                "the predicate is likely inverted or over a wrong field",
            )
        elif sample.exhausted and p.expectation == Expectation.SOMETIMES:
            report.add(
                "STR303",
                Severity.WARNING,
                "sometimes-property is unsatisfiable: the reachable space "
                "is exhausted and no state satisfies it",
                _loc(model, p),
                "the checker will report a missing example; fix the "
                "predicate or the model",
            )
        elif p.expectation == Expectation.SOMETIMES:
            report.add(
                "STR303",
                Severity.INFO,
                f"sometimes-property unsatisfied within the "
                f"{len(values)}-state sample (may still be reachable "
                "deeper)",
                _loc(model, p),
                "",
            )
