"""Command-line speclint: ``python -m stateright_tpu.analysis MODEL``.

MODEL is either a bundled-model shorthand (``NAME`` or ``NAME:ARGS`` with
comma-separated int args, e.g. ``2pc:5``, ``increment:2``, ``abd:2``) or
a dotted constructor path ``package.module:Factory:ARGS`` for user
models. Exit status is the CI contract: 0 = no error-severity findings,
1 = errors found, 2 = usage problems.

Examples::

    python -m stateright_tpu.analysis 2pc:5
    python -m stateright_tpu.analysis paxos:2 --samples 512 --json
    python -m stateright_tpu.analysis mypkg.mymodel:MyTensor:3 --strict
"""

from __future__ import annotations

import argparse
import importlib
import json
import sys
from typing import Any, Callable, Dict

from . import ALL_FAMILIES, analyze

# Bundled-model shorthands (lint targets double as living documentation
# of the registry; the dogfood test asserts all of them lint clean).
BUNDLED: Dict[str, Callable[..., Any]] = {}


def _register() -> None:
    from ..models import (
        AbdOrderedTensor,
        AbdTensor,
        Increment,
        IncrementLock,
        IncrementLockTensor,
        IncrementTensor,
        PaxosTensor,
        SingleCopyTensor,
        TwoPhaseSys,
        TwoPhaseTensor,
    )

    BUNDLED.update(
        {
            "2pc": TwoPhaseTensor,
            "2pc-host": TwoPhaseSys,
            "abd": AbdTensor,
            "abd-ordered": AbdOrderedTensor,
            "increment": IncrementTensor,
            "increment-host": Increment,
            "increment-lock": IncrementLockTensor,
            "increment-lock-host": IncrementLock,
            "paxos": PaxosTensor,
            "single-copy": SingleCopyTensor,
        }
    )


def resolve_model(spec: str):
    """``NAME[:ARGS]`` (bundled) or ``pkg.module:Factory[:ARGS]``."""
    _register()
    parts = spec.split(":")
    if parts[0] in BUNDLED:
        factory = BUNDLED[parts[0]]
        args = [int(a) for a in parts[1].split(",")] if len(parts) > 1 and parts[1] else []
        return factory(*args)
    if "." in parts[0] and len(parts) >= 2:
        mod = importlib.import_module(parts[0])
        factory = getattr(mod, parts[1])
        args = [int(a) for a in parts[2].split(",")] if len(parts) > 2 and parts[2] else []
        return factory(*args)
    print(
        f"unknown model {spec!r}; bundled: {', '.join(sorted(BUNDLED))} "
        "(append :ARGS, e.g. 2pc:5), or pkg.module:Factory:ARGS",
        file=sys.stderr,
    )
    raise SystemExit(2)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m stateright_tpu.analysis",
        description="pre-flight static analysis of a model "
        "(determinism, device compatibility, properties, symmetry)",
    )
    parser.add_argument("model", help="bundled shorthand (2pc:5) or pkg.module:Factory:ARGS")
    parser.add_argument(
        "--samples", type=int, default=256,
        help="breadth-first state-sample budget (default 256)",
    )
    parser.add_argument(
        "--families", default=",".join(ALL_FAMILIES),
        help=f"comma-separated rule families (default: all of {','.join(ALL_FAMILIES)})",
    )
    parser.add_argument(
        "--json", action="store_true", help="emit the report as one JSON object"
    )
    args = parser.parse_args(argv)

    model = resolve_model(args.model)
    report = analyze(
        model,
        samples=args.samples,
        families=[f.strip() for f in args.families.split(",") if f.strip()],
    )
    if args.json:
        print(json.dumps(report.to_dict(), indent=2))
    else:
        print(report.format())
    return 0 if report.ok else 1


if __name__ == "__main__":
    sys.exit(main())
