"""Command-line speclint: ``python -m stateright_tpu.analysis MODEL``.

MODEL is either a bundled-model shorthand (``NAME`` or ``NAME:ARGS`` with
comma-separated int args, e.g. ``2pc:5``, ``increment:2``, ``abd:2``) or
a dotted constructor path ``package.module:Factory:ARGS`` for user
models. Exit status is the CI contract: 0 = no error-severity findings,
1 = errors found, 2 = usage problems.

Examples::

    python -m stateright_tpu.analysis 2pc:5
    python -m stateright_tpu.analysis paxos:2 --samples 512 --json
    python -m stateright_tpu.analysis 2pc:7 --program
    python -m stateright_tpu.analysis 2pc:7 --program --write-budgets
    python -m stateright_tpu.analysis mypkg.mymodel:MyTensor:3 --strict
"""

from __future__ import annotations

import argparse
import importlib
import json
import sys
from typing import Any, Callable, Dict

from . import ALL_FAMILIES, analyze

# Bundled-model shorthands (lint targets double as living documentation
# of the registry; the dogfood test asserts all of them lint clean).
BUNDLED: Dict[str, Callable[..., Any]] = {}


def _lww_register(actor_count: int = 2):
    from examples.lww_register import lww_model

    return lww_model(actor_count)


def _linearizable_register(client_count: int = 2, server_count: int = 2):
    from examples.linearizable_register import abd_model

    return abd_model(client_count, server_count)


def _write_once_register(client_count: int = 2):
    from ..actor.write_once_register import wo_register_model

    return wo_register_model(client_count)


def _register() -> None:
    from ..models import (
        AbdOrderedTensor,
        AbdTensor,
        BinaryClock,
        Increment,
        IncrementLock,
        IncrementLockTensor,
        IncrementTensor,
        LinearEquation,
        PaxosTensor,
        SingleCopyTensor,
        TwoPhaseSys,
        TwoPhaseTensor,
    )

    BUNDLED.update(
        {
            "2pc": TwoPhaseTensor,
            "2pc-host": TwoPhaseSys,
            "abd": AbdTensor,
            "abd-ordered": AbdOrderedTensor,
            "binary-clock": BinaryClock,
            "increment": IncrementTensor,
            "increment-host": Increment,
            "increment-lock": IncrementLockTensor,
            "increment-lock-host": IncrementLock,
            "linear-equation": LinearEquation,
            "linearizable-register": _linearizable_register,
            "lww-register": _lww_register,
            "paxos": PaxosTensor,
            "single-copy": SingleCopyTensor,
            "write-once-register": _write_once_register,
        }
    )


def resolve_model(spec: str):
    """``NAME[:ARGS]`` (bundled) or ``pkg.module:Factory[:ARGS]``."""
    _register()
    parts = spec.split(":")
    if parts[0] in BUNDLED:
        factory = BUNDLED[parts[0]]
        args = [int(a) for a in parts[1].split(",")] if len(parts) > 1 and parts[1] else []
        return factory(*args)
    if "." in parts[0] and len(parts) >= 2:
        # A mistyped module or factory is a usage problem (exit 2), not a
        # lint verdict — keep the CI contract's exit codes meaningful.
        try:
            mod = importlib.import_module(parts[0])
            factory = getattr(mod, parts[1])
        except (ImportError, AttributeError) as exc:
            print(f"cannot resolve {spec!r}: {exc}", file=sys.stderr)
            raise SystemExit(2) from exc
        args = [int(a) for a in parts[2].split(",")] if len(parts) > 2 and parts[2] else []
        return factory(*args)
    print(
        f"unknown model {spec!r}; bundled: {', '.join(sorted(BUNDLED))} "
        "(append :ARGS, e.g. 2pc:5), or pkg.module:Factory:ARGS",
        file=sys.stderr,
    )
    raise SystemExit(2)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m stateright_tpu.analysis",
        description="pre-flight static analysis of a model "
        "(determinism, device compatibility, properties, symmetry)",
    )
    parser.add_argument("model", help="bundled shorthand (2pc:5) or pkg.module:Factory:ARGS")
    parser.add_argument(
        "--samples", type=int, default=256,
        help="breadth-first state-sample budget (default 256)",
    )
    parser.add_argument(
        "--families", default=",".join(ALL_FAMILIES),
        help=f"comma-separated rule families (default: all of {','.join(ALL_FAMILIES)})",
    )
    parser.add_argument(
        "--json", action="store_true", help="emit the report as one JSON object"
    )
    parser.add_argument(
        "--program", action="store_true",
        help="deep STR6xx program lint: lower EVERY device program "
        "(seed/insert/rehash/mux/sharded, not just the era loop) and run "
        "the compiled STR606 cost model (seconds per model)",
    )
    parser.add_argument(
        "--budgets",
        help="op-budget file for the STR604 gate "
        "(default: analysis/op_budgets.json)",
    )
    parser.add_argument(
        "--write-budgets", action="store_true",
        help="measure the era programs and COMMIT their op counts as the "
        "new STR604 budgets (use after an intentional hot-loop change)",
    )
    args = parser.parse_args(argv)

    model = resolve_model(args.model)
    if args.write_budgets:
        from ..tensor import TensorModel, TensorModelAdapter
        from .program import write_budgets

        tm = model.tm if isinstance(model, TensorModelAdapter) else model
        if not isinstance(tm, TensorModel):
            print(
                f"--write-budgets wants a TensorModel; {args.model!r} is "
                f"{type(model).__name__}",
                file=sys.stderr,
            )
            raise SystemExit(2)
        written = write_budgets(tm, label=args.model, path=args.budgets)
        for key, ent in sorted(written.items()):
            print(f"budget {key.split('|')[0]}: {ent['ops']} ops")
        return 0
    report = analyze(
        model,
        samples=args.samples,
        families=[f.strip() for f in args.families.split(",") if f.strip()],
        program_cost=args.program,
        budgets_path=args.budgets,
    )
    if args.json:
        print(json.dumps(report.to_dict(), indent=2))
    else:
        print(report.format())
    return 0 if report.ok else 1


if __name__ == "__main__":
    sys.exit(main())
