"""STR6xx proglint: static analysis of the COMPILED device programs.

The other speclint families look at the model; this one looks at what the
model compiles INTO. Each device engine's jitted programs (the era loop,
the fused seed+era loop, the visited-set insert/rehash kernels, the
multiplexed lane program, the sharded shard_map block) are traced and
lowered to jaxpr/StableHLO from `jax.ShapeDtypeStruct` abstract arguments
— no device buffer is allocated and nothing executes — then the lowered
artifacts are scanned for the regression classes that runtime profilers
(stageprof, the flight recorder) can only report AFTER a run paid for
them:

  STR600  a program failed to trace/lower — the family's findings for it
          are incomplete (the device family usually has the root cause)
  STR601  host<->device transfer or callback primitives in a device hot
          loop (pure_callback / io_callback / device_put / infeed / ...)
          — each one is a ~100ms tunnel round-trip per era on this
          platform
  STR602  broken/missed buffer donation: the program requests donation
          via `donate_argnums_safe` but the lowered StableHLO aliases
          fewer inputs to outputs than were donated (the regression class
          that forced donation off in PR 14)
  STR603  dtype drift: 64-bit or floating-point values inside the
          uint32/bool/int32 device programs, or `step_lanes` outputs that
          leave uint32 (the static twin of runtime STR207)
  STR604  per-era primitive op-count accounting against the committed
          `analysis/op_budgets.json`: growth over budget is an ERROR
          (the dispatch-gap push lives and dies on hot-loop op count,
          ROADMAP 1), shrink below budget is a WARNING to ratchet the
          budget down
  STR605  compile-signature instability: two fresh instances of the same
          model must produce equal `model_signature()` and intern to one
          canonical instance — otherwise every serve request retraces
          and the ExecutableCache never hits
  STR606  static cost model: XLA `cost_analysis()` flops + bytes-accessed
          per era step yield a memory-bound predicted roofline st/s,
          surfaced against the flight recorder's measured rate as an
          attribution ratio (`telemetry()["program"]`, bench JSON, the
          WriteReporter recap)

Tiers: the default lint pass (``Checker.lint()`` / ``strict()`` / serve
admission) traces the SOLO ERA LOOP only (~1s, cached per
`model_signature`). The deep pass (``--program`` on the CLI, bench)
additionally lowers the seed loop, visited-set insert/rehash, the mux
lane program, and the sharded block, and compiles the era loop for the
STR606 cost model (seconds — kept off the admission path).

The code -> meaning -> fix catalog lives in `analysis/README.md`; budget
regeneration is documented there too (`--write-budgets`).
"""

from __future__ import annotations

import copy
import json
import os
import threading
from collections import Counter
from typing import Any, Dict, Optional, Tuple

from ..tensor import TensorModel
from .diagnostics import AnalysisReport, Severity

__all__ = [
    "BUDGETS_PATH",
    "HBM_GBPS_DEFAULT",
    "TRANSFER_PRIMITIVES",
    "cached_summary",
    "check_donation_text",
    "program_summary",
    "run",
    "write_budgets",
]

#: Committed op-count budgets (STR604). One JSON document, versioned,
#: keyed "engine|model_signature". Regenerate with
#: ``python -m stateright_tpu.analysis MODEL --program --write-budgets``
#: after an INTENTIONAL hot-loop change (see analysis/README.md).
BUDGETS_PATH = os.path.join(os.path.dirname(__file__), "op_budgets.json")

#: Primitives that move data across the host<->device boundary or call
#: back into Python from inside a compiled program. NONE of these belong
#: in a device hot loop: on the remote-attached platform each costs a
#: full ~100ms tunnel round-trip per era (BASELINE.md), and callbacks
#: additionally serialize on the GIL. `convert_element_type` is NOT here
#: — u32<->i32/bool converts are free lane reinterpretations.
TRANSFER_PRIMITIVES = frozenset(
    {
        "pure_callback",
        "io_callback",
        "debug_callback",
        "callback",
        "device_put",
        "infeed",
        "outfeed",
        "copy_to_host",
        "transfer_to_host",
    }
)

#: 64-bit dtypes never belong in the uint32 lane programs (STR603):
#: TPU has no i64/f64 ALU — XLA widens to pairs (2x every op) or rejects.
WIDE_DTYPES = frozenset({"int64", "uint64", "float64"})

#: Roofline HBM bandwidth (GB/s) for the STR606 predicted rate; v4-lite
#: class default, overridable per deployment. bench.py single-sources its
#: roofline constant from here.
HBM_GBPS_DEFAULT = 819.0
HBM_GBPS_ENV = "STATERIGHT_TPU_HBM_GBPS"

# model-signature -> {"tier", "budgets_path", "diags", "summary"}.
# Replaying cached diagnostics keeps repeat lints (strict mode re-spawns,
# serve admission, the dogfood suite) at dict-lookup cost instead of a
# fresh ~1s trace per fresh model INSTANCE (the jit caches key by id()).
_SUMMARY_CACHE: Dict[Tuple[str, str], Dict[str, Any]] = {}
_CACHE_LOCK = threading.Lock()
_CACHE_CAP = 64


def _loc(tm: TensorModel, member: str) -> str:
    return f"{type(tm).__name__}.{member}"


def hbm_gbps() -> float:
    try:
        return float(os.environ.get(HBM_GBPS_ENV, HBM_GBPS_DEFAULT))
    except ValueError:
        return HBM_GBPS_DEFAULT


# -- jaxpr walking -----------------------------------------------------------


def _walk_jaxpr(jaxpr, prims: Counter, dtypes: set) -> None:
    """Count every primitive in `jaxpr` INCLUDING nested call/control-flow
    bodies (pjit, while, cond, scan carry their sub-jaxprs in eqn params),
    and collect every output aval dtype seen along the way. The outer
    pjit/while/cond eqns count too — each is a real dispatch boundary."""
    for eqn in jaxpr.eqns:
        prims[eqn.primitive.name] += 1
        for v in eqn.outvars:
            aval = getattr(v, "aval", None)
            if aval is not None and hasattr(aval, "dtype"):
                dtypes.add(str(aval.dtype))
        for p in eqn.params.values():
            _walk_param(p, prims, dtypes)


def _walk_param(p: Any, prims: Counter, dtypes: set) -> None:
    inner = getattr(p, "jaxpr", None)  # ClosedJaxpr
    if inner is not None and hasattr(inner, "eqns"):
        _walk_jaxpr(inner, prims, dtypes)
    elif hasattr(p, "eqns"):  # bare Jaxpr
        _walk_jaxpr(p, prims, dtypes)
    elif isinstance(p, (list, tuple)):
        for x in p:
            _walk_param(x, prims, dtypes)


def count_ops(closed_jaxpr) -> Tuple[Counter, set]:
    """(primitive -> count, dtype-name set) over the whole nested jaxpr."""
    prims: Counter = Counter()
    dtypes: set = set()
    _walk_jaxpr(closed_jaxpr.jaxpr, prims, dtypes)
    return prims, dtypes


def _trace(fn, args):
    """(closed_jaxpr, traced|None) for a jitted `fn` over abstract args.

    `jit(f).trace` (jax >= 0.4.34) produces the jaxpr AND a handle that
    lowers without re-tracing; older jax falls back to `make_jaxpr` and
    pays a second trace if lowering is needed."""
    import jax

    if hasattr(fn, "trace"):
        traced = fn.trace(*args)
        return traced.jaxpr, traced
    return jax.make_jaxpr(fn)(*args), None


# -- program lowering --------------------------------------------------------


#: Representative multi-era fusion factor linted and budgeted alongside
#: the classic single-era programs: a fused program is a DIFFERENT
#: compiled artifact (inner while_loop + fusion tail), so it gets its own
#: budget row — the engine key grows a ``+f{N}`` suffix (e.g.
#: ``tpu_bfs+f4``) and two fusion factors never share a ratchet.
FUSED_LINT_FACTOR = 4

GEOMETRY_KEYS = ("chunk", "qcap", "tcap", "cov", "sample_k", "fuse")


def _engine_key(base: str, fuse: int) -> str:
    return base if int(fuse) <= 1 else f"{base}+f{int(fuse)}"


def _era_geometry(tm: TensorModel) -> Dict[str, Any]:
    from ..engines.compiled import era_geometry

    return era_geometry(tm)


def _sharded_geometry(tm: TensorModel) -> Dict[str, Any]:
    """Mirror `ShardedBfsChecker.__init__`'s default shape resolution."""
    import jax

    from ..obs.sample import DEFAULT_SAMPLE_K

    n_shards = len(jax.devices())
    qcap = 1 << 16
    tcap = 1 << 18
    A = max(1, tm.max_actions)
    chunk = min(1024, qcap // (2 * A))
    quota = max(64, (chunk * A) // (4 * n_shards))
    return {
        "chunk": chunk,
        "qcap": qcap,
        "tcap": tcap,
        "n_shards": n_shards,
        "quota": quota,
        "cov": True,
        "sample_k": DEFAULT_SAMPLE_K,
        "fuse": 1,
    }


def _lower_era(tm: TensorModel, g: Dict[str, Any]):
    from ..engines.tpu_bfs import _build_loop, loop_abstract_args

    props = tm.tensor_properties()
    fuse = int(g.get("fuse", 1))
    loop = _build_loop(
        tm, props, g["chunk"], g["qcap"], False, g["cov"],
        sample_k=g["sample_k"], fuse=fuse,
    ).serial
    args = loop_abstract_args(
        tm, props, g["chunk"], g["qcap"], g["tcap"], g["cov"], g["sample_k"],
        fuse=fuse,
    )
    return loop, args


def _lower_seed_loop(tm: TensorModel, g: Dict[str, Any]):
    from ..engines.tpu_bfs import _build_seed_loop, seed_loop_abstract_args

    props = tm.tensor_properties()
    fuse = int(g.get("fuse", 1))
    fn = _build_seed_loop(
        tm, props, g["chunk"], g["qcap"], g["tcap"], False, g["cov"],
        sample_k=g["sample_k"], fuse=fuse,
    )
    args = seed_loop_abstract_args(
        tm, props, g["chunk"], g["qcap"], g["tcap"], g["cov"],
        g["sample_k"], g["n_init"], fuse=fuse,
    )
    return fn, args


def _lower_visited(tm: TensorModel, g: Dict[str, Any], which: str):
    import jax
    import jax.numpy as jnp

    from ..engines.tpu_bfs import _vcap
    from ..ops import visited_set as vs

    sds = jax.ShapeDtypeStruct
    u32 = jnp.uint32
    tcap = g["tcap"]
    if which == "insert":
        vcap = _vcap(max(1, tm.max_actions), g["chunk"])
        fn = jax.jit(
            lambda table, h1, h2, p1, p2, act: vs.insert(
                table, h1, h2, p1, p2, act
            )
        )
        lane = sds((vcap,), u32)
        args = (
            vs.abstract_table(tcap),
            lane, lane, lane, lane,
            sds((vcap,), jnp.bool_),
        )
        return fn, args
    fn = jax.jit(lambda old, new: vs.rehash(old, new))
    return fn, (vs.abstract_table(tcap), vs.abstract_table(2 * tcap))


def _lower_mux(tm: TensorModel):
    import jax
    import jax.numpy as jnp

    from ..engines.multiplex import _build_lane_program, _shape_options
    from ..engines.tpu_bfs import params_len

    props = tm.tensor_properties()
    lanes, icap = 32, 64
    chunk, qcap, tcap, icap = _shape_options(tm, 256, 1 << 13, 1 << 16, icap)
    fn = _build_lane_program(tm, props, lanes, chunk, qcap, tcap, icap, True)
    S, A, P = tm.state_width, tm.max_actions, len(props)
    plen = params_len(A, P, True, 0)  # raw loop: no sampling tail
    sds = jax.ShapeDtypeStruct
    u32 = jnp.uint32
    N, W = lanes, S + 2
    args = (
        sds((N, W, icap), u32),
        sds((N,), u32),
        sds((N, icap), u32),
        sds((N, icap), u32),
        sds((N, plen), u32),
        sds((N, P), u32),
        sds((N, P), u32),
    )
    return fn, args


def _lower_sharded(tm: TensorModel, g: Dict[str, Any]):
    import jax
    import numpy as np
    from jax.sharding import Mesh

    from ..parallel.mesh import _build_block, block_abstract_args

    props = tm.tensor_properties()
    mesh = Mesh(np.array(jax.devices()), ("shards",))
    fuse = int(g.get("fuse", 1))
    fn = _build_block(
        tm, props, g["chunk"], g["qcap"], g["n_shards"], g["quota"], mesh,
        "shards", cov=g["cov"], sample_k=g["sample_k"], fuse=fuse,
    ).serial
    args = block_abstract_args(
        tm, props, g["qcap"], g["tcap"], g["n_shards"], g["cov"],
        g["sample_k"], fuse=fuse,
    )
    return fn, args


# -- detectors ---------------------------------------------------------------


def _check_transfers(
    tm: TensorModel, program: str, prims: Counter, report: AnalysisReport
) -> None:
    found = {p: n for p, n in prims.items() if p in TRANSFER_PRIMITIVES}
    if not found:
        return
    listing = ", ".join(f"{p} x{n}" for p, n in sorted(found.items()))
    report.add(
        "STR601",
        Severity.ERROR,
        f"host<->device transfer/callback primitives inside the {program} "
        f"program: {listing} — each is a full tunnel round-trip per era "
        "on the remote-attached platform",
        _loc(tm, "step_lanes"),
        "compute device-side; move host logic outside the jitted loop "
        "(or into the era epilogue's packed params tail)",
        program=program,
        primitives=found,
    )


def check_donation_text(
    tm: TensorModel,
    program: str,
    lowered_text: str,
    expected_donated: int,
    report: AnalysisReport,
) -> None:
    """STR602 over a lowered StableHLO module: when `expected_donated`
    input buffers were requested for donation (`donate_argnums_safe`
    resolved non-empty), the lowering must carry at least that many
    input->output aliasing attributes; fewer means XLA dropped donations
    (shape/layout mismatch after a refactor) and the run silently doubles
    its working set. Factored over the raw text so tests can drive it
    against hand-built programs."""
    if expected_donated <= 0:
        report.add(
            "STR602",
            Severity.INFO,
            f"donation disabled for the {program} program on this backend "
            "(donate_argnums_safe resolved empty — expected on CPU, where "
            "persistent-cache executables corrupt donated buffers)",
            _loc(tm, "step_lanes"),
            program=program,
        )
        return
    aliased = lowered_text.count("tf.aliasing_output") + lowered_text.count(
        "jax.buffer_donor"
    )
    if aliased < expected_donated:
        report.add(
            "STR602",
            Severity.ERROR,
            f"{program} requests donation of {expected_donated} input "
            f"buffer(s) but the lowered program aliases only {aliased} to "
            "outputs — XLA dropped the rest (shape/layout drift between a "
            "donated input and every output), doubling device residency",
            _loc(tm, "step_lanes"),
            "keep donated operands shape- and dtype-identical to the "
            "outputs they hand their buffers to (PR 14's regression class)",
            program=program,
            expected=expected_donated,
            aliased=aliased,
        )


def _check_dtypes(
    tm: TensorModel, program: str, dtypes: set, report: AnalysisReport
) -> None:
    wide = sorted(d for d in dtypes if d in WIDE_DTYPES)
    if wide:
        report.add(
            "STR603",
            Severity.ERROR,
            f"64-bit values ({', '.join(wide)}) inside the {program} "
            "program; TPUs have no 64-bit ALU — XLA widens every op to "
            "pairs or rejects the program outright",
            _loc(tm, "step_lanes"),
            "keep lane math in uint32 (split wide fields across lanes)",
            program=program,
            dtypes=wide,
        )
    floats = sorted(
        d for d in dtypes if d.startswith(("float", "bfloat")) and d not in WIDE_DTYPES
    )
    if floats:
        report.add(
            "STR603",
            Severity.WARNING,
            f"floating-point values ({', '.join(floats)}) inside the "
            f"{program} program; the lane programs are integer-only — a "
            "float usually means an accidental true-division or mean()",
            _loc(tm, "step_lanes"),
            "use // and integer reductions in step_lanes",
            program=program,
            dtypes=floats,
        )


def _check_lane_dtypes(tm: TensorModel, report: AnalysisReport) -> None:
    """STR603 on `step_lanes` itself via `jax.eval_shape` — catches a
    non-uint32 lane (e.g. an int64 constant silently demoted to int32)
    from shapes alone, without executing the model."""
    import jax
    import jax.numpy as jnp
    import jax.tree_util as jtu

    lanes = tuple(
        jax.ShapeDtypeStruct((8,), jnp.uint32) for _ in range(tm.state_width)
    )
    try:
        out = jax.eval_shape(lambda ls: tm.step_lanes(jnp, ls), lanes)
    except Exception:
        return  # not traceable at all: STR201's finding, not ours
    bad = sorted(
        {
            str(leaf.dtype)
            for leaf in jtu.tree_leaves(out)
            if hasattr(leaf, "dtype")
            and str(leaf.dtype) not in ("uint32", "bool")
        }
    )
    if bad:
        report.add(
            "STR603",
            Severity.ERROR,
            f"step_lanes outputs leave uint32 under abstract evaluation "
            f"({', '.join(bad)}); the queue/table lanes are uint32 — the "
            "store truncates or the trace widens every downstream op",
            _loc(tm, "step_lanes"),
            "cast successor lanes back with .astype(xp.uint32) after "
            "arithmetic that promotes",
            dtypes=bad,
        )


def _check_signature_stability(tm: TensorModel, report: AnalysisReport) -> str:
    """STR605: the model's compile signature must be a pure function of
    its configuration. Three probes: repeated calls on one instance
    (catches RNG/time in `config_digest`), a deepcopied twin (catches
    `id()`-based digests — the classic), and the intern pool returning
    one canonical instance for both."""
    from ..engines.compiled import intern_model, model_signature

    sig1 = model_signature(tm)
    sig2 = model_signature(tm)
    if sig1 != sig2:
        report.add(
            "STR605",
            Severity.ERROR,
            "model_signature() differs across two calls on the SAME "
            "instance — config_digest() is reading a clock or RNG; every "
            "serve request will retrace and the ExecutableCache never hits",
            _loc(tm, "config_digest"),
            "derive config_digest purely from constructor parameters",
        )
        return sig1
    try:
        twin = copy.deepcopy(tm)
    except Exception:
        report.add(
            "STR605",
            Severity.INFO,
            "model is not deepcopy-able; cross-instance signature "
            "stability could not be probed",
            _loc(tm, "config_digest"),
        )
        return sig1
    sig_twin = model_signature(twin)
    if sig_twin != sig1:
        report.add(
            "STR605",
            Severity.ERROR,
            "two instances with identical configuration produce different "
            "model_signature() values — config_digest() depends on id() "
            "or other instance identity; every fresh instance recompiles "
            f"({sig1!r} vs {sig_twin!r})",
            _loc(tm, "config_digest"),
            "hash constructor parameters, never object identity",
        )
        return sig1
    canon, _ = intern_model(tm)
    canon_twin, _ = intern_model(twin)
    if canon_twin is not canon:
        report.add(
            "STR605",
            Severity.ERROR,
            "equal-signature instances intern to DIFFERENT canonical "
            "instances — the intern pool is broken for this model and "
            "the id()-keyed jit caches will never hit across requests",
            _loc(tm, "config_digest"),
            "report this as an intern_model bug with the model attached",
        )
    return sig1


# -- op budgets (STR604) -----------------------------------------------------


def _load_budgets(path: str) -> Dict[str, Any]:
    try:
        with open(path, "r", encoding="utf-8") as fh:
            doc = json.load(fh)
    except (OSError, ValueError):
        return {}
    return doc if isinstance(doc, dict) else {}


def _check_budget(
    tm: TensorModel,
    engine: str,
    signature: str,
    ops: int,
    geometry: Dict[str, Any],
    budgets: Dict[str, Any],
    report: AnalysisReport,
) -> None:
    import jax

    entries = budgets.get("entries", {})
    entry = entries.get(f"{engine}|{signature}")
    loc = _loc(tm, "step_lanes")
    if entry is None:
        report.add(
            "STR604",
            Severity.INFO,
            f"no committed op budget for the {engine} era program of this "
            f"model ({ops} ops measured); the hot-loop gate is not armed",
            loc,
            "commit one with `python -m stateright_tpu.analysis MODEL "
            "--program --write-budgets`",
            engine=engine,
            ops=ops,
        )
        return
    if entry.get("geometry") != geometry:
        report.add(
            "STR604",
            Severity.INFO,
            f"op budget for {engine} was committed at a different engine "
            "geometry; gate skipped (op counts are only comparable at "
            "equal shapes)",
            loc,
            "regenerate with --write-budgets on this host",
            engine=engine,
            committed=entry.get("geometry"),
            current=geometry,
        )
        return
    if entry.get("jax") != jax.__version__:
        report.add(
            "STR604",
            Severity.INFO,
            f"op budget for {engine} was committed under jax "
            f"{entry.get('jax')}; running {jax.__version__} — gate "
            "skipped (lowering differs across versions)",
            loc,
            "regenerate with --write-budgets under the CI jax version",
            engine=engine,
        )
        return
    budget = int(entry.get("ops", 0))
    if ops > budget:
        report.add(
            "STR604",
            Severity.ERROR,
            f"{engine} era program grew to {ops} primitives, over the "
            f"committed budget of {budget} (+{ops - budget}) — the "
            "dispatch-gap push (ROADMAP 1) forbids silent hot-loop growth",
            loc,
            "shrink the loop back, or (for an intentional change) "
            "regenerate analysis/op_budgets.json with --write-budgets and "
            "justify the growth in the PR",
            engine=engine,
            ops=ops,
            budget=budget,
        )
    elif ops < budget:
        report.add(
            "STR604",
            Severity.WARNING,
            f"{engine} era program shrank to {ops} primitives, under the "
            f"committed budget of {budget} (-{budget - ops}); ratchet the "
            "budget down so the win cannot silently regress",
            loc,
            "run --write-budgets and commit the smaller budget",
            engine=engine,
            ops=ops,
            budget=budget,
        )


# -- the family entry --------------------------------------------------------


def _trace_failed(
    tm: TensorModel, program: str, exc: BaseException, report: AnalysisReport
) -> None:
    report.add(
        "STR600",
        Severity.WARNING,
        f"the {program} program failed to trace/lower "
        f"({type(exc).__name__}: {exc}); STR6xx findings for it are "
        "incomplete",
        _loc(tm, "step_lanes"),
        "the device family (STR2xx) usually has the root cause",
        program=program,
    )


def _prog_summary(prims: Counter, dtypes: set) -> Dict[str, Any]:
    return {
        "ops": int(sum(prims.values())),
        "distinct": len(prims),
        "top": [
            {"primitive": p, "count": n} for p, n in prims.most_common(5)
        ],
        "dtypes": sorted(dtypes),
    }


def _analyze_programs(
    tm: TensorModel,
    report: AnalysisReport,
    *,
    cost: bool,
    budgets_path: str,
) -> Dict[str, Any]:
    """Trace, scan, and budget-gate the device programs; returns the
    summary dict that `cached_summary` later serves to telemetry/bench."""
    import jax

    from ..compat import donate_argnums_safe
    from ..engines.compiled import model_signature

    sig = model_signature(tm)
    g = _era_geometry(tm)
    budgets = _load_budgets(budgets_path)
    summary: Dict[str, Any] = {
        "signature": sig,
        "backend": jax.default_backend(),
        "geometry": {k: g[k] for k in GEOMETRY_KEYS},
        "programs": {},
    }

    # The era loop: the one program every run's wall clock is made of.
    # The SERIAL program variant fully donates its operands — table (3
    # lanes) + queue (S+2 lanes) + rec_fp1/rec_fp2 + the params vector
    # (the readback-tail donation: serial dispatches always feed a fresh
    # upload or a consumed buffer back in).
    donated_leaves = 0
    if donate_argnums_safe(0, 1):
        donated_leaves = 3 + (tm.state_width + 2) + 2 + 1
    era_traced = None
    try:
        loop, args = _lower_era(tm, g)
        closed, era_traced = _trace(loop, args)
        prims, dtypes = count_ops(closed)
        summary["programs"]["era_loop"] = _prog_summary(prims, dtypes)
        _check_transfers(tm, "era_loop", prims, report)
        _check_dtypes(tm, "era_loop", dtypes, report)
        _check_budget(
            tm, _engine_key("tpu_bfs", g["fuse"]), sig,
            int(sum(prims.values())), summary["geometry"], budgets, report,
        )
        # Lowering to StableHLO text is the expensive half of this pass;
        # pay it only when donation is actually expected (the detector
        # has attrs to count) or the deep tier needs the compile anyway.
        lowered = None
        if donated_leaves > 0 or cost:
            lowered = (
                era_traced.lower() if era_traced is not None
                else loop.lower(*args)
            )
        if donated_leaves > 0:
            check_donation_text(
                tm, "era_loop", lowered.as_text(), donated_leaves, report
            )
        else:
            # expected <= 0 short-circuits to the backend-disabled info
            # without scanning any text.
            check_donation_text(tm, "era_loop", "", donated_leaves, report)
    except Exception as exc:  # noqa: BLE001 — lint must not crash the lint
        _trace_failed(tm, "era_loop", exc, report)
        lowered = None

    if cost:
        deep = {
            "seed_loop": lambda: _lower_seed_loop(tm, g),
            "visited_insert": lambda: _lower_visited(tm, g, "insert"),
            "visited_rehash": lambda: _lower_visited(tm, g, "rehash"),
            "mux_expand": lambda: _lower_mux(tm),
        }
        for name, build in deep.items():
            try:
                fn, fargs = build()
                closed, _ = _trace(fn, fargs)
                prims, dtypes = count_ops(closed)
                summary["programs"][name] = _prog_summary(prims, dtypes)
                _check_transfers(tm, name, prims, report)
                _check_dtypes(tm, name, dtypes, report)
            except Exception as exc:  # noqa: BLE001
                _trace_failed(tm, name, exc, report)
        # The FUSED era loop (mega-dispatch, engines/tpu_bfs.py): a
        # different compiled artifact with its own budget row keyed
        # `tpu_bfs+f{N}`.
        gf = dict(g, fuse=FUSED_LINT_FACTOR)
        try:
            loop, args = _lower_era(tm, gf)
            closed, _ = _trace(loop, args)
            prims, dtypes = count_ops(closed)
            summary["programs"]["era_loop_fused"] = _prog_summary(
                prims, dtypes
            )
            _check_transfers(tm, "era_loop_fused", prims, report)
            _check_dtypes(tm, "era_loop_fused", dtypes, report)
            _check_budget(
                tm, _engine_key("tpu_bfs", gf["fuse"]), sig,
                int(sum(prims.values())),
                {k: gf[k] for k in GEOMETRY_KEYS}, budgets, report,
            )
        except Exception as exc:  # noqa: BLE001
            _trace_failed(tm, "era_loop_fused", exc, report)
        # The sharded block, with its own geometry and budget line. Its
        # serial variant donates table + queue + params (rec_fps stay
        # live for the host discovery reads).
        sharded_donated = (
            3 + (tm.state_width + 2) + 1 if donated_leaves > 0 else 0
        )
        sg = _sharded_geometry(tm)
        for prog_name, geo in (
            ("sharded_era", sg),
            ("sharded_era_fused", dict(sg, fuse=FUSED_LINT_FACTOR)),
        ):
            try:
                fn, fargs = _lower_sharded(tm, geo)
                closed, straced = _trace(fn, fargs)
                prims, dtypes = count_ops(closed)
                summary["programs"][prog_name] = _prog_summary(prims, dtypes)
                if prog_name == "sharded_era":
                    summary["sharded_geometry"] = dict(geo)
                _check_transfers(tm, prog_name, prims, report)
                _check_dtypes(tm, prog_name, dtypes, report)
                _check_budget(
                    tm, _engine_key("sharded", geo["fuse"]), sig,
                    int(sum(prims.values())), dict(geo), budgets, report,
                )
                if sharded_donated > 0:
                    slow = (
                        straced.lower() if straced is not None
                        else fn.lower(*fargs)
                    )
                    check_donation_text(
                        tm, prog_name, slow.as_text(), sharded_donated,
                        report,
                    )
                else:
                    check_donation_text(
                        tm, prog_name, "", sharded_donated, report
                    )
            except Exception as exc:  # noqa: BLE001
                _trace_failed(tm, prog_name, exc, report)

        if lowered is not None:
            _cost_model(tm, g, lowered, summary, report)
    return summary


def _cost_model(
    tm: TensorModel,
    g: Dict[str, Any],
    lowered,
    summary: Dict[str, Any],
    report: AnalysisReport,
) -> None:
    """STR606: compile the era loop and turn XLA's static cost analysis
    into a memory-bound roofline prediction. `cost_analysis` charges the
    while-loop body ONCE, so flops/bytes are per era STEP; one step pops
    `chunk` frontier rows, giving predicted st/s = chunk / step_secs with
    step_secs = bytes_accessed / HBM bandwidth (the survey's roofline —
    these programs are memory-bound, gather/scatter over HBM tables)."""
    try:
        compiled = lowered.compile()
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
    except Exception as exc:  # noqa: BLE001
        report.add(
            "STR606",
            Severity.INFO,
            f"XLA cost analysis unavailable ({type(exc).__name__}: {exc}); "
            "no predicted roofline for this run",
            _loc(tm, "step_lanes"),
        )
        return
    flops = float(ca.get("flops", 0.0) or 0.0)
    bytes_accessed = float(ca.get("bytes accessed", 0.0) or 0.0)
    gbps = hbm_gbps()
    cost: Dict[str, Any] = {
        "flops_per_step": flops,
        "bytes_per_step": bytes_accessed,
        "hbm_gbps": gbps,
    }
    if bytes_accessed > 0:
        step_secs = bytes_accessed / (gbps * 1e9)
        cost["predicted_step_secs"] = step_secs
        cost["predicted_states_per_sec"] = g["chunk"] / step_secs
    else:
        report.add(
            "STR606",
            Severity.INFO,
            "cost analysis reports zero bytes accessed; predicted "
            "roofline omitted",
            _loc(tm, "step_lanes"),
        )
    summary["cost"] = cost


def run(
    tm: TensorModel,
    report: AnalysisReport,
    *,
    cost: bool = False,
    budgets_path: Optional[str] = None,
) -> None:
    """Run the STR6xx program family over `tm` into `report`.

    ``cost=False`` (the default lint/strict/serve tier) probes signature
    stability, step_lanes dtypes, and the solo era loop. ``cost=True``
    (CLI ``--program``, bench) adds the remaining device programs, the
    sharded budget gate, and the STR606 compile + cost model."""
    report.families_run.append("program")
    budgets_path = budgets_path or BUDGETS_PATH

    sig = _check_signature_stability(tm, report)
    _check_lane_dtypes(tm, report)

    key = (sig, budgets_path)
    with _CACHE_LOCK:
        cached = _SUMMARY_CACHE.get(key)
    if cached is not None and (cached["tier"] >= (2 if cost else 1)):
        for code, sev, msg, loc, sugg, details in cached["diags"]:
            report.add(code, sev, msg, loc, sugg, **details)
        return

    before = len(report.diagnostics)
    summary = _analyze_programs(
        tm, report, cost=cost, budgets_path=budgets_path
    )
    diags = [
        (d.code, d.severity, d.message, d.location, d.suggestion, d.details)
        for d in report.diagnostics[before:]
    ]
    with _CACHE_LOCK:
        while len(_SUMMARY_CACHE) >= _CACHE_CAP:
            _SUMMARY_CACHE.pop(next(iter(_SUMMARY_CACHE)))
        _SUMMARY_CACHE[key] = {
            "tier": 2 if cost else 1,
            "diags": diags,
            "summary": summary,
        }


def program_summary(
    tm: TensorModel, *, cost: bool = True,
    budgets_path: Optional[str] = None,
) -> Dict[str, Any]:
    """The program-lint summary for `tm` (ops per program, geometry, and
    — with ``cost=True`` — the STR606 flops/bytes/predicted roofline),
    computing and caching it if absent. bench.py's static section."""
    report = AnalysisReport(type(tm).__name__)
    run(tm, report, cost=cost, budgets_path=budgets_path)
    from ..engines.compiled import model_signature

    key = (model_signature(tm), budgets_path or BUDGETS_PATH)
    with _CACHE_LOCK:
        cached = _SUMMARY_CACHE.get(key)
    return dict(cached["summary"]) if cached else {}


def cached_summary(signature: str) -> Optional[Dict[str, Any]]:
    """The cached program summary for a model signature, if any pass of
    the family has produced one this process — telemetry()'s cheap hook
    (a dict lookup; NEVER traces or compiles)."""
    best = None
    with _CACHE_LOCK:
        for (sig, _path), ent in _SUMMARY_CACHE.items():
            # Several entries can share a signature (one per budgets
            # path); prefer the deepest tier — only it carries the
            # STR606 cost fields.
            if sig == signature and (best is None or ent["tier"] > best["tier"]):
                best = ent
    return dict(best["summary"]) if best else None


def write_budgets(
    tm: TensorModel, label: str = "", path: Optional[str] = None
) -> Dict[str, Any]:
    """Measure the era programs and commit their op counts as the new
    budgets (STR604's ratchet). Returns the entries written."""
    import jax

    from ..engines.compiled import model_signature

    path = path or BUDGETS_PATH
    sig = model_signature(tm)
    doc = _load_budgets(path)
    doc.setdefault("version", 1)
    entries = doc.setdefault("entries", {})

    g = _era_geometry(tm)
    sg = _sharded_geometry(tm)
    written = {}
    # One row per (engine, fusion factor): the fused programs are
    # distinct compiled artifacts, so each carries its own ratchet.
    for fuse in (1, FUSED_LINT_FACTOR):
        gf = dict(g, fuse=fuse)
        loop, args = _lower_era(tm, gf)
        closed, _ = _trace(loop, args)
        prims, _dt = count_ops(closed)
        written[f"{_engine_key('tpu_bfs', fuse)}|{sig}"] = {
            "model": label,
            "ops": int(sum(prims.values())),
            "geometry": {k: gf[k] for k in GEOMETRY_KEYS},
            "jax": jax.__version__,
        }

        sgf = dict(sg, fuse=fuse)
        fn, fargs = _lower_sharded(tm, sgf)
        closed, _ = _trace(fn, fargs)
        prims, _dt = count_ops(closed)
        written[f"{_engine_key('sharded', fuse)}|{sig}"] = {
            "model": label,
            "ops": int(sum(prims.values())),
            "geometry": dict(sgf),
            "jax": jax.__version__,
        }

    entries.update(written)
    doc["entries"] = dict(sorted(entries.items()))
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=2, sort_keys=True)
        fh.write("\n")
    return written
