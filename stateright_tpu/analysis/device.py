"""STR2xx — device (jit/encoding) compatibility of TensorModels.

A `TensorModel` that breaks these rules fails LATE — inside a jitted
era loop (often under shard_map), where the XLA error names a lowered
primitive and nothing of the user's code — or worse, silently: lane
values past the uint32 packing truncate inside the fingerprint stream
and distinct states merge. These rules trace and execute `step_lanes`
OUTSIDE the engines, on a small batch, where failures are attributable.

Codes:
  STR201  step_lanes / within_boundary_lanes is not jit-traceable
  STR202  step_lanes output structure/shape/dtype is wrong or unstable
  STR203  init_states_array is malformed (shape/dtype/value range)
  STR204  decode_state raises on reachable rows
  STR205  numpy and jax evaluations of step_lanes disagree (host oracle
          and device engine would explore different systems)
  STR206  within_boundary_lanes output is not a bool[B]
  STR207  step_lanes output dtype drifts off uint32 (promotion), or lane
          values overflow the uint32 fingerprint packing
  STR208  default-geometry device footprint exceeds this host's device
          memory (obs/memory.py capacity planner) — the run would OOM
          mid-era; the finding names a fitting alternative engine
  STR209  a state lane's sampled maximum sits exactly at a packing
          boundary (2^b - 1 for b in 8/16/24/32) — the field has likely
          saturated its encoding and larger values would silently wrap
          or clamp, merging distinct states. Shares its detector with
          the runtime space profile (obs/sample.py detect_saturation),
          so the static pre-flight and the live run flag the same
          condition
"""

from __future__ import annotations

from typing import Any, List

import numpy as np

from ..tensor import TensorModel
from .diagnostics import AnalysisReport, Severity

_U32_MAX = 0xFFFFFFFF


def _loc(tm: TensorModel, member: str) -> str:
    return f"{type(tm).__name__}.{member}"


def run(tm: TensorModel, rows: np.ndarray, report: AnalysisReport) -> None:
    """Run the device rules over `rows` ([B, S] sampled states; row 0..n
    include the init states)."""
    report.families_run.append("device")
    S = getattr(tm, "state_width", None)
    A = getattr(tm, "max_actions", None)
    if not isinstance(S, int) or not isinstance(A, int) or S <= 0 or A <= 0:
        report.add(
            "STR203",
            Severity.ERROR,
            f"state_width/max_actions must be positive ints "
            f"(got {S!r}/{A!r})",
            _loc(tm, "state_width"),
            "declare both as class or instance attributes",
        )
        return

    if not _check_init_array(tm, report, S):
        return
    _check_footprint(tm, report)
    if rows.size == 0:
        return
    lanes = tuple(np.ascontiguousarray(rows[:, i]) for i in range(S))

    np_out = _check_numpy_step(tm, lanes, report, S, A)
    jax_ok = _check_traceability(tm, rows.shape[0], report, S, A)
    if np_out is not None and jax_ok:
        _check_host_device_agreement(tm, lanes, np_out, report)
    _check_boundary(tm, lanes, report)
    _check_decode(tm, rows, report)
    _check_saturation(tm, rows, report)


def _check_saturation(
    tm: TensorModel, rows: np.ndarray, report: AnalysisReport
) -> None:
    """STR209: sampled lane maxima sitting exactly at a packing boundary
    (ONE shared implementation with the runtime detector — obs/sample.py
    detect_saturation — so lint and live profile agree by construction)."""
    from ..obs.sample import detect_saturation

    for ent in detect_saturation(rows.astype(np.uint64)):
        report.add(
            "STR209",
            Severity.WARNING,
            f"state lane {ent['lane']} saturates its {ent['bits']}-bit "
            f"packing: {ent['hits']} of {rows.shape[0]} sampled states "
            f"hold the boundary value {ent['max']} (= 2^{ent['bits']}-1); "
            "larger values would wrap or clamp and distinct states would "
            "merge",
            _loc(tm, "step_lanes"),
            "widen the field across lanes or verify the domain really "
            f"tops out below 2^{ent['bits']}",
        )


def _check_init_array(tm: TensorModel, report: AnalysisReport, S: int) -> bool:
    try:
        arr = np.asarray(tm.init_states_array())
    except BaseException as e:  # noqa: BLE001
        report.add(
            "STR203",
            Severity.ERROR,
            f"init_states_array raised {type(e).__name__}: {e}",
            _loc(tm, "init_states_array"),
            "return a [N, state_width] uint32 array",
        )
        return False
    if arr.ndim != 2 or arr.shape[1] != S:
        report.add(
            "STR203",
            Severity.ERROR,
            f"init_states_array has shape {arr.shape}; expected "
            f"[N, state_width={S}]",
            _loc(tm, "init_states_array"),
            "return a 2-D row matrix, one row per initial state",
        )
        return False
    if arr.shape[0] == 0:
        report.add(
            "STR203",
            Severity.WARNING,
            "init_states_array is empty; the checker will explore nothing",
            _loc(tm, "init_states_array"),
            "provide at least one initial state",
        )
        return False
    if not np.issubdtype(arr.dtype, np.integer):
        report.add(
            "STR203",
            Severity.ERROR,
            f"init_states_array dtype is {arr.dtype}; lane packing and "
            "the fingerprint word stream require integers",
            _loc(tm, "init_states_array"),
            "encode state fields into uint32 lanes",
        )
        return False
    lo = int(arr.min())
    hi = int(arr.max())
    if lo < 0 or hi > _U32_MAX:
        report.add(
            "STR207",
            Severity.ERROR,
            f"init_states_array values span [{lo}, {hi}], outside the "
            "uint32 lane packing; the cast truncates silently and distinct "
            "states would share fingerprints",
            _loc(tm, "init_states_array"),
            "split wide fields across multiple lanes or shrink the domain",
        )
        return False
    return True


def _check_footprint(tm: TensorModel, report: AnalysisReport) -> None:
    """STR208: the default-geometry solo-engine footprint (obs/memory's
    capacity planner) exceeds this host's device memory — the run would
    OOM mid-era instead of failing here, attributably. Warning severity
    because geometry is overridable at spawn time; skipped entirely when
    no device limit is discoverable (CPU test hosts)."""
    from ..obs.memory import device_memory_bytes, plan, recommend_engine

    limit = device_memory_bytes()
    if limit is None:
        return
    try:
        p = plan(tm, engine="tpu_bfs", device_limit_bytes=limit)
    except Exception:
        return  # planning is advisory; never fail the lint on its bugs
    if p["fits"]:
        return
    alt = recommend_engine(tm, limit, exclude=("tpu_bfs",))
    rec = (
        f"spawn with the {alt!r} engine, or shrink table/queue capacity"
        if alt is not None
        else "shrink table/queue capacity or shard across more devices"
    )
    report.add(
        "STR208",
        Severity.WARNING,
        f"default-geometry tpu_bfs footprint is {p['total_bytes']} bytes, "
        f"over this host's device memory ({limit} bytes); the run would "
        "OOM mid-era",
        _loc(tm, "state_width"),
        rec,
    )


def _check_numpy_step(tm, lanes, report: AnalysisReport, S: int, A: int):
    try:
        succs, masks = tm.step_lanes(np, lanes)
    except BaseException as e:  # noqa: BLE001
        report.add(
            "STR202",
            Severity.ERROR,
            f"step_lanes raised under numpy on sampled rows: "
            f"{type(e).__name__}: {e}",
            _loc(tm, "step_lanes"),
            "step_lanes must be a pure array program valid for xp=numpy",
        )
        return None
    B = lanes[0].shape[0]
    if len(succs) != A or len(masks) != A:
        report.add(
            "STR202",
            Severity.ERROR,
            f"step_lanes returned {len(succs)} successor slots and "
            f"{len(masks)} masks; expected max_actions={A} of each",
            _loc(tm, "step_lanes"),
            "emit one (successor lanes, validity mask) pair per static "
            "action slot",
        )
        return None
    dtype_reported = False
    overflow_reported = False
    for a in range(A):
        slot = succs[a]
        if len(slot) != S:
            report.add(
                "STR202",
                Severity.ERROR,
                f"action slot {a} has {len(slot)} lanes; expected "
                f"state_width={S}",
                _loc(tm, "step_lanes"),
                "every successor must carry all state lanes",
            )
            return None
        mask = np.asarray(masks[a])
        if mask.shape != (B,) or mask.dtype != np.bool_:
            report.add(
                "STR202",
                Severity.ERROR,
                f"action slot {a} validity mask has shape {mask.shape} "
                f"dtype {mask.dtype}; expected bool[{B}]",
                _loc(tm, "step_lanes"),
                "masks must be elementwise boolean over the batch",
            )
            return None
        for s in range(S):
            lane = np.asarray(slot[s])
            if lane.shape != (B,):
                report.add(
                    "STR202",
                    Severity.ERROR,
                    f"action {a} lane {s} has shape {lane.shape}; expected "
                    f"[{B}] (batch-shape-stable)",
                    _loc(tm, "step_lanes"),
                    "lane programs must stay elementwise over the batch "
                    "axis",
                )
                return None
            if lane.dtype != np.uint32 and not dtype_reported:
                vals = lane[mask] if mask.any() else lane[:0]
                overflow = vals.size and (
                    (vals.min() < 0) or (vals.max() > _U32_MAX)
                )
                report.add(
                    "STR207",
                    Severity.ERROR if overflow else Severity.WARNING,
                    f"action {a} lane {s} has dtype {lane.dtype} under "
                    "numpy (promotion off uint32)"
                    + (
                        "; VALID successor values overflow the uint32 "
                        "packing — fingerprints would silently truncate"
                        if overflow
                        else "; values still fit but the promotion usually "
                        "signals an unwrapped Python-int constant"
                    ),
                    _loc(tm, "step_lanes"),
                    "wrap constants as xp.uint32(...) so arithmetic stays "
                    "in-lane",
                )
                dtype_reported = True
                overflow_reported = overflow
            elif lane.dtype == np.uint32 and not overflow_reported:
                pass  # uint32 cannot overflow the packing by construction
    return succs, masks


def _check_traceability(tm, B: int, report: AnalysisReport, S: int, A: int) -> bool:
    import jax
    import jax.numpy as jnp

    spec = tuple(
        jax.ShapeDtypeStruct((B,), jnp.uint32) for _ in range(S)
    )
    try:
        out = jax.eval_shape(lambda l: tm.step_lanes(jnp, l), spec)
    except BaseException as e:  # noqa: BLE001
        report.add(
            "STR201",
            Severity.ERROR,
            f"step_lanes fails to trace under jax.jit: "
            f"{type(e).__name__}: {str(e).splitlines()[0] if str(e) else e}",
            _loc(tm, "step_lanes"),
            "remove data-dependent Python control flow (if/while on lane "
            "values); express branches as xp.where masks",
        )
        return False
    succs, masks = out
    for a in range(A):
        for s in range(S):
            sd = succs[a][s]
            if tuple(sd.shape) != (B,) or sd.dtype != jnp.uint32:
                report.add(
                    "STR202",
                    Severity.ERROR,
                    f"traced action {a} lane {s} has shape "
                    f"{tuple(sd.shape)} dtype {sd.dtype}; the era loop "
                    f"carries uint32[{B}] lanes and XLA requires static "
                    "shapes",
                    _loc(tm, "step_lanes"),
                    "keep lane programs elementwise and uint32 end to end",
                )
                return False
        md = masks[a]
        if tuple(md.shape) != (B,) or md.dtype != jnp.bool_:
            report.add(
                "STR202",
                Severity.ERROR,
                f"traced action {a} mask has shape {tuple(md.shape)} "
                f"dtype {md.dtype}; expected bool[{B}]",
                _loc(tm, "step_lanes"),
                "derive masks from lane comparisons only",
            )
            return False
    return True


def _check_host_device_agreement(tm, lanes, np_out, report: AnalysisReport):
    import jax
    import jax.numpy as jnp

    np_succs, np_masks = np_out

    @jax.jit
    def step(l):
        return tm.step_lanes(jnp, l)

    try:
        j_succs, j_masks = step(tuple(jnp.asarray(l) for l in lanes))
    except BaseException as e:  # noqa: BLE001
        report.add(
            "STR201",
            Severity.ERROR,
            f"step_lanes traced but failed to execute under jit: "
            f"{type(e).__name__}: {e}",
            _loc(tm, "step_lanes"),
            "check gather indices and dynamic slices stay in bounds",
        )
        return
    A = len(np_masks)
    S = len(lanes)
    for a in range(A):
        nm = np.asarray(np_masks[a])
        jm = np.asarray(j_masks[a])
        if not np.array_equal(nm, jm):
            report.add(
                "STR205",
                Severity.ERROR,
                f"action {a} validity mask differs between numpy and jax "
                f"evaluation ({int(nm.sum())} vs {int(jm.sum())} valid); "
                "the host oracle and the device engine would explore "
                "different transition systems",
                _loc(tm, "step_lanes"),
                "avoid numpy-only semantics (value-dependent dtypes, "
                "Python bool casts); keep the program in the shared "
                "xp subset",
            )
            return
        for s in range(S):
            nl = np.asarray(np_succs[a][s]).astype(np.uint32)[nm]
            jl = np.asarray(j_succs[a][s]).astype(np.uint32)[nm]
            if not np.array_equal(nl, jl):
                i = int(np.nonzero(nl != jl)[0][0])
                report.add(
                    "STR205",
                    Severity.ERROR,
                    f"action {a} lane {s} differs between numpy and jax "
                    f"on a VALID successor (first mismatch at batch row "
                    f"{i}: {int(nl[i])} vs {int(jl[i])}); host/device "
                    "fingerprints would diverge",
                    _loc(tm, "step_lanes"),
                    "uint32 wraparound and shift semantics differ off the "
                    "shared subset; keep all arithmetic in xp.uint32",
                )
                return


def _check_boundary(tm, lanes, report: AnalysisReport):
    import jax
    import jax.numpy as jnp

    B = lanes[0].shape[0]
    try:
        nb = np.asarray(tm.within_boundary_lanes(np, lanes))
    except BaseException as e:  # noqa: BLE001
        report.add(
            "STR206",
            Severity.ERROR,
            f"within_boundary_lanes raised under numpy: "
            f"{type(e).__name__}: {e}",
            _loc(tm, "within_boundary_lanes"),
            "return xp.ones(B, bool) when every state is in bounds",
        )
        return
    if nb.shape != (B,) or nb.dtype != np.bool_:
        report.add(
            "STR206",
            Severity.ERROR,
            f"within_boundary_lanes returned shape {nb.shape} dtype "
            f"{nb.dtype}; expected bool[{B}]",
            _loc(tm, "within_boundary_lanes"),
            "return one boolean per batch row",
        )
        return
    spec = tuple(jax.ShapeDtypeStruct((B,), jnp.uint32) for _ in lanes)
    try:
        jax.eval_shape(lambda l: tm.within_boundary_lanes(jnp, l), spec)
    except BaseException as e:  # noqa: BLE001
        report.add(
            "STR201",
            Severity.ERROR,
            f"within_boundary_lanes fails to trace under jax.jit: "
            f"{type(e).__name__}: {str(e).splitlines()[0] if str(e) else e}",
            _loc(tm, "within_boundary_lanes"),
            "express the boundary as mask arithmetic over lanes",
        )


def _check_decode(tm, rows: np.ndarray, report: AnalysisReport):
    bad: List[Any] = []
    for row in rows:
        try:
            tm.decode_state(np.asarray(row, dtype=np.uint32))
        except BaseException as e:  # noqa: BLE001
            bad.append((row, e))
            break
    if bad:
        row, e = bad[0]
        report.add(
            "STR204",
            Severity.ERROR,
            f"decode_state raised {type(e).__name__} on reachable row "
            f"{row.tolist()}: {e}; the Explorer and counterexample "
            "rendering would crash on it",
            _loc(tm, "decode_state"),
            "decode every encodable lane combination reachable from the "
            "initial states",
        )
