"""speclint: pre-flight static analysis of models, properties, and symmetry.

TLC-style "sanity before search" (the reference trusts user models
completely; this framework does not have to). `analyze(model)` replays
the model's callbacks over a bounded breadth-first sample of its own
state space and runs five rule families:

  1. determinism/purity  (STR1xx, analysis/determinism.py) — hidden RNG,
     set-iteration-order nondeterminism, in-place mutation of the input
     state, unhashable or unstable fingerprints;
  2. device compatibility (STR2xx, analysis/device.py; TensorModels) —
     jit traceability and shape/dtype stability of `step_lanes`,
     fingerprint bit-packing overflow, numpy/jax divergence,
     `decode_state` round-trips;
  3. property well-formedness (STR3xx, analysis/properties.py) —
     duplicate names, raising predicates, constant-on-sample predicates,
     `eventually` without reachable terminal states;
  4. symmetry soundness (STR4xx, analysis/symmetry.py) —
     `representative()` idempotence, property preservation, and
     host/device canonicalizer agreement;
  5. spawnability (STR5xx, analysis/spawnability.py; ActorModels) —
     sampled in-flight messages must survive the `json_serializer`
     wire round-trip, or a deployed run silently drops/corrupts them
     (and trace conformance reports spurious divergences);
  6. compiled programs (STR6xx "proglint", analysis/program.py;
     TensorModels) — the device era/seed/insert/mux/sharded programs
     lowered to jaxpr/StableHLO WITHOUT executing, scanned for host
     transfers in the hot loop, dropped buffer donation, dtype drift,
     op-count budget regressions (analysis/op_budgets.json), signature
     instability, and (with ``program_cost=True``) an XLA-cost-model
     predicted roofline.

Wire-in points:

  - ``model.checker().lint()`` runs it over a builder's model + options;
  - ``model.checker().strict()`` auto-runs it before ANY engine spawn and
    refuses to launch on error-severity findings (`SpecLintError`);
  - ``python -m stateright_tpu.analysis MODEL`` lints from the shell;
  - diagnostic counts land in every engine's telemetry as ``lint_<code>``
    counters (obs/metrics.py catalog) and in BENCH json.

The code -> meaning -> fix catalog lives in `analysis/README.md`.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, List, Optional

import numpy as np

from ..core import Model
from ..tensor import TensorModel, TensorModelAdapter
from ..actor.model import ActorModel
from . import determinism, device, program, properties, spawnability, symmetry
from .diagnostics import (
    AnalysisReport,
    Diagnostic,
    SampleInfo,
    Severity,
    SpecLintError,
)
from .sampling import Sample, sample_states

__all__ = [
    "AnalysisReport",
    "Diagnostic",
    "Sample",
    "SampleInfo",
    "Severity",
    "SpecLintError",
    "analyze",
    "sample_states",
]

ALL_FAMILIES = (
    "determinism", "device", "properties", "symmetry", "spawn", "program",
)

# Device-rule batch width: tracing/executing step_lanes on more rows buys
# no additional coverage for shape/dtype/divergence findings, and keeps
# the pre-flight cheap enough for strict mode.
_DEVICE_BATCH = 128


def analyze(
    model: Any,
    *,
    samples: int = 256,
    families: Iterable[str] = ALL_FAMILIES,
    symmetry_fn: Optional[Callable[[Any], Any]] = None,
    orbit_fn: Optional[Callable[[Any], List[Any]]] = None,
    program_cost: bool = False,
    budgets_path: Optional[str] = None,
) -> AnalysisReport:
    """Statically analyze `model` before spending a checking run on it.

    `model` may be a host `Model`, a `TensorModel`, or a
    `TensorModelAdapter`; tensor models additionally get the device rule
    family over their lane programs. `samples` bounds the breadth-first
    state sample the rules replay on (shallow states sit on every path,
    so spec bugs overwhelmingly surface here). `symmetry_fn` lints an
    explicit canonicalizer (e.g. the one handed to
    `CheckerBuilder.symmetry_fn`); `orbit_fn(state) -> [equivalent
    states]` additionally cross-checks representative agreement across a
    known symmetry orbit. `program_cost` widens the STR6xx program
    family to the full device-program set plus the compiled STR606 cost
    model (the CLI's ``--program``); `budgets_path` overrides the
    committed op-budget file (tests).

    Returns an `AnalysisReport`; `report.ok` is False iff any finding is
    error-severity (those mean the checker's verdicts cannot be trusted).
    """
    families = tuple(families)
    unknown = set(families) - set(ALL_FAMILIES)
    if unknown:
        raise ValueError(
            f"unknown rule families {sorted(unknown)}; "
            f"available: {ALL_FAMILIES}"
        )

    tm: Optional[TensorModel] = None
    if isinstance(model, TensorModel):
        tm = model
        host: Model = TensorModelAdapter(model)
    elif isinstance(model, TensorModelAdapter):
        tm = model.tm
        host = model
    elif isinstance(model, Model):
        host = model
    else:
        raise TypeError(
            f"analyze() wants a Model, TensorModel, or TensorModelAdapter; "
            f"got {type(model).__name__}"
        )

    name = type(tm).__name__ if tm is not None else type(host).__name__
    report = AnalysisReport(name)
    sample = sample_states(host, samples)
    report.sample = sample.info()

    rows: Optional[np.ndarray] = None
    if tm is not None and sample.states:
        take = sample.states[:_DEVICE_BATCH]
        try:
            rows = np.asarray(take, dtype=np.uint32)
        except (TypeError, ValueError, OverflowError):
            rows = np.zeros((0, tm.state_width), dtype=np.uint32)

    if "determinism" in families:
        determinism.run(host, sample, report)
    if "device" in families and tm is not None:
        device.run(tm, rows if rows is not None else np.zeros((0, 0)), report)
    if "properties" in families:
        properties.run(host, sample, report)
    if "symmetry" in families:
        symmetry.run(
            host,
            sample,
            report,
            symmetry_fn=symmetry_fn,
            tm=tm,
            rows=rows,
            orbit_fn=orbit_fn,
        )
    if "spawn" in families and isinstance(host, ActorModel):
        spawnability.run(host, sample, report)
    if "program" in families and tm is not None:
        # `program_cost` widens the pass to every device program plus the
        # STR606 compile + cost model (seconds); the default tier stays
        # cheap enough for strict mode and serve admission.
        program.run(tm, report, cost=program_cost, budgets_path=budgets_path)
    return report
