"""Diagnostic records for the speclint static-analysis pass.

Every finding is a `Diagnostic` with a STABLE code (grep-able, pinnable in
CI, and counted into the obs metrics registry as ``lint_<code>``), a
severity, a location (model class + member), and a suggested fix. Codes
group by rule family:

  ``STR1xx``  determinism / purity of the host model interface
  ``STR2xx``  device (jit/vmap/encoding) compatibility of TensorModels
  ``STR3xx``  property well-formedness
  ``STR4xx``  symmetry-reduction soundness
  ``STR5xx``  spawnability (wire round-trip) of ActorModel messages
  ``STR6xx``  compiled-program lint ("proglint"): static jaxpr/StableHLO
              analysis of the device programs — transfers, donation,
              dtype drift, op budgets, signature stability, cost model

The full code -> meaning -> fix catalog lives in `analysis/README.md`
(mirroring the obs metric-name catalog in obs/metrics.py).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Dict, List


class Severity(enum.Enum):
    """How bad a finding is.

    ERROR findings mean the checker's verdicts cannot be trusted (hidden
    nondeterminism, state mutation, host/device divergence, unsound
    symmetry); strict mode refuses to launch engines over them. WARNING
    findings are probable spec mistakes that do not by themselves corrupt
    the search. INFO findings are observations (e.g. a `sometimes`
    property never satisfied within the sample).
    """

    ERROR = "error"
    WARNING = "warning"
    INFO = "info"


@dataclass
class Diagnostic:
    """One speclint finding."""

    code: str  # stable id, e.g. "STR103"
    severity: Severity
    message: str  # what was observed, with concrete evidence
    location: str  # "ModelClass.member" the finding anchors to
    suggestion: str = ""  # how to fix it
    details: Dict[str, Any] = field(default_factory=dict)

    def format(self) -> str:
        head = f"{self.code} {self.severity.value:<7} {self.location}: {self.message}"
        if self.suggestion:
            head += f"\n    fix: {self.suggestion}"
        return head


@dataclass
class SampleInfo:
    """What the state sampler actually covered (findings are only as good
    as the sample; exhausted=True means the WHOLE reachable space was
    examined)."""

    states: int = 0
    max_depth: int = 0
    exhausted: bool = False
    terminal_states: int = 0

    def to_dict(self) -> Dict[str, Any]:
        return {
            "states": self.states,
            "max_depth": self.max_depth,
            "exhausted": self.exhausted,
            "terminal_states": self.terminal_states,
        }


class AnalysisReport:
    """The result of one `analyze()` run: diagnostics plus sample coverage."""

    def __init__(self, model_name: str):
        self.model_name = model_name
        self.diagnostics: List[Diagnostic] = []
        self.sample = SampleInfo()
        self.families_run: List[str] = []

    # -- accumulation (rule modules call this) -------------------------------

    def add(
        self,
        code: str,
        severity: Severity,
        message: str,
        location: str,
        suggestion: str = "",
        **details: Any,
    ) -> Diagnostic:
        d = Diagnostic(code, severity, message, location, suggestion, details)
        self.diagnostics.append(d)
        return d

    # -- queries -------------------------------------------------------------

    @property
    def errors(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.severity is Severity.ERROR]

    @property
    def warnings(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.severity is Severity.WARNING]

    @property
    def ok(self) -> bool:
        """True when no error-severity findings exist."""
        return not self.errors

    def by_code(self, code: str) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.code == code]

    def counts_by_code(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for d in self.diagnostics:
            out[d.code] = out.get(d.code, 0) + 1
        return dict(sorted(out.items()))

    # -- export --------------------------------------------------------------

    def to_dict(self) -> Dict[str, Any]:
        return {
            "model": self.model_name,
            "ok": self.ok,
            "errors": len(self.errors),
            "warnings": len(self.warnings),
            "counts_by_code": self.counts_by_code(),
            "sample": self.sample.to_dict(),
            "families_run": list(self.families_run),
            "diagnostics": [
                {
                    "code": d.code,
                    "severity": d.severity.value,
                    "location": d.location,
                    "message": d.message,
                    "suggestion": d.suggestion,
                }
                for d in self.diagnostics
            ],
        }

    def format(self) -> str:
        lines = [
            f"speclint: {self.model_name} — "
            f"{len(self.errors)} error(s), {len(self.warnings)} warning(s), "
            f"{len(self.diagnostics) - len(self.errors) - len(self.warnings)} "
            f"note(s) over {self.sample.states} sampled state(s)"
            + (" [space exhausted]" if self.sample.exhausted else "")
        ]
        order = {Severity.ERROR: 0, Severity.WARNING: 1, Severity.INFO: 2}
        for d in sorted(self.diagnostics, key=lambda d: (order[d.severity], d.code)):
            lines.append("  " + d.format().replace("\n", "\n  "))
        if not self.diagnostics:
            lines.append("  clean: no findings")
        return "\n".join(lines)

    def raise_on_errors(self) -> "AnalysisReport":
        if self.errors:
            raise SpecLintError(self)
        return self


class SpecLintError(Exception):
    """Raised when strict mode refuses to launch over error findings."""

    def __init__(self, report: AnalysisReport):
        self.report = report
        codes = ", ".join(sorted({d.code for d in report.errors}))
        super().__init__(
            f"speclint found {len(report.errors)} error-severity finding(s) "
            f"({codes}) on {report.model_name}; fix the model or launch "
            f"without strict mode.\n{report.format()}"
        )
