"""STR1xx — determinism and purity of the host model interface.

The checker's core assumption is that a `Model` is a pure description of
a transition system: `actions` and `next_state` are functions of their
arguments, states are immutable values with stable fingerprints, and
`init_states` yields the same set every call. Violations (a hidden RNG,
set-iteration-order leakage, in-place mutation of the input state) do not
crash — they silently corrupt the search: the visited set dedups against
fingerprints that no longer mean anything, and the verdict hours later is
garbage. These rules REPLAY the callbacks on sampled states and compare.

Codes:
  STR101  `actions` is nondeterministic (replays disagree as sets)
  STR102  `next_state` is nondeterministic (replay fingerprints disagree)
  STR103  `actions`/`next_state` mutates its input state
  STR104  a reachable state cannot be fingerprinted
  STR105  fingerprinting the same state twice gives different values
  STR106  `init_states` is nondeterministic across calls
  STR108  `actions` replays agree as sets but disagree in ORDER (warning)
"""

from __future__ import annotations

from typing import Any, List

from ..core import Model
from .diagnostics import AnalysisReport, Severity
from .sampling import Sample

REPLAYS = 3  # replay count per callback (2 detects, 3 resists luck)


def _loc(model: Model, member: str) -> str:
    return f"{type(model).__name__}.{member}"


def _fp_or_none(model: Model, state: Any):
    try:
        return model.fingerprint_state(state)
    except BaseException:  # noqa: BLE001
        return None


def run(model: Model, sample: Sample, report: AnalysisReport) -> None:
    report.families_run.append("determinism")
    if sample.error is not None and not sample.states:
        report.add(
            "STR104",
            Severity.ERROR,
            f"model raised {type(sample.error).__name__} in "
            f"{sample.error_site} before any state could be sampled: "
            f"{sample.error}",
            _loc(model, sample.error_site or "init_states"),
            "make the model callbacks total over reachable states",
        )
        return

    _check_init_states(model, report)
    mutation_reported = False
    act_nondet_reported = False
    order_reported = False
    next_nondet_reported = False
    fp_bad_reported = False

    for state in sample.states:
        fp_before = _fp_or_none(model, state)
        if fp_before is None and not fp_bad_reported:
            report.add(
                "STR104",
                Severity.ERROR,
                f"state {state!r} cannot be fingerprinted (fingerprint_state "
                "raised); the visited set cannot dedup it",
                _loc(model, "fingerprint_state"),
                "use dataclasses/builtin containers for state, or define "
                "fingerprint_key()",
            )
            fp_bad_reported = True
        elif fp_before is not None and not fp_bad_reported:
            fp_again = _fp_or_none(model, state)
            if fp_again != fp_before:
                report.add(
                    "STR105",
                    Severity.ERROR,
                    f"fingerprinting state {state!r} twice gave "
                    f"{fp_before} then {fp_again}; dedup and path "
                    "reconstruction require stable fingerprints",
                    _loc(model, "fingerprint_state"),
                    "remove identity/address-dependent data (object ids, "
                    "unhashed memo fields) from the state encoding",
                )
                fp_bad_reported = True

        # Replay `actions` REPLAYS times; compare as sequences AND sets.
        runs: List[List[Any]] = []
        try:
            for _ in range(REPLAYS):
                acts: List[Any] = []
                model.actions(state, acts)
                runs.append(acts)
        except BaseException as e:  # noqa: BLE001
            report.add(
                "STR104",
                Severity.ERROR,
                f"actions raised {type(e).__name__} on sampled state "
                f"{state!r}: {e}",
                _loc(model, "actions"),
                "make actions total over reachable states",
            )
            return
        if not act_nondet_reported:
            reprs = [sorted(repr(a) for a in r) for r in runs]
            if any(r != reprs[0] for r in reprs[1:]):
                report.add(
                    "STR101",
                    Severity.ERROR,
                    f"actions returned different action SETS across "
                    f"{REPLAYS} replays on state {state!r} "
                    f"(e.g. {runs[0]!r} vs {runs[1]!r}); hidden randomness "
                    "or iteration over an unordered container",
                    _loc(model, "actions"),
                    "derive actions only from the state argument; sort any "
                    "set/dict iteration",
                )
                act_nondet_reported = True
            elif not order_reported and any(
                [repr(a) for a in r] != [repr(a) for a in runs[0]]
                for r in runs[1:]
            ):
                report.add(
                    "STR108",
                    Severity.WARNING,
                    f"actions returned the same set in different ORDER "
                    f"across replays on state {state!r}; golden traces and "
                    "path reconstruction depend on a stable order",
                    _loc(model, "actions"),
                    "iterate deterministically (sorted) when appending "
                    "actions",
                )
                order_reported = True

        # Mutation + next_state determinism, per action.
        if fp_before is not None:
            fp_after_actions = _fp_or_none(model, state)
            if (
                fp_after_actions != fp_before
                and not mutation_reported
            ):
                report.add(
                    "STR103",
                    Severity.ERROR,
                    f"calling actions mutated its input state {state!r} "
                    f"(fingerprint changed {fp_before} -> {fp_after_actions})",
                    _loc(model, "actions"),
                    "treat the state argument as read-only",
                )
                mutation_reported = True
        for action in runs[0]:
            try:
                n1 = model.next_state(state, action)
                n2 = model.next_state(state, action)
            except BaseException as e:  # noqa: BLE001
                report.add(
                    "STR104",
                    Severity.ERROR,
                    f"next_state raised {type(e).__name__} on sampled "
                    f"state {state!r}, action {action!r}: {e}",
                    _loc(model, "next_state"),
                    "make next_state total over (reachable state, enabled "
                    "action) pairs",
                )
                return
            if not next_nondet_reported:
                f1 = None if n1 is None else _fp_or_none(model, n1)
                f2 = None if n2 is None else _fp_or_none(model, n2)
                if f1 != f2:
                    report.add(
                        "STR102",
                        Severity.ERROR,
                        f"next_state({state!r}, {action!r}) gave different "
                        f"successors across replays ({n1!r} vs {n2!r}); "
                        "hidden randomness corrupts the search",
                        _loc(model, "next_state"),
                        "derive the successor only from (state, action)",
                    )
                    next_nondet_reported = True
            if fp_before is not None and not mutation_reported:
                fp_after = _fp_or_none(model, state)
                if fp_after != fp_before:
                    report.add(
                        "STR103",
                        Severity.ERROR,
                        f"next_state({state!r}, {action!r}) mutated its "
                        f"input state (fingerprint changed {fp_before} -> "
                        f"{fp_after}); every sibling expansion after it "
                        "sees a corrupted parent",
                        _loc(model, "next_state"),
                        "build the successor from copies "
                        "(dataclasses.replace, tuple rebuilds) instead of "
                        "editing the input in place",
                    )
                    mutation_reported = True

    if sample.error is not None:
        report.add(
            "STR104",
            Severity.ERROR,
            f"sampling stopped early: {sample.error_site} raised "
            f"{type(sample.error).__name__}: {sample.error}",
            _loc(model, sample.error_site),
            "make the model callbacks total over reachable states",
        )


def _check_init_states(model: Model, report: AnalysisReport) -> None:
    try:
        runs = [list(model.init_states()) for _ in range(REPLAYS)]
    except BaseException:  # noqa: BLE001 - sampling already reported it
        return
    keys = []
    for r in runs:
        try:
            keys.append(sorted(str(model.fingerprint_state(s)) for s in r))
        except BaseException:  # noqa: BLE001
            keys.append(sorted(repr(s) for s in r))
    if any(k != keys[0] for k in keys[1:]):
        report.add(
            "STR106",
            Severity.ERROR,
            f"init_states returned different state sets across {REPLAYS} "
            f"calls (e.g. {runs[0]!r} vs {runs[1]!r})",
            _loc(model, "init_states"),
            "construct initial states deterministically",
        )
