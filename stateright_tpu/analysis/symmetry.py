"""STR4xx — symmetry-reduction soundness.

Symmetry reduction replaces states by canonical representatives before
dedup. Three contracts make that sound, and breaking any of them is
invisible at runtime (the run just quietly explores the wrong quotient):

  - idempotence: rep(rep(s)) == rep(s). A non-idempotent canonicalizer
    makes the visited set treat a representative as unvisited, re-deriving
    different "canonical" forms forever (or until the table fills).
  - property preservation: every declared property must agree on s and
    rep(s) — otherwise the quotient search proves facts about states
    nobody asked about.
  - host/device agreement (tensor models): `representative_lanes` must
    give bit-identical results under numpy and jax, or the host oracle
    and device engine canonicalize into different quotients.

Codes:
  STR401  representative() raises on a sampled state
  STR402  representative is not idempotent
  STR403  a property value changes under canonicalization
  STR404  representative_lanes disagrees between numpy and jax
  STR405  orbit states map to different representatives (warning —
          an IMPERFECT canonicalizer is allowed, the reference's own 2pc
          rule is imperfect; it weakens reduction but stays sound)
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional

import numpy as np

from ..core import Model
from .diagnostics import AnalysisReport, Severity
from .sampling import Sample


def _loc(model: Model, member: str) -> str:
    return f"{type(model).__name__}.{member}"


def resolve_symmetry_fn(model: Model, symmetry_fn=None):
    """The canonicalizer to lint: an explicit builder fn, the adapter's
    representative_state, or the states' own representative() method.
    Returns None when the model has no symmetry story (rules skip)."""
    if symmetry_fn is not None:
        return symmetry_fn
    rep_state = getattr(model, "representative_state", None)
    if rep_state is not None:
        tm = getattr(model, "tm", None)
        if tm is not None and tm.representative_lanes is None:
            return None
        return rep_state
    try:
        inits = model.init_states()
    except BaseException:  # noqa: BLE001 - determinism rules report this
        return None
    if inits and hasattr(inits[0], "representative"):
        return lambda s: s.representative()
    return None


def run(
    model: Model,
    sample: Sample,
    report: AnalysisReport,
    symmetry_fn: Optional[Callable[[Any], Any]] = None,
    tm=None,
    rows: Optional[np.ndarray] = None,
    orbit_fn: Optional[Callable[[Any], List[Any]]] = None,
) -> None:
    fn = resolve_symmetry_fn(model, symmetry_fn)
    if fn is None and (tm is None or tm.representative_lanes is None):
        return  # no symmetry declared anywhere: nothing to lint
    report.families_run.append("symmetry")

    if fn is not None:
        _check_host(model, sample, report, fn, orbit_fn)
    if tm is not None and tm.representative_lanes is not None and rows is not None:
        _check_lanes(tm, rows, report)


def _check_host(model, sample, report, fn, orbit_fn) -> None:
    try:
        props = list(model.properties())
    except BaseException:  # noqa: BLE001
        props = []
    idem_reported = False
    prop_reported = False
    orbit_reported = False
    for state in sample.states:
        try:
            rep = fn(state)
            rep2 = fn(rep)
        except BaseException as e:  # noqa: BLE001
            report.add(
                "STR401",
                Severity.ERROR,
                f"representative raised {type(e).__name__} on sampled "
                f"state {state!r}: {e}",
                _loc(model, "representative"),
                "canonicalization must be total over reachable states",
            )
            return
        try:
            fp_rep = model.fingerprint_state(rep)
            fp_rep2 = model.fingerprint_state(rep2)
        except BaseException:  # noqa: BLE001 - STR104 territory
            continue
        if fp_rep != fp_rep2 and not idem_reported:
            report.add(
                "STR402",
                Severity.ERROR,
                f"representative is not idempotent: rep(s)={rep!r} but "
                f"rep(rep(s))={rep2!r} for sampled s={state!r}; the "
                "visited set never converges on a canonical form",
                _loc(model, "representative"),
                "canonicalize to a fixed point (e.g. a full sort, not one "
                "bubble pass)",
            )
            idem_reported = True
        if not prop_reported:
            for p in props:
                try:
                    v_raw = bool(p.condition(model, state))
                    v_rep = bool(p.condition(model, rep))
                except BaseException:  # noqa: BLE001 - STR302 territory
                    continue
                if v_raw != v_rep:
                    report.add(
                        "STR403",
                        Severity.ERROR,
                        f"property {p.name!r} is {v_raw} on state "
                        f"{state!r} but {v_rep} on its representative "
                        f"{rep!r}; the symmetry-reduced run would check a "
                        "DIFFERENT property than the full run",
                        _loc(model, "representative"),
                        "only permute identities the properties are "
                        "invariant under",
                    )
                    prop_reported = True
                    break
        if orbit_fn is not None and not orbit_reported:
            try:
                orbit = list(orbit_fn(state))
                fps = {
                    int(model.fingerprint_state(fn(o))) for o in orbit
                } | {int(fp_rep)}
            except BaseException:  # noqa: BLE001
                continue
            if len(fps) > 1:
                report.add(
                    "STR405",
                    Severity.WARNING,
                    f"{len(fps)} distinct representatives across one "
                    f"symmetry orbit of {state!r}; the canonicalizer is "
                    "imperfect (sound, but the reduction is weaker than "
                    "the orbit count suggests)",
                    _loc(model, "representative"),
                    "break canonicalization ties on ALL state components, "
                    "not just the sort key",
                )
                orbit_reported = True


def _check_lanes(tm, rows: np.ndarray, report: AnalysisReport) -> None:
    import jax
    import jax.numpy as jnp

    S = tm.state_width
    lanes = tuple(np.ascontiguousarray(rows[:, i]) for i in range(S))
    try:
        rep_np = tuple(
            np.asarray(l, dtype=np.uint32)
            for l in tm.representative_lanes(np, lanes)
        )
        rep2_np = tuple(
            np.asarray(l, dtype=np.uint32)
            for l in tm.representative_lanes(np, rep_np)
        )
    except BaseException as e:  # noqa: BLE001
        report.add(
            "STR401",
            Severity.ERROR,
            f"representative_lanes raised under numpy: "
            f"{type(e).__name__}: {e}",
            f"{type(tm).__name__}.representative_lanes",
            "the canonicalizer must be a pure batched array program",
        )
        return
    for s in range(S):
        if not np.array_equal(rep_np[s], rep2_np[s]):
            i = int(np.nonzero(rep_np[s] != rep2_np[s])[0][0])
            report.add(
                "STR402",
                Severity.ERROR,
                f"representative_lanes is not idempotent on lane {s} "
                f"(batch row {i}: rep={int(rep_np[s][i])} vs "
                f"rep(rep)={int(rep2_np[s][i])}); the canonical closure "
                "never converges",
                f"{type(tm).__name__}.representative_lanes",
                "run the sorting network to a full fixed point",
            )
            return

    @jax.jit
    def rep_j(l):
        return tm.representative_lanes(jnp, l)

    try:
        rep_jnp = rep_j(tuple(jnp.asarray(l) for l in lanes))
    except BaseException as e:  # noqa: BLE001
        report.add(
            "STR401",
            Severity.ERROR,
            f"representative_lanes fails under jax.jit: "
            f"{type(e).__name__}: {str(e).splitlines()[0] if str(e) else e}",
            f"{type(tm).__name__}.representative_lanes",
            "remove data-dependent Python control flow; use elementwise "
            "min/max networks",
        )
        return
    for s in range(S):
        j = np.asarray(rep_jnp[s]).astype(np.uint32)
        if not np.array_equal(rep_np[s], j):
            i = int(np.nonzero(rep_np[s] != j)[0][0])
            report.add(
                "STR404",
                Severity.ERROR,
                f"representative_lanes disagrees between numpy and jax on "
                f"lane {s} (batch row {i}: {int(rep_np[s][i])} vs "
                f"{int(j[i])}); host and device would canonicalize into "
                "different quotients",
                f"{type(tm).__name__}.representative_lanes",
                "keep every operation in the shared uint32 xp subset",
            )
            return
