"""STR5xx — spawnability: do the model's messages survive the wire?

An `ActorModel` that checks clean can still fail the moment it is
deployed with `actor.spawn`: the default wire format
(`json_serializer` / `make_json_deserializer`) encodes dataclasses as
``["TypeName", field...]`` and everything else as plain JSON — so a
message carrying a set, frozenset, dict, or other non-JSON payload
raises inside the actor loop (datagram silently dropped), and a message
carrying a LIST field decodes back as a TUPLE (JSON has no distinction;
the deserializer picks tuple because handlers compare tuple-typed fields
like paxos ballots). These rules round-trip the messages actually
observed in flight on the sampled state space and flag the types that do
not come back equal — BEFORE a live run spends an afternoon on it.

Trace conformance (conformance/check.py) has the same dependency: it
matches recorded wire messages against model envelopes through the same
encoding, so an STR5xx finding also predicts bogus `unexplained-deliver`
divergences.

Codes:
  STR501  a message raises during json_serializer/deserializer round-trip
  STR502  a message round-trips without raising but comes back UNEQUAL
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List

from ..actor.model import ActorModel
from ..actor.spawn import json_serializer, make_json_deserializer
from .diagnostics import AnalysisReport, Severity
from .sampling import Sample

# Round-tripping more than this many distinct in-flight messages buys no
# new findings (one finding per message TYPE per code) and keeps the
# pre-flight cheap enough for strict mode.
_MESSAGE_CAP = 64


def _collect_types(value: Any, out: Dict[str, type]) -> None:
    """Every dataclass type reachable from `value`, by name — the set the
    deployment's `make_json_deserializer(...)` would need to know."""
    if dataclasses.is_dataclass(value) and not isinstance(value, type):
        out.setdefault(type(value).__name__, type(value))
        for f in dataclasses.fields(value):
            _collect_types(getattr(value, f.name), out)
    elif isinstance(value, (list, tuple, set, frozenset)):
        for v in value:
            _collect_types(v, out)
    elif isinstance(value, dict):
        for k, v in value.items():
            _collect_types(k, out)
            _collect_types(v, out)


def run(model: ActorModel, sample: Sample, report: AnalysisReport) -> None:
    report.families_run.append("spawn")

    # The messages actually observed in flight across the sample — the
    # honest population a spawned run would put on the wire.
    messages: List[Any] = []
    seen = set()
    for state in sample.states:
        network = getattr(state, "network", None)
        if network is None:
            continue
        for env in network.iter_all():
            key = repr(env.msg)
            if key in seen:
                continue
            seen.add(key)
            messages.append(env.msg)
            if len(messages) >= _MESSAGE_CAP:
                break
        if len(messages) >= _MESSAGE_CAP:
            break

    if not messages:
        return

    types: Dict[str, type] = {}
    for msg in messages:
        _collect_types(msg, types)
    decode = make_json_deserializer(*types.values())

    loc = type(model).__name__
    flagged_raise = set()
    flagged_unequal = set()
    for msg in messages:
        tname = type(msg).__name__
        try:
            back = decode(json_serializer(msg))
        except BaseException as e:  # noqa: BLE001
            if tname not in flagged_raise:
                report.add(
                    "STR501",
                    Severity.ERROR,
                    f"message {msg!r} does not survive the spawn wire "
                    f"format: json_serializer round-trip raised "
                    f"{type(e).__name__}: {e}; a live run would drop these "
                    "datagrams silently",
                    f"{loc}.{tname}",
                    "restrict message fields to JSON-able values "
                    "(dataclasses, tuples, ints, strings) — sets, dicts, "
                    "and arbitrary objects do not serialize",
                )
                flagged_raise.add(tname)
            continue
        if back != msg and tname not in flagged_unequal:
            report.add(
                "STR502",
                Severity.ERROR,
                f"message {msg!r} round-trips the spawn wire format as "
                f"{back!r} (unequal); deployed handlers would see a "
                "different value than the checker verified — and trace "
                "conformance would report spurious divergences",
                f"{loc}.{tname}",
                "use tuples instead of lists in message fields (JSON "
                "cannot distinguish them; the deserializer decodes "
                "sequences as tuples)",
                round_trip=repr(back),
            )
            flagged_unequal.add(tname)
