"""Bounded state sampling for speclint.

The rules need concrete states to replay model callbacks on. A bounded
breadth-first walk from the initial states gives a depth-stratified sample
(shallow states are exactly where most spec bugs bite first — they are on
every path) and, as a free byproduct, knows whether the WHOLE reachable
space fit inside the budget (`exhausted`), which upgrades several
sample-relative findings from "within the sample" to facts.

Sampling is defensive: a model whose callbacks raise mid-walk yields a
truncated sample plus the exception (the rule families report it with a
stable code) instead of crashing the lint pass.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, List, Optional, Tuple

from ..core import Model
from .diagnostics import SampleInfo


@dataclass
class Sample:
    """States gathered by the bounded BFS, plus coverage facts."""

    states: List[Any] = field(default_factory=list)
    depths: List[int] = field(default_factory=list)
    init_count: int = 0
    terminal_states: List[Any] = field(default_factory=list)
    exhausted: bool = False
    max_depth: int = 0
    # First exception hit while walking (the walk stops there).
    error: Optional[BaseException] = None
    error_site: str = ""  # "init_states" / "actions" / "next_state"

    def info(self) -> SampleInfo:
        return SampleInfo(
            states=len(self.states),
            max_depth=self.max_depth,
            exhausted=self.exhausted,
            terminal_states=len(self.terminal_states),
        )


def sample_states(model: Model, budget: int) -> Sample:
    """Breadth-first sample of up to `budget` distinct reachable states.

    Dedup keys on the model's own fingerprints when they work and falls
    back to object identity when they do not (an unfingerprintable state
    is itself a finding — the determinism family reports it; sampling
    must still make progress to feed the other rules).
    """
    out = Sample()
    try:
        inits = list(model.init_states())
    except BaseException as e:  # noqa: BLE001 - lint pass must not crash
        out.error = e
        out.error_site = "init_states"
        return out
    out.init_count = len(inits)

    seen = set()
    frontier: List[Tuple[Any, int]] = []
    fingerprintable = True
    for s in inits:
        key = _key(model, s, fingerprintable)
        if key is None:
            fingerprintable = False
            key = id(s)
        if key not in seen:
            seen.add(key)
            frontier.append((s, 0))
    out.states = [s for s, _ in frontier]
    out.depths = [0] * len(frontier)

    while frontier and len(out.states) < budget:
        next_frontier: List[Tuple[Any, int]] = []
        for state, depth in frontier:
            try:
                actions: List[Any] = []
                model.actions(state, actions)
                succs = []
                for a in actions:
                    nxt = model.next_state(state, a)
                    if nxt is not None:
                        succs.append(nxt)
            except BaseException as e:  # noqa: BLE001
                out.error = e
                out.error_site = "actions" if not actions else "next_state"
                out.max_depth = max(out.depths, default=0)
                return out
            if not succs:
                out.terminal_states.append(state)
                continue
            for nxt in succs:
                if not model.within_boundary(nxt):
                    continue
                key = _key(model, nxt, fingerprintable)
                if key is None:
                    fingerprintable = False
                    key = id(nxt)
                if key in seen:
                    continue
                seen.add(key)
                next_frontier.append((nxt, depth + 1))
                if len(out.states) + len(next_frontier) >= budget:
                    break
            if len(out.states) + len(next_frontier) >= budget:
                break
        for s, d in next_frontier:
            out.states.append(s)
            out.depths.append(d)
        frontier = next_frontier
        if not next_frontier:
            out.exhausted = len(out.states) < budget
            break
    out.max_depth = max(out.depths, default=0)
    return out


def _key(model: Model, state: Any, fingerprintable: bool):
    if not fingerprintable:
        return None
    try:
        return model.fingerprint_state(state)
    except BaseException:  # noqa: BLE001 - reported by the determinism rules
        return None
