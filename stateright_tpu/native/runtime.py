"""ctypes bindings over the native event-loop core (_core.so).

The compiled core owns sockets, deadlines, and the poll loop (one C++
thread per actor); this module adapts its single event callback to the
Actor protocol and translates `Out` commands back into srn_* calls.
Message serialization stays in Python (it is user-pluggable).
"""

from __future__ import annotations

import ctypes
import logging
import os
import random as _random
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..actor.base import Actor, CancelTimer, ChooseRandom, Out, Send, SetTimer
from ..actor.ids import Id, addr_from_id
from . import build as _build

log = logging.getLogger(__name__)

_EVENT_CB = ctypes.CFUNCTYPE(
    None,
    ctypes.c_void_p,  # ctx (unused; we close over state)
    ctypes.c_int32,  # actor index
    ctypes.c_int32,  # kind: 0=start 1=msg 2=deadline
    ctypes.c_uint32,  # src ip (host order)
    ctypes.c_uint16,  # src port
    ctypes.POINTER(ctypes.c_uint8),  # payload
    ctypes.c_int64,  # payload length
    ctypes.c_uint64,  # deadline key
)

_lib: Optional[ctypes.CDLL] = None


def _load() -> Optional[ctypes.CDLL]:
    global _lib
    if _lib is not None:
        return _lib
    if not _build.is_built():
        if os.environ.get("STPU_NO_NATIVE_BUILD"):
            return None
        if not _build.build(quiet=True):
            return None
    try:
        lib = ctypes.CDLL(_build.OUTPUT)
    except OSError as e:
        log.warning("native core failed to load: %s", e)
        return None
    lib.srn_start.restype = ctypes.c_int64
    lib.srn_start.argtypes = [
        ctypes.POINTER(ctypes.c_uint32),
        ctypes.POINTER(ctypes.c_uint16),
        ctypes.c_int32,
        _EVENT_CB,
        ctypes.c_void_p,
    ]
    lib.srn_send.restype = None
    lib.srn_send.argtypes = [
        ctypes.c_int64,
        ctypes.c_int32,
        ctypes.c_uint32,
        ctypes.c_uint16,
        ctypes.POINTER(ctypes.c_uint8),
        ctypes.c_int64,
    ]
    lib.srn_set_deadline.restype = None
    lib.srn_set_deadline.argtypes = [
        ctypes.c_int64,
        ctypes.c_int32,
        ctypes.c_uint64,
        ctypes.c_double,
    ]
    lib.srn_cancel_deadline.restype = None
    lib.srn_cancel_deadline.argtypes = [
        ctypes.c_int64,
        ctypes.c_int32,
        ctypes.c_uint64,
    ]
    lib.srn_stop.restype = None
    lib.srn_stop.argtypes = [ctypes.c_int64]
    _lib = lib
    return lib


def is_available() -> bool:
    return _load() is not None


def _ip_to_u32(ip: str) -> int:
    a, b, c, d = (int(x) for x in ip.split("."))
    return (a << 24) | (b << 16) | (c << 8) | d


class _ActorShim:
    """Per-actor protocol state driven by native events."""

    def __init__(self, index: int, id: Id, actor: Actor):
        self.index = index
        self.id = id
        self.actor = actor
        self.state: Any = None
        # Deadline keys are interned: key id <-> ("t", timer) / ("r", value).
        self.key_of: Dict[Any, int] = {}
        self.obj_of: Dict[int, Any] = {}
        self.next_key = 1

    def intern(self, obj) -> int:
        k = self.key_of.get(obj)
        if k is None:
            k = self.next_key
            self.next_key += 1
            self.key_of[obj] = k
            self.obj_of[k] = obj
        return k


class NativeSpawnHandle:
    """Controls a running native deployment; mirrors spawn.SpawnHandle."""

    def __init__(self, lib, handle: int, shims: List[_ActorShim], cb_ref,
                 recorder=None, injector=None, netobs=None):
        self._lib = lib
        self._handle = handle
        self._shims = shims
        self._cb_ref = cb_ref  # keep the ctypes callback alive
        self._stopped = threading.Event()
        self._recorder = recorder
        self._injector = injector
        self.netobs = netobs

    def telemetry(self):
        """Snapshot of the deployment's live metrics ({} when netobs is off)."""
        return self.netobs.snapshot() if self.netobs is not None else {}

    def state(self, id) -> Any:
        for shim in self._shims:
            if shim.id == Id(id):
                return shim.state
        raise KeyError(f"no actor with id {id!r}")

    def shutdown(self, timeout: float = 2.0) -> None:
        if not self._stopped.is_set():
            self._stopped.set()
            self._lib.srn_stop(self._handle)
            # srn_send no-ops after srn_stop, so flushing the injector's
            # delayed/held datagrams here is safe; seal the trace last.
            if self._injector is not None:
                self._injector.close()
            if self._recorder is not None:
                self._recorder.close()


def spawn(
    serialize: Callable[[Any], bytes],
    deserialize: Callable[[bytes], Any],
    actors: List[Tuple[Id, Actor]],
    background: bool = False,
    recorder=None,
    injector=None,
    netobs=None,
) -> NativeSpawnHandle:
    """Run the actor system on the native core. Reference: spawn.rs:64-154.

    `recorder`/`injector` are pre-normalized conformance hooks (see
    `actor.spawn.spawn`'s ``record=``/``faults=``): same TraceEvent
    stream and fault schedule as the Python engine.
    """
    lib = _load()
    assert lib is not None, "native core not available"

    shims = [_ActorShim(i, id, actor) for i, (id, actor) in enumerate(actors)]
    if recorder is not None:
        recorder.attach(
            actors, engine="native",
            plan=injector.plan if injector is not None else None,
        )
    if netobs is not None:
        netobs.attach(actors, "native")
    handle_box: List[int] = []
    # Native threads can deliver on_start before srn_start returns on this
    # thread; events hold until the handle is published (Event.wait releases
    # the GIL, so the publishing thread is never blocked out).
    handle_ready = threading.Event()

    def dispatch(shim: _ActorShim, out: Out) -> None:
        for cmd in out.commands:
            if isinstance(cmd, Send):
                if netobs is not None:
                    netobs.command(shim.index, "send")
                try:
                    payload = serialize(cmd.msg)
                except Exception as e:
                    log.warning(
                        "actor %s: failed to serialize %r to %s: %s",
                        shim.id, cmd.msg, cmd.dst, e,
                    )
                    continue
                ip, port = addr_from_id(Id(cmd.dst))

                def wire_send(data, _ip=_ip_to_u32(ip), _port=port, _index=shim.index):
                    buf = (ctypes.c_uint8 * len(data)).from_buffer_copy(data)
                    lib.srn_send(handle_box[0], _index, _ip, _port, buf, len(data))
                    if netobs is not None:
                        netobs.transmit()

                if injector is not None:
                    injector.transmit(
                        int(shim.id), int(cmd.dst), payload, wire_send,
                        recorder=recorder, actor_index=shim.index,
                    )
                else:
                    wire_send(payload)
            elif isinstance(cmd, SetTimer):
                if netobs is not None:
                    netobs.command(shim.index, "timer_set")
                lo, hi = cmd.duration
                delay = _random.uniform(lo, hi) if lo < hi else lo
                key = shim.intern(("t", cmd.timer))
                lib.srn_set_deadline(handle_box[0], shim.index, key, delay)
            elif isinstance(cmd, CancelTimer):
                key = shim.key_of.get(("t", cmd.timer))
                if key is not None:
                    lib.srn_cancel_deadline(handle_box[0], shim.index, key)
            elif isinstance(cmd, ChooseRandom):
                if not cmd.choices:
                    continue
                # The runtime resolves the nondeterminism the checker
                # explored: one choice at a random instant (spawn.rs:216-231).
                chosen = _random.choice(list(cmd.choices))
                key = shim.intern(("r", chosen))
                lib.srn_set_deadline(
                    handle_box[0], shim.index, key, _random.uniform(0.0, 10.0)
                )

    def on_event(_ctx, actor_idx, kind, src_ip, src_port, data, length, key):
        handle_ready.wait(timeout=10.0)
        shim = shims[actor_idx]
        out = Out()
        try:
            if kind == 0:  # start
                t0 = time.monotonic()
                shim.state = shim.actor.on_start(shim.id, out)
                dur = time.monotonic() - t0
                if netobs is not None:
                    netobs.handler(shim.index, "init", dur)
                if recorder is not None:
                    recorder.record_handler(
                        shim.index, "init", shim.state, out, duration=dur
                    )
            elif kind == 1:  # datagram
                payload = bytes(
                    ctypes.cast(
                        data, ctypes.POINTER(ctypes.c_uint8 * length)
                    ).contents
                )
                try:
                    msg = deserialize(payload)
                except Exception:
                    return  # unparseable: ignore (spawn.rs:123-127)
                ip = ".".join(
                    str((src_ip >> s) & 0xFF) for s in (24, 16, 8, 0)
                )
                src = Id.from_addr(ip, src_port)
                t0 = time.monotonic()
                returned = shim.actor.on_msg(
                    shim.id, shim.state, src, msg, out
                )
                dur = time.monotonic() - t0
                if returned is not None:
                    shim.state = returned
                if netobs is not None:
                    netobs.handler(shim.index, "deliver", dur)
                if recorder is not None:
                    recorder.record_handler(
                        shim.index, "deliver", shim.state, out,
                        src=int(src), msg=msg, duration=dur,
                    )
            else:  # deadline
                obj = shim.obj_of.get(int(key))
                if obj is None:
                    return
                k, payload_obj = obj
                t0 = time.monotonic()
                if k == "t":
                    returned = shim.actor.on_timeout(
                        shim.id, shim.state, payload_obj, out
                    )
                else:
                    returned = shim.actor.on_random(
                        shim.id, shim.state, payload_obj, out
                    )
                dur = time.monotonic() - t0
                if returned is not None:
                    shim.state = returned
                if netobs is not None:
                    netobs.handler(
                        shim.index, "timeout" if k == "t" else "random", dur
                    )
                if recorder is not None:
                    if k == "t":
                        recorder.record_handler(
                            shim.index, "timeout", shim.state, out,
                            timer=payload_obj, duration=dur,
                        )
                    else:
                        recorder.record_handler(
                            shim.index, "random", shim.state, out,
                            value=payload_obj, duration=dur,
                        )
            dispatch(shim, out)
        except Exception:
            log.exception("actor %s: unhandled error in event handler", shim.id)

    cb = _EVENT_CB(on_event)
    n = len(actors)
    ips = (ctypes.c_uint32 * n)()
    ports = (ctypes.c_uint16 * n)()
    for i, (id, _actor) in enumerate(actors):
        ip, port = addr_from_id(id)
        ips[i] = _ip_to_u32(ip)
        ports[i] = port
    handle = lib.srn_start(ips, ports, n, cb, None)
    if handle <= 0:
        raise OSError(f"native spawn failed to bind actor {-1 - handle}")
    handle_box.append(handle)
    handle_ready.set()
    h = NativeSpawnHandle(
        lib, handle, shims, cb,
        recorder=recorder, injector=injector, netobs=netobs,
    )
    if not background:
        try:
            while True:
                threading.Event().wait(0.5)
        except KeyboardInterrupt:
            h.shutdown()
    return h
