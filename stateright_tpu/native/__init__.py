"""Native (C++) runtime core for real-network actor execution.

The event-loop core — sockets, deadline tracking, poll loop, datagram IO —
lives in compiled code (`core.cpp`, built to `_core.so`); Python is called
back only for protocol logic (the actor's `on_start`/`on_msg`/`on_timeout`/
`on_random`) and message serialization. This mirrors the reference keeping
its spawn runtime in compiled Rust (src/actor/spawn.rs:64-154).

Build with `python -m stateright_tpu.native.build` (requires g++); the
portable Python engine in `actor/spawn.py` is the fallback.
"""
