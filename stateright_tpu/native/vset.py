"""ctypes binding over the native threaded visited-key set (_checker.so).

See checker.cpp for the protocol. The binding auto-builds the shared
object on first use (mirroring runtime.py) and exposes a growable wrapper:
the C side owns a fixed-capacity atomic table; `VisitedSet` grows it by
creating a larger one and bulk re-inserting the retained keys.
"""

from __future__ import annotations

import ctypes
import os
import threading
from typing import Optional

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "_checker.so")

_lib = None
_lib_mu = threading.Lock()


def load() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native library; None if unavailable."""
    global _lib
    with _lib_mu:
        if _lib is not None:
            return _lib
        from . import build

        if not build.is_built("checker"):
            if not build.build(quiet=True, target="checker"):
                return None
        lib = ctypes.CDLL(_SO)
        lib.vset_create.restype = ctypes.c_int64
        lib.vset_create.argtypes = [ctypes.c_uint64]
        lib.vset_destroy.restype = None
        lib.vset_destroy.argtypes = [ctypes.c_int64]
        lib.vset_len.restype = ctypes.c_uint64
        lib.vset_len.argtypes = [ctypes.c_int64]
        lib.vset_capacity.restype = ctypes.c_uint64
        lib.vset_capacity.argtypes = [ctypes.c_int64]
        lib.vset_insert_batch.restype = ctypes.c_int64
        lib.vset_insert_batch.argtypes = [
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_int32,
        ]
        _lib = lib
        return _lib


class VisitedSet:
    """Growable threaded visited set over nonzero uint64 fingerprints."""

    MAX_LOAD = 0.5

    def __init__(self, capacity: int = 1 << 16):
        self._lib = load()
        if self._lib is None:
            raise RuntimeError(
                "native checker extension unavailable "
                "(run: python -m stateright_tpu.native.build)"
            )
        cap = 1 << max(10, (capacity - 1).bit_length())
        self._h = self._lib.vset_create(cap)
        self._cap = cap
        # Dense copy of inserted keys, for growth re-insertion (and cheap
        # iteration); parents are tracked by the engine.
        self._keys: list = []

    def __len__(self) -> int:
        return int(self._lib.vset_len(self._h))

    def insert_batch(self, keys: np.ndarray, nthreads: int) -> np.ndarray:
        """Insert nonzero uint64 keys; returns the is_new bool mask."""
        keys = np.ascontiguousarray(keys, dtype=np.uint64)
        n = len(keys)
        while len(self) + n > self.MAX_LOAD * self._cap:
            self._grow(nthreads)
        out = np.zeros(n, dtype=np.uint8)
        rc = self._lib.vset_insert_batch(
            self._h,
            keys.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            n,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            nthreads,
        )
        if rc != 0:
            raise RuntimeError(
                f"native visited set: {rc} unresolved inserts despite "
                "load-factor headroom"
            )
        mask = out.astype(bool)
        if mask.any():
            self._keys.append(keys[mask])
        return mask

    def _grow(self, nthreads: int) -> None:
        new_cap = self._cap * 2
        new_h = self._lib.vset_create(new_cap)
        try:
            if self._keys:
                all_keys = np.concatenate(self._keys)
                out = np.zeros(len(all_keys), dtype=np.uint8)
                rc = self._lib.vset_insert_batch(
                    new_h,
                    all_keys.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
                    len(all_keys),
                    out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
                    nthreads,
                )
                if rc != 0:
                    raise RuntimeError("native visited set: rehash failed")
                self._keys = [all_keys]
        except Exception:
            self._lib.vset_destroy(new_h)  # don't leak the half-built table
            raise
        self._lib.vset_destroy(self._h)
        self._h = new_h
        self._cap = new_cap

    def __del__(self):
        try:
            if self._lib is not None:
                self._lib.vset_destroy(self._h)
        except Exception:
            pass
