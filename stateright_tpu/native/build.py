"""Build the native event-loop core: `python -m stateright_tpu.native.build`.

Compiles core.cpp into _core.so next to this file with g++ (no pybind11 —
the binding layer is ctypes in runtime.py).
"""

from __future__ import annotations

import os
import shutil
import subprocess
import sys

_DIR = os.path.dirname(os.path.abspath(__file__))
SOURCE = os.path.join(_DIR, "core.cpp")
OUTPUT = os.path.join(_DIR, "_core.so")


def build(quiet: bool = False) -> bool:
    """Compile the core; returns True on success."""
    gxx = shutil.which("g++") or shutil.which("c++")
    if gxx is None:
        if not quiet:
            print("native build: no C++ compiler found", file=sys.stderr)
        return False
    cmd = [
        gxx,
        "-O2",
        "-std=c++17",
        "-shared",
        "-fPIC",
        "-o",
        OUTPUT,
        SOURCE,
        "-lpthread",
    ]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
    except Exception as e:
        if not quiet:
            print(f"native build failed to run: {e}", file=sys.stderr)
        return False
    if proc.returncode != 0:
        if not quiet:
            print(proc.stderr, file=sys.stderr)
        return False
    return True


def is_built() -> bool:
    return os.path.exists(OUTPUT) and os.path.getmtime(OUTPUT) >= os.path.getmtime(
        SOURCE
    )


if __name__ == "__main__":
    ok = build()
    print(f"native core: {'built ' + OUTPUT if ok else 'BUILD FAILED'}")
    sys.exit(0 if ok else 1)
