"""Build the native components: `python -m stateright_tpu.native.build`.

Compiles each .cpp target into a .so next to this file with g++ (no
pybind11 — the binding layers are ctypes in runtime.py / vset.py).
"""

from __future__ import annotations

import os
import shutil
import subprocess
import sys

from ..obs.log import get_logger

_log = get_logger("native.build")

_DIR = os.path.dirname(os.path.abspath(__file__))

# (source, output) pairs; each is an independent shared object.
TARGETS = {
    "core": (os.path.join(_DIR, "core.cpp"), os.path.join(_DIR, "_core.so")),
    "checker": (
        os.path.join(_DIR, "checker.cpp"),
        os.path.join(_DIR, "_checker.so"),
    ),
}

# Backwards-compatible aliases (round 1-3 callers import these).
SOURCE, OUTPUT = TARGETS["core"]


def build_one(source: str, output: str, quiet: bool = False) -> bool:
    gxx = shutil.which("g++") or shutil.which("c++")
    if gxx is None:
        if not quiet:
            _log.warning("native build: no C++ compiler found")
        return False
    cmd = [
        gxx,
        "-O2",
        "-std=c++17",
        "-shared",
        "-fPIC",
        "-o",
        output,
        source,
        "-lpthread",
    ]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
    except Exception as e:
        if not quiet:
            _log.warning("native build failed to run", error=repr(e))
        return False
    if proc.returncode != 0:
        if not quiet:
            _log.warning(
                "native build failed", compiler=gxx, stderr=proc.stderr
            )
        return False
    return True


def build(quiet: bool = False, target: str = "core") -> bool:
    """Compile one target; returns True on success."""
    source, output = TARGETS[target]
    return build_one(source, output, quiet)


def build_all(quiet: bool = False) -> dict:
    """Build every target; returns {name: succeeded}."""
    return {name: build(quiet, name) for name in TARGETS}


def is_built(target: str = "core") -> bool:
    source, output = TARGETS[target]
    return os.path.exists(output) and os.path.getmtime(output) >= os.path.getmtime(
        source
    )


if __name__ == "__main__":
    results = build_all()
    for name, (_src, out) in TARGETS.items():
        status = "built " + out if results[name] else "BUILD FAILED"
        print(f"native {name}: {status}")
    sys.exit(0 if all(results.values()) else 1)
