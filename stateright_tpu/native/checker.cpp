// Native threaded visited-key set for the vectorized host BFS engine.
//
// Role parity: the reference's concurrent visited map + work-stealing
// checker threads (src/checker/bfs.rs:29-30, src/job_market.rs:59-182).
// The host engine evaluates model steps as vectorized numpy batches (the
// same lane programs the device runs), so the parallel work here is the
// part numpy cannot do: claim-arbitrated membership over a shared hash
// set. Threads partition each candidate batch and insert via compare-
// exchange — the exact protocol the TPU engine's claim rounds implement
// with scatter/readback, expressed with hardware atomics.
//
// Keys are nonzero uint64 fingerprints (0 = empty slot). Double hashing:
// slot0 = key & mask, stride = (key >> 32) | 1 (odd, so it cycles the
// power-of-two table). The caller keeps the load factor <= 0.5 by growing
// (create a larger set, bulk re-insert) — at that load, probe chains are
// short and a fixed budget suffices; exhaustion is reported, never
// silently dropped.
//
// C ABI only (loaded via ctypes; no pybind11 in this environment).

#include <atomic>
#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <thread>
#include <vector>

namespace {

constexpr int kMaxProbes = 128;

struct KeySet {
  std::vector<std::atomic<uint64_t>> slots;
  std::atomic<uint64_t> count{0};
  uint64_t mask;
  explicit KeySet(uint64_t cap) : slots(cap), mask(cap - 1) {
    for (auto& s : slots) s.store(0, std::memory_order_relaxed);
  }
};

std::mutex g_mu;
std::map<int64_t, KeySet*> g_sets;
int64_t g_next = 1;

KeySet* lookup(int64_t h) {
  std::lock_guard<std::mutex> lk(g_mu);
  auto it = g_sets.find(h);
  return it == g_sets.end() ? nullptr : it->second;
}

// Insert keys[lo, hi) and set out_new[i] = 1 for each claimed key.
// Returns the number of keys whose probe budget was exhausted.
int64_t insert_range(KeySet* ks, const uint64_t* keys, int64_t lo, int64_t hi,
                     uint8_t* out_new) {
  int64_t unresolved = 0;
  uint64_t claimed = 0;
  for (int64_t i = lo; i < hi; i++) {
    uint64_t key = keys[i];
    out_new[i] = 0;
    if (key == 0) continue;  // reserved sentinel; caller remaps
    uint64_t idx = key & ks->mask;
    uint64_t stride = (key >> 32) | 1;
    bool done = false;
    for (int p = 0; p < kMaxProbes; p++) {
      uint64_t cur = ks->slots[idx].load(std::memory_order_relaxed);
      if (cur == key) {
        done = true;  // already visited (or in-batch duplicate lost)
        break;
      }
      if (cur == 0) {
        uint64_t expected = 0;
        if (ks->slots[idx].compare_exchange_strong(
                expected, key, std::memory_order_relaxed)) {
          out_new[i] = 1;
          claimed++;
          done = true;
          break;
        }
        if (expected == key) {  // another thread claimed this very key
          done = true;
          break;
        }
        // Foreign key won the slot; fall through to advance.
      }
      idx = (idx + stride) & ks->mask;
    }
    if (!done) unresolved++;
  }
  ks->count.fetch_add(claimed, std::memory_order_relaxed);
  return unresolved;
}

}  // namespace

extern "C" {

int64_t vset_create(uint64_t capacity) {
  if (capacity == 0 || (capacity & (capacity - 1))) return -1;
  auto* ks = new KeySet(capacity);
  std::lock_guard<std::mutex> lk(g_mu);
  int64_t h = g_next++;
  g_sets[h] = ks;
  return h;
}

void vset_destroy(int64_t h) {
  KeySet* ks = nullptr;
  {
    std::lock_guard<std::mutex> lk(g_mu);
    auto it = g_sets.find(h);
    if (it == g_sets.end()) return;
    ks = it->second;
    g_sets.erase(it);
  }
  delete ks;
}

uint64_t vset_len(int64_t h) {
  KeySet* ks = lookup(h);
  return ks ? ks->count.load(std::memory_order_relaxed) : 0;
}

uint64_t vset_capacity(int64_t h) {
  KeySet* ks = lookup(h);
  return ks ? ks->mask + 1 : 0;
}

// Threaded batch insert. out_new[i] = 1 iff keys[i] claimed a fresh slot
// (exactly one winner among in-batch duplicates). Returns the number of
// unresolved keys (probe budget exhausted; caller must grow and retry) or
// -1 for a bad handle.
int64_t vset_insert_batch(int64_t h, const uint64_t* keys, int64_t n,
                          uint8_t* out_new, int32_t nthreads) {
  KeySet* ks = lookup(h);
  if (!ks) return -1;
  if (nthreads < 1) nthreads = 1;
  if (nthreads == 1 || n < 4096) {
    return insert_range(ks, keys, 0, n, out_new);
  }
  std::vector<std::thread> threads;
  std::vector<int64_t> unresolved(nthreads, 0);
  int64_t chunk = (n + nthreads - 1) / nthreads;
  for (int t = 0; t < nthreads; t++) {
    int64_t lo = t * chunk;
    int64_t hi = lo + chunk < n ? lo + chunk : n;
    if (lo >= hi) break;
    threads.emplace_back([=, &unresolved] {
      unresolved[t] = insert_range(ks, keys, lo, hi, out_new);
    });
  }
  for (auto& th : threads) th.join();
  int64_t total = 0;
  for (auto u : unresolved) total += u;
  return total;
}

}  // extern "C"
