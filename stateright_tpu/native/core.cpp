// Native event-loop core for stateright_tpu's real-network actor runtime.
//
// Role parity: src/actor/spawn.rs:64-154 in the reference — one OS thread
// per actor owning a UDP socket, a deadline map driving timer/random
// interrupts (the socket wait is bounded by the earliest deadline), and
// fire-and-forget datagram sends. Protocol logic stays in the host
// language: every event is delivered through a single callback, and the
// host issues commands back through the srn_* entry points (which are
// safe to call from inside the callback — the mutex is not held across
// callback invocations).
//
// C ABI only (loaded via ctypes; no pybind11 in this environment).

#include <arpa/inet.h>
#include <netinet/in.h>
#include <poll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <cstdint>
#include <cstring>
#include <map>
#include <memory>
#include <mutex>
#include <thread>
#include <vector>

namespace {

using Clock = std::chrono::steady_clock;

double now_s() {
  return std::chrono::duration<double>(Clock::now().time_since_epoch())
      .count();
}

// Event kinds delivered to the host callback.
constexpr int32_t kEventStart = 0;
constexpr int32_t kEventMsg = 1;
constexpr int32_t kEventDeadline = 2;

using srn_event_cb = void (*)(void* ctx, int32_t actor, int32_t kind,
                              uint32_t src_ip, uint16_t src_port,
                              const uint8_t* data, int64_t len, uint64_t key);

struct ActorRt {
  // Atomic: concurrent readers (srn_send) may race the bind-failure
  // writer. Relaxed ordering suffices — the value is only a descriptor
  // number. The descriptor is closed ONLY here, after srn_stop has joined
  // the actor thread (or for a thread that never started) — closing it
  // earlier would let the kernel reuse the number while a concurrent
  // srn_send still holds it, silently writing through an unrelated
  // descriptor.
  std::atomic<int> fd{-1};
  std::mutex mu;
  std::map<uint64_t, double> deadlines;  // key -> absolute deadline (now_s)
  std::thread th;
  ~ActorRt() {
    int f = fd.load(std::memory_order_relaxed);
    if (f >= 0) close(f);
  }
};

struct Runtime {
  std::vector<std::unique_ptr<ActorRt>> actors;
  std::atomic<bool> stop{false};
  srn_event_cb cb = nullptr;
  void* ctx = nullptr;
};

std::mutex g_mu;
std::map<int64_t, Runtime*> g_runtimes;
int64_t g_next_handle = 1;

Runtime* lookup(int64_t handle) {
  std::lock_guard<std::mutex> lk(g_mu);
  auto it = g_runtimes.find(handle);
  return it == g_runtimes.end() ? nullptr : it->second;
}

constexpr size_t kRecvBuf = 65535;  // reference buffer size (spawn.rs:82)
constexpr int kStopPollMs = 50;     // stop-flag responsiveness bound

void actor_loop(Runtime* rt, int32_t index) {
  ActorRt& a = *rt->actors[index];
  const int fd = a.fd.load(std::memory_order_relaxed);
  rt->cb(rt->ctx, index, kEventStart, 0, 0, nullptr, 0, 0);

  std::vector<uint8_t> buf(kRecvBuf);
  while (!rt->stop.load(std::memory_order_relaxed)) {
    // Earliest pending deadline bounds the socket wait (spawn.rs:92-142).
    bool have = false;
    uint64_t due_key = 0;
    double due = 0;
    {
      std::lock_guard<std::mutex> lk(a.mu);
      for (const auto& kv : a.deadlines) {
        if (!have || kv.second < due) {
          have = true;
          due_key = kv.first;
          due = kv.second;
        }
      }
    }
    double now = now_s();
    if (have && due <= now) {
      {
        std::lock_guard<std::mutex> lk(a.mu);
        a.deadlines.erase(due_key);
      }
      rt->cb(rt->ctx, index, kEventDeadline, 0, 0, nullptr, 0, due_key);
      continue;
    }
    int timeout_ms = kStopPollMs;
    if (have) {
      double wait = (due - now) * 1000.0;
      if (wait < timeout_ms) timeout_ms = wait < 1 ? 1 : (int)wait;
    }
    struct pollfd pfd;
    pfd.fd = fd;
    pfd.events = POLLIN;
    int rc = poll(&pfd, 1, timeout_ms);
    if (rc <= 0 || !(pfd.revents & POLLIN)) continue;
    sockaddr_in src{};
    socklen_t srclen = sizeof(src);
    ssize_t n = recvfrom(fd, buf.data(), buf.size(), 0,
                         reinterpret_cast<sockaddr*>(&src), &srclen);
    if (n <= 0) continue;
    rt->cb(rt->ctx, index, kEventMsg, ntohl(src.sin_addr.s_addr),
           ntohs(src.sin_port), buf.data(), n, 0);
  }
  // The descriptor stays open (and a.fd set) until ~ActorRt runs after
  // srn_stop joins this thread — see the lifecycle note on ActorRt.
}

}  // namespace

extern "C" {

// Starts one thread+socket per actor. ips are host-order IPv4 addresses.
// Returns a handle (> 0), or -1-errno_index on bind failure.
int64_t srn_start(const uint32_t* ips, const uint16_t* ports, int32_t n,
                  srn_event_cb cb, void* ctx) {
  auto rt = std::make_unique<Runtime>();
  rt->cb = cb;
  rt->ctx = ctx;
  for (int32_t i = 0; i < n; i++) {
    auto a = std::make_unique<ActorRt>();
    a->fd = socket(AF_INET, SOCK_DGRAM, 0);
    if (a->fd < 0) return -1 - i;
    int one = 1;
    setsockopt(a->fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(ips[i]);
    addr.sin_port = htons(ports[i]);
    if (bind(a->fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0) {
      // ~ActorRt releases this socket (and rt's destructor the others).
      return -1 - i;
    }
    rt->actors.push_back(std::move(a));
  }
  Runtime* raw = rt.release();
  int64_t handle;
  {
    std::lock_guard<std::mutex> lk(g_mu);
    handle = g_next_handle++;
    g_runtimes[handle] = raw;
  }
  for (int32_t i = 0; i < n; i++) {
    raw->actors[i]->th = std::thread(actor_loop, raw, i);
  }
  return handle;
}

void srn_send(int64_t handle, int32_t actor, uint32_t dst_ip,
              uint16_t dst_port, const uint8_t* data, int64_t len) {
  Runtime* rt = lookup(handle);
  if (!rt || actor < 0 || (size_t)actor >= rt->actors.size()) return;
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(dst_ip);
  addr.sin_port = htons(dst_port);
  // Fire-and-forget (spawn.rs:188-196): errors intentionally ignored.
  int fd = rt->actors[actor]->fd.load(std::memory_order_relaxed);
  if (fd < 0) return;  // actor already shut down
  sendto(fd, data, (size_t)len, 0, reinterpret_cast<sockaddr*>(&addr),
         sizeof(addr));
}

void srn_set_deadline(int64_t handle, int32_t actor, uint64_t key,
                      double delay_s) {
  Runtime* rt = lookup(handle);
  if (!rt || actor < 0 || (size_t)actor >= rt->actors.size()) return;
  ActorRt& a = *rt->actors[actor];
  std::lock_guard<std::mutex> lk(a.mu);
  a.deadlines[key] = now_s() + delay_s;
}

void srn_cancel_deadline(int64_t handle, int32_t actor, uint64_t key) {
  Runtime* rt = lookup(handle);
  if (!rt || actor < 0 || (size_t)actor >= rt->actors.size()) return;
  ActorRt& a = *rt->actors[actor];
  std::lock_guard<std::mutex> lk(a.mu);
  a.deadlines.erase(key);
}

// Stops all actor threads and frees the runtime.
void srn_stop(int64_t handle) {
  Runtime* rt = nullptr;
  {
    std::lock_guard<std::mutex> lk(g_mu);
    auto it = g_runtimes.find(handle);
    if (it == g_runtimes.end()) return;
    rt = it->second;
    g_runtimes.erase(it);
  }
  rt->stop.store(true);
  for (auto& a : rt->actors) {
    if (a->th.joinable()) a->th.join();
  }
  delete rt;
}

}  // extern "C"
