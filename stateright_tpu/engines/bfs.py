"""Host breadth-first search engine.

Reference: src/checker/bfs.rs. Exhaustive BFS with parent-pointer path
reconstruction: the visited map stores fingerprint -> parent fingerprint
(None for initial states), and discoveries are reconstructed by walking the
parent chain and re-executing the model along it (bfs.rs:380-409, the TLC
technique). Queue discipline matches the reference exactly — jobs pop from
the back, successors push to the front (FIFO) — so visit-order goldens and
early-exit state counts are reproducible.
"""

from __future__ import annotations

from collections import deque
from typing import Any, Dict, Optional

from ..checker import CheckerBuilder
from ..path import Path
from .common import BLOCK_SIZE, HostEngineBase


class BfsChecker(HostEngineBase):
    def __init__(self, builder: CheckerBuilder):
        super().__init__(builder)
        model = self._model

        init_states = [s for s in model.init_states() if model.within_boundary(s)]
        self._state_count = len(init_states)
        # visited: fingerprint -> Optional[parent fingerprint] (bfs.rs:29-30)
        self._generated: Dict[int, Optional[int]] = {}
        for s in init_states:
            fp = self._fp(s)
            if fp not in self._generated and self._sampler is not None:
                self._sampler.offer(fp, depth=1, state=s)
            self._generated.setdefault(fp, None)
        self._coverage.record_depth(1, len(self._generated))
        # job: (state, fingerprint, ebits, depth) (bfs.rs:33)
        self._pending = deque(
            (s, self._fp(s), self._init_ebits, 1) for s in init_states
        )
        self._discoveries: Dict[str, int] = {}  # property name -> fingerprint
        self._start()

    # -- exploration --------------------------------------------------------

    def _run(self) -> None:
        while True:
            if not self._pending:
                return  # work exhausted
            with self._metrics.phase("check_block"):
                self._check_block()
            self._metrics.inc("waves")
            self._obs_event("wave", frontier=len(self._pending))
            if self._finish_matched(self._discoveries):
                return
            if (
                self._target_state_count is not None
                and self._state_count >= self._target_state_count
            ):
                return
            if self._timed_out():
                return

    def _check_block(self) -> None:
        """Process up to BLOCK_SIZE states. Mirrors bfs.rs:177-335."""
        model = self._model
        pending = self._pending
        generated = self._generated
        discoveries = self._discoveries

        for _ in range(BLOCK_SIZE):
            if not pending:
                return
            state, state_fp, ebits, depth = pending.pop()

            if depth > self._max_depth:
                self._max_depth = depth
            if self._target_max_depth is not None and depth >= self._target_max_depth:
                continue
            if self._visitor is not None:
                self._visitor.visit(model, self._reconstruct_path(state_fp))

            ebits, is_awaiting = self._check_properties(
                state, ebits, discoveries, lambda: state_fp
            )
            if not is_awaiting:
                return  # discoveries found for all properties (bfs.rs:278-280)

            # Expand successors.
            cov = self._coverage if self._coverage.enabled else None
            is_terminal = True
            actions: list = []
            model.actions(state, actions)
            for action in actions:
                next_state = model.next_state(state, action)
                if next_state is None:
                    continue
                if not model.within_boundary(next_state):
                    continue
                self._state_count += 1
                if cov is not None:
                    cov.record_action(self._action_label(action))
                next_fp = self._fp(next_state)
                if next_fp in generated:
                    # Revisit: could be a cycle or a DAG join; treated as
                    # non-terminal (documented false-negative, bfs.rs:302-315).
                    is_terminal = False
                    continue
                generated[next_fp] = state_fp
                if self._sampler is not None:
                    self._sampler.offer(
                        next_fp,
                        depth=depth + 1,
                        action=action,
                        state=next_state,
                        pred=state,
                    )
                if cov is not None:
                    cov.record_depth(depth + 1)
                is_terminal = False
                pending.appendleft((next_state, next_fp, ebits, depth + 1))
            if is_terminal:
                self._terminal_ebit_discoveries(
                    ebits, discoveries, lambda: state_fp
                )

    # -- accessors ----------------------------------------------------------

    def unique_state_count(self) -> int:
        return len(self._generated)

    def discoveries(self) -> Dict[str, Path]:
        return {
            name: self._reconstruct_path(fp)
            for name, fp in list(self._discoveries.items())
        }

    def _reconstruct_path(self, fp: int) -> Path:
        """Walk parent pointers back to an init state, then re-execute the
        model along the fingerprint chain (bfs.rs:380-409)."""
        fingerprints: deque = deque()
        next_fp: Optional[int] = fp
        while next_fp is not None and next_fp in self._generated:
            fingerprints.appendleft(next_fp)
            next_fp = self._generated[next_fp]
        return Path.from_fingerprints(self._model, list(fingerprints))
