"""Shared scaffolding for the host engines.

Mirrors the per-thread structure of the reference engines (spawn → background
work loop → block processing with finish_when checks between 1500-state
blocks; src/checker/bfs.rs:90-164, dfs.rs:93-168). CPython threads provide the
same lifecycle semantics (join/report polling) even though the GIL serializes
Python-level work; the parallel hot paths live in the TPU engine and the
native core.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

from ..checker import Checker, CheckerBuilder
from ..core import Expectation
from ..obs.coverage import Coverage
from ..obs.flight import FlightRecorder
from ..obs.log import get_logger
from ..obs.memory import MemoryRecorder
from ..obs.metrics import MetricsRegistry
from ..obs.trace import make_trace_writer, start_profile, stop_profile

BLOCK_SIZE = 1500  # states per finish_when re-check; reference bfs.rs:130

# Auto-N fusion (ISSUE 20): `_fuse_auto_n` needs this much flight history
# before trusting host_gap_pct, re-evaluates at this era cadence, and
# halves the factor only when the gap is already below this share of the
# wall clock (fusion has nothing left to amortize there).
FUSE_AUTO_MIN_ERAS = 8
FUSE_AUTO_RECHECK_ERAS = 8
FUSE_AUTO_LOW_GAP_PCT = 2.0

_log = get_logger("engines.common")


class HostEngineBase(Checker):
    """Common counters, lifecycle, and property bookkeeping for host engines."""

    # Host engines run one Python worker; parallel checking is the device
    # engine's job. Engines that genuinely parallelize set this True.
    _supports_threads = False

    def __init__(self, builder: CheckerBuilder, model=None):
        if builder.thread_count_ > 1 and not self._supports_threads:
            raise NotImplementedError(
                f"{type(self).__name__} is single-threaded; "
                "state-space parallelism lives in the batched device engine "
                "(CheckerBuilder.spawn_tpu_bfs). Drop .threads(n) or use the "
                "device engine."
            )
        # `model` lets engines that wrap the builder's model (e.g. a raw
        # TensorModel into its adapter) pass the WRAPPED model through
        # without mutating the caller's builder — a builder constructed
        # directly over a raw TensorModel must not crash in this base
        # constructor on the raw object's missing Model API.
        self._model = model if model is not None else builder.model
        self._properties = builder.model.properties()
        self._symmetry = builder.symmetry_fn_
        self._target_state_count = builder.target_state_count_
        self._target_max_depth = builder.target_max_depth_
        self._visitor = builder.visitor_
        self._finish_when = builder.finish_when_
        self._timeout = builder.timeout_
        self._thread_count = builder.thread_count_

        self._state_count = 0
        self._max_depth = 0
        # Observability: one metrics registry per run (obs/metrics.py) backs
        # Checker.telemetry() for every engine; an optional JSONL trace
        # stream and jax.profiler bracket ride the builder options.
        self._metrics = MetricsRegistry()
        # Speclint pre-flight (stateright_tpu.analysis): in strict mode the
        # engine refuses to launch over error-severity findings; whenever a
        # report exists (strict auto-run or an explicit builder.lint()),
        # its diagnostic counts ride the metrics registry into telemetry.
        self._lint_preflight(builder)
        # Coverage accumulator (obs/coverage.py): per-action fire counts,
        # per-depth unique-state histogram, per-property eval/hit counts,
        # and dead-action detection — populated by every engine, surfaced
        # via Checker.coverage(). Tensor-backed models register their full
        # action universe up front (that is what makes a zero count a DEAD
        # action rather than merely an unobserved one).
        self._coverage = Coverage(enabled=getattr(builder, "coverage_", True))
        self._coverage.register_properties(p.name for p in self._properties)
        tm = getattr(self._model, "tm", None)
        if tm is not None and hasattr(tm, "max_actions"):
            self._coverage.register_actions(
                tm.format_action(a) for a in range(tm.max_actions)
            )
        self._action_label_memo: Dict[Any, str] = {}
        trace_path = getattr(builder, "trace_path_", None)
        self._trace = (
            make_trace_writer(
                trace_path,
                engine=type(self).__name__,
                format=getattr(builder, "trace_format_", "jsonl"),
            )
            if trace_path
            else None
        )
        self._profile_dir: Optional[str] = getattr(builder, "profile_dir_", None)
        # Flight recorder (obs/flight.py): bounded ring of per-era records
        # — device_era vs host_gap wall split plus frontier/table/spill
        # counters — fed by each device engine at its existing once-per-era
        # packed-params readback. Host engines carry the (empty) recorder
        # too so Checker.flight() and telemetry stay uniform.
        self._flight = (
            FlightRecorder(
                capacity=getattr(builder, "flight_capacity_", 4096),
                engine=type(self).__name__,
            )
            if getattr(builder, "flight_", True)
            else None
        )
        self._flight_path: Optional[str] = getattr(builder, "flight_path_", None)
        self._flight_format: str = getattr(builder, "flight_format_", "jsonl")
        self._flight_prev_counters: Dict[str, int] = {}
        # Memory recorder (obs/memory.py): exact per-component ledger of
        # device allocations + growth forecaster. Device engines register
        # their buffers after seeding and feed it at the same per-era
        # readback as the flight recorder; host engines carry the (empty)
        # recorder so telemetry()["memory"] stays uniform.
        self._memory = (
            MemoryRecorder(engine=type(self).__name__, metrics=self._metrics)
            if getattr(builder, "memory_", True)
            else None
        )
        # Space sampler (obs/sample.py): deterministic bottom-k
        # fingerprint sample of the explored space. Host engines offer at
        # visited-insertion; device engines drain their on-device
        # candidate slab at the per-era readback. The sample set is a
        # pure function of the explored set, so every engine over the
        # same model keeps the identical sample.
        from ..obs.sample import DEFAULT_SAMPLE_K, SpaceSampler

        self._sampler = (
            SpaceSampler(k=getattr(builder, "sample_k_", DEFAULT_SAMPLE_K))
            if getattr(builder, "sample_", True)
            else None
        )
        self._space_profile_cache: Optional[Dict[str, Any]] = None
        # Span ledger (obs/spans.py) via CheckerBuilder.spans(): the whole
        # run becomes one "run" span with phase-timer children; the run
        # span's id is pre-assigned so per-era progress spans can parent to
        # it before it is sealed in _run_guarded's finally.
        self._spans = getattr(builder, "span_recorder_", None)
        if self._spans is not None:
            from ..obs.spans import new_span_id, new_trace_id

            self._span_trace_id = (
                getattr(builder, "span_trace_id_", None) or new_trace_id()
            )
            self._span_parent_id = getattr(builder, "span_parent_id_", None)
            self._span_run_id = new_span_id()
        else:
            self._span_trace_id = None
            self._span_parent_id = None
            self._span_run_id = None
        self._span_run_start: Optional[float] = None
        self._span_last_event: Optional[float] = None
        self._last_phase_ms: Dict[str, float] = {}
        self._done = threading.Event()
        # Graceful-stop request (SIGTERM/SIGINT flush, see
        # install_signal_checkpoint_flush below): checkpointing engines poll
        # this at era boundaries, flush a final checkpoint, and exit clean.
        self._ckpt_stop = threading.Event()
        self._error: Optional[BaseException] = None
        self._deadline = (
            time.monotonic() + self._timeout if self._timeout is not None else None
        )

        # Eventually-property bitmask: bit i set <=> property i is an
        # eventually property not yet satisfied on the current path
        # (reference EventuallyBits, checker.rs:580-587).
        self._init_ebits = 0
        for i, p in enumerate(self._properties):
            if p.expectation == Expectation.EVENTUALLY:
                self._init_ebits |= 1 << i

        self._thread: Optional[threading.Thread] = None
        # Pre-run snapshot for deterministic first "Checking." report lines;
        # engines refresh it after seeding counts, before starting the thread.
        self._initial_snapshot = (0, 0, 0)

    def _lint_preflight(self, builder: CheckerBuilder) -> None:
        report = getattr(builder, "lint_report_", None)
        if getattr(builder, "strict_", False) and report is None:
            report = builder.lint(samples=getattr(builder, "strict_samples_", 128))
        if report is None:
            return
        for code, n in report.counts_by_code().items():
            self._metrics.inc(f"lint_{code}", n)
        self._metrics.set_gauge("lint_errors", len(report.errors))
        self._metrics.set_gauge("lint_warnings", len(report.warnings))
        if getattr(builder, "strict_", False) and not report.ok:
            from ..analysis import SpecLintError

            raise SpecLintError(report)

    # -- lifecycle ----------------------------------------------------------

    def _start(self) -> None:
        self._initial_snapshot = (self._state_count, self.unique_state_count(), 0)
        self._thread = threading.Thread(target=self._run_guarded, daemon=True)
        self._thread.start()

    def _run_guarded(self) -> None:
        profiling = (
            start_profile(self._profile_dir) if self._profile_dir else False
        )
        if self._spans is not None:
            self._span_run_start = time.time()
            self._span_last_event = self._span_run_start
        if self._trace is not None:
            self._trace.emit(
                "run_start",
                states=int(self._state_count),
                unique=int(self.unique_state_count()),
            )
        if self._flight is not None:
            self._flight.start()
        try:
            self._run()
        except BaseException as e:  # surfaces at join(), like a Rust panic
            self._error = e
        finally:
            if profiling:
                stop_profile()
            if self._trace is not None:
                self._trace.emit(
                    "run_end",
                    states=int(self._state_count),
                    unique=int(self.unique_state_count()),
                    max_depth=int(self._max_depth),
                    phase_ms=self._metrics.phase_ms(),
                    error=repr(self._error) if self._error else None,
                    **(
                        {"space": self._sampler.snapshot()}
                        if self._sampler is not None and self._sampler.size()
                        else {}
                    ),
                )
            self._flush_flight()
            if self._spans is not None:
                self._seal_run_span()
            if self._trace is not None:
                self._trace.close()
            self._done.set()

    def _flush_flight(self) -> None:
        """At run end: export the flight recording if a path was
        configured, and append its counter tracks to a Chrome-format run
        trace so Perfetto lines them up under the phase lanes. Must run
        before ``self._trace.close()``."""
        fr = self._flight
        if fr is None or not len(fr):
            return
        if self._flight_path:
            try:
                if self._flight_format == "chrome":
                    fr.export_chrome(self._flight_path)
                else:
                    fr.export_jsonl(self._flight_path)
            except OSError as exc:
                _log.warning(
                    "flight export failed",
                    path=self._flight_path,
                    error=repr(exc),
                )
        if self._trace is not None and hasattr(self._trace, "write_counter_events"):
            self._trace.write_counter_events(fr.chrome_counter_events())

    def _seal_run_span(self) -> None:
        """Record the run span (pre-assigned id, so per-era children are
        already parented to it), attach one child span per phase timer,
        and — when the run also wrote a Chrome trace — embed the ledger
        into the trace file so phases and request spans share one
        Perfetto timeline."""
        from ..obs.spans import attach_phase_spans

        end = time.time()
        attach_phase_spans(
            self._spans,
            self._metrics.phase_ms(),
            trace_id=self._span_trace_id,
            parent_id=self._span_run_id,
            end=end,
            attributes={"engine": type(self).__name__},
        )
        self._spans.record(
            "run",
            start=self._span_run_start or end,
            end=end,
            trace_id=self._span_trace_id,
            span_id=self._span_run_id,
            parent_id=self._span_parent_id,
            status="error" if self._error else "ok",
            attributes={
                "engine": type(self).__name__,
                "states": int(self._state_count),
                "unique": int(self.unique_state_count()),
                "max_depth": int(self._max_depth),
                **({"error": repr(self._error)} if self._error else {}),
            },
        )
        if self._trace is not None and hasattr(self._trace, "embed_spans"):
            self._trace.embed_spans(self._spans.spans(self._span_trace_id))

    def _run(self) -> None:
        raise NotImplementedError

    def join(self) -> "HostEngineBase":
        if self._thread is not None:
            self._thread.join()
        if self._error is not None:
            raise self._error
        return self

    def is_done(self) -> bool:
        return self._done.is_set()

    def request_checkpoint_stop(self) -> None:
        """Ask the run to stop at its next era/block boundary, flushing a
        final checkpoint first (checkpointing engines poll this; engines
        without checkpoint support simply finish their run). Thread- and
        signal-safe: only sets an event."""
        self._ckpt_stop.set()

    def interrupted(self) -> bool:
        """True when the run stopped early on a graceful-stop request
        (SIGTERM/SIGINT flush or an explicit request_checkpoint_stop)."""
        return self._ckpt_stop.is_set() and self._done.is_set()

    # -- counters -----------------------------------------------------------

    def state_count(self) -> int:
        return self._state_count

    def max_depth(self) -> int:
        return self._max_depth

    # -- observability ------------------------------------------------------

    def telemetry(self) -> Dict[str, Any]:
        """The run's metrics-registry snapshot (counters + gauges +
        cumulative phase_ms; names catalogued in obs/metrics.py)."""
        if self._coverage.enabled:
            acts = self._coverage.action_counts()
            self._metrics.set_gauge(
                "coverage_actions_fired", sum(1 for v in acts.values() if v)
            )
            if self._coverage.action_labels is not None:
                self._metrics.set_gauge(
                    "coverage_dead_actions", len(self._coverage.dead_actions())
                )
        if self._sampler is not None and self._sampler.size():
            self._sampler.set_gauges(self._metrics)
        snap = self._metrics.snapshot()
        if self._flight is not None:
            fsum = self._flight.summary()
            if fsum["eras"]:
                snap["flight"] = fsum
        if self._memory is not None and self._memory.ledger.components():
            snap["memory"] = self._memory.snapshot()
        if self._sampler is not None and self._sampler.size():
            snap["space"] = self._sampler.snapshot()
        program = self._program_snapshot(snap)
        if program:
            snap["program"] = program
        snap["engine"] = type(self).__name__
        return snap

    def _program_snapshot(self, snap: Dict[str, Any]) -> Dict[str, Any]:
        """The STR6xx static program summary for this run's model, when a
        program-lint pass has produced one this process (a cached dict
        lookup — this NEVER traces or compiles), with the flight
        recorder's measured rate beside the STR606 prediction as an
        attribution ratio: measured/predicted ≈ 1 means the roofline
        explains the run; << 1 means dispatch gap or host stalls own it."""
        tm = getattr(self._model, "tm", None)
        if tm is None:
            return {}
        try:
            from ..analysis.program import cached_summary
            from .compiled import model_signature

            summary = cached_summary(model_signature(tm))
        except Exception:
            return {}
        if not summary:
            return {}
        era = summary.get("programs", {}).get("era_loop", {})
        out: Dict[str, Any] = {
            "signature": summary.get("signature"),
            "era_ops": era.get("ops"),
            "era_distinct_ops": era.get("distinct"),
        }
        cost = summary.get("cost") or {}
        if cost:
            out.update(
                {
                    "flops_per_step": cost.get("flops_per_step"),
                    "bytes_per_step": cost.get("bytes_per_step"),
                    "hbm_gbps": cost.get("hbm_gbps"),
                }
            )
            predicted = cost.get("predicted_states_per_sec")
            if predicted:
                out["predicted_states_per_sec"] = predicted
                wall = (snap.get("flight") or {}).get("wall_secs") or 0.0
                if wall > 0 and self._state_count:
                    measured = self._state_count / wall
                    out["measured_states_per_sec"] = measured
                    out["attribution_ratio"] = measured / predicted
        return out

    def coverage(self) -> Dict[str, Any]:
        """The run's coverage snapshot (obs/coverage.py)."""
        return self._coverage.snapshot()

    def flight(self) -> list:
        """Retained flight records (obs/flight.py), oldest first. Empty
        for engines without an era loop or when .flight(False) was set."""
        return self._flight.records() if self._flight is not None else []

    def _sample_resolver(self):
        """fp64 -> {"state","pred","action"} backfill for samples drained
        fingerprint-only (device engines override with their path
        reconstructor); None means rows were captured at offer time."""
        return None

    def _path_sample_resolver(self, reconstruct):
        """Wrap an fp -> Path reconstructor into a sample resolver: the
        path's final state is the sample, its last step the (pred,
        action) exemplar transition, its length the BFS depth."""

        def resolve(fp: int):
            path = reconstruct(fp)
            pairs = path.into_vec()
            out = {"state": pairs[-1][0], "depth": len(pairs)}
            if len(pairs) >= 2:
                out["pred"], out["action"] = pairs[-2]
            return out

        return resolve

    def space_profile(self) -> Dict[str, Any]:
        """The run's space profile (obs/sample.py): the bottom-k sample
        rendered into field sketches, depth/action exemplars, and
        saturation warnings. Built on demand; cached once the run is
        done (device engines resolve sample rows via path
        reconstruction, which is worth doing once, not per poll)."""
        if self._sampler is None or not self._sampler.size():
            return {}
        if self._space_profile_cache is not None:
            return self._space_profile_cache
        from ..obs.sample import build_space_profile

        profile = build_space_profile(
            self._model, self._sampler, resolver=self._sample_resolver()
        )
        if self.is_done():
            self._space_profile_cache = profile
        return profile

    def _flight_record(
        self,
        *,
        device_era_secs: float,
        steps: int = 0,
        generated: int = 0,
        unique: int = 0,
        frontier: int = 0,
        load_factor: float = 0.0,
        take_cap: int = 0,
        spill_rows: int = 0,
        shards: Optional[Dict[str, Any]] = None,
        grow_rows: Optional[int] = None,
        inner: Optional[List[Dict[str, Any]]] = None,
    ) -> None:
        """Append one era to the flight recording (no-op when disabled).
        Registry counters that move off the hot path (refill/grow/
        checkpoint) are diffed against the previous era here, so engines
        don't have to thread per-era volumes through their loops.
        ``grow_rows`` is what the engine's table-grow trigger compares
        (max per-shard unique on the mesh); the memory forecaster fits
        its growth curve to it, defaulting to ``unique``. ``inner`` is
        the per-inner-era attribution of a FUSED dispatch (fields per
        FlightRecorder.record_fused): the one readback then appends
        len(inner) records, with the once-per-dispatch counters on the
        last."""
        mem = None
        if self._memory is not None:
            mem = self._memory.on_era(
                unique=unique, load_factor=load_factor, grow_rows=grow_rows
            )
        fr = self._flight
        if fr is None:
            return
        cur = {
            name: self._metrics.get(name)
            for name in ("refill_rows", "table_growths", "checkpoint_saves")
        }
        prev = self._flight_prev_counters
        self._flight_prev_counters = cur
        if inner is not None and len(inner) > 1:
            rec = fr.record_fused(
                device_era_secs=device_era_secs,
                inner=inner,
                take_cap=take_cap,
                spill_rows=spill_rows,
                refill_rows=cur["refill_rows"] - prev.get("refill_rows", 0),
                table_growths=(
                    cur["table_growths"] - prev.get("table_growths", 0)
                ),
                checkpoint_saves=(
                    cur["checkpoint_saves"]
                    - prev.get("checkpoint_saves", 0)
                ),
                shards=shards,
                memory=mem,
            )
        else:
            rec = fr.record(
                device_era_secs=device_era_secs,
                steps=steps,
                generated=generated,
                unique=unique,
                frontier=frontier,
                load_factor=load_factor,
                take_cap=take_cap,
                spill_rows=spill_rows,
                refill_rows=cur["refill_rows"] - prev.get("refill_rows", 0),
                table_growths=(
                    cur["table_growths"] - prev.get("table_growths", 0)
                ),
                checkpoint_saves=(
                    cur["checkpoint_saves"]
                    - prev.get("checkpoint_saves", 0)
                ),
                shards=shards,
                memory=mem,
            )
        # Flat twins of the latest record for Prometheus (nested dicts are
        # skipped by render_prometheus) and the SSE metrics deltas.
        m = self._metrics
        m.set_gauge("flight_eras", rec["era"])
        m.set_gauge("flight_device_era_secs", rec["device_era_secs"])
        m.set_gauge("flight_host_gap_secs", rec["host_gap_secs"])

    def _fuse_auto_n(self, fuse: int) -> int:
        """Auto-N fusion pick (ISSUE 20 satellite of ROADMAP item 1a):
        instead of pinning the compiled maximum, choose the inner-era cap
        from recent flight history. A high host gap means the dispatch
        gap dominates — run the full factor; a near-zero gap means fusion
        has little left to amortize, so halve the exposure to mid-dispatch
        overshoot (never below 2: one compiled program serves every N and
        a degrade-to-1 already has its own triggers in `_fuse_lim_now`).
        Recomputed every FUSE_AUTO_RECHECK_ERAS eras — `summary()` walks
        the whole recording. The chosen N lands on the `fuse_auto_n`
        gauge (gate-tracked in bench history)."""
        eras = self._metrics.get("eras")
        cached = getattr(self, "_fuse_auto_cache", None)
        if cached is not None and eras - cached[0] < FUSE_AUTO_RECHECK_ERAS:
            return cached[1]
        n = fuse
        fr = self._flight
        if fr is not None:
            s = fr.summary()
            if s.get("eras", 0) >= FUSE_AUTO_MIN_ERAS:
                gap = float(s.get("host_gap_pct") or 0.0)
                if gap < FUSE_AUTO_LOW_GAP_PCT:
                    n = max(2, fuse // 2)
        self._fuse_auto_cache = (eras, n)
        self._metrics.set_gauge("fuse_auto_n", n)
        return n

    def _action_label(self, action: Any) -> str:
        """Memoized model.format_action — hot-loop action attribution must
        not re-format per generated successor. Unhashable actions fall
        back to formatting each time."""
        try:
            label = self._action_label_memo.get(action)
            if label is None:
                label = self._model.format_action(action)
                self._action_label_memo[action] = label
            return label
        except TypeError:
            return self._model.format_action(action)

    def _phase_ms_delta(self) -> Dict[str, float]:
        """Per-event phase-timer deltas (ms since the previous trace
        event) — what the JSONL era/wave events carry."""
        cur = self._metrics.phase_ms()
        last = self._last_phase_ms
        self._last_phase_ms = cur
        return {
            k: round(v - last.get(k, 0.0), 3)
            for k, v in cur.items()
            if v - last.get(k, 0.0) > 0.0 or k not in last
        }

    def _obs_event(self, event: str, frontier: int = 0, **extra: Any) -> None:
        """Record one unit of forward progress: refresh the standard gauges
        and, when tracing, emit the JSONL event for it."""
        m = self._metrics
        m.set_gauge("frontier_size", int(frontier))
        m.set_gauge("max_depth", int(self._max_depth))
        if self._spans is not None:
            # One progress span per era/wave/round, spanning the gap since
            # the previous progress event, under the run span.
            now = time.time()
            self._spans.record(
                event,
                start=self._span_last_event or now,
                end=now,
                trace_id=self._span_trace_id,
                parent_id=self._span_run_id,
                attributes={
                    "states": int(self._state_count),
                    "unique": int(self.unique_state_count()),
                    "frontier": int(frontier),
                },
            )
            self._span_last_event = now
        if self._trace is not None:
            if self._coverage.enabled and "coverage" not in extra:
                # Cumulative per-action fire counts ride every progress
                # event, so a trace alone reconstructs coverage over time.
                extra["coverage"] = {"actions": self._coverage.action_counts()}
            self._trace.emit(
                event,
                states=int(self._state_count),
                unique=int(self.unique_state_count()),
                frontier=int(frontier),
                max_depth=int(self._max_depth),
                phase_ms=self._phase_ms_delta(),
                **extra,
            )

    # -- shared helpers -----------------------------------------------------

    def _timed_out(self) -> bool:
        return self._deadline is not None and time.monotonic() >= self._deadline

    def _fp(self, state: Any) -> int:
        return self._model.fingerprint_state(state)

    def _check_properties(
        self, state: Any, ebits: int, discoveries: Dict[str, Any], discovery_value
    ) -> tuple[int, bool]:
        """Evaluate all properties on one state being processed.

        Returns (updated ebits, is_awaiting_discoveries). Inserts discoveries
        for failed always / satisfied sometimes properties. Mirrors the
        property loop at bfs.rs:231-277 / dfs.rs:235-281.
        """
        model = self._model
        cov = self._coverage if self._coverage.enabled else None
        is_awaiting = False
        for i, prop in enumerate(self._properties):
            if prop.name in discoveries:
                continue
            if cov is not None:
                cov.record_property_eval(prop.name)
            if prop.expectation == Expectation.ALWAYS:
                if not prop.condition(model, state):
                    discoveries[prop.name] = discovery_value()
                    if cov is not None:
                        cov.record_property_hit(prop.name)
                else:
                    is_awaiting = True
            elif prop.expectation == Expectation.SOMETIMES:
                if prop.condition(model, state):
                    discoveries[prop.name] = discovery_value()
                    if cov is not None:
                        cov.record_property_hit(prop.name)
                else:
                    is_awaiting = True
            else:  # EVENTUALLY: discoveries only arise at terminal states
                is_awaiting = True
                if prop.condition(model, state):
                    ebits &= ~(1 << i)
        return ebits, is_awaiting

    def _terminal_ebit_discoveries(
        self, ebits: int, discoveries: Dict[str, Any], discovery_value
    ) -> None:
        """At a terminal state, any surviving eventually-bit is a counterexample
        (bfs.rs:326-333)."""
        if not ebits:
            return
        for i, prop in enumerate(self._properties):
            if ebits & (1 << i):
                discoveries[prop.name] = discovery_value()
                if self._coverage.enabled:
                    self._coverage.record_property_hit(prop.name)

    def _finish_matched(self, discoveries: Dict[str, Any]) -> bool:
        return self._finish_when.matches(set(discoveries), self._properties)


# -- checkpoint metadata (shared by the device engines) ----------------------

FP_VER = 2  # round-4 decorrelated hash pair (fingerprint.py mix note)


def checkpoint_meta(tm, tprops, **fields) -> dict:
    """Common identity header for engine checkpoints: fingerprint version,
    model class + parameter digest, and property set — a resumed table is
    only meaningful for the exact model, properties, and hash that wrote
    it. Engine-specific fields are passed through."""
    meta = {
        "fp_ver": FP_VER,
        "model": f"{type(tm).__module__}.{type(tm).__qualname__}",
        "model_config": tm.config_digest(),
        "prop_names": [p.name for p in tprops],
        "state_width": tm.state_width,
    }
    meta.update(fields)
    return meta


def validate_checkpoint_meta(meta: dict, tm, tprops, exact: dict) -> None:
    """Reject a checkpoint whose identity or layout does not match this
    checker. `exact` maps field name -> required value (qcap, n_shards,
    chunk, quota, ...); every listed field must match exactly."""
    if meta.get("fp_ver") != FP_VER:
        raise ValueError(
            "checkpoint was written with a different fingerprint hash "
            f"version ({meta.get('fp_ver')!r} != {FP_VER}); its table keys "
            "are incompatible"
        )
    this_model = f"{type(tm).__module__}.{type(tm).__qualname__}"
    if meta.get("model") != this_model:
        raise ValueError(
            f"checkpoint was written by model {meta.get('model')!r}; "
            f"resuming it with {this_model!r} would silently produce wrong "
            "results"
        )
    if meta.get("model_config") != tm.config_digest():
        raise ValueError(
            f"checkpoint was written with model config "
            f"{meta.get('model_config')!r}; this instance has "
            f"{tm.config_digest()!r} — same-width different-parameter "
            "models must not share a visited table"
        )
    this_props = [p.name for p in tprops]
    if meta.get("prop_names") != this_props:
        raise ValueError(
            f"checkpoint property set {meta.get('prop_names')} does not "
            f"match this checker's {this_props}; rec_fp/rec_bits would "
            "misalign"
        )
    for field, want in exact.items():
        if meta.get(field) != want:
            raise ValueError(
                f"checkpoint {field}={meta.get(field)!r} does not match "
                f"this checker's {want!r}; resume with matching engine "
                "options"
            )


# -- crash-safe checkpoint IO (shared by the device engines) ------------------
#
# The write protocol: serialize to `<path>.tmp.npz`, fsync the file, rotate
# the previous generations (`<path>` -> `<path>.1` -> ... -> `<path>.N-1`),
# rename the tmp over `<path>`, and fsync the directory so the rename itself
# survives a crash. Every checkpoint carries a sha256 content digest in its
# meta; the loader recomputes it and rejects truncated/corrupt files with
# CheckpointCorruptError, falling back to the previous good generation.


class CheckpointCorruptError(RuntimeError):
    """A checkpoint file is unreadable, truncated, or fails its digest."""


def validate_checkpoint_cadence(checkpoint_every, checkpoint_path,
                                keep_checkpoints) -> None:
    """Builder-time validation of the checkpoint knobs, shared by the
    device engines. `checkpoint_every` is wall-clock SECONDS between
    periodic checkpoints (polled at era boundaries); non-positive values
    are a configuration error, not "checkpoint constantly"."""
    if checkpoint_every is not None:
        if checkpoint_path is None:
            raise ValueError(
                "checkpoint_every requires checkpoint_path (nothing would "
                "be written otherwise)"
            )
        if not float(checkpoint_every) > 0.0:
            raise ValueError(
                "checkpoint_every is wall-clock seconds between periodic "
                f"checkpoints and must be positive (got {checkpoint_every!r}); "
                "omit it to checkpoint only at run end"
            )
    if keep_checkpoints < 1:
        raise ValueError(
            f"keep_checkpoints must be >= 1 (got {keep_checkpoints})"
        )


def _checkpoint_digest(arrays: dict) -> str:
    """sha256 over every payload array's name, dtype, shape, and bytes
    (sorted by name; the meta array itself is excluded — it carries the
    digest)."""
    import hashlib

    import numpy as np

    h = hashlib.sha256()
    for name in sorted(arrays):
        if name == "meta":
            continue
        a = np.ascontiguousarray(arrays[name])
        h.update(name.encode())
        h.update(str(a.dtype).encode())
        h.update(str(a.shape).encode())
        h.update(a.tobytes())
    return h.hexdigest()


def checkpoint_generations(path: str) -> list:
    """All on-disk generations for `path`, newest first (`path`, then
    `path.1`, `path.2`, ...)."""
    import os

    out = [path] if os.path.exists(path) else []
    g = 1
    while os.path.exists(f"{path}.{g}"):
        out.append(f"{path}.{g}")
        g += 1
    return out


def _write_npz_atomic(path: str, meta: dict, arrays: dict) -> dict:
    """Digest + serialize one npz to ``path + ".tmp.npz"``, fsynced.
    Returns the final meta (with the digest); the caller finishes the
    rename so it can interleave generation rotation."""
    import json
    import os

    import numpy as np

    meta = dict(meta)
    meta["digest"] = _checkpoint_digest(arrays)
    payload = dict(arrays)
    payload["meta"] = np.frombuffer(
        json.dumps(meta).encode(), dtype=np.uint8
    ).copy()
    tmp = path + ".tmp.npz"  # savez appends .npz to bare paths otherwise
    with open(tmp, "wb") as f:
        np.savez_compressed(f, **payload)
        f.flush()
        os.fsync(f.fileno())
    return meta


def _fsync_dir(path: str) -> None:
    import os

    try:
        dfd = os.open(os.path.dirname(os.path.abspath(path)), os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    except OSError:
        pass  # platforms without directory fsync still get the file fsync


def save_checkpoint_atomic(path: str, meta: dict, arrays: dict, *,
                           keep: int = 1, metrics=None) -> dict:
    """Write one checkpoint crash-safely: tmp + fsync + generation rotation
    + rename + directory fsync, with the content digest in the manifest.
    Returns the final meta — the delta layer pins its chain to the
    returned ``digest``."""
    import os

    t0 = time.monotonic()
    meta = _write_npz_atomic(path, meta, arrays)
    tmp = path + ".tmp.npz"
    # Rotate the survivors BEFORE the rename lands: the previous good
    # checkpoint must exist (as `.1`) at every instant a crash could hit.
    if keep > 1 and os.path.exists(path):
        for g in range(keep - 1, 1, -1):
            older = f"{path}.{g - 1}"
            if os.path.exists(older):
                os.replace(older, f"{path}.{g}")
        os.replace(path, f"{path}.1")
    os.replace(tmp, path)
    _fsync_dir(path)
    if metrics is not None:
        metrics.inc("checkpoint_saves")
        metrics.inc("checkpoint_bytes", os.path.getsize(path))
        metrics.add_phase("checkpoint_save", time.monotonic() - t0)
    return meta


def load_checkpoint_verified(path: str):
    """Load one checkpoint file and verify its content digest. Returns
    ``(arrays, meta)``; raises CheckpointCorruptError on an unreadable
    zip, missing/garbled meta, or digest mismatch."""
    import json

    import numpy as np

    try:
        data = np.load(path)
        meta = json.loads(bytes(data["meta"]).decode())
        arrays = {k: data[k] for k in data.files if k != "meta"}
    except Exception as exc:
        raise CheckpointCorruptError(
            f"checkpoint {path!r} is unreadable (truncated or corrupt): "
            f"{type(exc).__name__}: {exc}"
        ) from exc
    want = meta.get("digest")
    if want is None:
        raise CheckpointCorruptError(
            f"checkpoint {path!r} carries no content digest (pre-durability "
            "layout); re-create it with the current engine"
        )
    got = _checkpoint_digest(arrays)
    if got != want:
        raise CheckpointCorruptError(
            f"checkpoint {path!r} fails its content digest "
            f"({got[:12]}... != recorded {want[:12]}...); the file is corrupt"
        )
    return arrays, meta


def load_checkpoint_with_fallback(path: str, metrics=None):
    """Load the newest verifiable checkpoint generation. A corrupt or
    truncated `path` falls back to `path.1`, `path.2`, ... (written by
    `save_checkpoint_atomic(keep=N)`); only when every generation fails
    does the error propagate, carrying each failure."""
    candidates = checkpoint_generations(path)
    if not candidates:
        raise FileNotFoundError(f"no checkpoint at {path!r}")
    failures = []
    for cand in candidates:
        try:
            arrays, meta = load_checkpoint_verified(cand)
        except CheckpointCorruptError as exc:
            failures.append(str(exc))
            if metrics is not None:
                metrics.inc("checkpoint_corrupt_rejected")
            continue
        if cand != path:
            if metrics is not None:
                metrics.inc("checkpoint_fallbacks")
            _log.warning(
                "checkpoint rejected; resuming from previous generation",
                path=path,
                reason=failures[-1] if failures else "missing",
                fallback=cand,
            )
        return arrays, meta
    raise CheckpointCorruptError(
        "no loadable checkpoint generation:\n  " + "\n  ".join(failures)
    )


# -- incremental delta checkpoints (ISSUE 20, on top of the generational
# protocol above) -------------------------------------------------------------
#
# A large visited table rewrites gigabytes every cadence tick under the
# full-save protocol, yet between ticks only newly claimed slots change
# (slots never move absent a rehash, and a rehash doubles tcap — which
# forces a fresh base). A delta checkpoint therefore carries: every
# non-table array verbatim (ring, heads/counts, rec fps, spill blocks —
# all small next to the table) plus ONLY the table slots occupied since
# the BASE generation was written (cumulative-vs-base, so a single
# delta + the base reconstructs the newest state and every older delta
# is disposable). The meta manifest pins the chain to the base's content
# digest and records per-region occupancy watermarks; the fold validates
# both, and any failure falls back delta-by-delta to the plain base
# (then the base's own generation fallback). Rolling compaction: once
# the chain reaches DELTA_CHAIN_MAX the next save is a fresh full base
# and the old chain is cleared.

DELTA_CHAIN_MAX = 4
# Occupancy watermarks are recorded per probe region (equal flat-index
# stripes of the table): a fold that silently dropped or duplicated
# rows shows up as a region-count mismatch even when digests agree.
TABLE_DELTA_REGIONS = 64


def delta_chain_paths(path: str) -> list:
    """On-disk delta chain for base `path`, oldest first
    (`path.d1`, `path.d2`, ...)."""
    import os

    out = []
    g = 1
    while os.path.exists(f"{path}.d{g}"):
        out.append(f"{path}.d{g}")
        g += 1
    return out


def clear_delta_chain(path: str) -> None:
    """Remove every delta of base `path` (after a compacting full save;
    a crash in between leaves stale deltas whose base-digest check
    rejects them on load — safe either way)."""
    import os

    for dpath in delta_chain_paths(path):
        try:
            os.unlink(dpath)
        except OSError:
            pass


def table_region_occupancy(occ_flat) -> list:
    """Per-region occupied-slot counts over the flattened table
    occupancy mask (the delta manifest's insert watermarks)."""
    import numpy as np

    occ_flat = np.asarray(occ_flat).reshape(-1)
    n = occ_flat.shape[0]
    r = min(TABLE_DELTA_REGIONS, max(1, n))
    edges = (np.arange(r, dtype=np.int64) * n) // r
    return [int(v) for v in np.add.reduceat(occ_flat.astype(np.int64), edges)]


def save_checkpoint_tiered(path: str, meta: dict, arrays: dict, *,
                           state, tcap: int, keep: int = 1, metrics=None,
                           chain_max: int = DELTA_CHAIN_MAX):
    """Save either a full base generation or a delta against the current
    base, whichever the chain state calls for. ``state`` is the opaque
    per-engine chain state (``None`` initially and after any resume);
    returns the new state. A tcap change (growth/reshard rehashed every
    slot) or a chain at ``chain_max`` forces a compacting full save."""
    import numpy as np

    occ = (
        (np.asarray(arrays["table0"]) != 0)
        | (np.asarray(arrays["table1"]) != 0)
    ).reshape(-1)
    if (
        state is None
        or state.get("tcap") != tcap
        or state.get("seq", 0) >= chain_max
    ):
        full_meta = save_checkpoint_atomic(
            path, meta, arrays, keep=keep, metrics=metrics
        )
        clear_delta_chain(path)
        return {
            "occ": occ,
            "tcap": int(tcap),
            "seq": 0,
            "base_digest": full_meta["digest"],
        }
    seq = state["seq"] + 1
    idx = np.flatnonzero(occ & ~state["occ"])
    darrays = {
        k: v for k, v in arrays.items() if not k.startswith("table")
    }
    darrays["delta_idx"] = idx.astype(np.int64)
    for t in range(4):
        darrays[f"delta_t{t}"] = (
            np.asarray(arrays[f"table{t}"]).reshape(-1)[idx]
        )
    meta = dict(meta)
    meta["delta"] = {
        "base_digest": state["base_digest"],
        "seq": int(seq),
        "base_tcap": int(tcap),
        "regions": table_region_occupancy(occ),
    }
    save_checkpoint_delta(f"{path}.d{seq}", meta, darrays, metrics=metrics)
    state = dict(state)
    state["seq"] = seq
    return state


def save_checkpoint_delta(dpath: str, meta: dict, arrays: dict, *,
                          metrics=None) -> dict:
    """Crash-safe write of one delta file (tmp + fsync + rename + dir
    fsync; no generation rotation — the chain IS the history)."""
    import os

    t0 = time.monotonic()
    meta = _write_npz_atomic(dpath, meta, arrays)
    os.replace(dpath + ".tmp.npz", dpath)
    _fsync_dir(dpath)
    if metrics is not None:
        metrics.inc("checkpoint_delta_saves")
        metrics.inc("checkpoint_delta_bytes", os.path.getsize(dpath))
        metrics.inc("checkpoint_delta_rows", int(len(arrays["delta_idx"])))
        metrics.add_phase("checkpoint_save", time.monotonic() - t0)
    return meta


def _fold_table_delta(base_data: dict, ddata: dict) -> dict:
    """Newest engine state = the delta's non-table arrays + the base's
    table lanes with the delta rows scattered in."""
    import numpy as np

    folded = {
        k: v for k, v in ddata.items() if not k.startswith("delta_")
    }
    idx = np.asarray(ddata["delta_idx"]).reshape(-1)
    for t in range(4):
        lane = np.array(base_data[f"table{t}"])  # copy; base stays pristine
        lane.reshape(-1)[idx] = ddata[f"delta_t{t}"]
        folded[f"table{t}"] = lane
    return folded


def load_checkpoint_folded(path: str, metrics=None):
    """Load the newest recoverable engine state: the newest verifiable
    base generation with the newest verifiable delta (pinned to that
    base's digest, region watermarks revalidated post-fold) folded on.
    Falls back delta-by-delta to the plain base; base-generation
    fallback itself is `load_checkpoint_with_fallback`."""
    import numpy as np

    base_data, base_meta = load_checkpoint_with_fallback(
        path, metrics=metrics
    )
    base_digest = base_meta.get("digest")
    for dpath in reversed(delta_chain_paths(path)):
        try:
            ddata, dmeta = load_checkpoint_verified(dpath)
            man = dmeta.get("delta") or {}
            if man.get("base_digest") != base_digest:
                # STALE, not corrupt: the base itself fell back a
                # generation (or the chain outlived a compaction), so a
                # digest-mismatched delta is the EXPECTED leftover of the
                # newer base — skip it without the corruption counters
                # (the base-fallback counter already told that story).
                if metrics is not None:
                    metrics.inc("checkpoint_delta_stale")
                _log.warning(
                    "delta checkpoint stale for the loaded base; skipped",
                    path=dpath,
                )
                continue
            folded = _fold_table_delta(base_data, ddata)
            occ = (
                (np.asarray(folded["table0"]) != 0)
                | (np.asarray(folded["table1"]) != 0)
            )
            if table_region_occupancy(occ) != list(man.get("regions", [])):
                raise CheckpointCorruptError(
                    f"delta checkpoint {dpath!r} fails its per-region "
                    "insert watermarks after folding"
                )
        except CheckpointCorruptError as exc:
            if metrics is not None:
                metrics.inc("checkpoint_corrupt_rejected")
                metrics.inc("checkpoint_fallbacks")
            _log.warning(
                "delta checkpoint rejected; falling back",
                path=dpath,
                reason=str(exc),
            )
            continue
        if metrics is not None:
            metrics.inc("checkpoint_delta_folds")
        return folded, dmeta
    return base_data, base_meta


# -- SIGTERM/SIGINT final-checkpoint flush ------------------------------------
#
# Preempted runs should resume, not restart: the FIRST signal asks every
# live checkpointing engine to stop at its next era boundary (each flushes
# a final checkpoint on the way out; the caller's join() then returns
# normally with partial results). The previous handler is restored after
# that first delivery, so a second signal behaves as before (force-kill /
# KeyboardInterrupt).

_signal_engines = None  # lazy WeakSet; module import must not cost anything
_signal_installed: Dict[int, Any] = {}


def register_signal_checkpoint_flush(engine) -> None:
    """Enroll a checkpointing engine in the graceful-flush set and install
    the SIGTERM/SIGINT handlers (first call only; no-op off the main
    thread, where CPython forbids signal.signal)."""
    global _signal_engines
    import signal
    import weakref

    if _signal_engines is None:
        _signal_engines = weakref.WeakSet()
    _signal_engines.add(engine)
    if _signal_installed:
        return
    for signum in (signal.SIGTERM, signal.SIGINT):
        try:
            _signal_installed[signum] = signal.signal(
                signum, _flush_signal_handler
            )
        except ValueError:
            # Not the main thread (e.g. an engine constructed inside a serve
            # worker): graceful flush still works via an explicit
            # request_checkpoint_stop(); only the OS hook is unavailable.
            _signal_installed.clear()
            return


def _flush_signal_handler(signum, frame) -> None:
    import signal

    for engine in list(_signal_engines or ()):
        engine.request_checkpoint_stop()
    # One graceful chance: restore the previous handlers so the next
    # signal is forceful.
    for num, prev in _signal_installed.items():
        try:
            signal.signal(num, prev if prev is not None else signal.SIG_DFL)
        except (ValueError, TypeError):
            pass
    _signal_installed.clear()
