"""On-demand engine: a lazy BFS that expands only what it is asked to.

Reference: src/checker/on_demand.rs. The engine seeds the frontier with the
initial states and then idles. The Explorer (or any caller) drives it:

  - `check_fingerprint(fp)` expands the pending frontier node with that
    fingerprint (on_demand.rs:136-177, 406-411), growing the frontier by its
    successors — so browsing the state space progressively materializes it;
  - `run_to_completion()` switches to exhaustive BFS over whatever remains
    (ControlFlow::RunToCompletion, checker.rs:33-36).

The visited map stores parent pointers exactly like BFS, so discovery paths
are reconstructed the same way. All entry points are serialized by a lock;
`run_to_completion` runs in a background thread so HTTP handlers that trigger
it stay responsive.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Any, Dict, List, Optional

from ..checker import CheckerBuilder
from ..path import Path
from .common import BLOCK_SIZE, HostEngineBase


class OnDemandChecker(HostEngineBase):
    def __init__(self, builder: CheckerBuilder):
        super().__init__(builder)
        model = self._model

        init_states = [s for s in model.init_states() if model.within_boundary(s)]
        self._state_count = len(init_states)
        self._generated: Dict[int, Optional[int]] = {}
        for s in init_states:
            fp = self._fp(s)
            if fp not in self._generated and self._sampler is not None:
                self._sampler.offer(fp, depth=1, state=s)
            self._generated.setdefault(fp, None)
        self._coverage.record_depth(1, len(self._generated))
        self._pending = deque(
            (s, self._fp(s), self._init_ebits, 1) for s in init_states
        )
        self._discoveries: Dict[str, int] = {}
        self._lock = threading.RLock()
        self._run_thread: Optional[threading.Thread] = None
        self._initial_snapshot = (self._state_count, self.unique_state_count(), 0)
        # The engine idles until driven, so seed the registry at
        # construction: telemetry() must reflect the frontier immediately.
        self._metrics.set_gauge("frontier_size", len(self._pending))

    # -- lifecycle (idle until driven; no auto-started thread) ---------------

    def is_done(self) -> bool:
        with self._lock:
            return not self._pending or self._finish_matched(self._discoveries)

    def join(self) -> "OnDemandChecker":
        t = self._run_thread
        if t is not None:
            t.join()
        if self._error is not None:
            raise self._error
        return self

    # -- control flow --------------------------------------------------------

    def check_fingerprint(self, fingerprint: int) -> None:
        """Expand the pending frontier node with this fingerprint, if any.

        Reference: ControlFlow::CheckFingerprint handling, on_demand.rs:140-163.
        """
        with self._lock:
            for i, job in enumerate(self._pending):
                if job[1] == fingerprint:
                    del self._pending[i]
                    self._metrics.inc("expand_requests")
                    with self._metrics.phase("check_block"):
                        self._process_job(job)
                    self._obs_event("round", frontier=len(self._pending))
                    return

    def run_to_completion(self) -> None:
        """Exhaustively check everything still pending, in the background."""
        with self._lock:
            if self._run_thread is not None:
                return
            self._run_thread = threading.Thread(target=self._run_guarded, daemon=True)
            self._run_thread.start()

    def _run(self) -> None:
        while True:
            with self._lock:
                with self._metrics.phase("check_block"):
                    for _ in range(BLOCK_SIZE):
                        if not self._pending:
                            self._metrics.inc("waves")
                            self._obs_event("wave", frontier=0)
                            return
                        self._process_job(self._pending.pop())
                self._metrics.inc("waves")
                self._obs_event("wave", frontier=len(self._pending))
                if self._finish_matched(self._discoveries):
                    return
                if (
                    self._target_state_count is not None
                    and self._state_count >= self._target_state_count
                ):
                    return
            if self._timed_out():
                return

    # -- expansion (single job; mirrors on_demand.rs check_block body) -------

    def _process_job(self, job) -> None:
        model = self._model
        generated = self._generated
        discoveries = self._discoveries
        state, state_fp, ebits, depth = job

        if depth > self._max_depth:
            self._max_depth = depth
        if self._target_max_depth is not None and depth >= self._target_max_depth:
            return
        if self._visitor is not None:
            self._visitor.visit(model, self._reconstruct_path(state_fp))

        ebits, is_awaiting = self._check_properties(
            state, ebits, discoveries, lambda: state_fp
        )
        if not is_awaiting:
            return

        cov = self._coverage if self._coverage.enabled else None
        is_terminal = True
        actions: List[Any] = []
        model.actions(state, actions)
        for action in actions:
            next_state = model.next_state(state, action)
            if next_state is None:
                continue
            if not model.within_boundary(next_state):
                continue
            self._state_count += 1
            if cov is not None:
                cov.record_action(self._action_label(action))
            next_fp = self._fp(next_state)
            if next_fp in generated:
                is_terminal = False
                continue
            generated[next_fp] = state_fp
            if self._sampler is not None:
                self._sampler.offer(
                    next_fp,
                    depth=depth + 1,
                    action=action,
                    state=next_state,
                    pred=state,
                )
            if cov is not None:
                cov.record_depth(depth + 1)
            is_terminal = False
            self._pending.appendleft((next_state, next_fp, ebits, depth + 1))
        if is_terminal:
            self._terminal_ebit_discoveries(ebits, discoveries, lambda: state_fp)

    # -- accessors ----------------------------------------------------------

    def unique_state_count(self) -> int:
        return len(self._generated)

    def discoveries(self) -> Dict[str, Path]:
        with self._lock:
            return {
                name: self._reconstruct_path(fp)
                for name, fp in list(self._discoveries.items())
            }

    def _reconstruct_path(self, fp: int) -> Path:
        fingerprints: deque = deque()
        next_fp: Optional[int] = fp
        while next_fp is not None and next_fp in self._generated:
            fingerprints.appendleft(next_fp)
            next_fp = self._generated[next_fp]
        return Path.from_fingerprints(self._model, list(fingerprints))
