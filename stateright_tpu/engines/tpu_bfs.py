"""The TPU-native batched BFS engine.

This is the framework's reason to exist (SURVEY.md §7, BASELINE.json north
star): the reference's per-thread hot loop — pop a state, evaluate
properties, enumerate actions, fingerprint successors, dedup against a
concurrent map (src/checker/bfs.rs:196-334) — re-designed as a data-parallel
frontier program:

  - the pending queue is a device-resident ring buffer of fixed-width
    uint32 state rows (+ per-row eventually-bits and depth),
  - each step pops a CHUNK of rows and runs one fused XLA program:
    batched property evaluation, batched successor generation
    (`TensorModel.step_batch`), vectorized 64-bit fingerprinting,
    sort-based in-batch dedup, scatter-claim insertion into the
    open-addressing visited table, stable compaction, and ring append,
  - the host thread only orchestrates: it reads a few scalars per step
    (new/generated counts, discovery flags), applies finish policies,
    grows the hash table, and spills/refills the queue if it overflows.

Semantics match the reference engine state-for-state (same property
timing, terminal rule, eventually-bit propagation, boundary filtering,
depth accounting); only scheduling order differs (level-synchronous
instead of a work-stealing interleave — the same freedom the reference's
multithreaded mode already has). Parent fingerprints stored in the table
drive the same TLC-style path reconstruction (bfs.rs:380-409).
"""

from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..checker import CheckerBuilder
from ..core import Expectation
from ..fingerprint import combine64, split64
from ..path import Path
from ..tensor import TensorModel, TensorModelAdapter
from .common import HostEngineBase


# Step cache: (id(tm), chunk) -> (tm ref, jitted step). Reusing the same
# function object across checker instances is what lets JAX's trace cache
# and the persistent compilation cache actually hit (a fresh closure per
# checker would recompile every run).
_STEP_CACHE: Dict[Tuple[int, int], Tuple[TensorModel, Any]] = {}


def _build_step(tm: TensorModel, props, chunk: int):
    """Compile the per-chunk BFS step for a given model and chunk size.

    Returns a jitted function:
      (table, queue, q_ebits, q_depth, head, count, depth_limit) ->
      (table, queue, q_ebits, q_depth,
       generated, new_count, unresolved, max_depth_seen,
       prop_found[P], prop_fp1[P], prop_fp2[P])
    """
    cached = _STEP_CACHE.get((id(tm), chunk))
    if cached is not None and cached[0] is tm:
        return cached[1]

    import jax
    import jax.numpy as jnp

    from ..ops import frontier as fr
    from ..ops import visited_set as vs
    from ..ops.expand import build_eval_and_expand

    A = tm.max_actions
    eval_and_expand = build_eval_and_expand(tm, props, chunk)

    @functools.partial(jax.jit, donate_argnums=(0, 1, 2, 3))
    def step(table, queue, q_ebits, q_depth, head, count, depth_limit):
        u = jnp.uint32
        qcap = queue.shape[0]
        qmask = u(qcap - 1)
        take = jnp.minimum(count, u(chunk))
        active = jnp.arange(chunk, dtype=jnp.uint32) < take
        rows, slots = fr.ring_gather(queue, head, chunk)
        ebits = q_ebits[slots]
        depth = q_depth[slots]

        ex = eval_and_expand(rows, ebits, depth, active, depth_limit)

        keep = fr.dedup_mask(ex.h1, ex.h2, ex.valid)
        table, is_new, unresolved = vs.insert(
            table, ex.h1, ex.h2, ex.parent1, ex.parent2, keep
        )

        order, new_count = fr.compact_indices(is_new)
        slot_valid = jnp.arange(chunk * A, dtype=jnp.uint32) < new_count
        tail = (head + count) & qmask
        queue = fr.ring_scatter(queue, tail, ex.flat[order], slot_valid)
        q_ebits = fr.ring_scatter(
            q_ebits[:, None], tail, ex.child_ebits[order][:, None], slot_valid
        )[:, 0]
        q_depth = fr.ring_scatter(
            q_depth[:, None], tail, ex.child_depth[order][:, None], slot_valid
        )[:, 0]

        return (
            table,
            queue,
            q_ebits,
            q_depth,
            ex.generated,
            new_count,
            unresolved.sum(dtype=jnp.uint32),
            ex.max_depth_seen,
            ex.prop_found,
            ex.prop_fp1,
            ex.prop_fp2,
        )

    _STEP_CACHE[(id(tm), chunk)] = (tm, step)
    return step


class TpuBfsChecker(HostEngineBase):
    """Batched BFS over a TensorModel on the default JAX device."""

    def __init__(
        self,
        builder: CheckerBuilder,
        *,
        chunk_size: int = 4096,
        queue_capacity: int = 1 << 17,
        table_capacity: int = 1 << 20,
    ):
        model = builder.model
        if isinstance(model, TensorModel):
            model = TensorModelAdapter(model)
            builder.model = model
        if not isinstance(model, TensorModelAdapter):
            raise TypeError(
                "spawn_tpu_bfs requires a TensorModel (or its adapter); "
                "rich host models must be encoded first — see stateright_tpu.tensor."
            )
        super().__init__(builder)
        if self._visitor is not None:
            raise ValueError("the TPU engine does not support visitors")
        # Like the reference's BFS, symmetry reduction is a DFS-only feature
        # and is ignored here (bfs.rs never reads options.symmetry).

        self.tm: TensorModel = model.tm
        self._tprops = self.tm.tensor_properties()
        n_event = sum(
            1 for p in self._tprops if p.expectation == Expectation.EVENTUALLY
        )
        if n_event > 32:
            raise ValueError("at most 32 eventually-properties supported")
        if queue_capacity & (queue_capacity - 1):
            raise ValueError("queue_capacity must be a power of two")
        # qcap >= 2*C*A guarantees (a) the ring append never wraps over
        # unconsumed rows while count <= high_water and (b) a spill block
        # (<= C*A rows) always fits during refill, so spilled states are
        # never stranded.
        self._chunk = min(
            chunk_size, queue_capacity // (2 * max(1, self.tm.max_actions))
        )
        if self._chunk == 0:
            raise ValueError("queue_capacity too small for this model's fanout")
        self._qcap = queue_capacity
        self._tcap = table_capacity
        self._step = _build_step(self.tm, self._tprops, self._chunk)

        # Host-side bookkeeping.
        self._unique = 0
        self._discovery_fps: Dict[str, int] = {}
        self._spill: List[Tuple[np.ndarray, np.ndarray, np.ndarray]] = []

        self._init_ebits_tensor = 0
        e = 0
        for p in self._tprops:
            if p.expectation == Expectation.EVENTUALLY:
                self._init_ebits_tensor |= 1 << e
                e += 1

        self._start()

    # -- engine body --------------------------------------------------------

    def _run(self) -> None:
        import jax
        import jax.numpy as jnp

        from ..fingerprint import hash_words_np
        from ..ops import frontier as fr
        from ..ops import visited_set as vs

        tm = self.tm
        S = tm.state_width
        A = tm.max_actions
        C = self._chunk

        inits = np.asarray(tm.init_states_array(), dtype=np.uint32)
        inb = np.asarray(tm.within_boundary_batch(np, inits), dtype=bool)
        inits = inits[inb]
        n_init = len(inits)
        self._state_count = n_init
        if n_init == 0:
            return
        if n_init > self._qcap:
            raise ValueError("more initial states than queue capacity")

        # Seed the table with init fingerprints (parent sentinel (0,0)).
        table = vs.empty_table(self._tcap)
        h1, h2 = hash_words_np(inits)
        zero = jnp.zeros(n_init, dtype=jnp.uint32)
        keep = fr.dedup_mask(jnp.asarray(h1), jnp.asarray(h2), jnp.ones(n_init, bool))
        table, is_new, unresolved = vs.insert(
            table, jnp.asarray(h1), jnp.asarray(h2), zero, zero, keep
        )
        assert int(unresolved.sum()) == 0
        self._unique = int(is_new.sum())

        # Queue: all init rows (dups included, reference bfs.rs:76-82).
        queue = jnp.zeros((self._qcap, S), dtype=jnp.uint32)
        queue = queue.at[:n_init].set(jnp.asarray(inits))
        q_ebits = jnp.full(
            self._qcap, self._init_ebits_tensor, dtype=jnp.uint32
        )
        q_depth = jnp.ones(self._qcap, dtype=jnp.uint32)
        head = 0
        count = n_init

        depth_limit = (
            self._target_max_depth
            if self._target_max_depth is not None
            else 0xFFFFFFFF
        )
        high_water = self._qcap - C * A

        while count > 0 or self._spill:
            # Refill from host spill, leaving room for the worst-case append
            # (count must stay <= high_water going into the step, or the ring
            # append could wrap over unconsumed frontier rows).
            while self._spill and count + len(self._spill[-1][0]) <= high_water:
                rows, ebs, dps = self._spill.pop()
                k = len(rows)
                tail_idx = (head + count + np.arange(k)) & (self._qcap - 1)
                queue = queue.at[jnp.asarray(tail_idx)].set(jnp.asarray(rows))
                q_ebits = q_ebits.at[jnp.asarray(tail_idx)].set(jnp.asarray(ebs))
                q_depth = q_depth.at[jnp.asarray(tail_idx)].set(jnp.asarray(dps))
                count += k
            if count == 0:
                break

            # Proactive growth: guarantee the worst-case insert batch keeps
            # the load factor <= ~0.5, so probe budgets can't be exhausted
            # (exhaustion would silently drop states).
            while self._unique + C * A > 0.45 * self._tcap:
                table, self._tcap = self._grow_table(table)

            (
                table,
                queue,
                q_ebits,
                q_depth,
                generated,
                new_count,
                unresolved,
                max_depth_seen,
                prop_found,
                prop_fp1,
                prop_fp2,
            ) = self._step(
                table,
                queue,
                q_ebits,
                q_depth,
                jnp.uint32(head),
                jnp.uint32(count),
                jnp.uint32(depth_limit),
            )

            processed = min(count, C)
            generated = int(generated)
            new_count = int(new_count)
            if int(unresolved) != 0:
                # Cannot happen with the proactive growth above short of a
                # pathological probe sequence; losing states would be an
                # unsound "verified", so fail loudly.
                raise RuntimeError(
                    "visited-table probe budget exhausted despite headroom"
                )
            head = (head + processed) & (self._qcap - 1)
            count = count - processed + new_count
            self._state_count += generated
            self._unique += new_count
            self._max_depth = max(self._max_depth, int(max_depth_seen))

            # Record first discovery per property (reference races are
            # benign; ours are deterministic).
            if len(self._tprops):
                found = np.asarray(prop_found)
                fp1 = np.asarray(prop_fp1)
                fp2 = np.asarray(prop_fp2)
                for i, p in enumerate(self._tprops):
                    if found[i] and p.name not in self._discovery_fps:
                        self._discovery_fps[p.name] = combine64(fp1[i], fp2[i])

            # Spill if the next chunk could overflow the ring.
            while count > high_water:
                k = min(C * A, count - high_water)
                take_idx = (head + count - k + np.arange(k)) & (self._qcap - 1)
                idxs = jnp.asarray(take_idx)
                self._spill.append(
                    (
                        np.asarray(queue[idxs]),
                        np.asarray(q_ebits[idxs]),
                        np.asarray(q_depth[idxs]),
                    )
                )
                count -= k

            if self._finish_matched(self._discovery_fps):
                break
            if (
                self._target_state_count is not None
                and self._state_count >= self._target_state_count
            ):
                break
            if self._timed_out():
                break

        self._table = np.asarray(table)  # retained for path reconstruction
        return

    def _grow_table(self, table):
        """Double capacity and rehash every occupied row, chunked."""
        import jax.numpy as jnp

        from ..ops import visited_set as vs

        old = np.asarray(table)
        rows = old[np.asarray(vs.occupied_rows(old))]
        new_cap = self._tcap * 2
        new_table = vs.empty_table(new_cap)
        B = 1 << 16
        for i in range(0, len(rows), B):
            blk = rows[i : i + B]
            n = len(blk)
            new_table, _is_new, unres = vs.insert(
                new_table,
                jnp.asarray(blk[:, 0]),
                jnp.asarray(blk[:, 1]),
                jnp.asarray(blk[:, 2]),
                jnp.asarray(blk[:, 3]),
                jnp.ones(n, dtype=bool),
            )
            if int(unres.sum()) != 0:
                raise RuntimeError("rehash failed; table pathologically full")
        return new_table, new_cap

    # -- accessors ----------------------------------------------------------

    def unique_state_count(self) -> int:
        return self._unique

    def discoveries(self) -> Dict[str, Path]:
        self.join()
        return {
            name: self._reconstruct(fp)
            for name, fp in list(self._discovery_fps.items())
        }

    def _reconstruct(self, fp64: int) -> Path:
        """Walk device-table parent pointers, then re-execute the model
        along the fingerprint chain (reference bfs.rs:380-409)."""
        import jax.numpy as jnp

        from ..ops import visited_set as vs

        table = jnp.asarray(self._table)
        chain = [fp64]
        cur = fp64
        for _ in range(10_000_000):
            h1, h2 = split64(cur)
            found, p1, p2 = vs.lookup_parent(
                table,
                jnp.asarray([h1], dtype=jnp.uint32),
                jnp.asarray([h2], dtype=jnp.uint32),
            )
            if not bool(found[0]):
                raise RuntimeError(
                    f"fingerprint {cur} missing from visited table during "
                    "path reconstruction"
                )
            p1, p2 = int(p1[0]), int(p2[0])
            if p1 == 0 and p2 == 0:
                break
            cur = combine64(p1, p2)
            chain.append(cur)
        chain.reverse()
        return Path.from_fingerprints(self._model, chain)
