"""The TPU-native batched BFS engine.

This is the framework's reason to exist (SURVEY.md §7, BASELINE.json north
star): the reference's per-thread hot loop — pop a state, evaluate
properties, enumerate actions, fingerprint successors, dedup against a
concurrent map (src/checker/bfs.rs:196-334) — re-designed as a data-parallel
frontier program that lives on the device:

  - the pending queue is a device-resident ring buffer in structure-of-
    arrays form: one dense [qcap] uint32 array per state lane plus lanes
    for the fingerprint halves, eventually-bits, and depth — states are
    hashed exactly once, when first enqueued,
  - one BFS step pops a CHUNK of rows and runs batched property
    evaluation, batched successor generation (`TensorModel.step_lanes`),
    vectorized 64-bit fingerprinting, claim-arbitrated insertion into the
    SoA open-addressing visited table (in-batch dedup falls out of the
    claim protocol — no sorting), and a cumsum-compacted ring append,
  - MANY steps run back-to-back inside a single `lax.while_loop` on the
    device; the host thread synchronizes only when the loop exits — queue
    near overflow (spill to host), table near full (grow + rehash), a new
    property discovery (finish-policy check), a step budget (progress
    reporting / timeout / state-count targets), or frontier exhaustion.

Everything stays in flat 1-D uint32 arrays because TPU vector tiling makes
gathers/scatters of [N, small] rows catastrophically slow (>1000x measured
vs per-lane access) — see ops/visited_set.py.

Semantics match the reference engine state-for-state (same property
timing, terminal rule, eventually-bit propagation, boundary filtering,
depth accounting); only scheduling order differs (level-synchronous
instead of a work-stealing interleave — the same freedom the reference's
multithreaded mode already has). Parent fingerprints stored in the table
drive the same TLC-style path reconstruction (bfs.rs:380-409).
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, List, NamedTuple, Optional, Tuple

import numpy as np

from ..obs.log import get_logger

_DEBUG = bool(os.environ.get("STPU_DEBUG"))
_log = get_logger("engines.tpu_bfs")


def _dbg(msg: str) -> None:
    # STPU_DEBUG is its own opt-in gate, so the stream bypasses the
    # logger threshold (force) — setting the env var IS the request.
    if _DEBUG:
        _log.force("debug", msg, t=round(time.monotonic(), 3))

from ..checker import CheckerBuilder
from ..core import Expectation
from ..fingerprint import combine64, split64
from ..path import Path
from ..tensor import TensorModel, TensorModelAdapter
from ..ops.tiering import TieredSpillStore, spill_host_budget_bytes
from .common import (
    HostEngineBase,
    load_checkpoint_folded,
    register_signal_checkpoint_flush,
    save_checkpoint_tiered,
    validate_checkpoint_cadence,
)


class _ProbeBudgetExhausted(RuntimeError):
    """An era closed with unresolved inserts (probe budget exhausted).
    Recoverable when a crash-safe checkpoint exists: reload it, regrow the
    table, and re-run the lost era (graceful degradation)."""


# Loop cache: (id(tm), chunk, qcap, n_props, ...) -> (tm ref, EraProgram).
# Reusing the same function object across checker instances is what lets
# JAX's trace cache and the persistent compilation cache actually hit (a
# fresh closure per checker would recompile every run).
_LOOP_CACHE: Dict[Tuple, Tuple[TensorModel, Any]] = {}


class EraProgram(NamedTuple):
    """One era program, jitted under two donation policies.

    ``serial``: full operand donation — safe only when the host has
    already consumed every input buffer (fresh upload, or dispatch after
    the readback landed). ``chain``: the params operand pinned
    (compat.donate_argnums_pinned) so a speculative chained dispatch can
    feed the previous era's output back in while its async readback is
    still in flight. On CPU both donation sets are empty and the two
    fields alias ONE jitted callable.
    """

    serial: Any
    chain: Any


# Packed scalar-parameter layout. On a remote-attached TPU every individual
# host<->device transfer costs a full tunnel round-trip (~100ms measured), so
# ALL scalar state crosses in ONE uint32 vector per direction. The loop reads
# [0:8], passes the config fields through, and writes the stats tail — so its
# own output can be fed straight back in with zero uploads when the host has
# nothing to change.
P_HEAD = 0  # ring head index
P_COUNT = 1  # frontier row count
P_UNIQUE = 2  # unique states so far
P_REC = 3  # recorded-discovery bitmask (bit i = property i)
P_DEPTH_LIMIT = 4
P_GROW_LIMIT = 5  # era exits when unique exceeds this (host grows table)
P_HIGH_WATER = 6  # era exits when count exceeds this (host spills)
P_MAX_STEPS = 7  # IN: step budget per era (host polls timeout/targets/ckpt);
# OUT: the NEXT era's adaptive budget (device-emitted, see P_BUDGET_CAP)
P_GEN = 8  # OUT: generated states this era
P_MAXD = 9  # OUT: max depth seen this era
P_STEPS = 10  # OUT: steps actually executed this era
P_ERR = 11  # IN: pre-existing error (seed unresolved); OUT: >0 = probe budget exhausted
P_TAKE_CAP = 12  # persisted across eras (self-tuned on vcap overflow)
P_FIN_ANY = 13  # era exits when (rec & fin_any) != 0
P_FIN_ALL = 14  # era exits when fin_all_en and (rec & fin_all) == fin_all
P_FIN_ALL_EN = 15
P_BUDGET_CAP = 16  # upper clamp for the device-adaptive step budget;
# 0 = adaptivity OFF (P_MAX_STEPS passes through unchanged — free-running
# and target-bounded runs keep the legacy fixed-budget behavior)
P_LEN = 17
# The packed vector is P_LEN + 2*P (+ coverage tail) words long: the tail
# carries the recorded discovery fingerprint halves (rec_fp1 | rec_fp2),
# so the era result download returns counters AND discovery fingerprints
# in ONE round-trip (a separate rec_fp read costs ~100ms on this
# platform — directly on the time-to-first-counterexample path). With
# coverage enabled (the default) the tail additionally carries this
# era's on-device coverage histograms (obs/coverage.py) — per-action
# valid-candidate counts [A], per-property hit counts [P], the consumed
# row count [1], and the per-depth unique-insert histogram [DEPTH_CAP] —
# so coverage costs ZERO extra host round-trips. The loop reads only
# [0:P_LEN] of its input; the tail is write-only output.


_COV_W = 16  # relative depth-offset window of the era loop's histogram
# Low-side slack of that window: the ring append lands children in
# candidate (action-major) order, so a pop window spanning a BFS depth
# boundary interleaves depth-(d+1) and depth-(d+2) children in the ring.
# A later window's lane-0 row is then NOT its shallowest — inserts from
# the shallower interleaved parents sit up to a few levels BELOW
# depth[0]+1. _COV_LO buckets below the anchor absorb them exactly
# (uint32-wrapped offsets compare exactly against their biased bucket).
_COV_LO = 8

# Adaptive era budget floor: the smallest per-era step budget the device
# emission may shrink to under spill/grow pressure, and the slow-start
# seed the host begins wall-clock-polled runs at.
BUDGET_MIN = 64


def _cov_len(A: int, P: int) -> int:
    from ..obs.coverage import DEPTH_CAP

    return A + P + 1 + DEPTH_CAP


def _vcap(A: int, chunk: int) -> int:
    """Compacted candidate-batch width (probe + enqueue width).

    Every op downstream of validity compaction runs at this width, so it
    bounds both the insert probe batch and the per-step enqueue. Sized for
    typical valid-candidate counts (~20-40%% of the padded C*A batch for
    the protocol models); the take_cap mechanism adapts when a model's
    step exceeds it. This is a SOUNDNESS-COUPLED constant: the device
    loop treats it as the overflow threshold while the host sizes
    grow_limit / pre-growth headroom from it — all sites must use this
    one definition.
    """
    div = int(os.environ.get("STPU_VCAP_DIV", "3"))
    return min(chunk * A, max(128 * A, (chunk * A) // div))


def fuse_tail_len(fuse: int) -> int:
    """Words of the multi-era fusion tail appended to the packed params
    when ``fuse > 1``: ``[fuse_lim, n_inner]`` followed by the
    per-inner-era flight-record lanes ``steps[fuse] | gen[fuse] |
    unique[fuse] | frontier[fuse]``. ``fuse <= 1`` compiles the classic
    single-era program with NO tail, so every existing layout consumer
    (checkpoint codec, lint, multiplex) is untouched by default."""
    return 2 + 4 * fuse if fuse > 1 else 0


def params_len(A: int, P: int, cov: bool, sample_k: int,
               fuse: int = 1) -> int:
    """Length of the packed uint32 params vector the era loop carries:
    scalars + rec_fp tail + optional coverage tail + optional sampling
    tail + optional multi-era fusion tail. This is THE layout contract —
    the engine, the checkpoint codec, and the STR6xx program lint all
    size their buffers from it."""
    n = P_LEN + 2 * P
    if cov:
        n += _cov_len(A, P)
    if sample_k:
        from ..obs.sample import slab_entries

        n += 4 + 5 * slab_entries(sample_k)
    return n + fuse_tail_len(fuse)


def loop_abstract_args(tm: TensorModel, props, chunk: int, qcap: int,
                       tcap: int, cov: bool, sample_k: int,
                       fuse: int = 1):
    """`jax.ShapeDtypeStruct` pytree matching `_build_loop`'s signature
    `(table, queue, rec_fp1, rec_fp2, params)` — lets the STR6xx program
    lint (analysis/program.py) trace/lower the era loop WITHOUT
    allocating a single device buffer or executing anything."""
    import jax
    import jax.numpy as jnp

    S, A, P = tm.state_width, tm.max_actions, len(props)
    u32 = jnp.uint32
    sds = jax.ShapeDtypeStruct
    table = (sds((2 * tcap,), u32), sds((tcap,), u32), sds((tcap,), u32))
    queue = tuple(sds((qcap,), u32) for _ in range(S + 2))
    plen = params_len(A, P, cov, sample_k, fuse)
    return (table, queue, sds((P,), u32), sds((P,), u32), sds((plen,), u32))


def seed_loop_abstract_args(tm: TensorModel, props, chunk: int, qcap: int,
                            tcap: int, cov: bool, sample_k: int,
                            n_init: int, fuse: int = 1):
    """Abstract args for `_build_seed_loop`'s fused
    `seed_run(qinit, h1, h2, params, rec_fp1, rec_fp2)` dispatch."""
    import jax
    import jax.numpy as jnp

    S, A, P = tm.state_width, tm.max_actions, len(props)
    u32 = jnp.uint32
    sds = jax.ShapeDtypeStruct
    n_init = max(1, n_init)
    plen = params_len(A, P, cov, sample_k, fuse)
    return (
        sds((S + 2, n_init), u32),
        sds((n_init,), u32),
        sds((n_init,), u32),
        sds((plen,), u32),
        sds((P,), u32),
        sds((P,), u32),
    )


def _build_loop(tm: TensorModel, props, chunk: int, qcap: int, canon: bool = False,
                cov: bool = True, raw: bool = False, sample_k: int = 0,
                fuse: int = 1):
    """Compile the BFS device "era" loop.

    Returns a jitted function
      (table, queue, rec_fp1, rec_fp2, params[P_LEN])
      -> (table, queue, rec_fp1, rec_fp2, params[P_LEN])
    that runs BFS steps in a device-resident `lax.while_loop` until a
    host-intervention condition closes the gate: frontier exhausted, ring
    near overflow (host spills), table near full (host grows), step budget
    reached (host polls timeouts/targets/checkpoints), probe budget
    exhausted (host raises), or the finish policy's discovery masks are
    satisfied. One era = ONE dispatch + ONE readback, so a full run that
    needs no host intervention costs a single ~100ms tunnel round-trip
    regardless of depth — the decisive constant on this remote-attached
    platform (see the measured notes below).

    With ``raw=True`` the UN-jitted loop function is returned instead (no
    donation): that is what the multiplexed lane engine
    (engines/multiplex.py) wraps in `jax.vmap` — an inner jit would defeat
    batching and donation is illegal on a vmapped operand it does not own.

    With ``sample_k > 0`` the loop additionally maintains the bottom-k
    space-sampling slab (obs/sample.py): every exactly-once insert whose
    fingerprint is lexicographically below the host-supplied threshold
    (read from the sample tail of the INPUT params — pass-through, so
    chained speculative dispatches reuse a stale-but-looser threshold,
    which only ever admits a superset of candidates) is appended to a
    fixed in-carry slab; the epilogue ranks the slab by h1 via one
    `top_k` and ships the smallest ``slab_entries(k)`` rows in the params
    tail, so the drain rides the existing once-per-era readback with
    ZERO extra round-trips. The host applies the exact 64-bit tie cut.

    With ``fuse > 1`` up to that many ERAS run inside one compiled
    program: an outer `lax.while_loop` re-enters the era body while the
    previous inner era exited on a PURE step-budget boundary (every
    device-visible trigger — spill high-water, grow limit, finish
    policy, probe error, empty frontier, sample-slab high-water — ends
    the fused dispatch so the host can act). The runtime fusion limit
    rides the params fusion tail (``fuse_lim``, pass-through), so one
    compiled program serves every degraded value down to 1, and the
    tail reports which inner era tripped plus per-inner-era
    steps/generated/unique/frontier lanes for exact flight records.
    Coverage and the sampling slab accumulate ACROSS inner eras (both
    are additive deltas drained once per readback), so one fused
    readback is indistinguishable from the sum of its serial eras.

    Non-raw builds return an `EraProgram(serial, chain)` pair: the same
    traced program jitted twice — ``serial`` donates the full operand
    set (table, queue, rec_fps, params; the driver only uses it when
    every input was already consumed host-side), ``chain`` excludes the
    readback-pinned params operand (compat.donate_argnums_pinned) for
    speculative chained dispatches. On CPU both donation sets resolve
    empty and ONE jitted object serves both slots (no double compile).
    """
    fuse = max(1, int(fuse))
    key = (id(tm), chunk, qcap, len(props), canon, cov, raw, sample_k, fuse)
    cached = _LOOP_CACHE.get(key)
    if cached is not None and cached[0] is tm:
        return cached[1]
    while len(_LOOP_CACHE) >= 16:  # bound executable/model pinning
        _LOOP_CACHE.pop(next(iter(_LOOP_CACHE)))

    import jax
    import jax.numpy as jnp
    from jax import lax

    from ..compat import donate_argnums_pinned
    from ..fingerprint import hash_lanes_jnp
    from ..obs.coverage import DEPTH_CAP
    from ..ops import frontier as fr
    from ..ops import visited_set as vs
    from ..ops.expand import build_expand_lean

    S = tm.state_width
    A = tm.max_actions
    P = len(props)
    expand_lean = build_expand_lean(tm, props, chunk)
    qmask = qcap - 1
    vcap = _vcap(A, chunk)
    if sample_k:
        from ..obs.sample import (
            DEVICE_STEP_CAP,
            slab_capacity,
            slab_entries,
            slab_high_water,
        )

        sk2 = slab_entries(sample_k)  # entries drained per era
        s_high = slab_high_water(sample_k)  # era-exit occupancy gate
        scap = slab_capacity(sample_k, DEVICE_STEP_CAP)  # in-carry slab
        # Loose-threshold take clamp: while the threshold is still MAX
        # (sampler under-full — fresh runs only) EVERY insert is a
        # candidate, so cap the pop so one step can never produce more
        # than the per-step capture width (candidates <= take * A). This
        # is what makes the sample EXACT from state one; once the host
        # drains k entries the threshold tightens and the clamp is moot.
        s_take = max(1, DEVICE_STEP_CAP // max(1, A))
        # Input-params offsets of the threshold words (the sample tail
        # starts right after the coverage tail; layout below).
        s_base = P_LEN + 2 * P + (_cov_len(A, P) if cov else 0)
    # Distinct-candidate (probe + enqueue) width: 2/5 of the valid width
    # measured fastest on 2pc-7 (vcap/2 pays ~15% more probe width than
    # needed; vcap/3 sits under the distinct-count peaks and burns steps
    # on partial-commit retries).
    rcap = max(128 * A, (2 * vcap) // 5)
    # Dedup scratch ~4x the valid width: cross-key collisions (which
    # harmlessly retain duplicates) stay rare, and the scratch stays small
    # enough to be cache-hot.
    dedup_cap = 1 << max(1, (4 * vcap - 1).bit_length())
    # Absolute offset of the fusion tail (== the fuse-free params length).
    f_base = params_len(A, P, cov, sample_k)

    def loop(table, queue, rec_fp1, rec_fp2, params):
        u = jnp.uint32
        head0 = params[P_HEAD]
        count0 = params[P_COUNT]
        unique0 = params[P_UNIQUE]
        rec_bits0 = params[P_REC]
        depth_limit = params[P_DEPTH_LIMIT]
        grow_limit = params[P_GROW_LIMIT]
        high_water = params[P_HIGH_WATER]
        max_steps0 = params[P_MAX_STEPS]
        fin_any = params[P_FIN_ANY]
        fin_all = params[P_FIN_ALL]
        fin_all_en = params[P_FIN_ALL_EN]
        budget_cap = params[P_BUDGET_CAP]
        if sample_k:
            # Sampling threshold (exclusive; hi/lo uint32 words of the
            # host sampler's 64-bit kth-smallest). Copied through to the
            # output tail so chained dispatches keep a valid (stale =>
            # looser => superset, host re-filters) threshold.
            st1 = params[s_base]
            st2 = params[s_base + 1]
        # The era is a data-dependent `lax.while_loop` whose predicate runs
        # ON DEVICE (measured round 4: a jitted while predicate costs
        # nothing extra — the old belief that it forced a host round-trip
        # per iteration only holds for NON-jitted top-level loops). This
        # matters doubly here: (a) no wasted gated no-op iterations (every
        # iteration costs nearly a full step's gather traffic whether or
        # not it does work), and (b) no per-block dispatch+readback
        # (~350-400ms measured) for the progressive block ramp the old
        # design needed.
        #
        # Inside the body only uint32 sum-reduction chains may feed values
        # that GATE the next iteration (count/unique/rec_acc-style); a gate
        # routed through a boolean any()-derived carry serializes the
        # pipeline (~1.5s/step measured), as do reduction -> broadcast ->
        # reduction chains anywhere in the carry (argmax selects, one-hot
        # extractions, max reduces). Discovery fingerprints are therefore
        # accumulated as per-position lane snapshots (first hit per
        # position wins, pure elementwise) and extracted once AFTER the
        # loop; only the scalar discovery BITS (via per-property uint32
        # sums) feed the gate, implementing the finish policy's early exit
        # on device (reference bfs.rs:134-144 checks between blocks).
        def cond(carry):
            (
                _table, _queue, _head, count, unique, _gen, steps,
                err_cnt, _take_cap, rec_acc, _hseen, _f1, _f2, _fd, _covc,
                sampc, max_steps,
            ) = carry
            fin_hit = ((rec_acc & fin_any) != u(0)) | (
                (fin_all_en != u(0)) & ((rec_acc & fin_all) == fin_all)
            )
            keep = (
                (count > u(0))
                & (count <= high_water)
                & (unique <= grow_limit)
                & (steps < max_steps)
                & (err_cnt == u(0))
                & ~fin_hit
            )
            if sample_k:
                # Slab-occupancy gate: exit the era so the host can drain
                # before the slab can overflow (one more step adds at most
                # DEVICE_STEP_CAP entries, and scap = s_high + that).
                # sampc[4] (occupied) is a uint32 sum chain — carry-safe.
                keep = keep & (sampc[4] <= u(s_high))
            return keep

        def body(carry):
            (
                table,
                queue,
                head,
                count,
                unique,
                gen,
                steps,
                err_cnt,
                take_cap,
                rec_acc,
                hseen,
                facc1,
                facc2,
                faccd,
                covc,
                sampc,
                max_steps,
            ) = carry
            take = jnp.minimum(jnp.minimum(count, u(chunk)), take_cap)
            if sample_k:
                # Loose-threshold clamp (see the sizing block above): only
                # binds while the sampler is under-full (threshold MAX).
                loose = (st1 == u(0xFFFFFFFF)) & (st2 == u(0xFFFFFFFF))
                take = jnp.minimum(
                    take, jnp.where(loose, u(s_take), u(chunk))
                )
            active = jnp.arange(chunk, dtype=jnp.uint32) < take
            popped, _idx = fr.ring_gather(queue, head, chunk)
            rows = popped[:S]
            ebits = popped[S]
            depth = popped[S + 1]
            # Fingerprints are recomputed on pop (elementwise — effectively
            # free) instead of being carried in the ring: two fewer ring
            # lanes in every ring gather/scatter, which ARE the cost here.
            row_h1, row_h2 = hash_lanes_jnp(rows)

            ex = expand_lean(rows, ebits, depth, active, depth_limit)
            # COMPACT EARLY (the round-5 redesign): validity compaction is
            # the only [C*A]-wide random-access work in the step. Hashing,
            # parent lookup, the visited-set insert, and the ring append
            # all run at the compacted [vcap] width. In-batch duplicate
            # candidates need no separate dedup pass — the insert's claim
            # protocol arbitrates them exactly (one winner per distinct
            # key, same benign-race semantics as the reference's DashMap
            # entry API, bfs.rs:302-315).
            vids, vvalid, n_val = vs._compact_ids(ex.valid, vcap)
            cl = tuple(ex.flat[s][vids] for s in range(S))
            if canon:
                # Symmetry reduction: canonicalize at the compacted width,
                # before fingerprinting — the ring and table then only
                # ever see representatives.
                cl = tm.representative_lanes(jnp, cl)
            ch1, ch2 = hash_lanes_jnp(cl)
            # Stage-2 compaction: in-batch dedup (claim-arbitrated,
            # approximate — the insert arbitrates leftovers exactly) then
            # re-compact to the distinct-candidate width, so the probe
            # batch, parent lookups, AND the ring append all run at rcap
            # (~half of vcap) instead of the valid width. The dedup's
            # scratch is small (cache-hot), so its four random ops cost
            # far less than the width they save downstream.
            reps = fr.claim_dedup(ch1, ch2, vvalid, dedup_cap)
            dids, dvalid, n_d = vs._compact_ids(reps, rcap)
            dh1 = ch1[dids]
            dh2 = ch2[dids]
            dl = tuple(cl[s][dids] for s in range(S))
            src = vids[dids] % u(chunk)  # parent row of candidate a*C+c is c
            dp1 = jnp.where(dvalid, row_h1[src], u(0))
            dp2 = jnp.where(dvalid, row_h2[src], u(0))
            debits = ex.ebits[src]
            ddepth = depth[src] + u(1)
            table, c_new, unresolved, _n_ovf = vs.insert(
                table, dh1, dh2, dp1, dp2, dvalid
            )
            unres = unresolved.sum(dtype=jnp.uint32)
            new_count = c_new.sum(dtype=jnp.uint32)

            if sample_k:
                # Space-sampling capture: exactly-once inserts (c_new is
                # exact even on retried partial steps — inserts commit
                # once) whose 64-bit fingerprint is lexicographically
                # below the threshold. Compacted to the small fixed
                # capture width and appended to the slab; entries past
                # the width are counted (sdrop) — astronomically rare
                # under a tight threshold and impossible under a loose
                # one thanks to the take clamp.
                below = c_new & (
                    (dh1 < st1) | ((dh1 == st1) & (dh2 < st2))
                )

                def _capture(sc):
                    sfp1, sfp2, sdep, sact, socc, sdrp = sc
                    cids, cvalid, n_c = vs._compact_ids(
                        below, DEVICE_STEP_CAP
                    )
                    fit = jnp.minimum(n_c, u(DEVICE_STEP_CAP))
                    pos = socc + jnp.arange(DEVICE_STEP_CAP, dtype=u)
                    ok_w = cvalid & (pos < u(scap))
                    # Masked lanes land in the trash slot (index scap) —
                    # the slab lanes are scap+1 wide and the epilogue
                    # reads [:scap] only.
                    widx = jnp.where(ok_w, pos, u(scap))
                    # flat id a*C+c -> action a
                    dact = vids[dids] // u(chunk)
                    return (
                        sfp1.at[widx].set(dh1[cids]),
                        sfp2.at[widx].set(dh2[cids]),
                        sdep.at[widx].set(ddepth[cids]),
                        sact.at[widx].set(dact[cids]),
                        socc + fit,
                        sdrp + (n_c - fit),
                    )

                # Once the threshold tightens (k-th smallest of the seen
                # set) almost every step captures NOTHING — the cond
                # skips the compaction and four slab scatters entirely,
                # so steady-state sampling costs one compare + reduce
                # per step.
                sampc = lax.cond(
                    below.any(), _capture, lambda sc: sc, sampc
                )

            # Overflow (> vcap valid candidates, > rcap distinct
            # candidates, OR probe-tail overflow reported as unresolved
            # candidates) => PARTIAL step: the inserted prefix is enqueued
            # (inserts are idempotent and enqueue==inserted keeps them
            # exactly-once), but the pops are NOT consumed — the same
            # parents re-expand with a halved take_cap until everything
            # fits/resolves. take_cap creeps back up on success.
            # Unresolved candidates are only FATAL when the batch cannot
            # shrink further (take == 1): that means genuinely exhausted
            # probe chains, i.e. state loss.
            err_cnt = err_cnt + jnp.where(take <= u(1), unres, u(0))
            ovf = (n_val > u(vcap)) | (n_d > u(rcap)) | (unres > u(0))
            tail = (head + count) & u(qmask)
            queue = fr.ring_scatter(
                queue, tail, dl + (debits, ddepth), c_new
            )

            consumed = jnp.where(ovf, u(0), take)
            head = (head + consumed) & u(qmask)
            count = count - consumed + new_count
            unique = unique + new_count
            gen = gen + jnp.where(ovf, u(0), ex.generated)
            steps = steps + (~ovf).astype(jnp.uint32)
            # Regrow at chunk/16 per clean step (was chunk/64): after an
            # overflow halves the cap, the old creep needed ~64 steps per
            # doubling to climb back — on 2pc-10 the run spent whole eras
            # popping quarter-width batches, paying full fixed per-step
            # cost for a fraction of the throughput (stage-profiled: the
            # per-step cost is width-insensitive below chunk). /16 restores
            # full width within ~16 clean steps while still backing off
            # geometrically under repeated overflow. Recovery is counted
            # in STEPS, not eras, and the cap round-trips through
            # P_TAKE_CAP — so adaptive era budgets (which make early eras
            # as short as BUDGET_MIN steps) and chained speculative
            # dispatches never reset or stall the climb; a halved cap
            # keeps recovering seamlessly across era boundaries.
            take_cap = jnp.where(
                ovf,
                jnp.maximum(take >> u(1), u(1)),
                jnp.minimum(take_cap + u(max(1, chunk // 16)), u(chunk)),
            )

            if cov:
                # Coverage histograms (obs/coverage.py), all in-carry:
                # per-action valid counts (the action-major [A*C] validity
                # mask reshaped and row-summed; gated on ovf exactly like
                # `gen`, so retried partial steps never double-count),
                # consumed rows (the per-property evaluation count), and
                # the per-depth insert histogram (inserts count
                # unconditionally, matching `unique`). None of these feed
                # the loop gate.
                #
                # The depth histogram deliberately avoids a scatter at the
                # distinct-candidate width (XLA:CPU scatter-adds cost
                # ~90ns/slot — 1.1ms/step at rcap width vs 0.13ms for this
                # form, microbenched) AND the reduction->broadcast
                # min-select the platform notes forbid in this carry:
                # candidate depths bucket into a fixed window of relative
                # offsets around depth[0]+1 via plain masked uint32 sums
                # (the carry-safe reduction pattern, same class as the
                # discovery-gate sums) and ONE fixed-width scatter lands
                # them. The window is TWO-SIDED: ring depth is only
                # non-decreasing up to the interleaved zones the
                # candidate-order append leaves at depth boundaries (see
                # _COV_LO above), so lane 0 is an anchor, not a minimum —
                # inserts up to _COV_LO levels below it count exactly via
                # wrapped-offset equality. Offsets past either edge clamp
                # into the boundary bucket — a step would have to pop
                # states spanning >= _COV_W (or interleave >= _COV_LO)
                # BFS levels at once to smear a depth, which no bundled
                # model comes near.
                act, covp, expanded, dhist = covc
                pa = ex.valid.reshape(A, chunk).sum(axis=1, dtype=u)
                act = act + jnp.where(ovf, u(0), pa)
                expanded = expanded + consumed
                dmin = depth[0] + u(1)
                # Biased offset: soffs == _COV_LO <=> ddepth == dmin.
                soffs = ddepth + u(_COV_LO) - dmin
                under = soffs >= u(0x80000000)  # beyond the low-side slack
                cnts = jnp.stack(
                    [(((soffs == u(0)) | under) & c_new).sum(dtype=u)]
                    + [
                        ((soffs == u(w)) & c_new).sum(dtype=u)
                        for w in range(1, _COV_LO + _COV_W - 1)
                    ]
                    + [
                        (
                            (soffs >= u(_COV_LO + _COV_W - 1))
                            & ~under
                            & c_new
                        ).sum(dtype=u)
                    ]
                )
                # Bucket w holds depth dmin - _COV_LO + w; saturate the
                # subtraction at 0 (early eras have dmin < _COV_LO — the
                # duplicate zero indices only ever receive zero counts,
                # since no insert sits at depth < 2).
                dd = dmin + jnp.arange(_COV_LO + _COV_W, dtype=u)
                idx = jnp.minimum(
                    jnp.where(dd >= u(_COV_LO), dd - u(_COV_LO), u(0)),
                    u(DEPTH_CAP - 1),
                )
                dhist = dhist.at[idx].add(cnts)
                covc = (act, covp, expanded, dhist)

            if P:
                hseen_n = []
                facc1_n = []
                facc2_n = []
                faccd_n = []
                covp_n = []
                for i in range(P):
                    hits = ex.prop_hits[i]
                    first = hits & ~hseen[i]
                    facc1_n.append(jnp.where(first, row_h1, facc1[i]))
                    facc2_n.append(jnp.where(first, row_h2, facc2[i]))
                    faccd_n.append(jnp.where(first, depth, faccd[i]))
                    hseen_n.append(hseen[i] | hits)
                    # Scalar discovery bit for the gate: a uint32 sum (NOT
                    # a boolean any()) so the carry stays on the fast path.
                    hs = hits.sum(dtype=jnp.uint32)
                    rec_acc = rec_acc | (jnp.minimum(hs, u(1)) << u(i))
                    if cov:
                        # Per-property hit totals ride the same sums the
                        # gate already pays for; ovf-gated like `gen` so
                        # retried rows are not re-counted.
                        covp_n.append(covc[1][i] + jnp.where(ovf, u(0), hs))
                hseen = tuple(hseen_n)
                facc1 = tuple(facc1_n)
                facc2 = tuple(facc2_n)
                faccd = tuple(faccd_n)
                if cov:
                    covc = (covc[0], tuple(covp_n), covc[2], covc[3])

            return (
                table,
                queue,
                head,
                count,
                unique,
                gen,
                steps,
                err_cnt,
                take_cap,
                rec_acc,
                hseen,
                facc1,
                facc2,
                faccd,
                covc,
                sampc,
                max_steps,
            )

        zero_lane = jnp.zeros(chunk, dtype=jnp.uint32) + (head0 & u(0))
        false_lane = zero_lane != 0
        covc0 = (
            (
                jnp.zeros(A, dtype=jnp.uint32),  # per-action valid counts
                tuple(u(0) for _ in range(P)),  # per-property hit counts
                u(0),  # consumed rows (property evaluation count)
                jnp.zeros(DEPTH_CAP, dtype=jnp.uint32),  # depth histogram
            )
            if cov
            else ()
        )
        sampc0 = (
            (
                # scap+1 wide: index scap is the masked-write trash slot.
                jnp.zeros(scap + 1, dtype=jnp.uint32),  # fp1
                jnp.zeros(scap + 1, dtype=jnp.uint32),  # fp2
                jnp.zeros(scap + 1, dtype=jnp.uint32),  # depth
                jnp.zeros(scap + 1, dtype=jnp.uint32),  # action index
                u(0),  # occupied
                u(0),  # dropped (per-step capture-width overflow)
            )
            if sample_k
            else ()
        )
        def run_era(table, queue, head0, count0, unique0, rec_bits,
                    max_steps, err0, take_cap0, covc, sampc,
                    rec_fp1, rec_fp2):
            # ONE era: the data-dependent while_loop plus its once-per-era
            # epilogue. Factored so the multi-era fusion path below can
            # chain N of these inside an outer device loop — coverage and
            # the sampling slab THREAD through (both are additive deltas /
            # persistent occupancy, drained once per readback), while the
            # fingerprint snapshot lanes reset per era (rec_bits threading
            # keeps first-discovery-wins across eras, matching the host
            # ordering of the serial driver).
            init = (
                table,
                queue,
                head0,
                count0,
                unique0,
                u(0),  # generated delta
                u(0),  # steps executed
                err0,  # unresolved-insert count (gates the era closed;
                # nonzero input = a seeding-time error surfacing on first
                # read)
                jnp.minimum(jnp.maximum(take_cap0, u(1)), u(chunk)),
                rec_bits,  # scalar discovery bits accumulated for fin gate
                tuple(false_lane for _ in range(P)),
                tuple(zero_lane for _ in range(P)),
                tuple(zero_lane for _ in range(P)),
                tuple(zero_lane for _ in range(P)),
                covc,
                sampc,
                max_steps,
            )
            (
                table,
                queue,
                head,
                count,
                unique,
                gen,
                steps,
                err_cnt,
                take_cap_out,
                _rec_acc,
                hseen,
                facc1,
                facc2,
                faccd,
                covc_out,
                sampc_out,
                _ms,
            ) = lax.while_loop(cond, body, init)

            # Era-level epilogue (runs ONCE per era, outside the step loop,
            # where argmax / dynamic gathers are cheap): extract discovery
            # fingerprints from the snapshots and the max depth from the
            # ring. Depth along the ring is non-decreasing, so the deepest
            # state visited is the last one popped, at ring slot head-1.
            # Under fusion this executes per INNER era — still bounded by
            # the fusion factor, not the step count, so the platform rule
            # (reductions only at era granularity) holds.
            rec_bits_out = rec_bits
            for i in range(P):
                found = jnp.any(hseen[i])
                # Select the SHALLOWEST snapshot hit, not an arbitrary
                # one: BFS must report a shortest counterexample even when
                # later, deeper iterations hit the property at other chunk
                # positions.
                sel = jnp.argmin(
                    jnp.where(hseen[i], faccd[i], u(0xFFFFFFFF))
                )
                take_new = found & (((rec_bits_out >> u(i)) & u(1)) == u(0))
                rec_fp1 = rec_fp1.at[i].set(
                    jnp.where(take_new, facc1[i][sel], rec_fp1[i])
                )
                rec_fp2 = rec_fp2.at[i].set(
                    jnp.where(take_new, facc2[i][sel], rec_fp2[i])
                )
                rec_bits_out = rec_bits_out | (found.astype(u) << u(i))
            maxd = jnp.where(
                steps > 0, queue[S + 1][(head - u(1)) & u(qmask)], u(0)
            )
            # Adaptive era budget (device-side emission): the NEXT era's
            # step budget rides the P_MAX_STEPS output slot, so a chained
            # (speculative) dispatch — or the next INNER era of a fused
            # dispatch — follows the exact deterministic schedule the
            # serial driver would. TCP-slow-start shape: double after an
            # era that exhausted its budget with no other exit reason
            # pending, halve under spill/grow pressure, floor at
            # BUDGET_MIN, clamp at budget_cap. budget_cap == 0 turns the
            # emission off (pure pass-through — free-running and
            # target-bounded runs keep their fixed budgets). The host's
            # wall-clock cap keeps checkpoint cadence and reporter updates
            # honest (see the engine driver).
            fin_hit_final = ((rec_bits_out & fin_any) != u(0)) | (
                (fin_all_en != u(0)) & ((rec_bits_out & fin_all) == fin_all)
            )
            pressure = (count > high_water) | (unique > grow_limit)
            budget_only = (
                (steps >= max_steps)
                & (count > u(0))
                & ~pressure
                & (err_cnt == u(0))
                & ~fin_hit_final
            )
            # In adaptive mode max_steps <= budget_cap <= 2^30 always
            # (host clamp), so the doubling cannot overflow uint32.
            grown = jnp.minimum(
                jnp.maximum(max_steps, u(1)) * u(2), budget_cap
            )
            shrunk = jnp.maximum(
                jnp.minimum(max_steps, budget_cap) >> u(1), u(BUDGET_MIN)
            )
            next_budget = jnp.where(
                budget_cap == u(0),
                max_steps,
                jnp.where(
                    pressure, shrunk,
                    jnp.where(budget_only, grown, max_steps),
                ),
            )
            return (table, queue, head, count, unique, rec_bits_out,
                    err_cnt, take_cap_out, covc_out, sampc_out,
                    rec_fp1, rec_fp2, steps, gen, maxd, next_budget,
                    budget_only)

        if fuse == 1:
            # Classic single-era program: no outer loop, no fusion tail —
            # bit-identical lowering to the pre-fusion build.
            (
                table, queue, head, count, unique, rec_bits_out, err_cnt,
                take_cap_out, covc_out, sampc_out, rec_fp1, rec_fp2,
                steps, gen, maxd, next_budget, _budget_only,
            ) = run_era(
                table, queue, head0, count0, unique0, rec_bits0,
                max_steps0, params[P_ERR], params[P_TAKE_CAP],
                covc0, sampc0, rec_fp1, rec_fp2,
            )
            ftail = []
        else:
            # Multi-era fusion: chain up to fuse_lim eras inside ONE
            # compiled program. The continuation gate re-derives the
            # serial driver's re-dispatch decision ON DEVICE: an inner
            # era chains iff its ONLY exit reason was budget exhaustion
            # with work remaining (budget_only — no spill/grow pressure,
            # no error, no finish hit, frontier nonempty) and the sample
            # slab still has a full era of headroom. Every other exit
            # needs host work, so the loop stops and the readback reports
            # which inner era tripped (n_inner) plus per-inner-era
            # steps/generated/unique/frontier lanes for exact flight
            # records. fuse_lim rides the params tail (clamped to
            # [1, fuse]), so the host degrades fusion at dispatch time —
            # checkpoint cadence due, spill backlog, targets — without a
            # recompile.
            fuse_lim = jnp.minimum(
                jnp.maximum(params[f_base], u(1)), u(fuse)
            )
            fzero = jnp.zeros(fuse, dtype=jnp.uint32)

            def ocond(oc):
                k, cont = oc[0], oc[1]
                return (k < fuse_lim) & (cont != u(0))

            def obody(oc):
                (
                    k, _cont, steps_acc, gen_acc, maxd_acc,
                    fsteps, fgen, funiq, fcount,
                    table, queue, head, count, unique, rec_bits, ms,
                    err, tc, covc, sampc, rfp1, rfp2,
                ) = oc
                uniq_in = unique
                (
                    table, queue, head, count, unique, rec_bits, err, tc,
                    covc, sampc, rfp1, rfp2, steps, gen, maxd,
                    next_budget, budget_only,
                ) = run_era(
                    table, queue, head, count, unique, rec_bits, ms,
                    err, tc, covc, sampc, rfp1, rfp2,
                )
                cont = budget_only.astype(u)
                if sample_k:
                    # One more era adds at most an era's worth of slab
                    # entries; stop while the host-drain high-water mark
                    # still guarantees no overflow.
                    cont = cont & (sampc[4] <= u(s_high)).astype(u)
                return (
                    k + u(1), cont, steps_acc + steps, gen_acc + gen,
                    jnp.maximum(maxd_acc, maxd),
                    fsteps.at[k].set(steps), fgen.at[k].set(gen),
                    funiq.at[k].set(unique - uniq_in),
                    fcount.at[k].set(count),
                    table, queue, head, count, unique, rec_bits,
                    next_budget, err, tc, covc, sampc, rfp1, rfp2,
                )

            oinit = (
                u(0), u(1), u(0), u(0), u(0),
                fzero, fzero, fzero, fzero,
                table, queue, head0, count0, unique0, rec_bits0,
                max_steps0, params[P_ERR], params[P_TAKE_CAP],
                covc0, sampc0, rec_fp1, rec_fp2,
            )
            (
                k_out, _cont, steps, gen, maxd,
                fsteps, fgen, funiq, fcount,
                table, queue, head, count, unique, rec_bits_out,
                next_budget, err_cnt, take_cap_out, covc_out, sampc_out,
                rec_fp1, rec_fp2,
            ) = lax.while_loop(ocond, obody, oinit)
            # Fusion tail: [fuse_lim (pass-through), n_inner] +
            # steps[fuse] | generated[fuse] | unique[fuse] |
            # frontier[fuse] — the host splits the one readback into
            # n_inner exact flight records.
            ftail = [
                jnp.stack([fuse_lim, k_out]),
                fsteps, fgen, funiq, fcount,
            ]

        parts = [
            jnp.stack(
                [
                    head,
                    count,
                    unique,
                    rec_bits_out,
                    depth_limit,
                    grow_limit,
                    high_water,
                    next_budget,
                    gen,
                    maxd,
                    steps,
                    (err_cnt > 0).astype(u),
                    take_cap_out,
                    fin_any,
                    fin_all,
                    fin_all_en,
                    budget_cap,
                ]
            ),
            rec_fp1,
            rec_fp2,
        ]
        if cov:
            # Coverage tail: act[A] | prop_hits[P] | expanded[1] | depth
            # hist[DEPTH_CAP] — this era's deltas, consumed by the host in
            # the SAME params download as everything else.
            act, covp, expanded, dhist = covc_out
            parts += [
                act,
                jnp.stack(list(covp)) if P else jnp.zeros(0, dtype=u),
                expanded[None],
                dhist,
            ]
        if sample_k:
            # Sample tail: [T1, T2, occupied, sdrop] + the sk2 smallest
            # slab entries by h1 (one top_k in the once-per-block
            # epilogue, where such reductions are cheap) with an explicit
            # validity lane — a real fp1 of 0xFFFFFFFF keys to 0 and
            # would otherwise be indistinguishable from padding. Ranking
            # by h1 alone skips 64-bit compares on device; the sk2 - k
            # pad rows plus the host's tie cut make the 64-bit bottom-k
            # exact (obs/sample.py module doc).
            sfp1, sfp2, sdep, sact, socc, sdrp = sampc_out
            used = jnp.arange(scap, dtype=u) < socc
            skey = jnp.where(used, ~sfp1[:scap], u(0))
            _topv, topi = lax.top_k(skey, sk2)
            parts += [
                jnp.stack([st1, st2, socc, sdrp]),
                sfp1[:scap][topi],
                sfp2[:scap][topi],
                sdep[:scap][topi],
                sact[:scap][topi],
                used[topi].astype(u),
            ]
        parts += ftail
        params_out = jnp.concatenate(parts)
        return table, queue, rec_fp1, rec_fp2, params_out

    if raw:
        _LOOP_CACHE[key] = (tm, loop)
        return loop
    # Two donation variants of the SAME traced program (device backends
    # only — donation under the CPU persistent compilation cache
    # miscompiles, compat docstring). The serial variant donates every
    # operand including the params row and the rec_fp lanes: the driver
    # only takes it when all five inputs were consumed host-side (fresh
    # upload / post-readback dispatch). The chain variant pins the params
    # operand (argnum 4): a speculative chained dispatch feeds the
    # PREVIOUS era's params output straight back in while its async
    # device->host readback is still in flight — donating it would alias
    # the in-place write over the copy source. rec_fps stay donated in
    # both: solo discovery state rides the params row, the fp handles are
    # never read back mid-chain.
    d_serial = donate_argnums_pinned((0, 1, 2, 3, 4))
    d_chain = donate_argnums_pinned((0, 1, 2, 3, 4), pinned=(4,))
    serial = jax.jit(loop, donate_argnums=d_serial)
    # On CPU both sets resolve () — reuse ONE executable, no double
    # compile (tier-1 runs on the CPU backend).
    chain = (
        serial
        if d_chain == d_serial
        else jax.jit(loop, donate_argnums=d_chain)
    )
    program = EraProgram(serial, chain)
    _LOOP_CACHE[key] = (tm, program)
    return program


_SEED_CACHE: Dict[Tuple, Any] = {}
_SEED_LOOP_CACHE: Dict[Tuple, Tuple[TensorModel, Any]] = {}


def _build_seed_loop(tm: TensorModel, props, chunk: int, qcap: int, tcap: int,
                     canon: bool, cov: bool, sample_k: int = 0,
                     fuse: int = 1):
    """Fuse run seeding and the FIRST era into one jitted dispatch.

    On this platform every dispatch costs a ~100ms tunnel round-trip, and
    time-to-first-counterexample is a primary metric (BASELINE.md): a bug
    a few steps deep should cost ONE round-trip, not a seed trip plus an
    era trip. The composed program inlines the raw seeder and era loop
    (at the engine's fusion factor — the seeding dispatch fuses its
    trailing eras exactly like a steady-state one); a run whose discovery
    fires in era 1 (or that completes outright) never pays a second
    dispatch.
    """
    fuse = max(1, int(fuse))
    key = (id(tm), chunk, qcap, tcap, len(props), canon, cov, sample_k,
           fuse)
    cached = _SEED_LOOP_CACHE.get(key)
    if cached is not None and cached[0] is tm:
        return cached[1]
    while len(_SEED_LOOP_CACHE) >= 16:
        _SEED_LOOP_CACHE.pop(next(iter(_SEED_LOOP_CACHE)))

    import jax

    loop = _build_loop(tm, props, chunk, qcap, canon, cov, raw=True,
                       sample_k=sample_k, fuse=fuse)
    seed = _build_seed(tm.state_width, qcap, tcap)

    @jax.jit
    def seed_run(qinit, h1, h2, params, rec_fp1, rec_fp2):
        table, queue, params2 = seed(qinit, h1, h2, params)
        return loop(table, queue, rec_fp1, rec_fp2, params2)

    _SEED_LOOP_CACHE[key] = (tm, seed_run)
    return seed_run


def _build_seed(S: int, qcap: int, tcap: int):
    """Compile the one-dispatch run seeder.

    Takes (qinit[W, n_init], params[P_LEN]) and returns (table, queue,
    params_out): fresh table/ring created ON DEVICE, init fingerprints
    claim-inserted (duplicate inits resolve exactly like the reference's
    bfs.rs:76-82 — all rows enqueue, the table keeps one), and the packed
    params filled in (count, unique, err). The output params feed the
    first era directly, so a run starts with ONE upload (qinit+params) and
    needs NO seed-time download — on this platform every host<->device
    sync costs a ~100ms round-trip, and the old eager seed path paid three.
    """
    key = (S, qcap, tcap)
    cached = _SEED_CACHE.get(key)
    if cached is not None:
        return cached
    while len(_SEED_CACHE) >= 16:
        _SEED_CACHE.pop(next(iter(_SEED_CACHE)))

    import jax
    import jax.numpy as jnp

    from ..ops import visited_set as vs

    W = S + 2  # ring lanes: state | ebits | depth (hashes recomputed on pop)

    @jax.jit
    def seed(qinit, h1, h2, params):
        u = jnp.uint32
        n_init = qinit.shape[1]
        table = vs.empty_table(tcap)
        zero = jnp.zeros(n_init, dtype=jnp.uint32)
        table, is_new, unresolved, _ovf = vs.insert(
            table, h1, h2, zero, zero,
            jnp.ones(n_init, bool),
        )
        queue = tuple(
            jnp.zeros(qcap, dtype=jnp.uint32).at[:n_init].set(qinit[i])
            for i in range(W)
        )
        params_out = (
            params.at[P_HEAD].set(u(0))
            .at[P_COUNT].set(u(n_init))
            .at[P_UNIQUE].set(is_new.sum(dtype=u))
            .at[P_ERR].set(unresolved.sum(dtype=u))
        )
        return table, queue, params_out

    _SEED_CACHE[key] = seed
    return seed


# Stage-profiler kernels: (id(tm), chunk, qcap, P, canon, iters) -> dict of
# jitted per-stage microbench kernels (obs/stageprof.py). Bounded like the
# loop caches; keyed without tcap because jit re-specializes per table shape.
_STAGE_KERNEL_CACHE: Dict[Tuple, Tuple[TensorModel, Dict[str, Any]]] = {}


def _build_stage_kernels(tm: TensorModel, props, chunk: int, qcap: int,
                         canon: bool, iters: int) -> Dict[str, Any]:
    """Build one jitted microbench kernel per era-loop stage.

    Each kernel has the uniform signature (table, queue, seed) -> uint32
    scalar and repeats EXACTLY the array program of one stage of one BFS
    step — at the era loop's compiled widths (chunk / C*A / vcap / rcap /
    dedup_cap, same derivations as `_build_loop`) — `iters` times inside a
    `lax.fori_loop`. A data dependence threads every iteration through the
    carried accumulator (or, for probe/ring, through the table/ring buffers
    themselves), so XLA can neither elide repetitions nor overlap them;
    the returned scalar anchors every stage output against dead-code
    elimination. Synthetic operands come from a lowbias32-style mixer at
    the right widths; the probe kernel inserts into (a copy-on-write fork
    of) the run's REAL table so it probes at the run's true load factor —
    it alternates between two bounded key pools, so the fork's load rises
    by at most 2*rcap/capacity over the whole measurement.
    """
    key = (id(tm), chunk, qcap, len(props), canon, iters)
    cached = _STAGE_KERNEL_CACHE.get(key)
    if cached is not None and cached[0] is tm:
        return cached[1]
    while len(_STAGE_KERNEL_CACHE) >= 8:
        _STAGE_KERNEL_CACHE.pop(next(iter(_STAGE_KERNEL_CACHE)))

    import jax
    import jax.numpy as jnp
    from jax import lax

    from ..fingerprint import hash_lanes_jnp
    from ..ops import frontier as fr
    from ..ops import visited_set as vs
    from ..ops.expand import build_expand_lean

    S = tm.state_width
    A = tm.max_actions
    W = S + 2
    u = jnp.uint32
    expand_lean = build_expand_lean(tm, props, chunk)
    qmask = qcap - 1
    vcap = _vcap(A, chunk)
    rcap = max(128 * A, (2 * vcap) // 5)
    dedup_cap = 1 << max(1, (4 * vcap - 1).bit_length())

    def _mix(x):
        # lowbias32: cheap elementwise mixer for synthetic lane data.
        x = x ^ (x >> 16)
        x = x * u(0x7FEB352D)
        x = x ^ (x >> 15)
        x = x * u(0x846CA68B)
        return x ^ (x >> 16)

    def _lane(n, salt):
        return _mix(jnp.arange(n, dtype=u) * u(0x9E3779B1) + u(salt))

    @jax.jit
    def k_expand(table, queue, seed):
        # Successor generation + property evaluation (expand_lean fuses
        # them, exactly as the era loop consumes it) over real ring rows.
        rows0 = tuple(queue[s][:chunk] for s in range(S))
        ebits0 = queue[S][:chunk]
        depth0 = queue[S + 1][:chunk]
        active = jnp.ones(chunk, dtype=bool)

        def body(_i, acc):
            rows = (rows0[0] ^ (acc & u(1)),) + rows0[1:]
            ex = expand_lean(rows, ebits0, depth0, active, u(0xFFFFFFFF))
            return acc + ex.generated

        return lax.fori_loop(0, iters, body, seed)

    @jax.jit
    def k_hash(table, queue, seed):
        # Both fingerprint passes of one step: popped rows at [chunk] and
        # compacted candidates at [vcap].
        rows0 = tuple(queue[s][:chunk] for s in range(S))
        cl0 = tuple(_lane(vcap, 11 + s) for s in range(S))

        def body(_i, acc):
            r = (rows0[0] ^ (acc & u(1)),) + rows0[1:]
            h1, h2 = hash_lanes_jnp(r)
            c = (cl0[0] ^ (acc & u(1)),) + cl0[1:]
            g1, g2 = hash_lanes_jnp(c)
            return acc + h1[0] + h2[0] + g1[0] + g2[0]

        return lax.fori_loop(0, iters, body, seed)

    @jax.jit
    def k_probe(table, queue, seed):
        # Visited-set insert at the distinct-candidate width, against the
        # run's real table (forked copy-on-write into the loop carry) so
        # probe chains run at the run's true load factor. Keys alternate
        # between two fixed pools (flip = acc & 1, data-dependent), so the
        # fork's load rises by at most 2*rcap over all iterations.
        pool1 = _lane(rcap, 21)
        pool2 = _mix(pool1 ^ u(0x6C62272E))
        ones = jnp.ones(rcap, dtype=bool)

        def body(_i, carry):
            tbl, acc = carry
            flip = acc & u(1)
            dh1 = pool1 ^ flip
            dh2 = pool2 ^ flip
            tbl, c_new, _unres, _ovf = vs.insert(tbl, dh1, dh2, dh1, dh2, ones)
            return tbl, acc + c_new.sum(dtype=u)

        tbl, acc = lax.fori_loop(0, iters, body, (table, seed))
        return acc + (tbl[0][0] & u(1))

    @jax.jit
    def k_claim(table, queue, seed):
        # In-batch dedup (fr.claim_dedup) at the valid-candidate width.
        p1 = _lane(vcap, 31)
        p2 = _lane(vcap, 37)
        valid = jnp.ones(vcap, dtype=bool)

        def body(_i, acc):
            h1 = p1 ^ (acc & u(1))
            reps = fr.claim_dedup(h1, p2, valid, dedup_cap)
            return acc + reps.sum(dtype=u)

        return lax.fori_loop(0, iters, body, seed)

    @jax.jit
    def k_compact(table, queue, seed):
        # Both validity compactions of one step — [C*A] -> vcap and
        # vcap -> rcap — INCLUDING the dependent gathers to the compacted
        # widths (the S state-lane gathers at vcap, then the S+3
        # candidate/parent gathers at rcap), which are the stage's real
        # cost on this platform (~65ns/element dependent-gather latency).
        flat0 = tuple(_lane(chunk * A, 41 + s) for s in range(S))
        r1 = _lane(chunk * A, 53)
        r2 = _lane(vcap, 59)
        rowl = _lane(chunk, 61)

        def body(_i, acc):
            m1 = ((r1 ^ acc) & u(3)) == u(0)  # ~25% valid: protocol fanout
            vids, _vv, n1 = vs._compact_ids(m1, vcap)
            cl = tuple(flat0[s][vids] for s in range(S))
            m2 = ((r2 ^ acc) & u(1)) == u(0)  # ~50% distinct post-dedup
            dids, _dv, n2 = vs._compact_ids(m2, rcap)
            dl = tuple(cl[s][dids] for s in range(S))
            src = vids[dids] % u(chunk)
            acc = acc + n1 + n2 + rowl[src].sum(dtype=u)
            for lane in dl:
                acc = acc + lane.sum(dtype=u)
            return acc

        return lax.fori_loop(0, iters, body, seed)

    @jax.jit
    def k_ring(table, queue, seed):
        # One step's ring traffic: pop-gather [chunk] rows, append-scatter
        # [rcap] rows, threaded through the (forked) ring so iterations
        # chain. Per-lane sums anchor the full-width gathers and seed the
        # appended rows (gather -> cand -> scatter -> next gather).
        base = jnp.arange(rcap, dtype=u)

        def body(_i, carry):
            q, head, acc = carry
            popped, _idx = fr.ring_gather(q, head, chunk)
            cand = tuple(
                _mix(base * u(2654435761) + popped[w].sum(dtype=u) + u(w * 17))
                for w in range(W)
            )
            valid = jnp.ones(rcap, dtype=bool)
            q = fr.ring_scatter(q, (head + u(chunk)) & u(qmask), cand, valid)
            head = (head + u(chunk)) & u(qmask)
            return q, head, acc + cand[0][0]

        _q, _h, acc = lax.fori_loop(0, iters, body, (queue, seed, seed))
        return acc

    kernels: Dict[str, Any] = {
        "expand": k_expand,
        "hash": k_hash,
        "probe": k_probe,
        "claim": k_claim,
        "compact": k_compact,
        "ring": k_ring,
    }

    if canon:

        @jax.jit
        def k_canon(table, queue, seed):
            # Symmetry canonicalization at the valid-candidate width.
            # Lane values are masked into a small domain so the model's
            # representative program sees plausible field encodings.
            cl0 = tuple(_lane(vcap, 71 + s) & u(7) for s in range(S))

            def body(_i, acc):
                cl = (((cl0[0] ^ (acc & u(1))) & u(7)),) + cl0[1:]
                reps = tm.representative_lanes(jnp, cl)
                for lane in reps:
                    acc = acc + lane.sum(dtype=u)
                return acc

            return lax.fori_loop(0, iters, body, seed)

        kernels["canon"] = k_canon

    _STAGE_KERNEL_CACHE[key] = (tm, kernels)
    return kernels


# Below roughly this many unique states, the host engine's per-state cost
# beats the device engine's fixed per-dispatch round-trips and compile time
# (measured: a 2pc-4-sized run reaches only ~32K st/s on device while
# spawn_bfs clears it host-side before the first era returns — see the
# README "engine crossover" note).
SMALL_WORKLOAD_STATES = 10_000


class TpuBfsChecker(HostEngineBase):
    """Batched BFS over a TensorModel on the default JAX device."""

    # Parallelism here is the data-parallel chunk, not worker threads;
    # .threads(n) is accepted (and is a no-op) for API compatibility.
    _supports_threads = True

    def __init__(
        self,
        builder: CheckerBuilder,
        *,
        chunk_size: int = 8192,
        queue_capacity: int = 1 << 20,
        table_capacity: int = 1 << 22,
        sync_steps: int = 4096,
        checkpoint_path: Optional[str] = None,
        checkpoint_every: Optional[float] = None,
        resume_from: Optional[str] = None,
        keep_checkpoints: int = 2,
        compiled=None,
    ):
        model = builder.model
        if isinstance(model, TensorModel):
            model = TensorModelAdapter(model)
        if not isinstance(model, TensorModelAdapter):
            raise TypeError(
                "spawn_tpu_bfs requires a TensorModel (or its adapter); "
                "rich host models must be encoded first — see stateright_tpu.tensor."
            )
        if compiled is not None:
            # Build/run split (engines/compiled.py): run against the
            # compiled check's interned model instance so every id(tm)-keyed
            # jit cache below hits — the request pays a dict lookup, not a
            # trace + XLA compile.
            from .compiled import model_signature

            if model_signature(model.tm) != compiled.signature:
                raise ValueError(
                    "CompiledCheck signature mismatch: executable was built "
                    f"for {compiled.signature!r}, builder model is "
                    f"{model_signature(model.tm)!r}"
                )
            if model.tm is not compiled.tm:
                model = TensorModelAdapter(compiled.tm)
        super().__init__(builder, model=model)
        if self._visitor is not None:
            raise ValueError("the TPU engine does not support visitors")

        self.tm: TensorModel = model.tm
        # Symmetry reduction ON DEVICE (beyond the reference, whose BFS
        # ignores options.symmetry — only its DFS canonicalizes): when the
        # builder asks for symmetry, candidates are canonicalized by the
        # model's batched representative_lanes program before hashing and
        # insertion, so the frontier and visited set live entirely in
        # representative space (2pc-5: 8,832 -> 1,092 states).
        self._canon = builder.symmetry_fn_ is not None
        if self._canon and self.tm.representative_lanes is None:
            raise ValueError(
                f"symmetry requested but {type(self.tm).__name__} defines "
                "no representative_lanes canonicalizer"
            )
        self._tprops = self.tm.tensor_properties()
        n_event = sum(
            1 for p in self._tprops if p.expectation == Expectation.EVENTUALLY
        )
        if n_event > 32:
            raise ValueError("at most 32 eventually-properties supported")
        if len(self._tprops) > 32:
            # The recorded-discovery set crosses the host boundary as one
            # uint32 bitmask (see the packed-params layout above).
            raise ValueError("at most 32 tensor properties supported")
        if queue_capacity & (queue_capacity - 1):
            raise ValueError("queue_capacity must be a power of two")
        # qcap >= 2*C*A guarantees (a) the ring append never wraps over
        # unconsumed rows while count <= high_water and (b) a spill block
        # (<= C*A rows) always fits during refill, so spilled states are
        # never stranded.
        self._chunk = min(
            chunk_size, queue_capacity // (2 * max(1, self.tm.max_actions))
        )
        if self._chunk == 0:
            raise ValueError("queue_capacity too small for this model's fanout")
        self._qcap = queue_capacity
        self._tcap = table_capacity
        self._max_sync_steps = sync_steps
        # Checkpoint/resume: a capability the reference lacks (its runs are
        # in-memory only, SURVEY.md §5) — the dense table/ring layout makes
        # a checkpoint a straight array download. Writes are crash-atomic
        # with rolling generations and a content digest (engines/common.py);
        # checkpoint_every is wall-clock seconds, polled at era boundaries.
        validate_checkpoint_cadence(
            checkpoint_every, checkpoint_path, keep_checkpoints
        )
        self._ckpt_path = checkpoint_path
        self._ckpt_every = checkpoint_every
        self._ckpt_keep = keep_checkpoints
        self._resume_from = resume_from
        self._last_ckpt = time.monotonic()
        # Chaos-injection hook (tests/test_durability_chaos.py): pretend the
        # probe budget was exhausted once this era count is reached,
        # exercising the degraded-regrow recovery without needing a
        # pathological probe sequence.
        self._chaos_probe_error_era: Optional[int] = None
        if checkpoint_path is not None:
            register_signal_checkpoint_flush(self)
        self._cov = self._coverage.enabled
        # Bottom-k space sampling (obs/sample.py): the compiled loop
        # carries the capture slab only when the builder asked for it.
        self._sample_k = self._sampler.k if self._sampler is not None else 0
        # Multi-era fusion factor (CheckerBuilder.pipeline(fuse=N)): the
        # compiled program chains up to N eras on device per dispatch.
        # The factor is part of the loop-cache / executable-cache key.
        self._fuse = max(1, int(getattr(builder, "fuse_eras_", None) or 1))
        program = _build_loop(
            self.tm, self._tprops, self._chunk, self._qcap, self._canon,
            self._cov, sample_k=self._sample_k, fuse=self._fuse,
        )
        self._loop = program.serial
        self._loop_chain = program.chain
        # Absolute params offset of the fusion tail (only present when
        # fuse > 1); cached for the driver's readback splitting.
        self._fbase = params_len(
            self.tm.max_actions, len(self._tprops), self._cov,
            self._sample_k,
        )

        # Host-side bookkeeping.
        self._unique = 0
        self._discovery_fps: Dict[str, int] = {}
        # Tiered spill staging (ops/tiering.py): a budgeted host-RAM LIFO
        # with an npz disk tier below it; unbudgeted (env unset) it is a
        # plain in-RAM stack, byte-for-byte the old list behavior.
        self._spill = TieredSpillStore(
            host_budget_bytes=spill_host_budget_bytes(),
            on_tier=self._on_spill_tier,
        )
        # Delta-checkpoint chain state (engines/common.py
        # save_checkpoint_tiered): None = next save is a full base.
        self._ckpt_delta = None
        # Era of the last proactive reshard (one doubling per forecast).
        self._reshard_last_era = -1
        # The metrics registry (obs/metrics.py, created by the base class)
        # carries the engine's health gauges — eras dispatched, steps
        # executed, spill/refill row volume, table growths, take_cap —
        # surfaced via Checker.telemetry() / report, plus per-era phase
        # timers (device_era, readback, spill, refill, table_grow).
        self._metrics.set_gauge("take_cap", self._chunk)
        self._era_t0: Optional[float] = None
        # Per-stage era profiling (CheckerBuilder.stage_profile()): after
        # the run, microbench each loop stage at the compiled shapes and
        # attribute the measured device_era time (obs/stageprof.py).
        self._stage_profile = bool(getattr(builder, "stage_profile_", False))
        self._stage_iters = int(getattr(builder, "stage_profile_iters_", 32))
        # Multiplexed-lane runs are the INTENDED path for sub-crossover
        # state spaces (serve/README.md): a lane shares one compiled
        # executable and one fused era with its whole batch, so the
        # per-run dispatch/compile overheads the hint warns about do not
        # apply — firing it there would steer users away from the right
        # engine.
        self._mux_lane = bool(getattr(builder, "multiplex_lane_", False))
        # Speculative era pipelining (CheckerBuilder.pipeline(), default
        # on): keep up to K eras chained off the still-on-device state
        # while their readbacks are in flight. See the _run driver for
        # the soundness argument (chained dispatch is an identity no-op
        # on every device-visible host-intervention exit).
        self._pipeline = bool(getattr(builder, "pipeline_", True))
        depth = getattr(builder, "pipeline_depth_", None)
        # Auto depth 2: one extra era of overlap beyond PR 14's depth-1
        # covers the readback+bookkeeping gap; deeper chains only pay off
        # when host work per era exceeds a full device era (rare), while
        # every extra in-flight era grows the wasted-work window on
        # host-intervention exits.
        self._chain_depth = max(1, int(depth)) if depth is not None else 2
        # High-water mark of in-flight chained dispatches (gauge).
        self._chain_max = 0
        # Small-workload guard: with a state-count target under the
        # crossover, the host engine will beat this one — say so up front
        # (the run-end check below catches untargeted small runs).
        if (
            builder.target_state_count_ is not None
            and builder.target_state_count_ < SMALL_WORKLOAD_STATES
        ):
            self._small_workload_hint(builder.target_state_count_, "targeted")

        self._init_ebits_tensor = 0
        e = 0
        for p in self._tprops:
            if p.expectation == Expectation.EVENTUALLY:
                self._init_ebits_tensor |= 1 << e
                e += 1

        self._start()

    # -- engine body --------------------------------------------------------

    def _run(self) -> None:
        import jax.numpy as jnp

        from ..fingerprint import hash_words_np
        from ..ops import visited_set as vs

        tm = self.tm
        S = tm.state_width
        A = tm.max_actions
        C = self._chunk
        P = len(self._tprops)
        W = S + 2  # queue lanes: state | ebits | depth
        ncov = _cov_len(A, P) if self._cov else 0
        # Sample tail sizing (obs/sample.py): [T1, T2, occupied, sdrop]
        # plus five drained lanes of slab_entries(k) words each.
        if self._sample_k:
            from ..obs.sample import slab_entries

            sk2 = slab_entries(self._sample_k)
            nsamp = 4 + 5 * sk2
            s_base = P_LEN + 2 * P + ncov
        else:
            sk2 = nsamp = s_base = 0
        last_thresh = None  # threshold words last uploaded to the device

        depth_limit = (
            self._target_max_depth
            if self._target_max_depth is not None
            else 0xFFFFFFFF
        )
        high_water = self._qcap - C * A
        # Era budget: the device loop exits by itself on every meaningful
        # condition (empty frontier, spill, grow, discovery-finish, probe
        # error); the step budget only exists so the host can poll wall-
        # clock concerns — timeouts and checkpoint cadence — at bounded
        # granularity. Unbudgeted runs use the full sync_steps allowance.
        max_sync = (
            self._max_sync_steps
            if self._timeout is None and self._ckpt_every is None
            else min(BUDGET_MIN, self._max_sync_steps)
        )
        # Adaptive era budget (TCP-slow-start): engaged only when a wall-
        # clock concern forces polling. The DEVICE emits the next era's
        # budget through the P_MAX_STEPS output slot (doubling after
        # budget-only exits, halving under spill/grow pressure — see
        # _build_loop's epilogue), which keeps the schedule deterministic
        # and identical whether eras are dispatched serially or
        # speculatively. The host only moves the CAP, by wall-clock
        # feedback, so growing eras can never starve checkpoint cadence or
        # reporter updates. budget_cap == 0 disables the emission entirely
        # (free-running runs keep the full fixed allowance).
        adaptive = self._timeout is not None or self._ckpt_every is not None
        budget = max_sync
        budget_cap = min(BUDGET_MIN, max_sync) if adaptive else 0
        cap_limit = min(self._max_sync_steps, 1 << 30)  # uint32-safe doubling
        poll_target = None
        if self._ckpt_every is not None:
            poll_target = self._ckpt_every / 4.0
        if self._timeout is not None:
            t = self._timeout / 4.0
            poll_target = t if poll_target is None else min(poll_target, t)
        # Finish-policy discovery masks for the device-side early exit.
        fin_any, fin_all, fin_all_en = self._finish_when.device_masks(
            self._tprops
        )
        params_dev = None
        last_max_steps = None
        last_budget_cap = budget_cap
        take_cap = self._chunk
        # Multi-era fusion: tail sizing and the per-dispatch degrade. The
        # device chains up to fuse_lim eras per dispatch; the host shrinks
        # fuse_lim to 1 (one compiled program serves every value — it
        # rides the params tail) whenever a per-era host concern is near:
        # spill backlog to refill, a state-count target to clamp, or a
        # wall-clock cadence (checkpoint, timeout) past half-elapsed —
        # fused eras can't poll mid-dispatch, so fusion backs off before
        # it could overshoot a cadence rather than after.
        nfuse = fuse_tail_len(self._fuse)
        fb = self._fbase
        last_fuse_lim = None

        def _fuse_lim_now() -> int:
            if self._fuse <= 1:
                return 1
            if self._spill or self._target_state_count is not None:
                return 1
            now = time.monotonic()
            if (
                self._ckpt_every is not None
                and now - self._last_ckpt >= self._ckpt_every / 2
            ):
                return 1
            if (
                self._deadline is not None
                and now >= self._deadline - self._timeout / 2
            ):
                return 1
            # Auto-N (engines/common.py): when the flight history shows
            # the dispatch gap already amortized, back the factor off to
            # keep the wasted-work window on host-intervention exits small.
            return self._fuse_auto_n(self._fuse)

        _dbg("run: encoding inits")
        if self._resume_from is not None:
            table, queue, head, count, rec_bits, rec_fp1, rec_fp2 = (
                self._load_checkpoint(self._resume_from, W)
            )
            first_result_pending = False
        else:
            inits = np.asarray(tm.init_states_array(), dtype=np.uint32)
            init_lanes = tuple(inits[:, i] for i in range(S))
            inb = np.asarray(
                tm.within_boundary_lanes(np, init_lanes), dtype=bool
            )
            inits = inits[inb]
            if self._canon:
                canon_lanes = tm.representative_lanes(
                    np, tuple(inits[:, i] for i in range(S))
                )
                inits = np.stack(
                    [np.asarray(l, dtype=np.uint32) for l in canon_lanes],
                    axis=1,
                )
                # Dedupe representatives host-side: distinct raw inits can
                # canonicalize to one representative, and while the table's
                # claim insert would keep exactly one, every duplicate ROW
                # would still enqueue (a redundant re-expansion each) and
                # count toward state_count — ring contents and counters
                # must agree with the table's view under symmetry.
                inits = np.unique(inits, axis=0)
            n_init = len(inits)
            self._state_count = n_init
            if n_init == 0:
                return
            if self._cov:
                # Unique initial states enter the visited set at depth 1
                # inside the fused seeder, before the loop's histogram
                # starts counting — record them host-side (distinct rows
                # == distinct fingerprints short of a hash collision).
                self._coverage.record_depth(
                    1, len(np.unique(inits, axis=0))
                )
            if n_init > self._qcap:
                raise ValueError("more initial states than queue capacity")
            vcap = _vcap(A, C)
            while n_init + vcap > vs.MAX_LOAD * self._tcap:
                self._tcap *= 2

            # One upload (qinit rows + params template), zero downloads: the
            # jitted seeder builds the table/ring on device, claim-inserts
            # the init fingerprints (dup inits resolve like bfs.rs:76-82;
            # all rows enqueue), and fills count/unique/err into the packed
            # params, which feed the first era dispatch directly.
            h1, h2 = hash_words_np(inits)
            if self._sampler is not None:
                # The seeder inserts init states before the era loop's
                # slab starts capturing — offer them host-side (their
                # rows are in hand anyway, so the sample records carry
                # real state lanes for free).
                self._sampler.offer_array(
                    (h1.astype(np.uint64) << np.uint64(32))
                    | h2.astype(np.uint64),
                    depths=np.ones(n_init, dtype=np.int64),
                    states=inits,
                )
            qinit = np.zeros((W, n_init), dtype=np.uint32)
            qinit[:S] = inits.T
            qinit[S] = self._init_ebits_tensor
            qinit[S + 1] = 1

            max_steps0 = max_sync
            if self._target_state_count is not None:
                remaining = max(0, self._target_state_count - n_init)
                max_steps0 = max(
                    1, min(max_steps0, 1 + remaining // max(1, C * A))
                )
            template = np.zeros(
                P_LEN + 2 * P + ncov + nsamp + nfuse, dtype=np.uint32
            )
            if nfuse:
                last_fuse_lim = _fuse_lim_now()
                template[fb] = last_fuse_lim
            if self._sampler is not None:
                t1, t2 = self._sampler.threshold_parts()
                template[s_base] = t1
                template[s_base + 1] = t2
                last_thresh = (t1, t2)
            template[P_DEPTH_LIMIT] = depth_limit
            template[P_HIGH_WATER] = high_water
            template[P_MAX_STEPS] = max_steps0
            template[P_TAKE_CAP] = take_cap
            template[P_FIN_ANY] = fin_any
            template[P_FIN_ALL] = fin_all
            template[P_FIN_ALL_EN] = fin_all_en
            template[P_BUDGET_CAP] = budget_cap
            template[P_GROW_LIMIT] = max(
                0, int(vs.MAX_LOAD * self._tcap) - vcap
            )

            rec_bits = 0
            rec_fp1 = jnp.zeros(P, dtype=jnp.uint32)
            rec_fp2 = jnp.zeros(P, dtype=jnp.uint32)
            _dbg("run: dispatching fused seed+first-era")
            seed_run = _build_seed_loop(
                tm, self._tprops, C, self._qcap, self._tcap, self._canon,
                self._cov, sample_k=self._sample_k, fuse=self._fuse,
            )
            self._era_t0 = time.monotonic()
            table, queue, rec_fp1, rec_fp2, params_dev = seed_run(
                jnp.asarray(qinit), jnp.asarray(h1), jnp.asarray(h2),
                jnp.asarray(template), rec_fp1, rec_fp2,
            )
            self._metrics.inc("dispatches")
            head = 0
            count = n_init
            # Provisional (exact unless dup inits); corrected at first read.
            self._unique = n_init
            self._mem_register(table, queue, (rec_fp1, rec_fp2), params_dev)
            last_max_steps = max_steps0
            first_result_pending = True
            _dbg("run: seeded; entering era loop")

        # Spill hysteresis: drain down to / refill up to this margin below
        # high_water, so a spilling run still gets long eras between host
        # round-trips instead of bouncing on the watermark (see the drain
        # note below). Guaranteed >= one block of room: qcap >= 2*C*A.
        spill_target = max(high_water // 2, high_water - 64 * C * A)
        stop = False

        def process_result(spec_in_flight=False):
            """Consume one era result (the fused seed+first-era dispatch or
            a loop dispatch): counters, discoveries, spill, checkpoints,
            and stop conditions. With ``spec_in_flight`` a chained
            speculative era is still executing on device: the checkpoint
            save is deferred to the next serial boundary (the table/queue
            bindings here are the NEXT era's output buffers, so a save now
            could pair this era's head/count with a ring the next era has
            already advanced — unless that era is a no-op, which the
            caller cannot know yet)."""
            nonlocal head, count, take_cap, rec_bits, stop, params_dev
            nonlocal budget, budget_cap, last_thresh
            with self._metrics.phase("readback"):
                vals = np.asarray(params_dev)  # the ONE download per block
            era_dt = 0.0
            if self._era_t0 is not None:
                # The era's true wall time: dispatch through readback
                # complete (dispatch alone returns immediately — JAX is
                # async on this platform).
                era_dt = time.monotonic() - self._era_t0
                self._metrics.add_phase("device_era", era_dt)
                # Distribution twin of the cumulative phase: era latency
                # percentiles for /stats and the Prometheus exposition.
                self._metrics.observe("era_secs", era_dt)
                self._era_t0 = None
            # Fused dispatch: the readback covers n_inner on-device eras;
            # the fusion tail carries which inner era tripped plus the
            # per-inner-era lanes the flight records need.
            n_inner = 1
            if nfuse:
                n_inner = max(1, min(int(vals[fb + 1]), self._fuse))
            _dbg(
                f"era result steps={vals[10]} gen={vals[8]} count={vals[1]} "
                f"unique={vals[2]} rec={vals[3]:b} inner={n_inner}"
            )
            err = int(vals[11])
            if not err and self._chaos_probe_error_era is not None and (
                self._metrics.get("eras") >= self._chaos_probe_error_era
            ):
                self._chaos_probe_error_era = None
                err = 1
            if err:
                # Cannot happen with the proactive growth short of a
                # pathological probe sequence; losing states would be an
                # unsound "verified", so the era's work must be discarded.
                # A nonzero error with ZERO steps on the first era means the
                # unresolved count flowed in from the seeder (init-state
                # insert), not the era loop — attribute it correctly.
                if self._metrics.get("eras") == 0 and int(vals[10]) == 0:
                    raise RuntimeError(
                        "init-state seeding exhausted the visited-table "
                        "probe budget (duplicate-heavy or adversarial "
                        "initial fingerprints); raise table_capacity"
                    )
                # Recoverable when a checkpoint exists: the while loop
                # reloads the pre-era state, regrows, and re-runs.
                raise _ProbeBudgetExhausted(
                    "visited-table probe budget exhausted despite headroom"
                )
            head = int(vals[0])
            count = int(vals[1])
            take_cap = int(vals[P_TAKE_CAP])
            # Device-emitted next-era budget (pass-through when adaptivity
            # is off); the budget USED by the era just consumed is gauged
            # for the obs catalog.
            budget = int(vals[P_MAX_STEPS])
            if last_max_steps is not None:
                self._metrics.set_gauge(
                    "era_step_budget", int(last_max_steps)
                )
            if poll_target is not None and era_dt > 0.0:
                # Wall-clock cap feedback: let the device's slow-start
                # climb only while eras stay well inside the polling
                # cadence; back the cap off when an era overshoots it.
                # Under fusion the dispatch covers n_inner eras — the
                # feedback steers the PER-ERA budget, so compare the
                # per-era share of the wall time.
                per_era_dt = era_dt / n_inner
                if per_era_dt < poll_target / 2 and budget_cap < cap_limit:
                    budget_cap = min(budget_cap * 2, cap_limit)
                elif per_era_dt > poll_target and budget_cap > BUDGET_MIN:
                    budget_cap = max(budget_cap // 2, BUDGET_MIN)
            self._metrics.inc("eras", n_inner)
            self._metrics.inc("steps", int(vals[10]))
            self._metrics.inc("states_generated", int(vals[8]))
            self._metrics.set_gauge("take_cap", take_cap)
            self._unique = int(vals[2])
            self._state_count += int(vals[8])
            self._max_depth = max(self._max_depth, int(vals[9]))
            # Record first discovery per property (reference races are
            # benign; ours are deterministic per compiled program).
            new_bits = int(vals[3])
            if new_bits != rec_bits:
                # Discovery fingerprints ride the params tail — no extra
                # device read on the counterexample path.
                fp1 = vals[P_LEN : P_LEN + P]
                fp2 = vals[P_LEN + P : P_LEN + 2 * P]
                for i, p in enumerate(self._tprops):
                    if (new_bits >> i) & 1 and p.name not in self._discovery_fps:
                        self._discovery_fps[p.name] = combine64(fp1[i], fp2[i])
                rec_bits = new_bits

            if self._cov:
                # The era's coverage deltas ride the same download
                # (layout: act[A] | prop_hits[P] | expanded | depth hist).
                base = P_LEN + 2 * P
                cov_acc = self._coverage
                cov_acc.record_action_counts(vals[base : base + A])
                expanded = int(vals[base + A + P])
                for i, p in enumerate(self._tprops):
                    cov_acc.record_property_eval(p.name, expanded)
                    cov_acc.record_property_hit(
                        p.name, int(vals[base + A + i])
                    )
                cov_acc.record_depth_counts(
                    vals[base + A + P + 1 : base + ncov]
                )

            if self._sampler is not None:
                # Sample-slab drain: same download as everything else.
                occupied = int(vals[s_base + 2])
                sdrop = int(vals[s_base + 3])
                off = s_base + 4
                if occupied or sdrop:
                    self._sampler.drain_slab(
                        vals[off : off + sk2],
                        vals[off + sk2 : off + 2 * sk2],
                        vals[off + 2 * sk2 : off + 3 * sk2],
                        vals[off + 4 * sk2 : off + 5 * sk2],
                        occupied,
                        dropped=sdrop,
                        actions=vals[off + 3 * sk2 : off + 4 * sk2],
                    )
                if self._sampler.threshold_parts() != last_thresh:
                    # The drain tightened the threshold: force a fresh
                    # params upload next era so the device stops
                    # capturing (sound either way — a stale threshold
                    # only admits a superset — but a tighter one keeps
                    # eras long and the slab quiet). Converges fast:
                    # expected total captures are ~k * ln(n / k).
                    params_dev = None

            # Spill if the next chunk could overflow the ring. Drain to the
            # MARGIN below the watermark, not just to it: draining only the
            # overhang lets the very next era re-cross the line after a few
            # steps, thrashing spill round-trips (measured on ABD c=4:
            # 2-3 useful steps per ~7s spill cycle). The margin trades one
            # bigger drain for eras long enough to amortize it.
            spilled = 0
            if count > high_water:
                k = count - spill_target
                take_idx = jnp.asarray(
                    (head + count - k + np.arange(k)) & (self._qcap - 1)
                )
                # Stack on device, download ONCE (per-lane downloads cost a
                # ~100ms round-trip each on this platform).
                with self._metrics.phase("spill"):
                    big = np.asarray(
                        jnp.stack(
                            [queue[i][take_idx] for i in range(W)], axis=1
                        )
                    )
                # Keep blocks refill-sized so partial refills stay possible.
                for off in range(0, k, C * A):
                    self._spill.append(big[off : off + C * A])
                count -= k
                spilled = k
                self._metrics.inc("spill_rows", k)
                # Refills can place these rows after deeper children, breaking
                # the ring's depth monotonicity that the block-level maxd read
                # relies on — fold their depth in here. (Counts rows that are
                # guaranteed to be visited unless the run stops early; a rare
                # slight over-report beats a systematic under-report.)
                self._max_depth = max(self._max_depth, int(big[:, S + 1].max()))
                params_dev = None  # host-side count changed; force re-upload
                if self._memory is not None:
                    self._memory.staging(
                        self._spill.host_bytes(),
                        event="spill",
                        rows=int(k),
                    )

            self._obs_event(
                "era",
                frontier=count,
                load_factor=round(self._unique / self._tcap, 4),
                take_cap=take_cap,
                steps=int(vals[10]),
                generated=int(vals[8]),
                spill_rows=spilled,
            )

            if not spec_in_flight and self._ckpt_path is not None and (
                self._ckpt_every is not None
                and time.monotonic() - self._last_ckpt >= self._ckpt_every
            ):
                self._save_checkpoint(
                    table, queue, head, count, rec_bits, rec_fp1, rec_fp2
                )

            # Flight record after spill/checkpoint so this era's host work
            # lands in its own host_gap (zero extra device reads: every
            # field is from `vals` or host clocks). A fused dispatch
            # splits into one record per inner era from the tail lanes
            # (steps/generated/unique-delta/frontier), keeping the
            # recording exact: the wall/device identity holds across the
            # group, and the per-era counters sum to the dispatch totals.
            inner = None
            if nfuse and n_inner > 1:
                fsteps = vals[fb + 2 : fb + 2 + self._fuse]
                fgen = vals[
                    fb + 2 + self._fuse : fb + 2 + 2 * self._fuse
                ]
                funiq = vals[
                    fb + 2 + 2 * self._fuse : fb + 2 + 3 * self._fuse
                ]
                fcount = vals[
                    fb + 2 + 3 * self._fuse : fb + 2 + 4 * self._fuse
                ]
                u_before = self._unique - int(funiq[:n_inner].sum())
                inner = []
                uacc = u_before
                for j in range(n_inner):
                    uacc += int(funiq[j])
                    inner.append(
                        {
                            "steps": int(fsteps[j]),
                            "generated": int(fgen[j]),
                            "unique": uacc,
                            "frontier": int(fcount[j]),
                            "load_factor": round(uacc / self._tcap, 4),
                        }
                    )
            self._flight_record(
                device_era_secs=era_dt,
                steps=int(vals[10]),
                generated=int(vals[8]),
                unique=self._unique,
                frontier=count,
                load_factor=round(self._unique / self._tcap, 4),
                take_cap=take_cap,
                spill_rows=spilled,
                inner=inner,
            )

            if self._finish_matched(self._discovery_fps):
                stop = True
            elif (
                self._target_state_count is not None
                and self._state_count >= self._target_state_count
            ):
                stop = True
            elif self._timed_out():
                stop = True
            elif self._ckpt_stop.is_set():
                # Graceful-stop request (SIGTERM/SIGINT flush): exit the
                # loop; the final checkpoint below captures this boundary.
                self._metrics.set_gauge("interrupted", 1)
                stop = True

        if first_result_pending:
            process_result()

        # Graceful degradation budget: each recovery doubles the table, so
        # a handful of rounds covers any realistic exhaustion; an unbounded
        # loop would mask a genuinely pathological model.
        regrow_budget = 8

        # Speculative era pipelining (tentpole; CheckerBuilder.pipeline()):
        # the device loop re-derives EVERY exit condition from the chained
        # params vector — count/high_water/grow_limit/fin bits/err_cnt all
        # gate the while predicate, and err_cnt seeds from P_ERR — so an
        # era dispatched off a host-intervention boundary is an exact
        # identity no-op (outputs value-identical to inputs). That makes
        # chaining era N+1 before era N's readback unconditionally SOUND
        # for device-visible exits; the chain is simply not entered while
        # any host-ONLY concern (spill-backlog refill, checkpoint cadence,
        # timeout, graceful stop, state-count targets) could fire, and the
        # two that can still land mid-era (timeout, SIGTERM) are handled
        # by consuming the speculative era's real, sound work before
        # stopping. Results are bit-identical to the serial driver either
        # way; only the dispatch gap between eras disappears.
        pipeline = self._pipeline and self._target_state_count is None

        while not stop and (count > 0 or self._spill):
            host_dirty = params_dev is None
            # Refill from host spill, leaving room for the worst-case append
            # (count must stay <= high_water going into the loop, or the
            # ring append could wrap over unconsumed frontier rows; the
            # margin below keeps refills from re-crossing the line
            # immediately). An empty frontier always refills at least one
            # block (a block is <= C*A <= high_water), so spill can't
            # strand.
            refill = []
            refill_rows = 0
            while self._spill and (
                count + refill_rows + self._spill.peek_rows() <= spill_target
                or (count == 0 and not refill)
            ):
                refill.append(self._spill.pop())
                refill_rows += len(refill[-1])
            if refill:
                rows = np.concatenate(refill, axis=0)
                k = len(rows)
                tail_idx = jnp.asarray(
                    (head + count + np.arange(k)) & (self._qcap - 1)
                )
                with self._metrics.phase("refill"):
                    rows_dev = jnp.asarray(rows)  # ONE upload for all blocks
                    queue = tuple(
                        queue[i].at[tail_idx].set(rows_dev[:, i])
                        for i in range(W)
                    )
                count += k
                self._metrics.inc("refill_rows", k)
                host_dirty = True
                if self._memory is not None:
                    self._memory.staging(
                        self._spill.host_bytes(),
                        event="refill",
                        rows=int(k),
                    )
            if count == 0:
                break

            # Proactive growth: guarantee the worst-case insert batch keeps
            # the load factor under vs.MAX_LOAD, so probe budgets can't be
            # exhausted (exhaustion would silently drop states).
            vcap = _vcap(A, C)
            grew = False
            while self._unique + vcap > vs.MAX_LOAD * self._tcap:
                with self._metrics.phase("table_grow"):
                    table, self._tcap = self._grow_table(table)
                self._metrics.inc("table_growths")
                host_dirty = True
                grew = True
            # Elastic re-shard (ISSUE 20): when the forecaster projects
            # growth within the horizon, take the doubling NOW at an era
            # boundary we already own — same rehash as the degraded
            # regrow, but before any probe-budget abort could trigger it.
            # Output is untouched: a bigger table changes slots, never
            # membership (growth rebuilds from the same fingerprints). At
            # most one proactive doubling per era: the forecast refreshes
            # at every _flight_record, so each further doubling needs a
            # projection that already accounts for the last one.
            if (
                self._proactive_reshard_due()
                and self._metrics.get("eras") != self._reshard_last_era
            ):
                self._reshard_last_era = self._metrics.get("eras")
                with self._metrics.phase("table_grow"):
                    table, self._tcap = self._grow_table(table)
                self._metrics.inc("table_growths")
                self._metrics.inc("reshard_proactive")
                self._obs_event(
                    "reshard_proactive", table_capacity=self._tcap
                )
                host_dirty = True
                grew = True
            if grew:
                self._mem_register(table, queue, (rec_fp1, rec_fp2), params_dev)
            grow_limit = max(0, int(vs.MAX_LOAD * self._tcap) - vcap)

            # The era budget is the device-emitted one (== max_sync
            # verbatim when adaptivity is off), host-clamped to the wall-
            # clock cap; a host override of either the budget or the cap
            # is a param change the feedback path cannot carry.
            max_steps = min(budget, budget_cap) if adaptive else budget
            if self._target_state_count is not None:
                # Bound overshoot past the state-count target: each step
                # generates at most C*A states.
                remaining = max(0, self._target_state_count - self._state_count)
                max_steps = max(1, min(max_steps, 1 + remaining // max(1, C * A)))
            if max_steps != budget or budget_cap != last_budget_cap:
                host_dirty = True
            # Fusion degrade: a changed fuse_lim can only reach the device
            # through an upload (the tail passes through otherwise).
            fuse_lim = _fuse_lim_now()
            if nfuse and fuse_lim != last_fuse_lim:
                host_dirty = True

            if host_dirty:
                arr = np.zeros(
                    P_LEN + 2 * P + ncov + nsamp + nfuse, dtype=np.uint32
                )
                if nfuse:
                    arr[fb] = fuse_lim
                    last_fuse_lim = fuse_lim
                if self._sampler is not None:
                    t1, t2 = self._sampler.threshold_parts()
                    arr[s_base] = t1
                    arr[s_base + 1] = t2
                    last_thresh = (t1, t2)
                arr[:P_LEN] = [
                    head,
                    count,
                    self._unique,
                    rec_bits,
                    depth_limit,
                    grow_limit,
                    high_water,
                    max_steps,
                    0,
                    0,
                    0,
                    0,
                    take_cap,
                    fin_any,
                    fin_all,
                    fin_all_en,
                    budget_cap,
                ]
                params_in = jnp.asarray(arr)
            else:
                params_in = params_dev
            last_max_steps = max_steps
            last_budget_cap = budget_cap

            _t0 = time.monotonic()
            self._era_t0 = _t0
            table, queue, rec_fp1, rec_fp2, params_dev = self._loop(
                table, queue, rec_fp1, rec_fp2, params_in
            )
            self._metrics.inc("dispatches")
            _dbg(
                f"block dirty={host_dirty} max_steps={max_steps} "
                f"dispatch={time.monotonic() - _t0:.3f}s"
            )
            # K-deep speculative chain (oldest first): chain[i] is the
            # params output of the i-th era chained past the one whose
            # readback (params_dev) the host is about to consume;
            # chain_t0[i] its dispatch timestamp.
            chain: List[Any] = []
            chain_t0: List[float] = []
            try:
                while True:
                    # Top up the chain while every host-only concern is
                    # quiet: each chained era launches off the newest
                    # on-device params with its readback queued behind the
                    # ones already in flight.
                    while (
                        pipeline
                        and len(chain) < self._chain_depth
                        and not self._spill
                        and not self._ckpt_stop.is_set()
                        and not self._timed_out()
                        and not self._proactive_reshard_due()
                        and (
                            self._ckpt_every is None
                            or time.monotonic() - self._last_ckpt
                            < self._ckpt_every
                        )
                    ):
                        # Kick the oldest pending readback without
                        # blocking, then chain off the on-device state
                        # (the chain variant pins the params operand, so
                        # every readback source stays live).
                        src = chain[-1] if chain else params_dev
                        try:
                            src.copy_to_host_async()
                        except AttributeError:
                            pass  # CPU backend: the copy is free anyway
                        t0 = time.monotonic()
                        (
                            table, queue, rec_fp1, rec_fp2, nxt,
                        ) = self._loop_chain(
                            table, queue, rec_fp1, rec_fp2, src
                        )
                        self._metrics.inc("dispatches")
                        self._metrics.inc("spec_dispatch")
                        chain.append(nxt)
                        chain_t0.append(t0)
                        if len(chain) > self._chain_max:
                            self._chain_max = len(chain)
                            self._metrics.set_gauge(
                                "spec_chain_depth", self._chain_max
                            )
                    if not chain:
                        # Serial boundary: consume the in-flight era with
                        # full host services (spill, checkpoint, stop).
                        process_result()
                        break
                    process_result(spec_in_flight=True)
                    if (
                        not stop
                        and count > 0
                        and not self._spill
                        and params_dev is not None
                        and self._unique + vcap <= vs.MAX_LOAD * self._tcap
                        and not self._proactive_reshard_due()
                    ):
                        # The era ended inside every gate: the oldest
                        # chained era IS the next era and has been
                        # executing since this readback completed.
                        # Marginal timing anchor: readback-to-readback, so
                        # the overlapped dispatch books as device time,
                        # not host gap.
                        params_dev = chain.pop(0)
                        chain_t0.pop(0)
                        last_max_steps = budget
                        self._era_t0 = time.monotonic()
                        continue
                    # Host action at this boundary: drain the chain in
                    # order. A device-visible exit (spill, grow, fin,
                    # empty frontier) made every later chained era an
                    # identity no-op — account those as wasted
                    # speculation, keep their (value-identical) outputs.
                    # A host-ONLY stop (timeout, SIGTERM) can land
                    # mid-chain instead; the chained eras then ran real,
                    # sound work — consume each normally before stopping.
                    while chain:
                        spec = chain.pop(0)
                        spec_t0 = chain_t0.pop(0)
                        if int(np.asarray(spec)[P_STEPS]) == 0:
                            self._metrics.inc("spec_wasted")
                            self._era_t0 = None
                            if params_dev is not None:
                                # Chain tail (value-equal): later
                                # dispatches feed off this one.
                                params_dev = spec
                            continue
                        params_dev = spec
                        self._era_t0 = spec_t0  # overlap-aware
                        last_max_steps = budget
                        process_result(spec_in_flight=bool(chain))
                    break
            except _ProbeBudgetExhausted:
                # Graceful degradation (degraded_regrow): discard the failed
                # era, reload the last crash-safe checkpoint (the pre-era
                # state), double the table, and continue — instead of
                # aborting the whole run. Only possible with a checkpoint:
                # the consumed frontier rows are otherwise gone.
                if chain:
                    # Chained eras were in flight. A REAL probe error is
                    # device-visible (err_cnt seeds from P_ERR), so every
                    # chained era was an identity no-op; a chaos-injected
                    # fake may have let them run real work. Either way the
                    # checkpoint reload below discards their buffers
                    # wholesale — just quiesce the dispatches and count
                    # the speculation as wasted.
                    for spec in chain:
                        np.asarray(spec)
                        self._metrics.inc("spec_wasted")
                    chain = []
                    chain_t0 = []
                from .common import checkpoint_generations

                if (
                    self._ckpt_path is None
                    or regrow_budget == 0
                    or not checkpoint_generations(self._ckpt_path)
                ):
                    raise
                regrow_budget -= 1
                table, queue, head, count, rec_bits, rec_fp1, rec_fp2 = (
                    self._load_checkpoint(self._ckpt_path, W)
                )
                with self._metrics.phase("table_grow"):
                    table, self._tcap = self._grow_table(table)
                self._metrics.inc("degraded_regrow")
                self._metrics.inc("table_growths")
                self._obs_event(
                    "degraded_regrow", frontier=count, new_tcap=self._tcap
                )
                params_dev = None  # host state changed; force re-upload
                if self._memory is not None:
                    self._memory.event(
                        "checkpoint_load", frontier=int(count)
                    )
                    self._mem_register(
                        table, queue, (rec_fp1, rec_fp2), params_dev
                    )

        # A final checkpoint makes interrupted runs (targets, timeouts)
        # resumable from their exact stopping point.
        if self._ckpt_path is not None:
            self._save_checkpoint(
                table, queue, head, count, rec_bits, rec_fp1, rec_fp2
            )
        # Any disk-tier spool is dead weight past this point (a resume
        # rebuilds the stack from the checkpoint's spill arrays).
        self._spill.close()

        if self._unique < SMALL_WORKLOAD_STATES:
            self._small_workload_hint(self._unique, "explored")

        # Mega-dispatch gauges: the deepest speculative chain reached and
        # the realized fusion ratio (device eras per host dispatch — the
        # dispatch-amortization headline, 1.0 when neither chaining nor
        # fusion engaged).
        self._metrics.set_gauge("spec_chain_depth", self._chain_max)
        n_disp = max(1, self._metrics.get("dispatches"))
        self._metrics.set_gauge(
            "fused_eras_per_dispatch",
            round(self._metrics.get("eras") / n_disp, 3),
        )

        self._profile_stages(table, queue)

        # Retained (on device) for path reconstruction; downloaded lazily.
        self._table_dev = table
        if self._memory is not None:
            # Re-point the ledger at the final era's live buffers (shapes
            # are identical across an era; this keeps the nbytes parity
            # check honest against what is actually resident at run end).
            led = self._memory.ledger
            led.attach("visited_table", table)
            led.attach("frontier_queue", queue)
            led.attach("record_fps", (rec_fp1, rec_fp2))
            if params_dev is not None:
                led.attach("packed_params", params_dev)
                led.attach("coverage_slab", params_dev)
                led.attach("sample_slab", params_dev)
                if self._fuse > 1:
                    led.attach("fusion_tail", params_dev)
        return

    def _on_spill_tier(self, direction, rows, nbytes, disk_bytes) -> None:
        """Tier-move hook from the TieredSpillStore: counters + the
        memory ledger's disk component and `spill_tier` event, so
        `plan == ledger == nbytes` stays exact across all three tiers."""
        if direction == "ram_to_disk":
            self._metrics.inc("spill_tier_rows", int(rows))
        else:
            self._metrics.inc("spill_tier_refill_rows", int(rows))
        self._metrics.set_gauge("spill_disk_bytes", int(disk_bytes))
        if self._memory is not None:
            self._memory.ledger.register(
                "spill_disk", nbytes=int(disk_bytes), kind="disk"
            )
            self._memory.event(
                "spill_tier",
                direction=direction,
                rows=int(rows),
                bytes=int(nbytes),
                disk_bytes=int(disk_bytes),
            )

    def _proactive_reshard_due(self) -> bool:
        """Forecast-triggered elastic reshard (ISSUE 20): with a device
        limit set and exhaustion projected, front-run the next table
        doubling once the forecaster puts it within the reshard horizon
        — the growth lands at a host-chosen era boundary (chain drained)
        instead of the forced mid-pressure one, and never fires on
        unlimited runs (eras_to_exhaustion needs a limit).  The measured
        load-fraction floor keeps it self-limiting: each doubling halves
        ``load_frac``, so a diverging fit cannot re-trigger every era."""
        rec = self._memory
        if rec is None:
            return False
        fc = rec.last_forecast()
        if fc.get("eras_to_exhaustion") is None:
            return False
        eta_grow = fc.get("eras_to_grow")
        from ..obs.memory import RESHARD_HORIZON_ERAS, RESHARD_MIN_LOAD_FRAC

        return (
            eta_grow is not None
            and eta_grow <= RESHARD_HORIZON_ERAS
            and fc.get("load_frac", 0.0) >= RESHARD_MIN_LOAD_FRAC
        )

    def _mem_register(self, table, queue, rec_fps, params_dev) -> None:
        """(Re-)register every device buffer with the memory ledger from
        the shared size formulas (obs/memory.py bfs_component_sizes) —
        the planner predicts exactly what lands here, and the parity test
        locks the formulas to the live nbytes. Called after seeding and
        after every table growth; re-registration at a new size logs the
        growth event. A ``None`` params_dev keeps the previous reference
        (sizes are unchanged; .nbytes is aval metadata either way)."""
        rec = self._memory
        if rec is None:
            return
        from ..obs.memory import bfs_component_sizes
        from ..ops import visited_set as vs

        sizes = bfs_component_sizes(
            self.tm.state_width,
            self.tm.max_actions,
            len(self._tprops),
            chunk=self._chunk,
            queue_capacity=self._qcap,
            table_capacity=self._tcap,
            coverage=self._cov,
            sample_k=self._sample_k,
            fuse=self._fuse,
        )
        arrays = {
            "visited_table": table,
            "frontier_queue": queue,
            "record_fps": rec_fps,
            "packed_params": params_dev,
            "coverage_slab": params_dev,
            "sample_slab": params_dev,
        }
        if self._fuse > 1:
            arrays["fusion_tail"] = params_dev
        rec.register_components(sizes, arrays=arrays)
        rec.set_geometry(
            rows=self._tcap,
            max_load=vs.MAX_LOAD,
            reserve_rows=_vcap(self.tm.max_actions, self._chunk),
        )

    def _small_workload_hint(self, n: int, kind: str) -> None:
        """One-line telemetry warning: below the crossover the host engine
        wins (the device engine's fixed dispatch/compile overheads dominate
        small state spaces — README "engine crossover")."""
        if getattr(self, "_mux_lane", False):
            return  # multiplexed lanes ARE the small-workload path
        if getattr(self, "_hinted_small", False):
            return  # once per run
        self._hinted_small = True
        self._metrics.set_gauge("small_workload_hint", n)
        _log.warning(
            "small workload: spawn_bfs() on the host is typically faster "
            "than spawn_tpu_bfs() here",
            states=n,
            kind=kind,
            crossover=SMALL_WORKLOAD_STATES,
        )

    def _profile_stages(self, table, queue) -> None:
        """Post-run per-stage attribution of the device_era wall time
        (CheckerBuilder.stage_profile(); obs/stageprof.py). Never fatal:
        a finished run's results must survive a profiler failure."""
        if not self._stage_profile:
            return
        try:
            import jax.numpy as jnp

            from ..obs import stageprof

            steps = int(self._metrics.get("steps"))
            era_secs = self._metrics.phase_ms().get("device_era", 0.0) / 1e3
            if steps <= 0 or era_secs <= 0.0:
                return
            kernels = _build_stage_kernels(
                self.tm, self._tprops, self._chunk, self._qcap, self._canon,
                self._stage_iters,
            )
            seed = jnp.asarray(1, dtype=jnp.uint32)
            with self._metrics.phase("profiler_overhead"):
                timed = stageprof.measure_stage_kernels(
                    {
                        name: (fn, (table, queue, seed))
                        for name, fn in kernels.items()
                    },
                    self._stage_iters,
                )
            stageprof.attribute_stages(
                self._metrics, timed, era_secs, steps, self._stage_iters
            )
        except Exception as exc:
            self._metrics.set_gauge("stage_profile_error", repr(exc)[:200])
            _log.warning(
                "stage profiling failed (run results unaffected)",
                error=repr(exc),
            )

    def _grow_table(self, table):
        """Double capacity and rehash on device (no table round-trips)."""
        from ..ops import visited_set as vs

        new_cap = self._tcap * 2
        new_table, n_unresolved = vs.rehash_jit(table, vs.empty_table(new_cap))
        if int(n_unresolved) != 0:
            raise RuntimeError("rehash failed; table pathologically full")
        return new_table, new_cap

    # -- checkpoint/resume --------------------------------------------------

    def _save_checkpoint(
        self, table, queue, head, count, rec_bits, rec_fp1, rec_fp2
    ) -> None:
        """Serialize the full engine state (table, ring, spill, counters) to
        one .npz via the crash-safe protocol in engines/common.py: tmp +
        fsync + generation rotation + rename, content digest in the meta.
        The reference has no equivalent — killed runs restart from scratch
        (SURVEY.md §5)."""
        from ..ops import visited_set as vs
        from .common import checkpoint_meta

        meta = checkpoint_meta(
            self.tm,
            self._tprops,
            ring_lanes=len(queue),
            head=head,
            count=count,
            rec_bits=rec_bits,
            state_count=self._state_count,
            unique=self._unique,
            max_depth=self._max_depth,
            tcap=self._tcap,
            qcap=self._qcap,
            chunk=self._chunk,
            max_probes=vs.MAX_PROBES,
            discovery_fps={k: str(v) for k, v in self._discovery_fps.items()},
            sampler=(
                self._sampler.export_state()
                if self._sampler is not None
                else None
            ),
        )
        arrays = {
            "rec_fp1": np.asarray(rec_fp1),
            "rec_fp2": np.asarray(rec_fp2),
        }
        # On-disk format keeps the four flat lanes (table0..3); the packed
        # key buffer is split host-side (free views over one download).
        for t, lane in enumerate(vs.unpack_lanes_np(table)):
            arrays[f"table{t}"] = lane
        for w, lane in enumerate(queue):
            arrays[f"queue{w}"] = np.asarray(lane)
        for i, blk in enumerate(self._spill.iter_blocks()):
            arrays[f"spill{i}"] = blk
        # Tiered save (ISSUE 20): a full base when the chain state says so
        # (first save, tcap changed, chain at max), else a delta holding
        # only the table rows inserted since the base.
        self._ckpt_delta = save_checkpoint_tiered(
            self._ckpt_path, meta, arrays,
            state=self._ckpt_delta, tcap=self._tcap,
            keep=self._ckpt_keep, metrics=self._metrics,
        )
        self._last_ckpt = time.monotonic()
        _dbg(f"checkpoint saved: {self._ckpt_path}")

    def _load_checkpoint(self, path: str, W: int):
        import jax.numpy as jnp

        from ..ops import visited_set as vs
        from .common import validate_checkpoint_meta

        # Digest-verified load with automatic fallback to the previous
        # generation when the newest file is truncated/corrupt, folding any
        # surviving delta chain onto the base (engines/common.py).
        data, meta = load_checkpoint_folded(path, metrics=self._metrics)
        validate_checkpoint_meta(
            meta,
            self.tm,
            self._tprops,
            exact={
                "qcap": self._qcap,
                "state_width": self.tm.state_width,
                # Ring layout changed in round 5 (hashes no longer carried);
                # checkpoints from the old layout must not load silently.
                "ring_lanes": W,
                # The probe cascade is part of the table's on-disk meaning:
                # a table written under a different probe schedule would
                # mis-resolve lookups.
                "max_probes": vs.MAX_PROBES,
            },
        )
        self._tcap = meta["tcap"]
        self._state_count = meta["state_count"]
        self._unique = meta["unique"]
        self._max_depth = meta["max_depth"]
        self._discovery_fps = {
            k: int(v) for k, v in meta["discovery_fps"].items()
        }
        if self._sampler is not None and meta.get("sampler"):
            # Restore the sampler's kept set + threshold: a resumed run's
            # sample must be identical to an uninterrupted one.
            self._sampler.restore_state(meta["sampler"])
        self._spill.reset(
            data[k] for k in sorted(
                (k for k in data if k.startswith("spill")),
                key=lambda s: int(s[5:]),
            )
        )
        # A reload invalidates the delta-chain baseline (the resumed run's
        # next save must be a fresh full base).
        self._ckpt_delta = None
        table = vs.pack_lanes(*(data[f"table{t}"] for t in range(4)))
        queue = tuple(jnp.asarray(data[f"queue{w}"]) for w in range(W))
        return (
            table,
            queue,
            meta["head"],
            meta["count"],
            meta["rec_bits"],
            jnp.asarray(data["rec_fp1"]),
            jnp.asarray(data["rec_fp2"]),
        )

    # -- accessors ----------------------------------------------------------

    def telemetry(self) -> Dict[str, Any]:
        m = self._metrics
        m.set_gauge("table_capacity", self._tcap)
        m.set_gauge("load_factor", round(self._unique / self._tcap, 4))
        m.set_gauge("chunk", self._chunk)
        return super().telemetry()

    def unique_state_count(self) -> int:
        return self._unique

    def discoveries(self) -> Dict[str, Path]:
        self.join()
        return {
            name: self._reconstruct(fp)
            for name, fp in list(self._discovery_fps.items())
        }

    def _sample_resolver(self):
        # Device slabs drain fingerprint-only; sample rows are resolved
        # lazily at profile build by the same table-parent walk that
        # reconstructs counterexample paths.
        return self._path_sample_resolver(self._reconstruct)

    def _reconstruct(self, fp64: int) -> Path:
        """Walk table parent pointers, then re-execute the model along the
        fingerprint chain (reference bfs.rs:380-409). The table is downloaded
        once; chains are walked in numpy (per-node device lookups would cost
        a host round-trip each)."""
        from ..ops import visited_set as vs

        if not hasattr(self, "_table_np"):
            import jax.numpy as jnp

            # Concatenate on device, download ONCE (per-lane downloads cost
            # a ~100ms round-trip each on this platform), then split into
            # the four flat lanes lookup_parent_np walks.
            flat = np.asarray(jnp.concatenate(self._table_dev))
            cap = flat.shape[0] // 4
            self._table_np = tuple(
                flat[t * cap:(t + 1) * cap] for t in range(4)
            )
        chain = [fp64]
        cur = fp64
        for _ in range(10_000_000):
            h1, h2 = split64(cur)
            found, p1, p2 = vs.lookup_parent_np(self._table_np, h1, h2)
            if not found:
                raise RuntimeError(
                    f"fingerprint {cur} missing from visited table during "
                    "path reconstruction"
                )
            if p1 == 0 and p2 == 0:
                break
            cur = combine64(p1, p2)
            chain.append(cur)
        chain.reverse()
        model = self._model
        if self._canon:
            # The table stores representative fingerprints; match raw
            # successors by their canonical fingerprint while walking.
            from ..tensor import CanonicalTensorAdapter

            model = CanonicalTensorAdapter(self.tm)
        return Path.from_fingerprints(model, chain)
