"""The build/run split: compiled checking executables reusable across runs.

Every device engine in this package keys its jit caches by ``id(tm)``
(engines/tpu_bfs.py `_LOOP_CACHE`, parallel/mesh.py) — correct for a
single checking run, but a *service* receives a fresh model instance per
request, and a fresh instance means a fresh cache key means a fresh XLA
compile, even though two `IncrementTensor(2)` instances lower to the
identical program. The compile is the dominant per-request cost for small
workloads (seconds, vs milliseconds of actual search), so a run server
amortizing it across requests is the difference between "demo" and
"serves traffic" (ROADMAP item 3).

Three layers fix this, composing with (not replacing) the per-``id(tm)``
jit caches and JAX's persistent compilation cache:

  1. `model_signature(tm)` — a stable *shape signature* for a tensor
     model: class identity + `config_digest()` + the property set. Two
     instances with equal signatures lower to the identical device
     program (the digest covers every scalar baked into `step_lanes`).
  2. the model *intern pool* — `intern_model()` maps a signature to one
     canonical `TensorModel` instance. Every downstream ``id(tm)``-keyed
     cache (era loops, seed loops, mux programs, expand programs) then
     hits naturally for same-shape requests; this is the load-bearing
     refactor, and it benefits `spawn_tpu_bfs`, `spawn_sharded_bfs`, and
     the vectorized host engines alike because they all key by the model
     instance.
  3. `CompiledCheck` + `ExecutableCache` — an LRU of warm executables
     keyed by (engine kind, signature, shape options). A `CompiledCheck`
     pins the interned model together with the engine shape (chunk /
     queue / table capacities, mux lane count), builds the jitted
     programs once (`warm()`), and hands out fresh `CheckerBuilder`s
     whose runs all reuse that one executable.

The cache sits *on top of* the persistent compilation cache: a persistent-
cache hit still pays trace + lowering per new model instance (hundreds of
ms); an executable-cache hit pays a dict lookup.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, Dict, Optional, Tuple

from ..tensor import TensorModel, TensorModelAdapter

__all__ = [
    "CompiledCheck",
    "ExecutableCache",
    "era_geometry",
    "intern_model",
    "model_signature",
]


def _tm_of(model: Any) -> TensorModel:
    if isinstance(model, TensorModelAdapter):
        return model.tm
    if isinstance(model, TensorModel):
        return model
    raise TypeError(
        "compiled checks require a TensorModel (or its adapter); "
        f"got {type(model).__name__}"
    )


def model_signature(model: Any) -> str:
    """Stable shape signature of a tensor model: two models with equal
    signatures lower to the identical device program.

    Covers class identity (the `step_lanes` code), `config_digest()`
    (every scalar constant baked into that code), and the property set
    (names + expectations fix the property-evaluation program and the
    rec_bits layout). Deliberately NOT ``id()``-based: equality across
    instances is the whole point.
    """
    tm = _tm_of(model)
    cls = type(tm)
    props = ",".join(
        f"{p.name}:{p.expectation.value}" for p in tm.tensor_properties()
    )
    return (
        f"{cls.__module__}.{cls.__qualname__}|{tm.config_digest()}|{props}"
    )


# Signature -> canonical instance. Bounded: each retained instance pins its
# jit caches (the per-id loop caches evict at 16, but the pool is what keeps
# an instance's id stable enough for them to hit at all).
_INTERN_CAP = 64
_INTERN: "OrderedDict[str, TensorModel]" = OrderedDict()
_INTERN_LOCK = threading.Lock()


def intern_model(model: Any) -> Tuple[TensorModel, str]:
    """Map `model` to the canonical instance for its shape signature.

    Returns ``(tm, signature)`` where `tm` is the first instance seen with
    this signature (possibly `model` itself). All ``id(tm)``-keyed jit
    caches hit across requests once every caller interns first.
    """
    tm = _tm_of(model)
    sig = model_signature(tm)
    with _INTERN_LOCK:
        cached = _INTERN.get(sig)
        if cached is not None:
            _INTERN.move_to_end(sig)
            return cached, sig
        while len(_INTERN) >= _INTERN_CAP:
            _INTERN.popitem(last=False)
        _INTERN[sig] = tm
    return tm, sig


def era_geometry(model: Any, options: Optional[Dict[str, Any]] = None) -> Dict[str, int]:
    """The solo engine shape a default run compiles at, resolved from
    `options` exactly like `CompiledCheck.warm()` / `spawn_tpu_bfs`:
    chunk clamp, coverage/sample defaults, and the proactive table
    pre-growth. Single source of truth shared by `warm()` and the
    STR6xx program lint (analysis/program.py) — if lint lowered at a
    different shape its op budgets would gate a program no run executes.
    """
    from ..obs.sample import DEFAULT_SAMPLE_K
    from ..ops import visited_set as vs
    from .tpu_bfs import _vcap

    tm = _tm_of(model)
    options = options or {}
    qcap = int(options.get("queue_capacity", 1 << 20))
    tcap = int(options.get("table_capacity", 1 << 22))
    chunk = min(
        int(options.get("chunk_size", 8192)),
        qcap // (2 * max(1, tm.max_actions)),
    )
    cov = bool(options.get("coverage", True))
    sample_k = int(options.get("sample_k", DEFAULT_SAMPLE_K))
    fuse = max(1, int(options.get("fuse_eras", 1)))
    n_init = len(tm.init_states_array())
    vcap = _vcap(tm.max_actions, chunk)
    while n_init + vcap > vs.MAX_LOAD * tcap:
        tcap *= 2
    return {
        "chunk": chunk,
        "qcap": qcap,
        "tcap": tcap,
        "cov": cov,
        "sample_k": sample_k,
        "fuse": fuse,
        "n_init": n_init,
    }


class CompiledCheck:
    """One warm checking executable: an interned model + engine shape.

    ``engine`` is ``"tpu_bfs"`` (the solo device engine) or
    ``"multiplex"`` (the vmapped lane-batched engine,
    engines/multiplex.py). `warm()` builds the jitted programs through the
    same ``id(tm)``-keyed caches the engines use, so a subsequent run over
    the same `CompiledCheck` re-traces nothing.
    """

    def __init__(self, engine: str, model: Any, options: Dict[str, Any]):
        self.tm, self.signature = intern_model(model)
        self.engine = engine
        self.options = dict(options)
        self.uses = 0
        self._warmed = False

    def builder(self):
        """A fresh `CheckerBuilder` over the interned model. Every run
        spawned from it shares this executable."""
        return TensorModelAdapter(self.tm).checker()

    def warm(self) -> "CompiledCheck":
        """Build (trace + lower) the device programs now, outside any
        request's latency budget. Idempotent."""
        if self._warmed:
            return self
        if self.engine == "tpu_bfs":
            from .tpu_bfs import _build_loop, _build_seed_loop

            tm = self.tm
            props = tm.tensor_properties()
            # Space sampling defaults ON at k=64 (CheckerBuilder.sample) and
            # the engine pre-grows the table proactively; era_geometry()
            # mirrors both, so the loop is traced at the shape a default
            # run actually compiles.
            g = era_geometry(tm, self.options)
            chunk, qcap, tcap = g["chunk"], g["qcap"], g["tcap"]
            cov, sample_k, fuse = g["cov"], g["sample_k"], g["fuse"]
            _build_loop(
                tm, props, chunk, qcap, False, cov, sample_k=sample_k,
                fuse=fuse,
            )
            _build_seed_loop(
                tm, props, chunk, qcap, tcap, False, cov, sample_k=sample_k,
                fuse=fuse,
            )
        elif self.engine == "multiplex":
            from .multiplex import warm_lane_program

            warm_lane_program(self.tm, **self.options)
        else:
            raise ValueError(f"unknown compiled-check engine {self.engine!r}")
        self._warmed = True
        return self

    def spawn(self, builder=None, **kw):
        """Spawn a solo device run reusing this executable. Only valid for
        ``engine="tpu_bfs"`` (multiplexed batches go through
        `multiplex.run_multiplexed`)."""
        if self.engine != "tpu_bfs":
            raise ValueError(
                f"spawn() is for tpu_bfs compiled checks, not {self.engine!r}"
            )
        if builder is None:
            builder = self.builder()
        opts = {
            k: self.options[k]
            for k in ("chunk_size", "queue_capacity", "table_capacity")
            if k in self.options
        }
        opts.update(kw)
        self.uses += 1
        return builder.spawn_tpu_bfs(compiled=self, **opts)


class ExecutableCache:
    """Thread-safe LRU of `CompiledCheck`s keyed by (engine, signature,
    shape options) — the run service's executable cache, layered on top of
    the persistent compilation cache."""

    def __init__(self, capacity: int = 8):
        self.capacity = max(1, int(capacity))
        self.hits = 0
        self.misses = 0
        self._lock = threading.Lock()
        self._entries: "OrderedDict[Tuple, CompiledCheck]" = OrderedDict()

    def get(self, model: Any, engine: str, **options) -> Tuple[CompiledCheck, bool]:
        """Return ``(compiled, hit)`` for this model shape + engine shape,
        building (and warming) a new executable on miss."""
        sig = model_signature(model)
        key = (engine, sig, tuple(sorted(options.items())))
        with self._lock:
            cached = self._entries.get(key)
            if cached is not None:
                self._entries.move_to_end(key)
                self.hits += 1
                return cached, True
            self.misses += 1
        # Build outside the lock: warm() can take seconds (trace + lower)
        # and the underlying id(tm)-keyed caches already dedupe races.
        compiled = CompiledCheck(engine, model, options).warm()
        with self._lock:
            existing = self._entries.get(key)
            if existing is not None:
                return existing, False
            self._entries[key] = compiled
            while len(self._entries) > self.capacity:
                self._entries.popitem(last=False)
        return compiled, False

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "hits": self.hits,
                "misses": self.misses,
                "size": len(self._entries),
                "capacity": self.capacity,
            }
