"""Batched device simulation: B independent seeded random walks per step.

The device twin of the host simulation engine (engines/simulation.py;
reference src/checker/simulation.rs:138-201, where parallelism = one
independent seeded walk per OS thread). Here parallelism is data-parallel:
B walks advance together, one random transition per walk per device step,
inside the same era-loop architecture as the batched BFS engine (many
steps per dispatch; the host syncs once per era).

Design notes (TPU-first, not a translation):

  - Walk state is structure-of-arrays: S state lanes of width [B], plus
    per-walk seed / path-length / eventually-bits lanes.
  - Each walk's fingerprint path lives in a device-resident [B, L] buffer
    (L = walk_cap). That one structure serves THREE roles the reference
    implements separately: per-run cycle detection (membership test is an
    elementwise [B, L] compare — simulation.rs:285-289's HashSet), the
    depth bound, and counterexample reporting (a discovery's full
    fingerprint path is read straight out of the buffer — no replay).
  - The chooser is a counter-based PRNG (splitmix-style avalanche of
    (walk_seed, step)): stateless, so any walk's trace is reproducible
    from the master seed alone, matching the reference's reseeded-
    per-trace discipline (simulation.rs:154-197).
  - Ended walks (terminal / cycle / depth-cap) restart IN PLACE with an
    evolved seed; a walk that records a discovery freezes until the era
    ends so its path buffer survives for extraction. The frozen flag is a
    walk lane that CROSSES the era boundary: the host harvests discovery
    paths between dispatches, and the next era restarts frozen walks
    (fresh init, evolved seed, cleared path row) — resuming them mid-walk
    would make each see its own recorded path as a cycle and fabricate
    EVENTUALLY counterexamples.

Semantic divergences from the host engine (documented, both benign for
the engine's purpose of finding examples/counterexamples fast):
  - boundary handling: the device walk never *enters* an out-of-boundary
    state (such successors are masked off as disabled), while the host
    walk may select one and then end; walk distributions differ when a
    boundary is active.
  - the uniform chooser picks among actions whose successor is valid,
    rather than retrying disabled actions without replacement — the same
    distribution, computed without the swap_remove loop.
"""

from __future__ import annotations

import functools
import os
import time
from typing import Any, Dict, List, Tuple

import numpy as np

from ..checker import CheckerBuilder
from ..core import Expectation
from ..fingerprint import combine64
from ..path import Path
from ..tensor import TensorModel, TensorModelAdapter
from .common import HostEngineBase

# Packed scalar params (one uint32 vector per direction, as in tpu_bfs).
P_REC = 0  # recorded-discovery bitmask
P_MAX_STEPS = 1
P_FIN_ANY = 2
P_FIN_ALL = 3
P_FIN_ALL_EN = 4
P_TARGET_GEN = 5  # era exits when generated-this-run exceeds this (0 = off)
P_GEN0 = 6  # generated before this era (for the target check)
P_GEN = 7  # OUT: generated states total after era
P_STEPS = 8  # OUT: device steps executed this era
P_MAXD = 9  # OUT: max walk length seen
P_SEED = 10  # master seed (consumed by the fused seed+first-era dispatch)
P_LEN = 11

_LOOP_CACHE: Dict[Tuple, Tuple[TensorModel, Any]] = {}


def _build_sim_loop(tm: TensorModel, props, B: int, L: int, cov: bool = True,
                    sample_k: int = 0):
    key = (id(tm), B, L, len(props), cov, sample_k)
    cached = _LOOP_CACHE.get(key)
    if cached is not None and cached[0] is tm:
        return cached[1]  # (loop, seed_run, n_init)
    while len(_LOOP_CACHE) >= 16:
        _LOOP_CACHE.pop(next(iter(_LOOP_CACHE)))

    import jax
    import jax.numpy as jnp
    from jax import lax

    from ..fingerprint import hash_lanes_jnp
    from ..obs.coverage import DEPTH_CAP

    S = tm.state_width
    A = tm.max_actions
    P = len(props)
    if sample_k:
        # Bottom-k space sampling (obs/sample.py): counted states below
        # the host threshold append to an in-carry slab drained in the
        # params tail (same protocol as tpu_bfs, with two differences:
        # the capture width is the full walk batch B — so per-step drops
        # are impossible even under a loose threshold — and the slab
        # carries the S state lanes, since walks revisit states and no
        # visited table exists to reconstruct rows from later).
        from ..obs.sample import slab_entries, slab_high_water

        sk2 = slab_entries(sample_k)
        s_high = slab_high_water(sample_k)
        scap = s_high + B  # one more step always fits
        s_base = P_LEN + 2 * P + ((A + P + DEPTH_CAP) if cov else 0)

    init_np = np.asarray(tm.init_states_array(), dtype=np.uint32)
    # Boundary-filter init states at build time (host-side, static) so the
    # fused device seeder and the host path agree on the init set.
    _inb = np.asarray(
        tm.within_boundary_lanes(np, tuple(init_np[:, s] for s in range(S))),
        dtype=bool,
    )
    init_np = init_np[_inb]
    n_init = len(init_np)
    init_lanes_const = tuple(init_np[:, s] for s in range(S))

    init_ebits = 0
    e_slot = {}
    e_idx = 0
    for i, p in enumerate(props):
        if p.expectation == Expectation.EVENTUALLY:
            e_slot[i] = e_idx
            init_ebits |= 1 << e_idx
            e_idx += 1

    def prng(x):
        u = jnp.uint32
        x = (x ^ (x >> u(16))) * u(0x7FEB352D)
        x = (x ^ (x >> u(15))) * u(0x846CA68B)
        return x ^ (x >> u(16))

    from ..compat import donate_argnums_safe

    @functools.partial(jax.jit, donate_argnums=donate_argnums_safe(0, 1, 2))
    def loop(walk, fp1buf, fp2buf, params):
        """walk = (rows[S], seed, ptr, ebits, frozen) lanes of [B];
        fp*buf = [B * L] flat path buffers. The frozen lane MUST cross the
        era boundary: a walk freezes when it records a discovery and its
        current state is already in its own path buffer, so silently
        thawing it mid-walk (the pre-fix behavior) made its first
        membership test see itself — a fake cycle, which with surviving
        eventually-bits fabricated spurious EVENTUALLY counterexamples.
        The host harvests discovery paths between dispatches, so frozen
        arrivals RESTART here (fresh init, evolved seed, cleared path row)
        instead of resuming — sound, and generation never starves."""
        u = jnp.uint32
        rec_bits0 = params[P_REC]
        max_steps = params[P_MAX_STEPS]
        fin_any = params[P_FIN_ANY]
        fin_all = params[P_FIN_ALL]
        fin_all_en = params[P_FIN_ALL_EN]
        target_gen = params[P_TARGET_GEN]
        gen0 = params[P_GEN0]
        if sample_k:
            # Sampling threshold words (pass-through; stale = looser =
            # superset, host re-filters).
            st1 = params[s_base]
            st2 = params[s_base + 1]
        iota_b = jnp.arange(B, dtype=u)
        iota_l = jnp.arange(L, dtype=u)
        inits = tuple(jnp.asarray(l) for l in init_lanes_const)

        def cond(carry):
            (
                _w, _f1, _f2, gen, steps, rec_acc, _h, _pl, maxd, _covc,
                sampc,
            ) = carry
            fin_hit = ((rec_acc & fin_any) != u(0)) | (
                (fin_all_en != u(0)) & ((rec_acc & fin_all) == fin_all)
            )
            under_target = (target_gen == u(0)) | (gen0 + gen < target_gen)
            keep = (steps < max_steps) & ~fin_hit & under_target
            if sample_k:
                # Slab-occupancy gate (uint32 sum chain — carry-safe):
                # exit so the host drains before the slab can overflow.
                keep = keep & (sampc[3] <= u(s_high))
            return keep

        def body(carry):
            (
                (rows, seed, ptr, ebits, frozen),
                fp1buf,
                fp2buf,
                gen,
                steps,
                rec_acc,
                hseen,
                plen,
                maxd,
                covc,
                sampc,
            ) = carry
            active = ~frozen
            h1, h2 = hash_lanes_jnp(rows)

            # Cycle detection: membership of the current state in the
            # walk's own path so far ([B, L] elementwise compare).
            f1m = fp1buf.reshape(B, L)
            f2m = fp2buf.reshape(B, L)
            in_path = (
                ((f1m == h1[:, None]) & (f2m == h2[:, None])
                 & (iota_l[None, :] < ptr[:, None])).sum(axis=1, dtype=u)
                > u(0)
            )
            cycle = active & in_path

            # Record the current state into the path buffer.
            pos = jnp.where(active & ~cycle, iota_b * u(L) + ptr, u(B * L) + iota_b)
            fp1buf = fp1buf.at[pos].set(h1, mode="drop", unique_indices=True)
            fp2buf = fp2buf.at[pos].set(h2, mode="drop", unique_indices=True)
            counted = active & ~cycle
            ptr = jnp.where(counted, ptr + u(1), ptr)
            gen = gen + counted.sum(dtype=u)
            if sample_k:
                # Sample capture: counted states lexicographically below
                # the threshold, with their state rows in hand (walks
                # revisit states across traces — the host sampler dedups
                # by fingerprint, so the sample stays a pure function of
                # the visited set). Full-B capture width: no drops, ever.
                from ..ops.visited_set import _compact_ids

                below = counted & (
                    (h1 < st1) | ((h1 == st1) & (h2 < st2))
                )

                def _capture(sc):
                    sfp1, sfp2, sdep, socc, sst = sc
                    cids, cvalid, n_c = _compact_ids(below, B)
                    pos = socc + iota_b
                    ok_w = cvalid & (pos < u(scap))
                    widx = jnp.where(ok_w, pos, u(scap))
                    return (
                        sfp1.at[widx].set(h1[cids]),
                        sfp2.at[widx].set(h2[cids]),
                        sdep.at[widx].set(ptr[cids]),
                        socc + n_c,
                        tuple(
                            sst[s].at[widx].set(rows[s][cids])
                            for s in range(S)
                        ),
                    )

                # Tight-threshold steps capture nothing almost always;
                # the cond skips the compaction and the (3+S)-lane slab
                # scatter on those steps.
                sampc = lax.cond(
                    below.any(), _capture, lambda sc: sc, sampc
                )
            if cov:
                # Depth histogram: each counted state lands at its walk
                # depth (the just-incremented ptr; clamped into the
                # DEPTH_CAP overflow bucket). One scatter-add at [B].
                act, covp, dhist = covc
                dhist = dhist.at[
                    jnp.minimum(ptr, u(DEPTH_CAP - 1))
                ].add(counted.astype(u))
                covc = (act, covp, dhist)
            # maxd is a PER-WALK lane, reduced once in the epilogue — a
            # scalar max-reduce in the carry knocks the loop off the fast
            # dispatch path on this platform (see engines/tpu_bfs.py).
            maxd = jnp.maximum(maxd, ptr)

            # Property evaluation on the current states (simulation.rs
            # property loop; eventually-bits clear on satisfaction).
            prop_hits = [None] * P
            for i, p in enumerate(props):
                if p.expectation == Expectation.EVENTUALLY:
                    sat = p.check(jnp, rows) & counted
                    ebits = jnp.where(sat, ebits & ~u(1 << e_slot[i]), ebits)
                elif p.expectation == Expectation.ALWAYS:
                    prop_hits[i] = counted & ~p.check(jnp, rows)
                else:
                    prop_hits[i] = counted & p.check(jnp, rows)

            # Successors + enabled mask.
            succs, amask = tm.step_lanes(jnp, rows)
            valid_a = []
            ne = jnp.zeros(B, dtype=u)
            for a in range(A):
                v = amask[a] & tm.within_boundary_lanes(jnp, succs[a])
                valid_a.append(v)
                ne = ne + v.astype(u)

            terminal = counted & (ne == u(0))
            capped = counted & (ptr >= u(L))
            # Walk-end eventually discoveries (terminal rule; a cycle exit
            # matches the host engine, which also falls through to the
            # terminal ebits check on loops. Depth-capped walks skip it,
            # like the host's target_max_depth path).
            ended_for_ebits = terminal | cycle
            for i, p in enumerate(props):
                if p.expectation == Expectation.EVENTUALLY:
                    prop_hits[i] = ended_for_ebits & (
                        (ebits & u(1 << e_slot[i])) != u(0)
                    )

            # Discovery snapshots: first hit per property freezes its walk
            # so the path buffer survives until the era ends.
            newly_frozen = frozen & False
            for i in range(P):
                hits = prop_hits[i]
                first = hits & ~hseen[i]
                plen = tuple(
                    jnp.where(first, ptr, plen[j]) if j == i else plen[j]
                    for j in range(P)
                )
                hseen = tuple(
                    (hseen[j] | hits) if j == i else hseen[j] for j in range(P)
                )
                hs = hits.sum(dtype=u)
                rec_acc = rec_acc | (jnp.minimum(hs, u(1)) << u(i))
                if cov:
                    # Per-property hit totals ride the sums the discovery
                    # gate already pays for.
                    act, covp, dhist = covc
                    covc = (
                        act,
                        tuple(
                            (covp[j] + hs) if j == i else covp[j]
                            for j in range(P)
                        ),
                        dhist,
                    )
                newly_frozen = newly_frozen | first
            frozen = frozen | newly_frozen

            # Choose one enabled action uniformly (counter-based PRNG).
            r = prng(seed ^ (ptr * u(0x9E3779B9)))
            pick = jnp.where(ne > u(0), r % jnp.maximum(ne, u(1)), u(0))
            cum = jnp.zeros(B, dtype=u)
            new_rows = rows
            chosen_any = ne < u(0)  # all-false, varying
            sels = []
            for a in range(A):
                sel = valid_a[a] & (cum == pick) & ~chosen_any
                chosen_any = chosen_any | sel
                sels.append(sel)
                new_rows = tuple(
                    jnp.where(sel, succs[a][s], new_rows[s]) for s in range(S)
                )
                cum = cum + valid_a[a].astype(u)

            advance = counted & ~terminal & ~capped & ~newly_frozen
            restart = active & ~newly_frozen & (cycle | terminal | capped)
            if cov:
                # Action coverage: the transition each advancing walk
                # actually took this step (the simulation twin of the BFS
                # engines' valid-successor attribution).
                act, covp, dhist = covc
                act = act + jnp.stack(
                    [(sels[a] & advance).sum(dtype=u) for a in range(A)]
                )
                covc = (act, covp, dhist)

            # Restarts: evolved seed, fresh init state, cleared path row.
            seed2 = prng(seed + u(0x6A09E667))
            init_pick = prng(seed2) % u(n_init)
            rows = tuple(
                jnp.where(
                    restart,
                    inits[s][init_pick],
                    jnp.where(advance, new_rows[s], rows[s]),
                )
                for s in range(S)
            )
            seed = jnp.where(restart, seed2, seed)
            ebits = jnp.where(restart, u(init_ebits), ebits)
            keep_row = ~restart
            fp1buf = (fp1buf.reshape(B, L) * keep_row[:, None]).reshape(-1)
            fp2buf = (fp2buf.reshape(B, L) * keep_row[:, None]).reshape(-1)
            ptr = jnp.where(restart, u(0), ptr)

            steps = steps + u(1)
            return (
                (rows, seed, ptr, ebits, frozen),
                fp1buf,
                fp2buf,
                gen,
                steps,
                rec_acc,
                hseen,
                plen,
                maxd,
                covc,
                sampc,
            )

        rows, seed, ptr, ebits = walk[:S], walk[S], walk[S + 1], walk[S + 2]
        # Era prologue: restart walks that arrived frozen (see docstring).
        frozen_in = walk[S + 3] != u(0)
        fseed = prng(seed + u(0x6A09E667))
        fpick = prng(fseed) % u(n_init)
        rows = tuple(
            jnp.where(frozen_in, inits[s][fpick], rows[s]) for s in range(S)
        )
        seed = jnp.where(frozen_in, fseed, seed)
        ebits = jnp.where(frozen_in, u(init_ebits), ebits)
        ptr = jnp.where(frozen_in, u(0), ptr)
        keep = ~frozen_in
        fp1buf = (fp1buf.reshape(B, L) * keep[:, None]).reshape(-1)
        fp2buf = (fp2buf.reshape(B, L) * keep[:, None]).reshape(-1)
        zero_b = seed & u(0)
        false_b = zero_b != 0
        covc0 = (
            (
                jnp.zeros(A, dtype=u),  # per-action taken counts
                tuple(zero_b[0] for _ in range(P)),  # per-property hits
                jnp.zeros(DEPTH_CAP, dtype=u),  # depth histogram
            )
            if cov
            else ()
        )
        sampc0 = (
            (
                # scap+1 wide: index scap is the masked-write trash slot.
                jnp.zeros(scap + 1, dtype=u),  # fp1
                jnp.zeros(scap + 1, dtype=u),  # fp2
                jnp.zeros(scap + 1, dtype=u),  # depth (walk position)
                zero_b[0],  # occupied
                tuple(jnp.zeros(scap + 1, dtype=u) for _ in range(S)),
            )
            if sample_k
            else ()
        )
        init_carry = (
            (tuple(rows), seed, ptr, ebits, false_b),
            fp1buf,
            fp2buf,
            zero_b[0],
            zero_b[0],
            rec_bits0,
            tuple(false_b for _ in range(P)),
            tuple(zero_b for _ in range(P)),
            zero_b,
            covc0,
            sampc0,
        )
        (
            (rows, seed, ptr, ebits, frozen),
            fp1buf,
            fp2buf,
            gen,
            steps,
            rec_acc,
            hseen,
            plen,
            maxd,
            covc_out,
            sampc_out,
        ) = lax.while_loop(cond, body, init_carry)

        # Epilogue: per newly-hit property, report the SHORTEST hit's walk
        # (parity with the BFS engine's shallowest-snapshot rule) as
        # (walk_index, path_length, fp pair).
        rec_bits_out = rec_bits0
        disc_walk = jnp.zeros(P, dtype=u)
        disc_plen = jnp.zeros(P, dtype=u)
        for i in range(P):
            found = jnp.any(hseen[i])
            sel = jnp.argmin(jnp.where(hseen[i], plen[i], u(0xFFFFFFFF)))
            disc_walk = disc_walk.at[i].set(sel.astype(u))
            disc_plen = disc_plen.at[i].set(plen[i][sel])
            rec_bits_out = rec_bits_out | (found.astype(u) << u(i))

        walk_out = tuple(rows) + (seed, ptr, ebits, frozen.astype(u))
        # Discovery walk indices and path lengths ride the params tail so
        # the era result is ONE download (each separate device read costs
        # ~100ms here — the simulation TTFC floor). With coverage enabled
        # the era's histograms (act[A] | prop_hits[P] | depth[DEPTH_CAP])
        # ride the same download.
        parts = [
            jnp.stack(
                [
                    rec_bits_out,
                    params[P_MAX_STEPS],
                    params[P_FIN_ANY],
                    params[P_FIN_ALL],
                    params[P_FIN_ALL_EN],
                    params[P_TARGET_GEN],
                    gen0 + gen,
                    gen0 + gen,
                    steps,
                    maxd.max(),
                    params[P_SEED],
                ]
            ),
            disc_walk,
            disc_plen,
        ]
        if cov:
            act, covp, dhist = covc_out
            parts += [
                act,
                jnp.stack(list(covp)) if P else jnp.zeros(0, dtype=u),
                dhist,
            ]
        if sample_k:
            # Sample tail: [T1, T2, occupied, sdrop=0] + the sk2 smallest
            # slab entries by h1 (fp1 | fp2 | depth | S state lanes | ok)
            # — one top_k in the once-per-era epilogue. The ok lane
            # disambiguates padding from a real fp1 of 0xFFFFFFFF.
            # Walks revisit states, so the slab holds duplicate fps and a
            # plain top_k would spend all sk2 lanes on copies of the few
            # smallest — dedup first (first occurrence wins; O(scap^2)
            # bool matrix, epilogue-only so it costs once per era).
            sfp1, sfp2, sdep, socc, sst = sampc_out
            used = jnp.arange(scap, dtype=u) < socc
            f1, f2 = sfp1[:scap], sfp2[:scap]
            same = (
                (f1[:, None] == f1[None, :])
                & (f2[:, None] == f2[None, :])
                & used[None, :]
            )
            idx = jnp.arange(scap, dtype=u)
            dup = (same & (idx[None, :] < idx[:, None])).any(axis=1)
            used = used & ~dup
            skey = jnp.where(used, ~sfp1[:scap], u(0))
            _topv, topi = lax.top_k(skey, sk2)
            parts += [
                jnp.stack([st1, st2, socc, u(0)]),
                sfp1[:scap][topi],
                sfp2[:scap][topi],
                sdep[:scap][topi],
            ]
            parts += [sst[s][:scap][topi] for s in range(S)]
            parts += [used[topi].astype(u)]
        params_out = jnp.concatenate(parts)
        return walk_out, fp1buf, fp2buf, params_out

    @jax.jit
    def seed_run(params):
        """Fused seeding + first era: ONE small upload, walk state and
        path buffers created on device (host<->device round-trips are the
        TTFC floor on this platform; the walk lanes would otherwise cost
        an upload each). Walk 0 uses the master seed directly for
        reproducibility parity with the host engine (simulation.rs:154)."""
        u = jnp.uint32
        master = params[P_SEED]
        iota_b = jnp.arange(B, dtype=u)
        seeds = prng(master ^ (iota_b * u(0x9E3779B9)))
        seeds = seeds.at[0].set(master)
        picks = prng(seeds) % u(n_init)
        rows = tuple(jnp.asarray(l)[picks] for l in init_lanes_const)
        walk = rows + (
            seeds,
            jnp.zeros(B, dtype=u),
            jnp.full(B, init_ebits, dtype=u),
            jnp.zeros(B, dtype=u),  # frozen lane: nothing frozen yet
        )
        fp1buf = jnp.zeros(B * L, dtype=u)
        fp2buf = jnp.zeros(B * L, dtype=u)
        return loop(walk, fp1buf, fp2buf, params)


    _LOOP_CACHE[key] = (tm, (loop, seed_run, n_init))
    return loop, seed_run, n_init


# Stage-profiler kernels (obs/stageprof.py): one jitted microbench per
# sim-loop stage, uniform signature (fp1buf, fp2buf, seed) -> uint32.
_STAGE_KERNEL_CACHE: Dict[Tuple, Tuple[TensorModel, Dict[str, Any]]] = {}


def _build_sim_stage_kernels(tm: TensorModel, props, B: int, L: int,
                             iters: int) -> Dict[str, Any]:
    """Per-stage microbench kernels for the simulation era loop.

    Stage map (one walk step): `hash` — fingerprint the B current states;
    `cycle` — the [B, L] own-path membership compare; `record` — the path
    buffer scatter plus the restart row-clear multiply; `expand` —
    `tm.step_lanes` + boundary masks + property evaluation (evaluated
    together in the loop); `choose` — the counter-PRNG pick and the
    A-round successor select. Same measurement discipline as the BFS
    engine's `_build_stage_kernels` (engines/tpu_bfs.py): `iters`
    repetitions per dispatch chained through the carry, outputs anchored
    into the returned scalar.
    """
    key = (id(tm), B, L, len(props), iters)
    cached = _STAGE_KERNEL_CACHE.get(key)
    if cached is not None and cached[0] is tm:
        return cached[1]
    while len(_STAGE_KERNEL_CACHE) >= 8:
        _STAGE_KERNEL_CACHE.pop(next(iter(_STAGE_KERNEL_CACHE)))

    import jax
    import jax.numpy as jnp
    from jax import lax

    from ..fingerprint import hash_lanes_jnp

    S = tm.state_width
    A = tm.max_actions
    u = jnp.uint32

    def _mix(x):
        x = x ^ (x >> 16)
        x = x * u(0x7FEB352D)
        x = x ^ (x >> 15)
        x = x * u(0x846CA68B)
        return x ^ (x >> 16)

    def _lane(n, salt):
        return _mix(jnp.arange(n, dtype=u) * u(0x9E3779B1) + u(salt))

    @jax.jit
    def k_hash(fp1buf, fp2buf, seed):
        rows0 = tuple(_lane(B, 3 + s) & u(7) for s in range(S))

        def body(_i, acc):
            r = ((rows0[0] ^ (acc & u(1))) & u(7),) + rows0[1:]
            h1, h2 = hash_lanes_jnp(r)
            return acc + h1[0] + h2[0]

        return lax.fori_loop(0, iters, body, seed)

    @jax.jit
    def k_cycle(fp1buf, fp2buf, seed):
        h0 = _lane(B, 13)
        g0 = _lane(B, 17)
        ptr = _lane(B, 19) % u(max(1, L))
        il = jnp.arange(L, dtype=u)
        f1m = fp1buf.reshape(B, L)
        f2m = fp2buf.reshape(B, L)

        def body(_i, acc):
            h1 = h0 ^ (acc & u(1))
            in_path = (
                ((f1m == h1[:, None]) & (f2m == g0[:, None])
                 & (il[None, :] < ptr[:, None])).sum(axis=1, dtype=u)
                > u(0)
            )
            return acc + in_path.sum(dtype=u)

        return lax.fori_loop(0, iters, body, seed)

    @jax.jit
    def k_record(fp1buf, fp2buf, seed):
        # Path-buffer scatter of the step's B fingerprints, plus the
        # restart row-clear multiply — the two [B*L]-touching writes of a
        # step. Buffers thread through the carry so iterations chain.
        ib = jnp.arange(B, dtype=u)
        h0 = _lane(B, 23)
        restart0 = (_lane(B, 29) & u(15)) == u(0)  # ~6% restarts/step

        def body(i, carry):
            f1, f2, acc = carry
            pos = ib * u(L) + ((acc + i.astype(u)) % u(max(1, L)))
            h1 = h0 ^ (acc & u(1))
            f1 = f1.at[pos].set(h1, mode="drop", unique_indices=True)
            f2 = f2.at[pos].set(h1, mode="drop", unique_indices=True)
            keep = ~restart0
            f1 = (f1.reshape(B, L) * keep[:, None]).reshape(-1)
            f2 = (f2.reshape(B, L) * keep[:, None]).reshape(-1)
            return f1, f2, acc + f1[0]

        _f1, _f2, acc = lax.fori_loop(
            0, iters, body, (fp1buf, fp2buf, seed)
        )
        return acc

    @jax.jit
    def k_expand(fp1buf, fp2buf, seed):
        # Successor generation + boundary masks + property evaluation
        # (the loop evaluates them on the same rows in the same step).
        rows0 = tuple(_lane(B, 31 + s) & u(7) for s in range(S))

        def body(_i, acc):
            rows = ((rows0[0] ^ (acc & u(1))) & u(7),) + rows0[1:]
            succs, amask = tm.step_lanes(jnp, rows)
            ne = jnp.zeros(B, dtype=u)
            for a in range(A):
                v = amask[a] & tm.within_boundary_lanes(jnp, succs[a])
                ne = ne + v.astype(u)
            for p in props:
                ne = ne + p.check(jnp, rows).sum(dtype=u)
            return acc + ne[0] + ne.sum(dtype=u)

        return lax.fori_loop(0, iters, body, seed)

    @jax.jit
    def k_choose(fp1buf, fp2buf, seed):
        # Counter-PRNG pick + the A-round uniform successor select.
        rows0 = tuple(_lane(B, 47 + s) for s in range(S))
        succs0 = tuple(
            tuple(_lane(B, 101 + a * S + s) for s in range(S))
            for a in range(A)
        )
        valid0 = tuple((_lane(B, 211 + a) & u(1)) == u(0) for a in range(A))
        ptr = _lane(B, 223) % u(max(1, L))

        def prng(x):
            x = (x ^ (x >> u(16))) * u(0x7FEB352D)
            x = (x ^ (x >> u(15))) * u(0x846CA68B)
            return x ^ (x >> u(16))

        def body(_i, acc):
            sd = _lane(B, 227) ^ acc
            ne = jnp.zeros(B, dtype=u)
            for a in range(A):
                ne = ne + valid0[a].astype(u)
            r = prng(sd ^ (ptr * u(0x9E3779B9)))
            pick = jnp.where(ne > u(0), r % jnp.maximum(ne, u(1)), u(0))
            cum = jnp.zeros(B, dtype=u)
            new_rows = rows0
            chosen_any = ne < u(0)
            for a in range(A):
                sel = valid0[a] & (cum == pick) & ~chosen_any
                chosen_any = chosen_any | sel
                new_rows = tuple(
                    jnp.where(sel, succs0[a][s], new_rows[s])
                    for s in range(S)
                )
                cum = cum + valid0[a].astype(u)
            out = acc
            for lane in new_rows:
                out = out + lane[0]
            return out

        return lax.fori_loop(0, iters, body, seed)

    kernels = {
        "hash": k_hash,
        "cycle": k_cycle,
        "record": k_record,
        "expand": k_expand,
        "choose": k_choose,
    }
    _STAGE_KERNEL_CACHE[key] = (tm, kernels)
    return kernels


class TpuSimulationChecker(HostEngineBase):
    """B batched seeded random walks on the default JAX device."""

    _supports_threads = True  # parallelism = the walk batch

    def __init__(
        self,
        builder: CheckerBuilder,
        seed: int,
        *,
        walks: int = 1024,
        walk_cap: int = 256,
        sync_steps: int = 1024,
    ):
        model = builder.model
        if isinstance(model, TensorModel):
            model = TensorModelAdapter(model)
        if not isinstance(model, TensorModelAdapter):
            raise TypeError(
                "spawn_tpu_simulation requires a TensorModel (or its adapter)"
            )
        super().__init__(builder, model=model)
        if self._visitor is not None:
            raise ValueError("the device simulation engine does not support visitors")
        if self._symmetry is not None:
            raise ValueError(
                "the device simulation engine does not support symmetry "
                "reduction (use the host simulation engine)"
            )
        self.tm = model.tm
        self._tprops = self.tm.tensor_properties()
        if len(self._tprops) > 32:
            raise ValueError("at most 32 tensor properties supported")
        self._seed = seed & 0xFFFFFFFF
        self._B = walks
        self._L = (
            min(walk_cap, self._target_max_depth)
            if self._target_max_depth is not None
            else walk_cap
        )
        self._sync = sync_steps
        self._discovery_paths: Dict[str, List[int]] = {}
        self._metrics.set_gauge("walks", self._B)
        self._metrics.set_gauge("walk_cap", self._L)
        self._cov = self._coverage.enabled
        self._stage_profile = bool(getattr(builder, "stage_profile_", False))
        self._stage_iters = int(getattr(builder, "stage_profile_iters_", 32))
        self._sample_k = self._sampler.k if self._sampler is not None else 0
        self._loop, self._seed_run, self._n_init = _build_sim_loop(
            self.tm, self._tprops, self._B, self._L, self._cov,
            sample_k=self._sample_k,
        )
        self._start()

    def _run(self) -> None:
        import jax.numpy as jnp

        tm = self.tm
        S = tm.state_width
        B, L, P = self._B, self._L, len(self._tprops)

        fin_any, fin_all, fin_all_en = self._finish_when.device_masks(
            self._tprops
        )
        if self._n_init == 0:
            # No in-boundary init states: the compiled seeder's modulo
            # over n_init would be undefined — never dispatch it.
            return
        rec_bits = 0
        gen_total = 0

        max_sync = (
            self._sync
            if self._timeout is None
            else min(64, self._sync)
        )
        target_gen = self._target_state_count or 0

        from ..obs.coverage import DEPTH_CAP

        A = tm.max_actions
        ncov = (A + P + DEPTH_CAP) if self._cov else 0
        # Sample tail: [T1, T2, occupied, sdrop] + (fp1|fp2|depth|S state
        # lanes|ok) x slab_entries(k) words.
        if self._sample_k:
            from ..obs.sample import slab_entries

            sk2 = slab_entries(self._sample_k)
            nsamp = 4 + (4 + S) * sk2
            s_base = P_LEN + 2 * P + ncov
        else:
            sk2 = nsamp = s_base = 0
        last_thresh = None
        params = np.zeros(P_LEN + 2 * P + ncov + nsamp, dtype=np.uint32)
        if self._sampler is not None:
            t1, t2 = self._sampler.threshold_parts()
            params[s_base] = t1
            params[s_base + 1] = t2
            last_thresh = (t1, t2)
        params[P_MAX_STEPS] = max_sync
        params[P_FIN_ANY] = fin_any
        params[P_FIN_ALL] = fin_all
        params[P_FIN_ALL_EN] = fin_all_en
        params[P_TARGET_GEN] = min(target_gen, 0xFFFFFFFF)
        params[P_SEED] = self._seed

        # Fused seeding + first era: one small upload, one dispatch (walk
        # lanes and path buffers are created on device).
        first = True
        walk = fp1buf = fp2buf = None
        params_dev = jnp.asarray(params)

        while True:
            era_t0 = time.monotonic()
            if first:
                walk, fp1buf, fp2buf, params_dev = self._seed_run(params_dev)
                first = False
                if self._memory is not None:
                    # Static footprint (no growth/spill): register once
                    # from the shared size formulas so the planner and the
                    # nbytes parity test agree with the live allocation.
                    from ..obs.memory import sim_component_sizes

                    self._memory.register_components(
                        sim_component_sizes(
                            S,
                            A,
                            P,
                            walks=B,
                            walk_cap=L,
                            coverage=self._cov,
                            sample_k=self._sample_k,
                        ),
                        arrays={
                            "walk_lanes": walk,
                            "path_fps": (fp1buf, fp2buf),
                            "packed_params": params_dev,
                            "coverage_slab": params_dev,
                            "sample_slab": params_dev,
                        },
                    )
            else:
                walk, fp1buf, fp2buf, params_dev = self._loop(
                    walk, fp1buf, fp2buf, params_dev
                )
            with self._metrics.phase("readback"):
                vals = np.asarray(params_dev)
            era_dt = time.monotonic() - era_t0
            self._metrics.add_phase("device_era", era_dt)
            self._metrics.observe("era_secs", era_dt)
            self._metrics.inc("eras")
            self._metrics.inc("steps", int(vals[P_STEPS]))
            gen_prev = gen_total
            gen_total = int(vals[P_GEN])
            self._metrics.inc("states_generated", gen_total - gen_prev)
            self._state_count = gen_total
            self._max_depth = max(self._max_depth, int(vals[P_MAXD]))

            if self._cov:
                # Era coverage deltas ride the same params download
                # (layout: act[A] | prop_hits[P] | depth hist).
                base = P_LEN + 2 * P
                cov_acc = self._coverage
                cov_acc.record_action_counts(vals[base : base + A])
                for i, p in enumerate(self._tprops):
                    # Every property is evaluated on every counted state.
                    cov_acc.record_property_eval(p.name, gen_total - gen_prev)
                    cov_acc.record_property_hit(
                        p.name, int(vals[base + A + i])
                    )
                cov_acc.record_depth_counts(
                    vals[base + A + P : base + ncov]
                )

            if self._sampler is not None:
                # Sample-slab drain (same download). Device walks revisit
                # states, so re-drains of the same fingerprint are normal;
                # the sampler dedups.
                occupied = int(vals[s_base + 2])
                off = s_base + 4
                if occupied:
                    srows = np.stack(
                        [
                            vals[
                                off + (3 + s) * sk2 : off + (4 + s) * sk2
                            ]
                            for s in range(S)
                        ],
                        axis=1,
                    )
                    # exact=False: walk revisits put DUPLICATE fps in the
                    # slab, so occupied > drained means duplicates, not
                    # truncation — the exact tie cut would starve the sample.
                    self._sampler.drain_slab(
                        vals[off : off + sk2],
                        vals[off + sk2 : off + 2 * sk2],
                        vals[off + 2 * sk2 : off + 3 * sk2],
                        vals[off + (3 + S) * sk2 : off + (4 + S) * sk2],
                        occupied,
                        states=srows,
                        exact=False,
                    )
                if self._sampler.threshold_parts() != last_thresh:
                    # Tightened threshold: re-upload the params vector
                    # (everything else in it is the era's own pass-through
                    # output, so a host copy with only the T words changed
                    # is exact).
                    arr = np.array(vals)
                    t1, t2 = self._sampler.threshold_parts()
                    arr[s_base] = t1
                    arr[s_base + 1] = t2
                    last_thresh = (t1, t2)
                    params_dev = jnp.asarray(arr)

            new_bits = int(vals[P_REC])
            if new_bits != rec_bits:
                # Extract the freshly-hit walks' fingerprint paths from the
                # device buffers: the walk/length indices came with the
                # params download; the two path buffers stack into ONE read.
                both = np.asarray(jnp.stack([fp1buf, fp2buf]))
                f1 = both[0].reshape(B, L)
                f2 = both[1].reshape(B, L)
                dw = vals[P_LEN : P_LEN + P]
                dp = vals[P_LEN + P : P_LEN + 2 * P]
                for i, p in enumerate(self._tprops):
                    if not ((new_bits >> i) & 1) or p.name in self._discovery_paths:
                        continue
                    w = int(dw[i])
                    n = int(dp[i])  # plen snapshots the post-write count
                    chain = [
                        combine64(int(f1[w, k]), int(f2[w, k]))
                        for k in range(min(n, L))
                    ]
                    self._discovery_paths[p.name] = chain
                rec_bits = new_bits

            self._obs_event(
                "era",
                frontier=self._B,
                steps=int(vals[P_STEPS]),
                generated=gen_total - gen_prev,
            )
            self._flight_record(
                device_era_secs=era_dt,
                steps=int(vals[P_STEPS]),
                generated=gen_total - gen_prev,
                unique=gen_total,
                frontier=self._B,
            )
            if self._finish_matched(self._discovery_paths):
                break
            if target_gen and gen_total >= target_gen:
                break
            if self._timed_out():
                break

        self._profile_stages(fp1buf, fp2buf)

    def _profile_stages(self, fp1buf, fp2buf) -> None:
        """Post-run per-stage attribution of device_era wall time
        (CheckerBuilder.stage_profile(); obs/stageprof.py). Never fatal."""
        if not self._stage_profile:
            return
        try:
            import jax.numpy as jnp

            from ..obs import stageprof

            steps = int(self._metrics.get("steps"))
            era_secs = self._metrics.phase_ms().get("device_era", 0.0) / 1e3
            if steps <= 0 or era_secs <= 0.0:
                return
            kernels = _build_sim_stage_kernels(
                self.tm, self._tprops, self._B, self._L, self._stage_iters
            )
            seed = jnp.asarray(1, dtype=jnp.uint32)
            with self._metrics.phase("profiler_overhead"):
                timed = stageprof.measure_stage_kernels(
                    {
                        name: (fn, (fp1buf, fp2buf, seed))
                        for name, fn in kernels.items()
                    },
                    self._stage_iters,
                )
            stageprof.attribute_stages(
                self._metrics, timed, era_secs, steps, self._stage_iters
            )
        except Exception as exc:
            from ..obs.log import get_logger

            self._metrics.set_gauge("stage_profile_error", repr(exc)[:200])
            get_logger("engines.tpu_simulation").warning(
                "stage profiling failed (run results unaffected)",
                error=repr(exc),
            )

    # -- accessors ----------------------------------------------------------

    def unique_state_count(self) -> int:
        # Like the host simulation engine: no global visited set is kept
        # (simulation.rs:413-417).
        return self._state_count

    def discoveries(self) -> Dict[str, Path]:
        self.join()
        return {
            name: Path.from_fingerprints(self._model, chain)
            for name, chain in list(self._discovery_paths.items())
        }
