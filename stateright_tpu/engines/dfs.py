"""Host depth-first search engine.

Reference: src/checker/dfs.rs. Exhaustive DFS carrying the full fingerprint
path in each job (dfs.rs:31) — low memory, longer counterexamples. This is
the engine wired to symmetry reduction: successor states are canonicalized
via the representative function before visited-set insertion, while the job's
path keeps the pre-canonicalized fingerprints so path reconstruction stays
within reachable space (dfs.rs:309-318).
"""

from __future__ import annotations

from collections import deque
from typing import Dict, List

from ..checker import CheckerBuilder
from ..path import Path
from .common import BLOCK_SIZE, HostEngineBase


def _cons(parent, fp):
    """Fingerprint paths are shared cons cells (parent_node, fp): O(1) per
    successor instead of the reference's per-job Vec clone (dfs.rs:338-342),
    which is quadratic in depth and prohibitive for deep Python searches."""
    return (parent, fp)


def _materialize(node) -> List[int]:
    out: List[int] = []
    while node is not None:
        node, fp = node[0], node[1]
        out.append(fp)
    out.reverse()
    return out


class DfsChecker(HostEngineBase):
    def __init__(self, builder: CheckerBuilder):
        super().__init__(builder)
        model = self._model
        symmetry = self._symmetry

        init_states = [s for s in model.init_states() if model.within_boundary(s)]
        self._state_count = len(init_states)
        self._generated: set = set()  # fingerprints (of representatives if symmetry)
        for s in init_states:
            fp = self._fp(symmetry(s)) if symmetry is not None else self._fp(s)
            if fp not in self._generated and self._sampler is not None:
                self._sampler.offer(fp, depth=1, state=s)
            self._generated.add(fp)
        self._coverage.record_depth(1, len(self._generated))
        # job: (state, fingerprint cons-path, ebits, depth) (dfs.rs:31)
        self._pending = deque(
            (s, _cons(None, self._fp(s)), self._init_ebits, 1) for s in init_states
        )
        self._discoveries: Dict[str, List[int]] = {}  # name -> fingerprint path
        self._start()

    # -- exploration --------------------------------------------------------

    def _run(self) -> None:
        while True:
            if not self._pending:
                return
            with self._metrics.phase("check_block"):
                self._check_block()
            self._metrics.inc("waves")
            self._obs_event("wave", frontier=len(self._pending))
            if self._finish_matched(self._discoveries):
                return
            if (
                self._target_state_count is not None
                and self._state_count >= self._target_state_count
            ):
                return
            if self._timed_out():
                return

    def _check_block(self) -> None:
        """Process up to BLOCK_SIZE states. Mirrors dfs.rs:182-359."""
        model = self._model
        symmetry = self._symmetry
        pending = self._pending
        generated = self._generated
        discoveries = self._discoveries

        for _ in range(BLOCK_SIZE):
            if not pending:
                return
            state, fp_node, ebits, depth = pending.pop()

            if depth > self._max_depth:
                self._max_depth = depth
            if self._target_max_depth is not None and depth >= self._target_max_depth:
                continue
            if self._visitor is not None:
                self._visitor.visit(
                    model, Path.from_fingerprints(model, _materialize(fp_node))
                )

            ebits, is_awaiting = self._check_properties(
                state, ebits, discoveries, lambda: _materialize(fp_node)
            )
            if not is_awaiting:
                return

            # Expand successors (LIFO push for depth-first order).
            cov = self._coverage if self._coverage.enabled else None
            is_terminal = True
            actions: list = []
            model.actions(state, actions)
            for action in actions:
                next_state = model.next_state(state, action)
                if next_state is None:
                    continue
                if not model.within_boundary(next_state):
                    continue
                self._state_count += 1
                if cov is not None:
                    cov.record_action(self._action_label(action))
                if symmetry is not None:
                    rep_fp = self._fp(symmetry(next_state))
                    if rep_fp in generated:
                        is_terminal = False
                        continue
                    generated.add(rep_fp)
                    sample_fp = rep_fp
                    # Continue the path with the pre-canonicalized fingerprint
                    # so the path stays extendable (dfs.rs:315-318).
                    next_fp = self._fp(next_state)
                else:
                    next_fp = self._fp(next_state)
                    if next_fp in generated:
                        is_terminal = False
                        continue
                    generated.add(next_fp)
                    sample_fp = next_fp
                if self._sampler is not None:
                    # Sample by the dedup key (the canonical fingerprint
                    # under symmetry) — the same key the device engines
                    # explore, keeping sample sets engine-independent.
                    self._sampler.offer(
                        sample_fp,
                        depth=depth + 1,
                        action=action,
                        state=next_state,
                        pred=state,
                    )
                if cov is not None:
                    cov.record_depth(depth + 1)
                is_terminal = False
                pending.append(
                    (next_state, _cons(fp_node, next_fp), ebits, depth + 1)
                )
            if is_terminal:
                self._terminal_ebit_discoveries(
                    ebits, discoveries, lambda: _materialize(fp_node)
                )

    # -- accessors ----------------------------------------------------------

    def unique_state_count(self) -> int:
        return len(self._generated)

    def discoveries(self) -> Dict[str, Path]:
        return {
            name: Path.from_fingerprints(self._model, fps)
            for name, fps in list(self._discoveries.items())
        }
