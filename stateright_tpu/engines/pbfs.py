"""Parallel host BFS for RICH Python models: multiprocessing ownership shards.

Closes the reference's `.threads(n)`-for-any-model capability
(job_market.rs:59-182 + bfs.rs:90-164). The reference parallelizes with
OS threads over a shared DashMap; under CPython the GIL makes that shape
worthless, so this engine re-designs it the same way the device mesh
engine re-designed multi-chip checking: N worker PROCESSES, each OWNING
the fingerprint range `fp % N == w` — its own visited dict (fp -> parent
fp) and pending queue — exchanging candidate batches over pipes. Each
candidate crosses process boundaries exactly once, to its owner; dedup is
a plain dict lookup in the owner (no cross-process synchronization at
all). This is the job market's work-distribution role with ownership
routing in place of work stealing — the same trade the sharded device
engine makes (parallel/mesh.py), for the same reason: cheap local dedup
beats migrating shared state.

Semantics match the reference BFS state-for-state: property evaluation at
visit time, eventually-bit propagation along paths, the terminal rule,
boundary filtering, depth accounting, parent-pointer path reconstruction
(bfs.rs:196-334, 380-409). Like the reference's multithreaded BFS, visit
order differs run to run, discovery RACES are benign (first reported
wins), and `state_count` totals are exact.

Termination is the classic double-count protocol: the coordinator polls
(sent, received, idle) from every worker and stops when all are idle with
equal global sent/received counts on two consecutive polls.

Requirements: the model and its states must be picklable. Visitors are
not supported (they would serialize every path across processes).
"""

from __future__ import annotations

import multiprocessing as mp
import queue as queue_mod
import time
from typing import Any, Dict, List, Optional, Tuple

from ..checker import CheckerBuilder
from ..core import Expectation
from ..path import Path
from .common import HostEngineBase

_BLOCK = 1500  # states per pop block, reference bfs.rs:130


def _worker(
    wid: int,
    n_workers: int,
    model_blob: bytes,
    depth_limit: Optional[int],
    coverage_enabled: bool,
    in_q,
    out_qs,
    ctl_q,
    res_q,
):
    """One ownership shard: own visited dict + pending queue + expansion."""
    import cloudpickle

    model = cloudpickle.loads(model_blob)
    visited: Dict[int, int] = {}  # fp -> parent fp (0 = init)
    pending: List[Tuple[Any, int, int, int]] = []  # (state, fp, ebits, depth)
    discoveries: Dict[str, int] = {}  # name -> fp
    properties = model.properties()
    state_count = 0
    max_depth = 0
    sent = 0
    received = 0
    stop = False
    last_report = 0.0
    # Shard-local coverage tallies (obs/coverage.py); shipped once with the
    # final report and merged into the coordinator's accumulator.
    cov_actions: Dict[str, int] = {}
    cov_depths: Dict[int, int] = {}
    cov_prop_evals: Dict[str, int] = {}
    cov_prop_hits: Dict[str, int] = {}
    label_memo: Dict[Any, str] = {}

    def action_label(action) -> str:
        try:
            label = label_memo.get(action)
            if label is None:
                label = model.format_action(action)
                label_memo[action] = label
            return label
        except TypeError:
            return model.format_action(action)

    def accept(batch):
        nonlocal received
        received += len(batch)
        for state, fp, parent_fp, ebits, depth in batch:
            if fp in visited:
                continue
            visited[fp] = parent_fp
            if coverage_enabled:
                cov_depths[depth] = cov_depths.get(depth, 0) + 1
            pending.append((state, fp, ebits, depth))

    def flush_out(buckets):
        # Local handoffs go through accept() too, so every candidate is
        # counted once in `sent` and once in `received` globally — the
        # invariant the quiescence protocol relies on.
        nonlocal sent
        for w, batch in enumerate(buckets):
            if not batch:
                continue
            sent += len(batch)
            if w == wid:
                accept(batch)
            else:
                out_qs[w].put(batch)

    def report(kind, epoch=0):
        nonlocal last_report
        if kind == "progress":
            now = time.monotonic()
            if now - last_report < 0.05:
                return
            last_report = now
        cov = (
            {
                "actions": cov_actions,
                "depths": cov_depths,
                "prop_evals": cov_prop_evals,
                "prop_hits": cov_prop_hits,
            }
            if kind == "final" and coverage_enabled
            else None
        )
        res_q.put(
            (
                kind,
                wid,
                epoch,
                state_count,
                len(visited),
                max_depth,
                sent,
                received,
                not pending,
                dict(discoveries),
                cov,
            )
        )

    while True:
        # Drain control messages (stop / termination-poll epochs). Poll
        # replies are the ONLY input to the coordinator's quiescence
        # decision: they carry counts sampled at reply time, tagged with
        # the epoch, so the coordinator never reasons from stale
        # unsolicited snapshots (a stale-pair race ended runs early in
        # round-5 verification).
        try:
            while True:
                msg = ctl_q.get_nowait()
                if msg == "stop":
                    stop = True
                elif isinstance(msg, tuple) and msg[0] == "poll":
                    # Answer AFTER draining the inbox so "idle" reflects
                    # everything already delivered to us.
                    try:
                        while True:
                            accept(in_q.get_nowait())
                    except queue_mod.Empty:
                        pass
                    report("poll_reply", msg[1])
        except queue_mod.Empty:
            pass
        if stop:
            break

        # Drain incoming candidates.
        drained = False
        try:
            while True:
                batch = in_q.get_nowait()
                accept(batch)
                drained = True
        except queue_mod.Empty:
            pass

        if not pending:
            if not drained:
                time.sleep(0.002)
            continue

        block = pending[-_BLOCK:]
        del pending[-len(block):]
        buckets: List[List] = [[] for _ in range(n_workers)]
        for state, fp, ebits, depth in block:
            state_count += 1
            if depth > max_depth:
                max_depth = depth
            if depth_limit is not None and depth >= depth_limit:
                continue

            is_awaiting = False
            for i, prop in enumerate(properties):
                if prop.name in discoveries:
                    continue
                if coverage_enabled:
                    cov_prop_evals[prop.name] = (
                        cov_prop_evals.get(prop.name, 0) + 1
                    )
                if prop.expectation == Expectation.ALWAYS:
                    if not prop.condition(model, state):
                        discoveries[prop.name] = fp
                        if coverage_enabled:
                            cov_prop_hits[prop.name] = (
                                cov_prop_hits.get(prop.name, 0) + 1
                            )
                    else:
                        is_awaiting = True
                elif prop.expectation == Expectation.SOMETIMES:
                    if prop.condition(model, state):
                        discoveries[prop.name] = fp
                        if coverage_enabled:
                            cov_prop_hits[prop.name] = (
                                cov_prop_hits.get(prop.name, 0) + 1
                            )
                    else:
                        is_awaiting = True
                else:  # EVENTUALLY
                    is_awaiting = True
                    if prop.condition(model, state):
                        ebits &= ~(1 << i)

            actions: List[Any] = []
            model.actions(state, actions)
            n_children = 0
            for action in actions:
                child = model.next_state(state, action)
                if child is None:
                    continue
                n_children += 1
                if not model.within_boundary(child):
                    continue
                if coverage_enabled:
                    label = action_label(action)
                    cov_actions[label] = cov_actions.get(label, 0) + 1
                cfp = model.fingerprint_state(child)
                buckets[cfp % n_workers].append((child, cfp, fp, ebits, depth + 1))
            if n_children == 0 and ebits:
                # Terminal eventually-counterexamples (bfs.rs:326-333).
                for i, prop in enumerate(properties):
                    if (ebits >> i) & 1 and prop.name not in discoveries:
                        discoveries[prop.name] = fp
                        if coverage_enabled:
                            cov_prop_hits[prop.name] = (
                                cov_prop_hits.get(prop.name, 0) + 1
                            )
        flush_out(buckets)
        report("progress")

    # Final: one last exact report, then the visited table for path
    # reconstruction.
    last_report = 0.0
    report("final")
    res_q.put(("table", wid, visited))


class ParallelBfsChecker(HostEngineBase):
    """Multiprocessing ownership-sharded BFS over any picklable Model."""

    _supports_threads = True

    def __init__(self, builder: CheckerBuilder):
        super().__init__(builder)
        if self._visitor is not None:
            raise ValueError(
                "the parallel host engine does not support visitors"
            )
        # Reference parity: BFS ignores options.symmetry (bfs.rs never
        # reads it); DFS is the symmetry engine.
        self._n = max(2, self._thread_count)
        self._discovery_fps: Dict[str, int] = {}
        self._unique = 0
        self._tables: List[Dict[int, int]] = []
        self._metrics.set_gauge("workers", self._n)
        self._start()

    def _run(self) -> None:
        import cloudpickle

        model = self._model
        # cloudpickle (not plain pickle) ships the model: actor models are
        # typically assembled from closures/lambdas, which pickle rejects.
        model_blob = cloudpickle.dumps(model)
        n = self._n
        # spawn, not fork: the parent typically holds a live JAX runtime
        # (device tunnels, threads) that must not be duplicated into the
        # workers; workers import only the model's own modules.
        ctx = mp.get_context("spawn")
        in_qs = [ctx.Queue() for _ in range(n)]
        ctl_qs = [ctx.Queue() for _ in range(n)]
        res_q = ctx.Queue()
        procs = [
            ctx.Process(
                target=_worker,
                args=(
                    w,
                    n,
                    model_blob,
                    self._target_max_depth,
                    self._coverage.enabled,
                    in_qs[w],
                    in_qs,
                    ctl_qs[w],
                    res_q,
                ),
                daemon=True,
            )
            for w in range(n)
        ]
        for p in procs:
            p.start()

        # Seed: route init states to their owners.
        seeds: List[List] = [[] for _ in range(n)]
        for state in model.init_states():
            if not model.within_boundary(state):
                continue
            fp = model.fingerprint_state(state)
            seeds[fp % n].append((state, fp, 0, self._init_ebits, 1))
        n_seeded = sum(len(s) for s in seeds)
        for w in range(n):
            if seeds[w]:
                in_qs[w].put(seeds[w])

        stats = {
            w: dict(sc=0, uniq=0, maxd=0, sent=0, recv=0, idle=False, disc={})
            for w in range(n)
        }

        def ingest(msg):
            _, wid, _epoch, sc, uniq, maxd, sent, recv, idle, disc, cov = msg
            stats[wid] = dict(
                sc=sc, uniq=uniq, maxd=maxd, sent=sent, recv=recv,
                idle=idle, disc=disc,
            )
            for name, fp in disc.items():
                self._discovery_fps.setdefault(name, fp)
            if cov:
                # Workers attach their coverage tallies exactly once, on
                # the final report; merge is therefore add-exact.
                self._coverage.merge_counts(**cov)

        # Termination: coordinator-driven polling epochs. Each epoch
        # broadcasts a poll; every worker replies with counts sampled at
        # reply time (after draining its inbox). The run is quiescent when
        # TWO consecutive epochs each show all workers idle with global
        # sent == received (+ seeds) AND identical totals across the two
        # epochs — a message in flight at the first epoch either still
        # shows sent > received at the second, or its delivery changes the
        # totals; either way the pair is rejected. (Unsolicited progress
        # reports feed counters/discoveries only, never this decision:
        # stale-snapshot pairs can momentarily balance — observed as a
        # premature stop in round-5 verification.)
        prev_quiet_totals = None
        epoch = 0
        try:
            while True:
                epoch += 1
                replies = {}
                with self._metrics.phase("poll"):
                    for w in range(n):
                        ctl_qs[w].put(("poll", epoch))
                    deadline = time.monotonic() + 5.0
                    while len(replies) < n and time.monotonic() < deadline:
                        try:
                            msg = res_q.get(timeout=0.05)
                        except queue_mod.Empty:
                            continue
                        if msg[0] in ("progress", "final"):
                            ingest(msg)
                        elif msg[0] == "poll_reply":
                            ingest(msg)
                            if msg[2] == epoch:
                                replies[msg[1]] = msg

                self._state_count = sum(s["sc"] for s in stats.values())
                self._unique = sum(s["uniq"] for s in stats.values())
                self._max_depth = max(
                    [s["maxd"] for s in stats.values()] + [self._max_depth]
                )
                self._metrics.inc("rounds")
                self._obs_event(
                    "round",
                    frontier=sum(0 if s["idle"] else 1 for s in stats.values()),
                    workers=n,
                    epoch=epoch,
                )

                if self._finish_matched(self._discovery_fps):
                    break
                if (
                    self._target_state_count is not None
                    and self._state_count >= self._target_state_count
                ):
                    break
                if self._timed_out():
                    break

                if len(replies) == n:
                    all_idle = all(r[8] for r in replies.values())
                    g_sent = sum(r[6] for r in replies.values()) + n_seeded
                    g_recv = sum(r[7] for r in replies.values())
                    totals = (g_sent, g_recv)
                    if all_idle and g_sent == g_recv:
                        if prev_quiet_totals == totals:
                            break
                        prev_quiet_totals = totals
                    else:
                        prev_quiet_totals = None
                else:
                    prev_quiet_totals = None
        finally:
            for w in range(n):
                ctl_qs[w].put("stop")
            tables: Dict[int, Dict[int, int]] = {}
            # Shard tables cross the result queue as pickled dicts; the
            # collection deadline must scale with their size or large runs
            # time out, lose tables, and later raise "fingerprint missing
            # from shard table" during path reconstruction. Budget ~10µs
            # per visited entry (generous vs measured pickle+pipe cost) on
            # top of the old 30s floor.
            deadline = time.monotonic() + 30 + self._unique * 1e-5
            while len(tables) < n and time.monotonic() < deadline:
                try:
                    msg = res_q.get(timeout=1.0)
                except queue_mod.Empty:
                    continue
                if msg[0] == "table":
                    tables[msg[1]] = msg[2]
                else:
                    ingest(msg)
            self._tables = [tables.get(w, {}) for w in range(n)]
            if self._sampler is not None:
                # Workers are separate processes, so sampling happens at
                # the table merge: one vectorized bottom-k pass over each
                # shard's visited fingerprints (rows/depths resolve
                # lazily through _reconstruct at profile-build time).
                import numpy as np

                for table in self._tables:
                    if table:
                        self._sampler.offer_array(
                            np.fromiter(
                                table.keys(), dtype=np.uint64, count=len(table)
                            )
                        )
            self._state_count = sum(s["sc"] for s in stats.values())
            self._unique = sum(s["uniq"] for s in stats.values())
            self._max_depth = max(
                [s["maxd"] for s in stats.values()] + [self._max_depth]
            )
            for p in procs:
                p.join(timeout=5)
                if p.is_alive():
                    p.terminate()

    # -- accessors ----------------------------------------------------------

    def unique_state_count(self) -> int:
        return self._unique

    def discoveries(self) -> Dict[str, Path]:
        self.join()
        return {
            name: self._reconstruct(fp)
            for name, fp in list(self._discovery_fps.items())
        }

    def _sample_resolver(self):
        return self._path_sample_resolver(self._reconstruct)

    def _reconstruct(self, fp: int) -> Path:
        """Walk parent pointers across the shard tables (owner = fp % N)."""
        chain = [fp]
        cur = fp
        for _ in range(10_000_000):
            parent = self._tables[cur % self._n].get(cur)
            if parent is None:
                raise RuntimeError(
                    f"fingerprint {cur} missing from shard table during "
                    "path reconstruction"
                )
            if parent == 0:
                break
            cur = parent
            chain.append(cur)
        chain.reverse()
        return Path.from_fingerprints(self._model, chain)
