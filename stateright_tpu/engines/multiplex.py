"""Scenario multiplexing: N same-shape checks as vmapped lanes of ONE era.

The small-workload guard (engines/tpu_bfs.py, ~10k-state crossover) exists
because a solo device run pays fixed compile + dispatch overheads that
dwarf the actual search for small state spaces. A run *service* sees
thousands of such checks — overwhelmingly same-shaped (same model class +
config, different tenants) — and the fix is the BASELINE vmap insight
applied across tenants instead of across states: wrap the existing era
loop, UN-jitted (`_build_loop(..., raw=True)`), in `jax.vmap`, and run N
independent BFS instances as batch lanes of one fused device program.

Per-lane semantics are *identical to a solo run by construction*: JAX's
`lax.while_loop` batching rule iterates while ANY lane's condition holds
and select-masks finished lanes' carries through unchanged, and every
other op in the loop body is lane-local. One compiled executable, one
dispatch, one params readback for the whole batch.

Lane state is deliberately fixed-shape and small (default: chunk 256,
ring 2^13, table 2^16 — comfortable for any sub-crossover check): the
compiled program depends only on (model signature, lane count, shape
options), so ANY batch of ≤ `lanes` same-signature checks reuses it. The
engine targets single-era completion; a lane that outgrows its table/ring
budget raises with guidance to raise the capacities or run solo
(`spawn_tpu_bfs` exists precisely for those).

Deliberate non-goals (run solo instead): symmetry reduction, visitors,
timeouts, state-count targets, tracing, stage profiling.

Durability: `run_multiplexed(checkpoint_path=...)` snapshots every
completed batch of lanes (the per-lane result vectors + lane tables) via
the crash-safe protocol in engines/common.py; `resume_from=` skips the
batches whose snapshots verify and re-runs only the rest, so a killed
thousand-check sweep resumes instead of restarting.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..checker import Checker, CheckerBuilder
from ..core import Expectation
from ..fingerprint import combine64, split64
from ..obs.coverage import Coverage
from ..obs.metrics import MetricsRegistry
from ..path import Path
from ..tensor import TensorModel, TensorModelAdapter
from .compiled import intern_model, model_signature
from .tpu_bfs import (
    P_COUNT,
    P_DEPTH_LIMIT,
    P_ERR,
    P_FIN_ALL,
    P_FIN_ALL_EN,
    P_FIN_ANY,
    P_GEN,
    P_GROW_LIMIT,
    P_HEAD,
    P_HIGH_WATER,
    P_LEN,
    P_MAXD,
    P_MAX_STEPS,
    P_REC,
    P_STEPS,
    P_TAKE_CAP,
    P_UNIQUE,
    _build_loop,
    _cov_len,
    _vcap,
)

__all__ = ["MultiplexLaneChecker", "run_multiplexed", "warm_lane_program"]

# Per-era step budget for a lane. Generous: small checks finish in tens to
# hundreds of steps; the budget only backstops a runaway model (a lane
# exiting on it without finishing raises below).
_LANE_MAX_STEPS = 1 << 20

# One vmapped program per (model instance, shape). Bounded like the solo
# loop caches.
_MUX_CACHE: Dict[Tuple, Tuple[TensorModel, Any]] = {}


def _build_lane_program(tm: TensorModel, props, lanes: int, chunk: int,
                        qcap: int, tcap: int, icap: int, cov: bool):
    """jit(vmap(seed + era loop)) over `lanes` independent lane instances.

    Signature (leading axis = lane):
      (qinit[N,W,icap], n_init[N], h1[N,icap], h2[N,icap],
       params[N,plen], rec_fp1[N,P], rec_fp2[N,P])
      -> (tables[N,4,tcap], params_out[N,plen])

    The seeder differs from the solo engine's in one load-bearing way:
    the init count is DATA (`n_init`, masking a fixed `icap`-wide slab),
    not a baked shape — so lanes with different init counts, and empty
    padding lanes (n_init=0, whose era condition is False immediately),
    all share the one compiled program.
    """
    key = (id(tm), lanes, chunk, qcap, tcap, icap, len(props), cov)
    cached = _MUX_CACHE.get(key)
    if cached is not None and cached[0] is tm:
        return cached[1]
    while len(_MUX_CACHE) >= 8:
        _MUX_CACHE.pop(next(iter(_MUX_CACHE)))

    import jax
    import jax.numpy as jnp

    from ..ops import visited_set as vs

    loop_fn = _build_loop(tm, props, chunk, qcap, False, cov, raw=True)
    S = tm.state_width
    W = S + 2

    def one_lane(qinit, n_init, h1, h2, params, rec_fp1, rec_fp2):
        u = jnp.uint32
        valid = jnp.arange(icap, dtype=u) < n_init
        table = vs.empty_table(tcap)
        zero = jnp.zeros(icap, dtype=u)
        table, is_new, unresolved, _ovf = vs.insert(
            table,
            jnp.where(valid, h1, u(0)),
            jnp.where(valid, h2, u(0)),
            zero,
            zero,
            valid,
        )
        # All valid init rows enqueue (duplicate inits resolve exactly like
        # the solo seeder: the table keeps one, every row still expands).
        queue = tuple(
            jnp.zeros(qcap, dtype=u)
            .at[:icap]
            .set(jnp.where(valid, qinit[i], u(0)))
            for i in range(W)
        )
        params = (
            params.at[P_HEAD].set(u(0))
            .at[P_COUNT].set(n_init)
            .at[P_UNIQUE].set(is_new.sum(dtype=u))
            .at[P_ERR].set(unresolved.sum(dtype=u))
        )
        table, queue, rec_fp1, rec_fp2, params_out = loop_fn(
            table, queue, rec_fp1, rec_fp2, params
        )
        # Split the packed key buffer back into the four flat lanes the
        # bundle/snapshot format stacks (see visited_set.empty_table).
        keys, pv1, pv2 = table
        return jnp.stack([keys[:tcap], keys[tcap:], pv1, pv2]), params_out

    program = jax.jit(jax.vmap(one_lane))
    _MUX_CACHE[key] = (tm, program)
    return program


def _shape_options(tm: TensorModel, chunk: int, qcap: int, tcap: int,
                   icap: int) -> Tuple[int, int, int, int]:
    """Validate + clamp the lane shape exactly like the solo engine."""
    if qcap & (qcap - 1):
        raise ValueError("queue_capacity must be a power of two")
    A = max(1, tm.max_actions)
    chunk = min(chunk, qcap // (2 * A))
    if chunk == 0:
        raise ValueError("queue_capacity too small for this model's fanout")
    return chunk, qcap, tcap, icap


def warm_lane_program(tm: TensorModel, *, lanes: int = 32, chunk: int = 256,
                      queue_capacity: int = 1 << 13,
                      table_capacity: int = 1 << 16,
                      init_capacity: int = 64,
                      coverage: bool = True) -> None:
    """Build (trace + lower) the vmapped lane program for this model shape
    without running anything — `CompiledCheck.warm()`'s hook."""
    chunk, qcap, tcap, icap = _shape_options(
        tm, chunk, queue_capacity, table_capacity, init_capacity
    )
    _build_lane_program(
        tm, tm.tensor_properties(), lanes, chunk, qcap, tcap, icap, coverage
    )


class MultiplexLaneChecker(Checker):
    """One lane's results, behind the standard `Checker` query API.

    Constructed done (the batch ran synchronously); `join()` is a no-op.
    Discovery paths reconstruct lazily from the lane's visited table —
    the stacked table download is shared across the whole batch.
    """

    def __init__(self, model: TensorModelAdapter, tprops, vals: np.ndarray,
                 tables, lane: int, n_init: int, cov_enabled: bool,
                 lanes: int, chunk: int, tcap: int, init_rows=None):
        self._model = model
        self._tprops = tprops
        self._tables = tables  # shared _TableBundle
        self._lane = lane
        P = len(tprops)
        A = model.tm.max_actions
        self._state_count = n_init + int(vals[P_GEN])
        self._unique = int(vals[P_UNIQUE])
        self._max_depth = int(vals[P_MAXD])
        self._discovery_fps: Dict[str, int] = {}
        rec_bits = int(vals[P_REC])
        for i, p in enumerate(tprops):
            if (rec_bits >> i) & 1:
                self._discovery_fps[p.name] = combine64(
                    int(vals[P_LEN + i]), int(vals[P_LEN + P + i])
                )
        self._paths: Optional[Dict[str, Path]] = None

        self._metrics = MetricsRegistry()
        m = self._metrics
        m.inc("eras")  # the lane's share of the batch: one fused era
        m.inc("steps", int(vals[P_STEPS]))
        m.inc("states_generated", int(vals[P_GEN]))
        m.set_gauge("chunk", chunk)
        m.set_gauge("table_capacity", tcap)
        m.set_gauge("load_factor", round(self._unique / tcap, 4))
        m.set_gauge("max_depth", self._max_depth)
        m.set_gauge("frontier_size", int(vals[P_COUNT]))
        m.set_gauge("multiplexed_lanes", lanes)

        self._coverage = Coverage(enabled=cov_enabled)
        self._coverage.register_properties(p.name for p in tprops)
        self._coverage.register_actions(
            model.tm.format_action(a) for a in range(A)
        )
        if cov_enabled:
            if init_rows is not None and len(init_rows):
                # Unique inits insert at depth 1 in the seeder, before the
                # loop histogram starts counting (same as the solo engine).
                self._coverage.record_depth(
                    1, len(np.unique(init_rows, axis=0))
                )
            base = P_LEN + 2 * P
            self._coverage.record_action_counts(vals[base : base + A])
            expanded = int(vals[base + A + P])
            for i, p in enumerate(tprops):
                self._coverage.record_property_eval(p.name, expanded)
                self._coverage.record_property_hit(
                    p.name, int(vals[base + A + i])
                )
            self._coverage.record_depth_counts(vals[base + A + P + 1 :])

    # -- Checker API ---------------------------------------------------------

    def state_count(self) -> int:
        return self._state_count

    def unique_state_count(self) -> int:
        return self._unique

    def max_depth(self) -> int:
        return self._max_depth

    def is_done(self) -> bool:
        return True

    def join(self) -> "MultiplexLaneChecker":
        return self

    def telemetry(self) -> Dict[str, Any]:
        snap = self._metrics.snapshot()
        snap["engine"] = type(self).__name__
        return snap

    def coverage(self) -> Dict[str, Any]:
        return self._coverage.snapshot()

    def discoveries(self) -> Dict[str, Path]:
        if self._paths is None:
            self._paths = {
                name: self._reconstruct(fp)
                for name, fp in self._discovery_fps.items()
            }
        return dict(self._paths)

    def _reconstruct(self, fp64: int) -> Path:
        from ..ops import visited_set as vs

        table_np = self._tables.lane(self._lane)
        chain = [fp64]
        cur = fp64
        for _ in range(10_000_000):
            h1, h2 = split64(cur)
            found, p1, p2 = vs.lookup_parent_np(table_np, h1, h2)
            if not found:
                raise RuntimeError(
                    f"fingerprint {cur} missing from lane {self._lane}'s "
                    "visited table during path reconstruction"
                )
            if p1 == 0 and p2 == 0:
                break
            cur = combine64(p1, p2)
            chain.append(cur)
        chain.reverse()
        return Path.from_fingerprints(self._model, chain)


class _TableBundle:
    """Lazily downloads the batch's stacked tables ONCE, shared by every
    lane's path reconstruction (per-lane downloads would pay a device
    round-trip each)."""

    def __init__(self, tables_dev):
        self._dev = tables_dev
        self._np: Optional[np.ndarray] = None

    def lane(self, i: int):
        return tuple(self.asarray()[i][t] for t in range(4))

    def asarray(self) -> np.ndarray:
        """The whole [lanes, 4, tcap] stack on host (downloaded once) —
        also what the batch progress snapshot persists."""
        if self._np is None:
            self._np = np.asarray(self._dev)
            self._dev = None
        return self._np


def _reject_unsupported(builder: CheckerBuilder) -> None:
    for attr, what in (
        ("symmetry_fn_", "symmetry reduction"),
        ("visitor_", "visitors"),
        ("timeout_", "timeouts"),
        ("target_state_count_", "state-count targets"),
        ("trace_path_", "tracing"),
    ):
        if getattr(builder, attr, None) is not None:
            raise ValueError(
                f"multiplexed lanes do not support {what}; run this check "
                "solo via spawn_tpu_bfs/spawn_bfs"
            )
    if getattr(builder, "stage_profile_", False):
        raise ValueError(
            "multiplexed lanes do not support stage profiling; run solo"
        )


def _batch_snapshot_path(base: str, off: int) -> str:
    return f"{base}.batch{off}.npz"


def _save_batch_snapshot(base: str, off: int, n: int, tm: TensorModel,
                         tprops, shape: dict, vals: np.ndarray,
                         tables_np: np.ndarray) -> None:
    from .common import checkpoint_meta, save_checkpoint_atomic

    meta = checkpoint_meta(
        tm, tprops, batch_off=off, batch_n=n, **shape
    )
    save_checkpoint_atomic(
        _batch_snapshot_path(base, off), meta,
        {"vals": vals, "tables": tables_np},
    )


def _load_batch_snapshot(base: str, off: int, n: int, tm: TensorModel,
                         tprops, shape: dict):
    """A verifiable snapshot of this exact batch, or None (missing or
    corrupt snapshots simply re-run the batch — progress snapshots are an
    optimization, never a correctness dependency)."""
    import os

    from .common import (
        CheckpointCorruptError,
        load_checkpoint_verified,
        validate_checkpoint_meta,
    )

    path = _batch_snapshot_path(base, off)
    if not os.path.exists(path):
        return None
    try:
        arrays, meta = load_checkpoint_verified(path)
        validate_checkpoint_meta(
            meta, tm, tprops,
            exact={
                "batch_off": off, "batch_n": n,
                "state_width": tm.state_width, **shape,
            },
        )
    except (CheckpointCorruptError, ValueError):
        return None
    return arrays


def run_multiplexed(
    builders: List[CheckerBuilder],
    *,
    lanes: int = 32,
    chunk: int = 256,
    queue_capacity: int = 1 << 13,
    table_capacity: int = 1 << 16,
    init_capacity: int = 64,
    checkpoint_path: Optional[str] = None,
    resume_from: Optional[str] = None,
) -> List[MultiplexLaneChecker]:
    """Run every builder's check as one lane of a fused vmapped era.

    All builders must carry models with the SAME shape signature
    (engines/compiled.py) — that is what makes one compiled program serve
    them all. Batches larger than `lanes` run as multiple dispatches of
    the same (padded) executable; smaller batches pad with empty lanes.
    Returns one `MultiplexLaneChecker` per builder, in order.

    `checkpoint_path` writes one crash-safe progress snapshot per
    COMPLETED batch (`<path>.batch<off>.npz`: per-lane result vectors +
    lane tables); `resume_from` rebuilds lanes from every snapshot that
    verifies and dispatches only the remaining batches.
    """
    import jax.numpy as jnp

    from ..fingerprint import hash_words_np
    from ..ops import visited_set as vs

    if not builders:
        return []
    tm, sig = intern_model(builders[0].model)
    for b in builders:
        _reject_unsupported(b)
        if model_signature(b.model) != sig:
            raise ValueError(
                "multiplexed lanes must share one model shape signature; "
                f"got {model_signature(b.model)!r} != {sig!r}"
            )
        # Lanes are the intended sub-crossover path; the small-workload
        # hint must not fire if a lane later re-runs solo off this builder.
        b.multiplex_lane_ = True
    tprops = tm.tensor_properties()
    P = len(tprops)
    if P > 32:
        raise ValueError("at most 32 tensor properties supported")
    cov = all(getattr(b, "coverage_", True) for b in builders)

    chunk, qcap, tcap, icap = _shape_options(
        tm, chunk, queue_capacity, table_capacity, init_capacity
    )
    S = tm.state_width
    A = tm.max_actions
    W = S + 2
    vcap = _vcap(A, chunk)
    ncov = _cov_len(A, P) if cov else 0
    plen = P_LEN + 2 * P + ncov

    # Shared init prep: signature-equal models generate identical inits.
    inits = np.asarray(tm.init_states_array(), dtype=np.uint32)
    init_lanes = tuple(inits[:, i] for i in range(S))
    inb = np.asarray(tm.within_boundary_lanes(np, init_lanes), dtype=bool)
    inits = inits[inb]
    n_init = len(inits)
    if n_init > icap:
        raise ValueError(
            f"{n_init} initial states exceed the lane init capacity "
            f"({icap}); raise init_capacity"
        )
    if n_init + vcap > vs.MAX_LOAD * tcap:
        raise ValueError(
            "lane table_capacity too small for this model's init count + "
            "insert batch; raise table_capacity"
        )
    init_ebits = 0
    e = 0
    for p in tprops:
        if p.expectation == Expectation.EVENTUALLY:
            init_ebits |= 1 << e
            e += 1
    h1_row = np.zeros(icap, dtype=np.uint32)
    h2_row = np.zeros(icap, dtype=np.uint32)
    if n_init:
        h1_row[:n_init], h2_row[:n_init] = hash_words_np(inits)
    qinit_row = np.zeros((W, icap), dtype=np.uint32)
    qinit_row[:S, :n_init] = inits.T
    qinit_row[S, :n_init] = init_ebits
    qinit_row[S + 1, :n_init] = 1

    def lane_params(b: CheckerBuilder) -> np.ndarray:
        t = np.zeros(plen, dtype=np.uint32)
        t[P_DEPTH_LIMIT] = (
            b.target_max_depth_ if b.target_max_depth_ is not None
            else 0xFFFFFFFF
        )
        t[P_HIGH_WATER] = qcap - chunk * A
        t[P_MAX_STEPS] = _LANE_MAX_STEPS
        t[P_TAKE_CAP] = chunk
        fin_any, fin_all, fin_all_en = b.finish_when_.device_masks(tprops)
        t[P_FIN_ANY] = fin_any
        t[P_FIN_ALL] = fin_all
        t[P_FIN_ALL_EN] = fin_all_en
        t[P_GROW_LIMIT] = max(0, int(vs.MAX_LOAD * tcap) - vcap)
        return t

    # The snapshot identity: a batch snapshot only resumes under the exact
    # lane geometry that wrote it (different shapes compile different
    # programs and lay tables out differently).
    shape = dict(lanes=lanes, chunk=chunk, qcap=qcap, tcap=tcap,
                 icap=icap, cov=cov)
    program = None  # built lazily: a fully-resumed sweep never compiles
    model = TensorModelAdapter(tm)
    out: List[MultiplexLaneChecker] = []

    for off in range(0, len(builders), lanes):
        batch = builders[off : off + lanes]
        n = len(batch)
        vals = None
        resumed = False
        batch_secs = 0.0
        if resume_from is not None:
            snap = _load_batch_snapshot(resume_from, off, n, tm, tprops, shape)
            if snap is not None:
                vals = snap["vals"]
                tables = _TableBundle(snap["tables"])
                resumed = True
        if vals is None:
            if program is None:
                program = _build_lane_program(
                    tm, tprops, lanes, chunk, qcap, tcap, icap, cov
                )
            qinit = np.zeros((lanes, W, icap), dtype=np.uint32)
            qinit[:n] = qinit_row
            n_inits = np.zeros(lanes, dtype=np.uint32)
            n_inits[:n] = n_init
            h1 = np.zeros((lanes, icap), dtype=np.uint32)
            h2 = np.zeros((lanes, icap), dtype=np.uint32)
            h1[:n] = h1_row
            h2[:n] = h2_row
            params = np.zeros((lanes, plen), dtype=np.uint32)
            for i, b in enumerate(batch):
                params[i] = lane_params(b)
            rec_fp = jnp.zeros((lanes, P), dtype=jnp.uint32)

            _era_t0 = time.monotonic()
            tables_dev, params_dev = program(
                jnp.asarray(qinit), jnp.asarray(n_inits), jnp.asarray(h1),
                jnp.asarray(h2), jnp.asarray(params), rec_fp, rec_fp,
            )
            vals = np.asarray(params_dev)  # ONE readback for the whole batch
            batch_secs = time.monotonic() - _era_t0
            tables = _TableBundle(tables_dev)

        for i, b in enumerate(batch):
            v = vals[i]
            if int(v[P_ERR]):
                raise RuntimeError(
                    f"lane {off + i}: visited-table probe budget exhausted; "
                    "raise table_capacity"
                )
            checker = MultiplexLaneChecker(
                model, tprops, v, tables, i, n_init, cov,
                lanes=lanes, chunk=chunk, tcap=tcap, init_rows=inits,
            )
            if batch_secs > 0.0:
                # Every lane shared the ONE fused dispatch+readback, so
                # each reports the batch's era wall time (the phase) and
                # its latency sample (the distribution twin).
                checker._metrics.add_phase("device_era", batch_secs)
                checker._metrics.observe("era_secs", batch_secs)
            if int(v[P_COUNT]) > 0 and not b.finish_when_.matches(
                set(checker._discovery_fps), model.properties()
            ):
                # The lane exited its era with work left and no finish —
                # it hit the ring/table/step budget. Lanes are sized for
                # sub-crossover checks; anything bigger runs solo.
                raise RuntimeError(
                    f"lane {off + i} did not complete within the lane "
                    f"budget (frontier={int(v[P_COUNT])}, "
                    f"unique={int(v[P_UNIQUE])}); raise "
                    "queue_capacity/table_capacity or run it solo via "
                    "spawn_tpu_bfs"
                )
            out.append(checker)
        # Snapshot only after every lane of the batch validated: a snapshot
        # asserts "this batch is done and correct", never partial work.
        if checkpoint_path is not None and not (
            resumed and checkpoint_path == resume_from
        ):
            _save_batch_snapshot(
                checkpoint_path, off, n, tm, tprops, shape,
                vals, tables.asarray(),
            )
    return out
