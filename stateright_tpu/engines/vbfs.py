"""Vectorized threaded host BFS over TensorModels.

The host-side counterpart of the reference's multithreaded checker
(src/checker/bfs.rs:90-164 + src/job_market.rs:59-182), re-designed the
tensor-first way: instead of work-stealing per-state jobs, the frontier is
processed as numpy LANE BATCHES (the same `step_lanes` programs the TPU
engine jits — vectorized numpy IS the fast host path for them), and the
genuinely concurrent piece — claim-arbitrated membership in the shared
visited set — runs in the native C++ key set (native/checker.cpp), where
`.threads(n)` worker threads partition each candidate batch and insert
with hardware compare-exchange. The GIL is released for the ctypes call,
so the threads truly run in parallel.

Semantics mirror the plain BFS engine and the device engine exactly
(property timing, terminal rule, eventually-bit propagation, boundary
filtering, depth accounting, level-synchronous order); this engine is the
LIVE HOST ORACLE for large device runs — fast enough (≥1M states/sec on
2pc-7) that goldens no longer need to be cached constants.

Spawn via `.threads(n).spawn_bfs()` on a tensor-backed checker, or
`spawn_vbfs()` explicitly.
"""

from __future__ import annotations

from collections import deque
from typing import Dict, Optional

import numpy as np

from ..checker import CheckerBuilder
from ..core import Expectation
from ..fingerprint import hash_words_np
from ..path import Path
from ..tensor import TensorModel, TensorModelAdapter
from .common import HostEngineBase

_BLOCK_ROWS = 1 << 15  # rows processed per vectorized block


class VectorizedBfsChecker(HostEngineBase):
    """Batched BFS over a TensorModel on the host (numpy + native set)."""

    _supports_threads = True

    def __init__(self, builder: CheckerBuilder, block_rows: int = _BLOCK_ROWS):
        model = builder.model
        if isinstance(model, TensorModel):
            model = TensorModelAdapter(model)
        if not isinstance(model, TensorModelAdapter):
            raise TypeError(
                "spawn_vbfs (and .threads(n).spawn_bfs()) require a "
                "TensorModel; rich host models run on the single-threaded "
                "reference engine."
            )
        super().__init__(builder, model=model)
        if self._visitor is not None:
            raise ValueError(
                "the vectorized engine does not support visitors; use the "
                "single-threaded spawn_bfs()"
            )
        self.tm: TensorModel = model.tm
        self._tprops = self.tm.tensor_properties()
        self._nthreads = max(1, self._thread_count)
        self._block_rows = block_rows

        from ..native.vset import VisitedSet

        self._visited = VisitedSet(1 << 16)
        self._parents: Dict[int, int] = {}
        self._discovery_fps: Dict[str, int] = {}

        # Eventually-bit slots (device-engine parity: bit e per
        # eventually-prop, in declaration order).
        self._e_slot: Dict[int, int] = {}
        e = 0
        init_ebits = 0
        for i, p in enumerate(self._tprops):
            if p.expectation == Expectation.EVENTUALLY:
                self._e_slot[i] = e
                init_ebits |= 1 << e
                e += 1
        self._init_ebits_tensor = init_ebits

        tm = self.tm
        inits = np.asarray(tm.init_states_array(), dtype=np.uint32)
        lanes = tuple(inits[:, i] for i in range(tm.state_width))
        inb = np.asarray(tm.within_boundary_lanes(np, lanes), dtype=bool)
        inits = inits[inb]
        self._state_count = len(inits)
        h1, h2 = hash_words_np(inits)
        keys = (h1.astype(np.uint64) << np.uint64(32)) | h2.astype(np.uint64)
        is_new = self._visited.insert_batch(keys, self._nthreads)
        for k in keys[is_new]:
            self._parents[int(k)] = 0
        if self._sampler is not None:
            self._sampler.offer_array(
                keys[is_new],
                depths=np.ones(int(is_new.sum()), dtype=np.int64),
                states=inits[is_new],
            )
        self._coverage.record_depth(1, int(is_new.sum()))
        self._metrics.set_gauge("threads", self._nthreads)
        self._blocks = deque()
        if len(inits):
            self._blocks.append(
                (
                    inits,
                    keys,
                    np.full(len(inits), init_ebits, dtype=np.uint32),
                    np.ones(len(inits), dtype=np.uint32),
                )
            )
        self._start()

    # -- engine body --------------------------------------------------------

    def _run(self) -> None:
        tm = self.tm
        S = tm.state_width
        A = tm.max_actions
        depth_limit = (
            self._target_max_depth
            if self._target_max_depth is not None
            else 0xFFFFFFFF
        )

        while self._blocks:
            rows, keys, ebits, depth = self._blocks.popleft()
            if len(rows) > self._block_rows:
                self._blocks.appendleft(
                    (
                        rows[self._block_rows :],
                        keys[self._block_rows :],
                        ebits[self._block_rows :],
                        depth[self._block_rows :],
                    )
                )
                rows = rows[: self._block_rows]
                keys = keys[: self._block_rows]
                ebits = ebits[: self._block_rows]
                depth = depth[: self._block_rows]
            B = len(rows)
            self._max_depth = max(self._max_depth, int(depth.max()))
            live = depth < depth_limit
            lanes = tuple(rows[:, i] for i in range(S))
            cov = self._coverage if self._coverage.enabled else None
            act_counts = np.zeros(A, dtype=np.int64) if cov is not None else None

            # Property evaluation (ops/expand.py parity).
            ebits = ebits.copy()
            prop_hits = []
            with self._metrics.phase("property_eval"):
                for i, p in enumerate(self._tprops):
                    if p.expectation == Expectation.EVENTUALLY:
                        vals = np.asarray(p.check(np, lanes), dtype=bool) & live
                        ebits[vals] &= ~np.uint32(1 << self._e_slot[i])
                        prop_hits.append(None)
                        continue
                    cond = np.asarray(p.check(np, lanes), dtype=bool)
                    if p.expectation == Expectation.ALWAYS:
                        prop_hits.append(live & ~cond)
                    else:
                        prop_hits.append(live & cond)

            with self._metrics.phase("expand"):
                succs, amask = tm.step_lanes(np, lanes)
            any_valid = np.zeros(B, dtype=bool)
            cand_rows = []
            cand_parent = []
            cand_ebits = []
            cand_depth = []
            for a in range(A):
                v = (
                    np.asarray(amask[a], dtype=bool)
                    & live
                    & np.asarray(
                        tm.within_boundary_lanes(np, succs[a]), dtype=bool
                    )
                )
                any_valid |= v
                if not v.any():
                    continue
                idx = np.flatnonzero(v)
                if act_counts is not None:
                    act_counts[a] += len(idx)
                block = np.stack(
                    [np.asarray(succs[a][s])[idx] for s in range(S)], axis=1
                ).astype(np.uint32)
                cand_rows.append(block)
                cand_parent.append(keys[idx])
                cand_ebits.append(ebits[idx])
                cand_depth.append(depth[idx] + 1)
                self._state_count += len(idx)

            # Terminal eventually-bit discoveries (expand.py parity).
            for i, p in enumerate(self._tprops):
                if p.expectation != Expectation.EVENTUALLY:
                    continue
                bit = np.uint32(1 << self._e_slot[i])
                prop_hits[i] = live & ~any_valid & ((ebits & bit) != 0)

            n_live = int(live.sum()) if cov is not None else 0
            for i, p in enumerate(self._tprops):
                hits = prop_hits[i]
                if cov is not None:
                    cov.record_property_eval(p.name, n_live)
                    cov.record_property_hit(p.name, int(hits.sum()))
                if p.name not in self._discovery_fps and hits.any():
                    # Level order => first block hit is a shallowest hit.
                    self._discovery_fps[p.name] = int(
                        keys[int(np.flatnonzero(hits)[0])]
                    )

            if cand_rows:
                crows = np.concatenate(cand_rows, axis=0)
                cparent = np.concatenate(cand_parent)
                cebits = np.concatenate(cand_ebits)
                cdepth = np.concatenate(cand_depth)
                with self._metrics.phase("hash"):
                    h1, h2 = hash_words_np(crows)
                ckeys = (h1.astype(np.uint64) << np.uint64(32)) | h2.astype(
                    np.uint64
                )
                with self._metrics.phase("visited_insert"):
                    is_new = self._visited.insert_batch(ckeys, self._nthreads)
                if is_new.any():
                    nidx = np.flatnonzero(is_new)
                    nk = ckeys[nidx]
                    np_par = cparent[nidx]
                    self._parents.update(
                        zip(nk.tolist(), np_par.tolist())
                    )
                    if self._sampler is not None:
                        self._sampler.offer_array(
                            nk,
                            depths=cdepth[nidx],
                            states=crows[nidx],
                        )
                    if cov is not None:
                        cov.record_depth_counts(
                            np.bincount(cdepth[nidx].astype(np.int64))
                        )
                    self._blocks.append(
                        (
                            crows[nidx],
                            nk,
                            cebits[nidx],
                            cdepth[nidx],
                        )
                    )

            if cov is not None:
                cov.record_action_counts(act_counts)
            self._metrics.inc("waves")
            self._obs_event(
                "wave",
                frontier=sum(len(b[0]) for b in self._blocks),
                block_rows=B,
            )
            if self._finish_matched(self._discovery_fps):
                return
            if (
                self._target_state_count is not None
                and self._state_count >= self._target_state_count
            ):
                return
            if self._timed_out():
                return

    # -- accessors ----------------------------------------------------------

    def unique_state_count(self) -> int:
        return len(self._visited)

    def discoveries(self) -> Dict[str, Path]:
        self.join()
        return {
            name: self._reconstruct(fp)
            for name, fp in list(self._discovery_fps.items())
        }

    def _reconstruct(self, key: int) -> Path:
        # Keys pack (h1 << 32) | h2 — identical to combine64, so they ARE
        # the canonical fingerprint ints Path.from_fingerprints expects.
        chain = []
        cur = key
        for _ in range(10_000_000):
            chain.append(cur)
            parent = self._parents.get(cur)
            if parent is None:
                raise RuntimeError(
                    f"fingerprint {cur} missing from parent map during "
                    "path reconstruction"
                )
            if parent == 0:
                break
            cur = parent
        chain.reverse()
        return Path.from_fingerprints(self._model, chain)
