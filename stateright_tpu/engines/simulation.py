"""Simulation engine: repeated seeded random walks from init to terminal.

Reference: src/checker/simulation.rs. Each trace walks the model by letting a
pluggable `Chooser` select an initial state and then an enabled action per
step, until the walk terminates (no actions), loops back on itself (per-run
cycle detection via a generated-fingerprint set, simulation.rs:285-289),
leaves the boundary, or all properties have discoveries. Traces repeat with
fresh derived seeds until `finish_when` matches, the target state count is
reached, or the timeout fires.

Discoveries store the full fingerprint path of the violating trace, so the
reported counterexample is exactly the random walk that found it.
"""

from __future__ import annotations

import random
import threading
from typing import Any, Dict, List, Optional

from ..checker import CheckerBuilder
from ..path import Path
from .common import HostEngineBase


class Chooser:
    """Chooses transitions for simulation runs. Reference: simulation.rs:22-39.

    One chooser instance is shared; `new_state(seed)` creates the per-trace
    mutable state (e.g. an RNG).
    """

    def new_state(self, seed: int) -> Any:
        raise NotImplementedError

    def choose_initial_state(self, state: Any, initial_states: List[Any]) -> int:
        raise NotImplementedError

    def choose_action(self, state: Any, current_state: Any, actions: List[Any]) -> int:
        raise NotImplementedError


class UniformChooser(Chooser):
    """Uniform random choices from a seeded, reproducible PRNG.

    Reference: simulation.rs:42-79 (which notes its StdRng is not
    version-stable; we use Python's Mersenne Twister, which is).
    """

    def new_state(self, seed: int) -> random.Random:
        return random.Random(seed)

    def choose_initial_state(self, rng: random.Random, initial_states: List[Any]) -> int:
        return rng.randrange(len(initial_states))

    def choose_action(self, rng: random.Random, current_state: Any, actions: List[Any]) -> int:
        return rng.randrange(len(actions))


class _TraceDiscoveries:
    """A trace-local discovery buffer: membership checks consult the shared
    map too (so an already-recorded property is skipped), but writes stay
    local until the owning worker merges them under the counter lock —
    threaded workers must not mutate the shared dict mid-trace."""

    def __init__(self, shared: Dict[str, List[int]]):
        self._shared = shared
        self.local: Dict[str, List[int]] = {}

    def __contains__(self, name: str) -> bool:
        return name in self.local or name in self._shared

    def __setitem__(self, name: str, value: List[int]) -> None:
        self.local[name] = value


class SimulationChecker(HostEngineBase):
    """Reference: SimulationChecker::spawn, simulation.rs:95-211.

    `.threads(n)` runs n concurrent workers, each with its own seed
    stream — the reference's exact parallelism model (one independent
    reseeded walk loop per thread, simulation.rs:138-201). Under CPython
    the GIL serializes Python-level work, so this buys seed-stream
    diversity and reference-parity semantics rather than wall-clock
    speedup; the batched device engine (spawn_tpu_simulation) is the
    throughput path.
    """

    _supports_threads = True

    def __init__(self, builder: CheckerBuilder, seed: int, chooser: Chooser):
        super().__init__(builder)
        self._seed = seed
        self._chooser = chooser
        self._discoveries: Dict[str, List[int]] = {}  # name -> fingerprint path
        # Guards _state_count / _max_depth / _discoveries: with .threads(n)
        # every worker thread merges its per-trace tallies here (unguarded
        # `+=` read-modify-write races lose counts under free-threading).
        self._counter_lock = threading.Lock()
        self._metrics.set_gauge("threads", max(1, self._thread_count))
        self._start()

    # -- exploration --------------------------------------------------------

    def _run(self) -> None:
        import threading

        if self._thread_count <= 1:
            return self._worker(0)
        # Thread 0 keeps the caller's seed for its first trace; workers
        # t>0 derive distinct streams (simulation.rs:150-156 hands each
        # thread a distinct u64 from the spawn RNG).
        workers = [
            threading.Thread(target=self._worker, args=(t,), daemon=True)
            for t in range(self._thread_count)
        ]
        for w in workers:
            w.start()
        for w in workers:
            w.join()

    def _worker(self, tid: int) -> None:
        # Per-thread seed evolution mirrors simulation.rs:154-197: the first
        # trace uses the thread's seed for reproducibility; subsequent trace
        # seeds are drawn from a thread RNG seeded with the same value.
        seed = (
            self._seed
            if tid == 0
            else random.Random((self._seed, tid)).getrandbits(64)
        )
        thread_rng = random.Random(seed)
        while True:
            with self._metrics.phase("walk"):
                self._check_trace_from_initial(seed)
            self._obs_event("walk", frontier=0, worker=tid)
            if self._finish_matched(self._discoveries):
                return
            if (
                self._target_state_count is not None
                and self._state_count >= self._target_state_count
            ):
                return
            if self._timed_out():
                return
            seed = thread_rng.getrandbits(64)

    def _check_trace_from_initial(self, seed: int) -> None:
        """One random walk. Mirrors simulation.rs:213-398.

        Counters accumulate trace-locally and merge into the shared tallies
        under `_counter_lock` when the walk ends (per-thread counters summed
        at trace end — threaded workers would otherwise race the `+=`)."""
        model = self._model
        chooser = self._chooser
        symmetry = self._symmetry
        discoveries = _TraceDiscoveries(self._discoveries)
        trace_states = 0
        trace_max_depth = 0
        # Trace-local coverage tallies merge once at walk end — the shared
        # accumulator's lock must not sit on the per-step path.
        cov = self._coverage if self._coverage.enabled else None
        trace_actions: Dict[str, int] = {}
        trace_depths: Dict[int, int] = {}

        chooser_state = chooser.new_state(seed)
        initial_states = model.init_states()
        state = initial_states[
            chooser.choose_initial_state(chooser_state, initial_states)
        ]

        fingerprint_path: List[int] = []
        generated: set = set()  # per-run cycle detection
        ebits = self._init_ebits
        reached_max_depth = False

        while True:
            if len(fingerprint_path) > trace_max_depth:
                trace_max_depth = len(fingerprint_path)
            if (
                self._target_max_depth is not None
                and len(fingerprint_path) >= self._target_max_depth
            ):
                # Not known to be terminal: skip the final ebits check
                # (simulation.rs:252-263 returns, not breaks).
                reached_max_depth = True
                break
            if not model.within_boundary(state):
                break

            fp = self._fp(state)
            fingerprint_path.append(fp)
            key = self._fp(symmetry(state)) if symmetry is not None else fp
            if key in generated:
                break  # found a loop
            generated.add(key)
            trace_states += 1
            if self._sampler is not None:
                # Walks revisit states across traces; the sampler dedups
                # by fingerprint, so the sample is still a pure function
                # of the VISITED set (depth = first-visit walk position).
                self._sampler.offer(
                    key, depth=len(fingerprint_path), state=state
                )
            if cov is not None:
                d = len(fingerprint_path)
                trace_depths[d] = trace_depths.get(d, 0) + 1

            if self._visitor is not None:
                self._visitor.visit(
                    model, Path.from_fingerprints(model, fingerprint_path)
                )

            ebits, is_awaiting = self._check_properties(
                state, ebits, discoveries, lambda: list(fingerprint_path)
            )
            if not is_awaiting:
                break  # discoveries found for all properties

            # Choose actions until one yields a next state (simulation.rs:355-390).
            actions: List[Any] = []
            model.actions(state, actions)
            advanced = False
            while actions:
                index = chooser.choose_action(chooser_state, state, actions)
                # swap_remove discipline, matching the reference's sampling
                # without replacement.
                actions[index], actions[-1] = actions[-1], actions[index]
                action = actions.pop()
                next_state = model.next_state(state, action)
                if next_state is not None:
                    if cov is not None:
                        label = self._action_label(action)
                        trace_actions[label] = trace_actions.get(label, 0) + 1
                    state = next_state
                    advanced = True
                    break
            if not advanced:
                break  # terminal: no enabled action produced a state

        if not reached_max_depth:
            self._terminal_ebit_discoveries(
                ebits, discoveries, lambda: list(fingerprint_path)
            )

        with self._counter_lock:
            self._state_count += trace_states
            if trace_max_depth > self._max_depth:
                self._max_depth = trace_max_depth
            for name, fp_path in discoveries.local.items():
                self._discoveries.setdefault(name, fp_path)
        if cov is not None:
            cov.merge_counts(actions=trace_actions, depths=trace_depths)
        self._metrics.inc("traces")
        self._metrics.inc("states_generated", trace_states)

    # -- accessors ----------------------------------------------------------

    def unique_state_count(self) -> int:
        # No global visited set is kept (simulation.rs:413-417).
        return self._state_count

    def discoveries(self) -> Dict[str, Path]:
        return {
            name: Path.from_fingerprints(self._model, fps)
            for name, fps in list(self._discoveries.items())
        }
