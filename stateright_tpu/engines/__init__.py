"""Checking engines.

Host engines (bfs, dfs, simulation, on_demand) mirror the reference's
src/checker/{bfs,dfs,simulation,on_demand}.rs semantics exactly — same queue
discipline, counters, eventually-bit propagation, and early-exit rules — so
golden state counts and visit orders are reproducible. The TPU engine
(tpu_bfs) is the new data-parallel design: a batched frontier over fixed-width
state encodings with a device-resident visited set.
"""
