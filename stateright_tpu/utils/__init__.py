"""Utility data structures (reference: src/util.rs, src/util/*).

Python's builtin set/dict already hash order-insensitively under this
framework's canonical fingerprinting (stateright_tpu.fingerprint sorts
element encodings, the same strategy as the reference's HashableHashSet /
HashableHashMap, util.rs:137-159) — so no wrapper types are needed for
model states; plain set/frozenset/dict are the idiomatic spelling.
"""

from .densenatmap import DenseNatMap
from .vector_clock import VectorClock

__all__ = ["DenseNatMap", "VectorClock"]
