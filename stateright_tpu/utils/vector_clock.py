"""Vector clocks: a partial causal order on distributed events.

Reference: src/util/vector_clock.rs. Semantics preserved exactly:
equality/hash/fingerprint ignore trailing zeros (a clock is conceptually
infinite-dimensional with zero defaults), `merge_max` takes elementwise
maxima, `incremented` grows the vector on demand, and `partial_cmp` returns
None for causally concurrent (incomparable) clocks.
"""

from __future__ import annotations

from typing import List, Optional, Sequence


class VectorClock:
    __slots__ = ("_v",)

    def __init__(self, components: Sequence[int] = ()):
        self._v: List[int] = list(components)

    # -- construction -------------------------------------------------------

    @staticmethod
    def merge_max(c1: "VectorClock", c2: "VectorClock") -> "VectorClock":
        """Elementwise maximum. Reference: vector_clock.rs:18-30."""
        n = max(len(c1._v), len(c2._v))
        return VectorClock(
            [
                max(
                    c1._v[i] if i < len(c1._v) else 0,
                    c2._v[i] if i < len(c2._v) else 0,
                )
                for i in range(n)
            ]
        )

    def incremented(self, index: int) -> "VectorClock":
        """A copy with component `index` incremented (growing as needed).

        Reference: vector_clock.rs:32-39.
        """
        v = list(self._v)
        if index >= len(v):
            v.extend([0] * (1 + index - len(v)))
        v[index] += 1
        return VectorClock(v)

    # -- comparison ----------------------------------------------------------

    def _trimmed(self) -> tuple:
        cutoff = 0
        for i in range(len(self._v) - 1, -1, -1):
            if self._v[i] != 0:
                cutoff = i + 1
                break
        return tuple(self._v[:cutoff])

    def __eq__(self, other) -> bool:
        if not isinstance(other, VectorClock):
            return NotImplemented
        return self._trimmed() == other._trimmed()

    def __hash__(self) -> int:
        # Zero-suffix-insensitive, like the reference Hash (vector_clock.rs:53-62).
        return hash(self._trimmed())

    def fingerprint_key(self) -> tuple:
        return self._trimmed()

    def partial_cmp(self, rhs: "VectorClock") -> Optional[int]:
        """-1 / 0 / +1 for happens-before / equal / happens-after; None if
        concurrent. Reference: vector_clock.rs:84-106."""
        expected = 0
        for i in range(max(len(self._v), len(rhs._v))):
            a = self._v[i] if i < len(self._v) else 0
            b = rhs._v[i] if i < len(rhs._v) else 0
            ordering = (a > b) - (a < b)
            if expected == 0:
                expected = ordering
            elif ordering != expected and ordering != 0:
                return None
        return expected

    def __lt__(self, rhs: "VectorClock") -> bool:
        return self.partial_cmp(rhs) == -1

    def __le__(self, rhs: "VectorClock") -> bool:
        return self.partial_cmp(rhs) in (-1, 0)

    def __gt__(self, rhs: "VectorClock") -> bool:
        return self.partial_cmp(rhs) == 1

    def __ge__(self, rhs: "VectorClock") -> bool:
        return self.partial_cmp(rhs) in (0, 1)

    # -- display -------------------------------------------------------------

    def __repr__(self) -> str:
        return f"VectorClock({self._v!r})"

    def __str__(self) -> str:
        """Reference display: "<1, 2, ...>" (vector_clock.rs:42-51)."""
        return "<" + "".join(f"{c}, " for c in self._v) + "...>"
