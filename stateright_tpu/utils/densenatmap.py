"""DenseNatMap: a Vec-backed map for dense index-like keys.

Reference: src/util/densenatmap.rs. Keys must convert to ints densely
covering [0, len): inserting out of order raises, mirroring the reference's
panic. The key type is remembered from the first insert so lookups with a
different key family can be caught in tests (the reference enforces this
statically with PhantomData).
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Iterator, List, Optional, Tuple


def _as_index(key: Any) -> int:
    i = int(key)
    if i < 0:
        raise ValueError(f"DenseNatMap keys must be non-negative, got {i}")
    return i


class DenseNatMap:
    __slots__ = ("_values", "_key_from_index")

    def __init__(
        self,
        values: Iterable[Any] = (),
        key_from_index: Optional[Callable[[int], Any]] = None,
    ):
        self._values: List[Any] = list(values)
        # How to rebuild keys for iteration; defaults to plain ints.
        self._key_from_index = key_from_index or (lambda i: i)

    # -- construction -------------------------------------------------------

    @staticmethod
    def from_pairs(pairs: Iterable[Tuple[Any, Any]]) -> "DenseNatMap":
        """Collect (key, value) pairs in any order; keys must be dense.

        Reference: FromIterator, densenatmap.rs:64-71.
        """
        pairs = list(pairs)
        out: List[Any] = [None] * len(pairs)
        seen = [False] * len(pairs)
        key_proto = None
        for k, v in pairs:
            i = _as_index(k)
            if i >= len(out) or seen[i]:
                raise ValueError(f"keys are not dense in [0, {len(out)}): {i}")
            out[i] = v
            seen[i] = True
            key_proto = type(k)
        kf = (
            (lambda i: key_proto(i))
            if key_proto is not None and key_proto is not int
            else (lambda i: i)
        )
        return DenseNatMap(out, key_from_index=kf)

    def insert(self, key: Any, value: Any) -> None:
        """Insert in ascending key order; out-of-order insertion raises."""
        i = _as_index(key)
        if i != len(self._values):
            raise ValueError(
                f"DenseNatMap::insert out of order: expected key {len(self._values)}, got {i}"
            )
        self._values.append(value)
        if type(key) is not int:
            kp = type(key)
            self._key_from_index = lambda i: kp(i)

    # -- access --------------------------------------------------------------

    def get(self, key: Any) -> Optional[Any]:
        i = _as_index(key)
        return self._values[i] if 0 <= i < len(self._values) else None

    def __getitem__(self, key: Any) -> Any:
        return self._values[_as_index(key)]

    def __setitem__(self, key: Any, value: Any) -> None:
        i = _as_index(key)
        if i == len(self._values):
            self.insert(key, value)
        else:
            self._values[i] = value

    def values(self) -> List[Any]:
        return list(self._values)

    def keys(self) -> List[Any]:
        return [self._key_from_index(i) for i in range(len(self._values))]

    def items(self) -> Iterator[Tuple[Any, Any]]:
        for i, v in enumerate(self._values):
            yield self._key_from_index(i), v

    def __iter__(self) -> Iterator[Tuple[Any, Any]]:
        return self.items()

    def __len__(self) -> int:
        return len(self._values)

    def __contains__(self, key: Any) -> bool:
        return 0 <= int(key) < len(self._values)

    # -- equality / fingerprinting -------------------------------------------

    def __eq__(self, other) -> bool:
        if not isinstance(other, DenseNatMap):
            return NotImplemented
        return self._values == other._values

    def __hash__(self) -> int:
        return hash(tuple(self._values))

    def fingerprint_key(self) -> list:
        return self._values

    def __repr__(self) -> str:
        return f"DenseNatMap({self._values!r})"
