"""Per-visited-state callbacks for checking runs.

Reference: src/checker/visitor.rs — `CheckerVisitor`, `PathRecorder`
(records the set of visited paths), `StateRecorder` (records evaluated states
in visit order; the BFS/DFS visit-order golden tests depend on it).
Plain callables are accepted wherever a visitor is expected.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, List, Set


class CheckerVisitor:
    """Reference: visitor.rs:19-31."""

    def visit(self, model, path) -> None:
        raise NotImplementedError


class _FnVisitor(CheckerVisitor):
    def __init__(self, fn: Callable):
        self.fn = fn

    def visit(self, model, path) -> None:
        self.fn(path)


def as_visitor(v) -> CheckerVisitor:
    if isinstance(v, CheckerVisitor):
        return v
    if callable(v):
        return _FnVisitor(v)
    raise TypeError(f"not a visitor: {v!r}")


class PathRecorder(CheckerVisitor):
    """Records every visited Path. Reference: visitor.rs:47-73."""

    def __init__(self):
        self._paths: Set = set()
        self._lock = threading.Lock()

    def visit(self, model, path) -> None:
        with self._lock:
            self._paths.add(path)

    @staticmethod
    def new_with_accessor():
        recorder = PathRecorder()

        def accessor() -> Set:
            with recorder._lock:
                return set(recorder._paths)

        return recorder, accessor


class StateRecorder(CheckerVisitor):
    """Records evaluated states in visit order. Reference: visitor.rs:87-111."""

    def __init__(self):
        self._states: List[Any] = []
        self._lock = threading.Lock()

    def visit(self, model, path) -> None:
        with self._lock:
            self._states.append(path.last_state())

    @staticmethod
    def new_with_accessor():
        recorder = StateRecorder()

        def accessor() -> List[Any]:
            with recorder._lock:
                return list(recorder._states)

        return recorder, accessor
