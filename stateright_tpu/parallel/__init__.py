"""Multi-chip state-space exploration over a jax.sharding.Mesh.

The reference scales with work-stealing OS threads over a shared-memory
DashMap (src/job_market.rs, src/checker/bfs.rs:90-164). The TPU-native
equivalent shards both the visited table and the frontier queue across the
device mesh by fingerprint ownership (owner = h1 mod n_devices) and keeps
every structure device-resident: each step, devices expand their local
frontier slice, exchange candidate fingerprints over ICI (all_gather),
keep the candidates they own, and insert into their local table shard.
Load balance comes from the hash itself — fingerprints spread uniformly,
the same property the reference's sharded DashMap relies on.
"""

from .mesh import ShardedBfs

__all__ = ["ShardedBfs"]
