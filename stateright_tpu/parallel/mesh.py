"""Sharded batched BFS: the multi-chip engine core.

Design (SURVEY.md §7 step 4, §5 "distributed communication backend"):

  - mesh axis "shards" over N devices,
  - visited table: [N, cap_local, 4] sharded on dim 0 — each device owns
    the fingerprints with h1 % N == its index,
  - frontier queue: [N, qcap_local, S] ring buffers, one per device, holding
    only states that device owns,
  - per step (one `shard_map`-ped XLA program):
      1. each device pops a chunk from its local ring and evaluates
         properties on it (results returned per-device; host merges),
      2. expands successors locally with the model's batched step,
      3. `all_gather`s candidate (state, fingerprint, parent, ebits, depth)
         tuples over the mesh axis — this is the ICI hop, the analogue of
         the reference's cross-thread job market (src/job_market.rs),
      4. keeps only candidates it owns, dedups in-batch, scatter-claims
         into its local table shard, compacts, and appends to its ring.

The all_gather exchange is simple and correct; a sorted all_to_all that
routes each candidate only to its owner is the planned optimization (it
cuts ICI traffic by ~N_devices x).

Initial states are pre-routed to their owners on the host. Queue overflow
raises (size the ring for the model; per-shard spill is future work).
"""

from __future__ import annotations

import functools
from typing import Dict, List, Optional

import numpy as np

from ..core import Expectation
from ..fingerprint import combine64, hash_words_np
from ..tensor import TensorModel


def _build_sharded_step(tm: TensorModel, props, chunk: int, n_shards: int, axis: str):
    import jax
    import jax.numpy as jnp
    from jax import lax

    from ..ops import frontier as fr
    from ..ops import visited_set as vs
    from ..ops.expand import build_eval_and_expand

    A = tm.max_actions
    eval_and_expand = build_eval_and_expand(tm, props, chunk)

    def per_device(table, queue, q_ebits, q_depth, head, count, depth_limit):
        # Local blocks arrive with a leading length-1 shard dim; drop it.
        table = table[0]
        queue = queue[0]
        q_ebits = q_ebits[0]
        q_depth = q_depth[0]
        head = head[0]
        count = count[0]
        depth_limit = depth_limit[0]

        u = jnp.uint32
        me = lax.axis_index(axis).astype(jnp.uint32)
        qcap = queue.shape[0]
        qmask = u(qcap - 1)
        take = jnp.minimum(count, u(chunk))
        active = jnp.arange(chunk, dtype=jnp.uint32) < take
        rows, slots = fr.ring_gather(queue, head, chunk)
        ebits = q_ebits[slots]
        depth = q_depth[slots]

        ex = eval_and_expand(rows, ebits, depth, active, depth_limit)
        generated = ex.generated
        max_depth_seen = ex.max_depth_seen

        # --- ICI exchange: gather all candidates, keep what I own -------
        def gather(x):
            return lax.all_gather(x, axis, tiled=True)

        g_flat = gather(ex.flat)  # [Nshards*C*A, S]
        g_h1 = gather(ex.h1)
        g_h2 = gather(ex.h2)
        g_p1 = gather(ex.parent1)
        g_p2 = gather(ex.parent2)
        g_ebits = gather(ex.child_ebits)
        g_depth = gather(ex.child_depth)
        g_valid = gather(ex.valid)

        mine = g_valid & ((g_h1 % u(n_shards)) == me)
        keep = fr.dedup_mask(g_h1, g_h2, mine)
        table, is_new, unresolved = vs.insert(table, g_h1, g_h2, g_p1, g_p2, keep)

        order, new_count = fr.compact_indices(is_new)
        packed_rows = g_flat[order]
        packed_ebits = g_ebits[order]
        packed_depth = g_depth[order]
        n_cand = g_h1.shape[0]
        slot_valid = jnp.arange(n_cand, dtype=jnp.uint32) < new_count
        tail = (head + count) & qmask
        queue = fr.ring_scatter(queue, tail, packed_rows, slot_valid)
        q_ebits = fr.ring_scatter(
            q_ebits[:, None], tail, packed_ebits[:, None], slot_valid
        )[:, 0]
        q_depth = fr.ring_scatter(
            q_depth[:, None], tail, packed_depth[:, None], slot_valid
        )[:, 0]

        head = (head + take) & qmask
        count = count - take + new_count
        overflow = count > u(qcap)

        def exp(x):
            return jnp.expand_dims(x, 0)

        pf = ex.prop_found
        p1 = ex.prop_fp1
        p2 = ex.prop_fp2

        return (
            exp(table),
            exp(queue),
            exp(q_ebits),
            exp(q_depth),
            exp(head),
            exp(count),
            exp(generated),
            exp(new_count),
            exp(unresolved.sum(dtype=jnp.uint32)),
            exp(max_depth_seen),
            exp(overflow),
            exp(pf),
            exp(p1),
            exp(p2),
        )

    return per_device


class ShardedBfs:
    """Host driver for the sharded batched BFS across a device mesh."""

    def __init__(
        self,
        tm: TensorModel,
        devices: Optional[List] = None,
        *,
        chunk_size: int = 1024,
        queue_capacity_per_shard: int = 1 << 14,
        table_capacity_per_shard: int = 1 << 16,
        target_max_depth: Optional[int] = None,
    ):
        import jax
        from jax import shard_map
        from jax.sharding import Mesh, PartitionSpec as P

        self.tm = tm
        self._props = tm.tensor_properties()
        devices = devices if devices is not None else jax.devices()
        self.n_shards = len(devices)
        self.mesh = Mesh(np.array(devices), ("shards",))
        self._chunk = chunk_size
        self._qcap = queue_capacity_per_shard
        self._tcap = table_capacity_per_shard
        self._target_max_depth = target_max_depth
        if self._qcap & (self._qcap - 1) or self._tcap & (self._tcap - 1):
            raise ValueError("capacities must be powers of two")

        per_device = _build_sharded_step(
            tm, self._props, chunk_size, self.n_shards, "shards"
        )
        spec = P("shards")
        n_in = 7
        n_out = 14
        self._step = jax.jit(
            shard_map(
                per_device,
                mesh=self.mesh,
                in_specs=(spec,) * n_in,
                out_specs=(spec,) * n_out,
            ),
            donate_argnums=(0, 1, 2, 3),
        )

        self.state_count = 0
        self.unique_state_count = 0
        self.max_depth = 0
        self.discovery_fps: Dict[str, int] = {}

    def run(self, max_steps: int = 1_000_000) -> "ShardedBfs":
        import jax.numpy as jnp

        tm = self.tm
        N = self.n_shards
        S = tm.state_width

        inits = np.asarray(tm.init_states_array(), dtype=np.uint32)
        inb = np.asarray(tm.within_boundary_batch(np, inits), dtype=bool)
        inits = inits[inb]
        self.state_count = len(inits)
        h1, h2 = hash_words_np(inits)

        init_ebits = 0
        e = 0
        for p in self._props:
            if p.expectation == Expectation.EVENTUALLY:
                init_ebits |= 1 << e
                e += 1

        # Route init states to their owner shards; dedup via host set.
        queue = np.zeros((N, self._qcap, S), dtype=np.uint32)
        q_ebits = np.full((N, self._qcap), init_ebits, dtype=np.uint32)
        q_depth = np.ones((N, self._qcap), dtype=np.uint32)
        counts = np.zeros(N, dtype=np.uint32)
        table = np.zeros((N, self._tcap, 4), dtype=np.uint32)
        seen = set()
        for i in range(len(inits)):
            owner = int(h1[i]) % N
            queue[owner, counts[owner]] = inits[i]
            counts[owner] += 1
            fp = combine64(h1[i], h2[i])
            if fp not in seen:
                seen.add(fp)
                # Seed the owner's table directly (host-side, pre-run).
                self._host_insert(table[owner], int(h1[i]), int(h2[i]))
                self.unique_state_count += 1

        table = jnp.asarray(table)
        queue = jnp.asarray(queue)
        q_ebits = jnp.asarray(q_ebits)
        q_depth = jnp.asarray(q_depth)
        head = jnp.zeros(N, dtype=jnp.uint32)
        count = jnp.asarray(counts)
        depth_limit = jnp.full(
            N,
            self._target_max_depth
            if self._target_max_depth is not None
            else 0xFFFFFFFF,
            dtype=jnp.uint32,
        )

        for _ in range(max_steps):
            if int(np.asarray(count).sum()) == 0:
                break
            (
                table,
                queue,
                q_ebits,
                q_depth,
                head,
                count,
                generated,
                new_count,
                unresolved,
                max_depth_seen,
                overflow,
                pf,
                p1,
                p2,
            ) = self._step(table, queue, q_ebits, q_depth, head, count, depth_limit)
            if bool(np.asarray(overflow).any()):
                raise RuntimeError(
                    "per-shard frontier ring overflow; increase "
                    "queue_capacity_per_shard"
                )
            if int(np.asarray(unresolved).sum()) != 0:
                raise RuntimeError(
                    "visited-table probe budget exhausted; increase "
                    "table_capacity_per_shard"
                )
            self.state_count += int(np.asarray(generated).sum())
            self.unique_state_count += int(np.asarray(new_count).sum())
            self.max_depth = max(self.max_depth, int(np.asarray(max_depth_seen).max()))
            if self._props:
                pf_np = np.asarray(pf)
                p1_np = np.asarray(p1)
                p2_np = np.asarray(p2)
                for i, p in enumerate(self._props):
                    if p.name in self.discovery_fps:
                        continue
                    hits = np.nonzero(pf_np[:, i])[0]
                    if len(hits):
                        d = hits[0]
                        self.discovery_fps[p.name] = combine64(
                            p1_np[d, i], p2_np[d, i]
                        )
        self._table = np.asarray(table)
        return self

    @staticmethod
    def _host_insert(table_shard: np.ndarray, h1: int, h2: int) -> None:
        cap = table_shard.shape[0]
        idx = h1 & (cap - 1)
        while table_shard[idx, 0] != 0 or table_shard[idx, 1] != 0:
            if table_shard[idx, 0] == h1 and table_shard[idx, 1] == h2:
                return
            idx = (idx + 1) & (cap - 1)
        table_shard[idx] = (h1, h2, 0, 0)
